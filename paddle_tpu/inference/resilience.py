"""Fault injection, failure containment, and crash recovery for the
serving stack.

At the ROADMAP's scale — heavy traffic from millions of users —
transient faults are the steady state, not the exception: a preempted
device, an OOM spike, NaN logits out of a bad batch, a step that
stalls, a user callback that throws.  Before this module one raising
step executable killed the whole continuous batch, pool exhaustion was
a bare ``RuntimeError``, and a dead driver failed every open stream.
This module makes every one of those survivable, and — just as
important — makes every recovery path *testable on CPU in tier-1*
through a deterministic fault-injection harness.

Three pieces:

* **`FaultPlan`** (armed via ``FLAGS_fault_inject`` or
  ``DecodeEngine(fault_plan=...)``) — a deterministic, occurrence-
  count-driven schedule of failures at named sites (`FAULT_SITES`):
  step-executable raise (generic ``step`` or per-executable
  ``mixed_step`` / ``decode_step`` / ``verify``), ``pool`` exhaustion
  on alloc, ``nan_logits`` row corruption, ``drafter`` raise,
  ``slow_step`` stall, ``host_callback`` raise, plus a
  ``poison@TOKEN`` mode where the step site fails exactly while a
  request whose prompt contains TOKEN is in the batch (the bisect
  containment must isolate it).  No wall-clock anywhere: the Nth
  consult of a site fires, every run replays identically.

* **`ResilienceManager`** — per-engine containment ladder
  `DecodeEngine.step` runs under:

  1. **retry** the failed step with capped exponential backoff
     (``FLAGS_step_retries`` attempts; deterministic backoff *ticks*
     1, 2, 4 ... capped at 8, each tick optionally sleeping
     ``FLAGS_step_backoff_ms``);
  2. **degrade** the failing subsystem after
     ``FLAGS_degrade_after`` consecutive failures — speculation
     disables (verify-only rounds already contained drafter raises),
     chunked prefill falls back to the legacy one-shot oracle path —
     with a re-enable probe after ``FLAGS_degraded_probe_steps``
     clean steps and ``paddle_degraded_mode`` gauges either way;
  3. **bisect-quarantine**: preempt the newest-admitted request and
     retry; repeat until the step succeeds — the last removal is the
     suspect and is retired with ``finish_reason="fault"`` (a
     structured `errors.FaultInfo` on the request), while the
     innocents it was preempted with resume from the queue (their
     replay rides the prefix cache);
  4. still failing with an empty batch → re-raise as a FATAL
     `errors.StepFault` — the engine itself is broken.

* **`EngineSnapshot` / `recover`** — crash recovery over the prefix
  cache.  A snapshot is pure host state captured between steps: every
  in-flight request's prompt + generated ids, remaining budget, and
  the engine's RNG fold counters.  `recover(engine)` rebuilds a fresh
  engine from the dead one's resolved constructor config and
  re-admits every request with its generated tokens FOLDED into the
  prompt (the same fold `DecodeEngine.preempt` uses), so replay is an
  ordinary prompt: chunked prefill recomputes it deterministically,
  requests sharing prefixes hit the rebuilt cache against each other,
  and greedy outputs are bit-identical to a fault-free run.  Tokens
  already emitted live in the folded prompt — they are never
  re-emitted, which is what keeps `frontend.ServingFrontend` streams
  alive across a rebuild.  `serve_with_recovery` is the blocking
  supervisor (the frontend's ``_drive`` embeds the same loop).

Everything here is host-side control between steps: no executable
shape ever changes, and with no plan armed every hook in the serve
loop is a single ``is None`` check — the
``FLAGS_fault_inject``-off path is bit-exact with the pre-resilience
engine (pinned by tests/test_resilience.py).

See docs/RELIABILITY.md for the operator-facing walk-through.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as _obs
from ..analysis import sanitizer as _san
from .errors import (DegradedMode, FaultInfo, InjectedFault,
                     PoolExhausted, StepFault)

__all__ = ["FAULT_SITES", "FaultPlan", "ResilienceManager",
           "EngineSnapshot", "recover", "serve_with_recovery"]


FAULT_SITES = ("step", "mixed_step", "decode_step", "verify", "drafter",
               "pool", "nan_logits", "slow_step", "host_callback")


# ---------------------------------------------------------------------------
# The fault plan
# ---------------------------------------------------------------------------
class FaultPlan:
    """Deterministic fault schedule: ``schedule[site]`` is the set of
    1-based occurrence indices at which the site fires (the engine
    consults a site's counter every time execution passes the hook;
    the Nth consult fires iff N is scheduled).  ``poison_token`` arms
    the batch-content fault: the generic ``step`` site fails whenever
    a request whose PROMPT contains the token occupies an active slot
    — deterministic, and only the bisect containment can clear it.
    ``slow_ms`` is the stall the ``slow_step`` site injects.

    No wall-clock, no RNG at consult time: two runs over the same
    workload replay the same faults at the same steps.  Counters are
    carried across an engine rebuild (`recover` passes the same plan
    object), so a schedule never re-fires after recovery."""

    def __init__(self, schedule: Optional[Dict[str, Sequence[int]]] = None,
                 poison_token: Optional[int] = None, slow_ms: float = 5.0):
        self.schedule: Dict[str, frozenset] = {}
        for site, occs in (schedule or {}).items():
            if site not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {site!r}: one of {FAULT_SITES}")
            occs = frozenset(int(o) for o in occs)
            if any(o < 1 for o in occs):
                raise ValueError(
                    f"occurrence indices are 1-based, got {sorted(occs)} "
                    f"for site {site!r}")
            self.schedule[site] = occs
        self.poison_token = None if poison_token is None \
            else int(poison_token)
        self.slow_ms = float(slow_ms)
        self._counts: Dict[str, int] = {}

    def consult(self, site: str) -> bool:
        """Advance ``site``'s occurrence counter; True iff this
        occurrence is scheduled to fire."""
        n = self._counts.get(site, 0) + 1
        self._counts[site] = n
        return n in self.schedule.get(site, ())

    def poisoned(self, engine) -> bool:
        """Batch-content fault: True while any ACTIVE slot's request
        has the poison token in its prompt."""
        tok = self.poison_token
        if tok is None:
            return False
        for s in range(engine._slots):
            if not engine._active[s]:
                continue
            req = engine._by_slot[s]
            if req is not None and tok in req.prompt_ids:
                return True
        return False

    def consults(self, site: str) -> int:
        """How many times ``site`` has been consulted (telemetry)."""
        return self._counts.get(site, 0)

    @classmethod
    def parse(cls, spec: str) -> Optional["FaultPlan"]:
        """Parse the FLAGS_fault_inject grammar; None for an empty
        spec (harness disarmed, zero hot-path cost).

        ``spec`` is ';'-separated entries:

        * ``site@occs`` — ``occs`` is a ','-separated list of 1-based
          occurrence indices and ``a-b`` inclusive ranges, e.g.
          ``step@3,7-9``;
        * ``poison@TOKEN`` — arm the batch-content fault;
        * ``slow_ms=X`` — the ``slow_step`` stall duration.
        """
        spec = (spec or "").strip()
        if not spec:
            return None
        schedule: Dict[str, List[int]] = {}
        poison = None
        slow_ms = 5.0
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("slow_ms="):
                slow_ms = float(entry.split("=", 1)[1])
                continue
            if "@" not in entry:
                raise ValueError(
                    f"bad fault_inject entry {entry!r}: expected "
                    f"'site@occurrences', 'poison@TOKEN' or 'slow_ms=X'")
            site, _, occs = entry.partition("@")
            site = site.strip()
            if site == "poison":
                poison = int(occs)
                continue
            out = schedule.setdefault(site, [])
            for part in occs.split(","):
                part = part.strip()
                if "-" in part:
                    a, _, b = part.partition("-")
                    out.extend(range(int(a), int(b) + 1))
                else:
                    out.append(int(part))
        return cls(schedule, poison_token=poison, slow_ms=slow_ms)

    @classmethod
    def seeded(cls, seed: int, sites: Sequence[str], rate: float,
               horizon: int, slow_ms: float = 5.0,
               poison_token: Optional[int] = None) -> "FaultPlan":
        """A pseudo-random — but fully deterministic given ``seed`` —
        schedule: each of the first ``horizon`` consults of every site
        fires with probability ``rate`` (drawn once, at construction,
        from a seeded RandomState; nothing is random at consult
        time).  The chaos bench (tools/bench_chaos.py) builds its
        storms with this."""
        rng = np.random.RandomState(seed)
        schedule = {
            site: [i + 1 for i in range(int(horizon))
                   if rng.random_sample() < rate]
            for site in sites
        }
        return cls(schedule, poison_token=poison_token, slow_ms=slow_ms)


# ---------------------------------------------------------------------------
# The containment ladder
# ---------------------------------------------------------------------------
class ResilienceManager:
    """Per-engine fault containment: injection hooks, the
    retry -> degrade -> bisect-quarantine ladder around
    `DecodeEngine._step_inner`, and the degraded-mode state machine.
    Constructed unconditionally (one per engine); with no plan armed
    and no faults raised it costs one ``try`` per step."""

    # never retried, never contained: these mean the PROCESS state is
    # suspect (sanitizer invariants, audit asserts), not the step
    NONRETRYABLE = (_san.SanitizerError, AssertionError)

    def __init__(self, engine):
        self.engine = engine
        # consecutive failures per subsystem kind ("spec" | "mixed" |
        # "decode"), cleared by any clean step
        self._fail: Dict[str, int] = {}
        # contained drafter faults leave the STEP successful (the
        # round completes verify-only), so they carry their own
        # consecutive counter — cleared only by a round with no
        # drafter fault, not by mere step completion
        self._drafter_fail = 0
        self._drafter_faulted = False
        self.spec_disabled = False
        self.legacy_mode = False
        self._clean_since_degrade = 0
        self.backoff_ticks = 0  # deterministic, cumulative (telemetry)

    # -- injection hooks -----------------------------------------------------
    def _count_injection(self, site: str):
        from .serving import _stats_add

        _stats_add(faults_injected=1)
        _obs.FAULTS_INJECTED.inc(site=site)

    def fault_point(self, site: str):
        """Consult one named site.  Fires according to the plan:
        ``pool`` raises `PoolExhausted`, ``slow_step`` stalls for
        ``plan.slow_ms``, everything else raises `InjectedFault`.
        Callers guard with ``engine._fault is not None`` so the
        disarmed hot path never enters here."""
        plan = self.engine._fault
        if plan is None or not plan.consult(site):
            return
        self._count_injection(site)
        if site == "slow_step":
            time.sleep(plan.slow_ms / 1e3)
            if self.engine._abandoned:
                # the frontend watchdog abandoned this engine while we
                # stalled: die here instead of running a step whose
                # requests already belong to the rebuilt engine
                raise StepFault(
                    "engine abandoned by the hung-step watchdog "
                    "mid-stall", site="hung", fatal=True)
            return
        if site == "pool":
            raise PoolExhausted(
                "injected: KV page pool exhausted (fault site 'pool')")
        raise InjectedFault(
            f"injected fault at site {site!r}", site=site)

    def step_fault_point(self, kind_site: str):
        """The guard in front of every step executable: consults the
        generic ``step`` site (plus the poison-token batch fault),
        then the executable-specific site (``mixed_step`` /
        ``decode_step`` / ``verify``)."""
        plan = self.engine._fault
        if plan is None:
            return
        if plan.consult("step") or plan.poisoned(self.engine):
            self._count_injection("step")
            raise InjectedFault(
                "injected fault at site 'step'", site=kind_site)
        self.fault_point(kind_site)

    def corrupt_tokens(self, toks, eligible_slots):
        """The ``nan_logits`` site, host half: when scheduled, replace
        the lowest eligible slot's sampled token with the NaN sentinel
        the in-graph `serving._guard_tokens` guard produces for a
        genuinely non-finite row — injection and organic NaN take the
        exact same quarantine path from here on.  ``toks`` is the
        fetched [B] token vector (or [B, Q] verify-target matrix:
        position 0 is corrupted)."""
        plan = self.engine._fault
        if plan is None or not eligible_slots or \
                not plan.consult("nan_logits"):
            return toks
        self._count_injection("nan_logits")
        toks = np.array(toks)  # the fetched buffer may be read-only
        s = min(eligible_slots)
        if toks.ndim == 1:
            toks[s] = -1
        else:
            toks[s, 0] = -1
        return toks

    # -- degraded-mode state machine -----------------------------------------
    def spec_active(self) -> bool:
        return self.engine._spec is not None and not self.spec_disabled

    def on_drafter_fault(self, err: Exception):
        """A contained drafter raise (the round proceeds verify-only).
        Counts toward the spec-degradation threshold on its own
        consecutive counter — the step itself completes, so the
        generic per-step failure accounting never sees it."""
        self._drafter_faulted = True
        self._drafter_fail += 1
        self._maybe_disable_spec(err)

    def _maybe_disable_spec(self, err: Exception) -> bool:
        from ..core import flags as _flags
        from .serving import _stats_add

        eng = self.engine
        consecutive = max(self._fail.get("spec", 0), self._drafter_fail)
        if eng._spec is None or self.spec_disabled or \
                consecutive < int(_flags.flag("degrade_after")):
            return False
        self.spec_disabled = True
        self._clean_since_degrade = 0
        self._fail.pop("spec", None)
        self._drafter_fail = 0
        _stats_add(spec_disables=1)
        _obs.DEGRADED_MODE.set(1, engine=eng._engine_id, mode="spec_off")
        from .durability import set_health

        set_health(eng._engine_id, "degraded")
        _obs.record_span("engine", "degrade:spec_off", _obs.now_ns(), 0,
                         tid=eng._engine_id,
                         args={"error": str(err)[:200]})
        if eng._flight is not None:
            eng._flight.event("degrade", mode="spec_off",
                              error=str(err)[:120])
        return True

    def _maybe_degrade_legacy(self, err: Exception) -> bool:
        """Persistent mixed-step failure: fall back to the legacy
        one-shot prefill oracle path.  Mid-prefill slots are preempted
        (their partially consumed prompts replay through the legacy
        prefill), chunked mode and the prefix cache switch off; the
        re-enable probe restores both after clean steps."""
        from ..core import flags as _flags
        from .serving import _stats_add

        eng = self.engine
        if not eng._chunked or \
                self._fail.get("mixed", 0) < int(_flags.flag(
                    "degrade_after")):
            return False
        for s in range(eng._slots):
            if eng._active[s] and eng._is_prefilling(s):
                eng.preempt(eng._by_slot[s])
        eng._chunked = False
        eng._prefix_cache = False
        self.legacy_mode = True
        self._clean_since_degrade = 0
        self._fail.pop("mixed", None)
        _stats_add(legacy_fallbacks=1)
        _obs.DEGRADED_MODE.set(1, engine=eng._engine_id,
                               mode="legacy_prefill")
        from .durability import set_health

        set_health(eng._engine_id, "degraded")
        _obs.record_span("engine", "degrade:legacy_prefill",
                         _obs.now_ns(), 0, tid=eng._engine_id,
                         args={"error": str(err)[:200]})
        if eng._flight is not None:
            eng._flight.event("degrade", mode="legacy_prefill",
                              error=str(err)[:120])
        return True

    def _note_success(self):
        from ..core import flags as _flags

        self._fail.clear()
        if not self._drafter_faulted:
            self._drafter_fail = 0  # a round with a healthy drafter
        self._drafter_faulted = False
        if not (self.spec_disabled or self.legacy_mode):
            return
        self._clean_since_degrade += 1
        if self._clean_since_degrade < int(_flags.flag(
                "degraded_probe_steps")):
            return
        eng = self.engine
        self._clean_since_degrade = 0
        if self.spec_disabled and \
                not getattr(eng._spec.drafter, "stateful", False):
            # probe: try speculation again; a fresh failure re-degrades
            self.spec_disabled = False
            _obs.DEGRADED_MODE.set(0, engine=eng._engine_id,
                                   mode="spec_off")
            if eng._flight is not None:
                eng._flight.event("degrade_end", mode="spec_off")
        if self.legacy_mode:
            eng._chunked = eng._chunked_cfg
            eng._prefix_cache = eng._prefix_cache_cfg
            self.legacy_mode = False
            _obs.DEGRADED_MODE.set(0, engine=eng._engine_id,
                                   mode="legacy_prefill")
            if eng._flight is not None:
                eng._flight.event("degrade_end", mode="legacy_prefill")
        if not (self.spec_disabled or self.legacy_mode):
            from .durability import set_health

            set_health(eng._engine_id, "live")

    # -- the ladder ----------------------------------------------------------
    def _mode_kind(self) -> str:
        eng = self.engine
        if self.spec_active():
            return "spec"
        if eng._chunked and eng._prefilling_any():
            return "mixed"
        return "decode"

    def _backoff(self, attempt: int):
        """Capped exponential backoff between same-step retries:
        deterministic tick accounting (1, 2, 4, ... capped at 8) —
        the wall sleep is tick * FLAGS_step_backoff_ms and defaults to
        ZERO, so tier-1 tests replay instantly while production can
        give a transient device fault room to clear."""
        from ..core import flags as _flags
        from .serving import _stats_add

        ticks = min(1 << (attempt - 1), 8)
        self.backoff_ticks += ticks
        _stats_add(step_retries=1)
        _obs.STEP_RETRIES.inc()
        fl = self.engine._flight
        if fl is not None:
            fl.event("retry", attempt=attempt, ticks=ticks)
        base_ms = float(_flags.flag("step_backoff_ms"))
        if base_ms > 0:
            time.sleep(ticks * base_ms / 1e3)

    def run_step(self) -> bool:
        """Run `DecodeEngine._step_inner` under the containment
        ladder.  See the module docstring for the rungs; any step that
        completes clears the consecutive-failure counters and advances
        the degraded-mode re-enable probe."""
        from ..core import flags as _flags

        eng = self.engine
        retries = int(_flags.flag("step_retries"))
        attempt = 0
        last = None
        while True:
            kind = self._mode_kind()
            try:
                out = eng._step_inner()
            except self.NONRETRYABLE:
                raise
            except Exception as e:
                if eng._abandoned:
                    # the watchdog abandoned this engine: its requests
                    # live on the rebuilt one — containment here would
                    # mutate state nobody owns anymore
                    raise
                last = e
                self._fail[kind] = self._fail.get(kind, 0) + 1
                if attempt < retries:
                    attempt += 1
                    self._backoff(attempt)
                    continue
                # retries exhausted: degrade the failing subsystem —
                # the degraded path gets its own retry budget
                if kind == "spec" and self._maybe_disable_spec(e):
                    attempt = 0
                    continue
                if kind == "mixed" and self._maybe_degrade_legacy(e):
                    attempt = 0
                    continue
                return self._bisect_quarantine(e, attempt)
            self._note_success()
            return out

    def _newest_running(self):
        """The most recently admitted running request (bisect order:
        newest admits are the most likely suspects — they are what
        changed about the batch)."""
        eng = self.engine
        live = [r for r in eng._by_slot if r is not None]
        if not live:
            return None
        return max(live, key=lambda r: (r.t_admit_ns or 0, r.request_id))

    def _bisect_quarantine(self, err: Exception, attempts: int) -> bool:
        """Isolate the suspect: preempt the newest-admitted request
        and retry, repeating until the step succeeds.  The LAST
        removal is the suspect — retired with ``finish_reason="fault"``
        and a structured `FaultInfo` — while the innocents preempted
        along the way resume from the queue (their replay rides the
        prefix cache, so the detour costs at most one partial page of
        recompute each).  An empty batch that still fails re-raises as
        a FATAL `StepFault`: the engine itself is broken and only
        `recover` can continue."""
        eng = self.engine
        removed = []
        while True:
            victim = self._newest_running()
            if victim is None:
                site = getattr(err, "site", "step")
                raise StepFault(
                    f"step fault survived retry, degradation and "
                    f"batch bisection — the engine is broken "
                    f"(last error: {err})", site=site,
                    attempts=attempts + len(removed), fatal=True) \
                    from err
            eng.preempt(victim)
            removed.append(victim)
            attempt_ns = _obs.now_ns()
            try:
                out = eng._step_inner()
            except self.NONRETRYABLE:
                raise
            except Exception as e:
                err = e
                continue
            suspect = removed[-1]
            # the suspect was preempted back into the queue: retire it
            # from there with the fault verdict; everyone else stays
            # queued and resumes on the following steps
            suspect.fault_info = FaultInfo(
                site=getattr(err, "site", "step"),
                attempts=attempts + len(removed), step=eng._step_no,
                recovered=False, message=str(err))
            eng._retire_queued(suspect, "fault")
            _obs.record_span(
                "engine", "quarantine", attempt_ns,
                _obs.now_ns() - attempt_ns, tid=eng._engine_id,
                args={"request": suspect.request_id,
                      "site": suspect.fault_info.site,
                      "bisected": len(removed)})
            if eng._flight is not None:
                eng._flight.event("quarantine",
                                  request=suspect.request_id,
                                  site=suspect.fault_info.site,
                                  bisected=len(removed))
            self._note_success()
            return out


# ---------------------------------------------------------------------------
# Crash recovery
# ---------------------------------------------------------------------------
class _ReqRecord:
    __slots__ = ("request", "prompt_ids", "output_ids", "max_new",
                 "absorbed", "orig_len", "streamed")

    def __init__(self, request):
        self.request = request
        self.prompt_ids = list(request.prompt_ids)
        self.output_ids = list(request.output_ids)
        self.max_new = int(request.max_new_tokens)
        self.absorbed = int(request._absorbed)
        self.orig_len = int(request.orig_prompt_len)
        # emitted-token watermark at capture: generated tokens the
        # stream has consumed, plus any still-pending emit gate (a
        # gated token was streamed by an earlier life)
        self.streamed = self.absorbed + len(self.output_ids) + \
            int(request._emit_gate)


class EngineSnapshot:
    """Pure host state of every in-flight request, captured between
    steps: prompt + generated ids, remaining token budget, preemption
    fold accounting, and the engine's RNG fold counters.  Sampling
    parameters, SLO metadata and streaming hooks live ON the `Request`
    objects, which the snapshot keeps by reference — recovery re-admits
    the same objects, so `TokenStream`s and schedulers keep working
    without re-wiring.

    Capture order is admission order (running requests by admit stamp,
    then the queue front-to-back), so a FIFO engine replays in the
    same order it originally served."""

    def __init__(self, engine):
        self.engine_id = engine._engine_id
        self.step_no = int(engine._step_no)
        self.prefill_no = int(engine._prefill_no)
        running = sorted(
            (r for r in engine._by_slot if r is not None),
            key=lambda r: (r.t_admit_ns or 0, r.request_id))
        self.records = [_ReqRecord(r) for r in running] + \
            [_ReqRecord(r) for r in engine._queue]

    def __len__(self):
        return len(self.records)

    def to_wire(self, journal_pos: int = 0):
        """The serialization-safe split (`durability.SnapshotWire`):
        the in-process form keeps `Request` objects BY REFERENCE so
        streams/hooks survive a rebuild, which is exactly wrong on
        disk — the wire form carries only picklable/JSON-able state
        (original prompt, generated values, budgets, the emitted-token
        watermark) a fresh process can re-admit from."""
        from .durability import RequestWire, SnapshotWire

        return SnapshotWire(
            engine_id=self.engine_id, step_no=self.step_no,
            prefill_no=self.prefill_no, journal_pos=int(journal_pos),
            records=[RequestWire.from_record(rec)
                     for rec in self.records])


def recover(engine, snapshot: Optional[EngineSnapshot] = None,
            fault: Optional[BaseException] = None,
            handoff: bool = True):
    """Rebuild a fresh engine after a fatal fault and re-admit every
    in-flight request.  The dead engine's resolved constructor config
    (`engine._ctor`) rebuilds an identical engine — same weights, same
    shapes, same seed; the scheduler/drafter instances are unbound and
    re-bound (their per-engine state rebuilds), and the SAME fault
    plan object carries its occurrence counters over so an injected
    schedule cannot re-fire after the rebuild.

    ``handoff=True`` (default) additionally hands the dead engine's
    live compiled executables to the rebuilt engine
    (`DecodeEngine.adopt_executables`): the config fingerprints match
    by construction (same `_ctor`), so the signature keys are
    identical and the rebuilt engine's first step reuses the warm jit
    caches instead of recompiling — recompile DOMINATED recovery
    latency before this (tools/bench_recovery.py pins the ratio).
    Any fingerprint mismatch falls back to recompile silently.

    Each request's generated tokens fold into its prompt (exactly the
    `DecodeEngine.preempt` fold: ``max_new_tokens`` shrinks one for
    one, ``generated_ids`` stays complete), so replay is an ordinary
    prompt the chunked prefill recomputes deterministically — greedy
    outputs are bit-identical to a fault-free serve, recovered
    requests sharing prefixes hit the rebuilt prefix cache against
    each other, and already-emitted tokens are never re-emitted (the
    streaming hook only ever sees novel tokens).  When recovering from
    an OLDER snapshot than the live request state (the watchdog's
    abandon path hands the pre-step snapshot), tokens the live request
    emitted past the snapshot are recomputed behind the `_emit` gate —
    streamed once, never twice.

    The OLD engine is retired: its scheduler/drafter now belong to the
    new engine and its device buffers are garbage."""
    from .durability import retire_engine_series, set_health
    from .serving import DecodeEngine, _stats_add

    snap = snapshot if snapshot is not None else EngineSnapshot(engine)
    dead_dur, engine._durability = engine._durability, None
    if dead_dur is not None:
        # a fatal fault escaped step() BEFORE its boundary flush:
        # records buffered during the failing step (e.g. a bisect
        # quarantine's finish) must reach disk, or a later process
        # death would restore a request this recovery already retired.
        # close() retires the handle too — the SUCCESSOR engine owns
        # the journal from here, never two live writers
        try:
            dead_dur.close()
        except Exception:
            pass  # best effort — the old handle may already be dead
    t0 = time.perf_counter()
    t0_ns = _obs.now_ns()
    kw = dict(engine._ctor)
    for key in ("scheduler", "drafter"):
        obj = kw.get(key)
        if obj is not None and hasattr(obj, "engine"):
            obj.engine = None  # unbind: bind() rebuilds per-engine state
    if engine._cost is not None:
        # carry the dead engine's LIVE cost calibration (the _ctor
        # holds only the construction-time seed): the rebuilt engine's
        # step-cost predictor starts warm, like its executables
        kw["cost_calibration"] = engine._cost.calibration_wire()
    new = DecodeEngine(**kw)
    set_health(new._engine_id, "recovering")
    if handoff:
        new.adopt_executables(engine)
    # RNG fold counters carry over so the rebuilt engine's sampling
    # streams continue where the dead engine's stopped (greedy ignores
    # them; stochastic streams must not restart from fold 1)
    new._step_no = snap.step_no
    new._prefill_no = snap.prefill_no
    site = getattr(fault, "site", "engine")
    n_readmitted = 0
    for rec in snap.records:
        req = rec.request
        if req.state == "done":
            continue  # quarantined/finished between capture and recover
        # tokens the live request streamed PAST the captured record
        # (the watchdog abandoned a step that had already emitted):
        # replay recomputes them deterministically, the gate keeps
        # them from re-firing at the stream
        live_streamed = req._absorbed + len(req.output_ids) + \
            req._emit_gate
        n_gen = len(rec.output_ids)
        req.prompt_ids = list(rec.prompt_ids) + list(rec.output_ids)
        req.max_new_tokens = rec.max_new - n_gen
        req._absorbed = rec.absorbed + n_gen
        req._emit_gate = max(0, live_streamed - rec.absorbed - n_gen)
        req.output_ids = []
        req.pages = []
        req.slot = None
        req.cached_page_count = 0
        req.cached_prefix_len = 0
        req._page_hashes = None
        req.state = "queued"
        req._engine = new
        if req.fault_info is None:
            req.fault_info = FaultInfo(
                site=site, step=snap.step_no, recovered=True,
                message=str(fault) if fault is not None else
                "rode an engine recovery")
        else:
            req.fault_info.history.append(req.fault_info.site)
            req.fault_info.site = site
            req.fault_info.recovered = True
        new._queue.append(req)
        n_readmitted += 1
    _stats_add(recoveries=1)
    _obs.RECOVERIES.inc()
    _obs.RECOVERY_SECONDS.observe(time.perf_counter() - t0)
    _obs.record_span("engine", "recovery", t0_ns,
                     _obs.now_ns() - t0_ns, tid=new._engine_id,
                     args={"from_engine": snap.engine_id,
                           "requests": n_readmitted, "site": site})
    if new._flight is not None:
        new._flight.event("recovery", from_engine=snap.engine_id,
                          requests=n_readmitted, site=site)
    set_health(new._engine_id, "live")
    # retire the dead engine from the WHOLE gauge catalog, not just
    # health: a recovered hang must not leave {state="hung"} latched
    # at 1 forever, and the dead id's pool/occupancy/queue/burn gauges
    # must stop reading stale levels on every scrape after it
    retire_engine_series(engine._engine_id)
    return new


def serve_with_recovery(engine, max_recoveries: Optional[int] = None,
                        max_steps: int = 100000
                        ) -> Tuple[object, int]:
    """Blocking serve loop with crash recovery: drive ``engine`` to
    completion like `DecodeEngine.run`, rebuilding it via `recover`
    whenever a step fault survives the containment ladder.  Returns
    ``(final_engine, recoveries)`` — the caller must use the RETURNED
    engine (a recovery retires the one passed in).  More than
    ``max_recoveries`` (default FLAGS_engine_recoveries) rebuilds
    raises `DegradedMode` chained from the last fatal fault."""
    from ..core import flags as _flags

    limit = int(_flags.flag("engine_recoveries")) \
        if max_recoveries is None else int(max_recoveries)
    recoveries = 0
    steps = 0
    while engine._queue or engine._active.any():
        if steps >= max_steps:
            raise RuntimeError(
                f"serve_with_recovery(max_steps={max_steps}) exhausted "
                f"with work pending after {recoveries} recoveries")
        try:
            engine.step()
        except StepFault as e:
            if recoveries >= limit:
                raise DegradedMode(
                    f"engine recovery budget exhausted "
                    f"({limit} rebuilds): {e}") from e
            engine = recover(engine, fault=e)
            recoveries += 1
        steps += 1
    return engine, recoveries
