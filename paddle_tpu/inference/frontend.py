"""SLO-aware serving front-end: pluggable admission scheduling + async
streaming over the decode engine.

Two halves, both pure HOST-side control — neither ever changes an
executable shape, so the engine's zero-warm-retrace contract and greedy
parity are untouched (greedy tokens are a function of weights + prompt
only; scheduling changes WHEN a request runs, never WHAT it emits).

**Schedulers** own `DecodeEngine._admit`'s between-steps decision:
which queued request binds to the next free slot, whether a queued
request is still worth admitting, and whether a running request should
give its slot back.

* `FIFOScheduler` (the default, FLAGS_sched_policy="fifo") reproduces
  the historical strict-arrival-order admission loop bit for bit: try
  the queue head, stop at the first request that does not fit.  It
  never reorders, never expires, never preempts.
* `SLOScheduler` ("slo") treats goodput under SLO — not raw
  throughput — as the objective (the serving-engine lineage this stack
  follows judges a TPU serving stack on the fraction of requests that
  meet their latency targets, see PAPERS.md):

  - **ordering**: priority class first (`Request.priority`, lower =
    more urgent; `PRIORITY_INTERACTIVE`/`PRIORITY_BATCH` name the
    ends), earliest deadline next, arrival id last;
  - **deadline expiry**: a never-admitted request whose
    ``deadline_ms`` already passed is retired with
    ``finish_reason="deadline"`` — it never takes a slot, so the
    capacity it would have wasted goes to requests that can still win;
  - **head-of-line skip**: when the best candidate does not fit (pool
    capacity), a smaller request behind it may take the slot — bounded
    by an anti-starvation fence (``hol_skip_limit`` skips, then no
    admission past the blocked head until it admits);
  - **preemption**: under slot/pool pressure a more-urgent candidate
    preempts the lowest-priority running request that is over budget
    (has emitted at least ``preempt_min_output`` tokens — its replay
    pages can enter the prefix cache, so resume recomputes at most one
    partial page).  The victim re-enqueues via `DecodeEngine.preempt`
    and resumes later with ``prompt_ids + output_ids`` as its replay
    prompt;
  - **adaptive chunk budget**: the per-step prefill token budget
    (FLAGS_prefill_chunk_tokens) is steered from the live TTFT/TPOT
    histograms the engine already emits — TPOT running hot against the
    tightest declared target halves the budget (decode latency wins),
    comfortable TPOT with queued work doubles it back toward the
    configured ceiling (TTFT wins).  Budget changes are data, not
    shapes: the mixed executable is untouched.

**`ServingFrontend`** is the asyncio entry point the blocking
`DecodeEngine.generate()`/`run()` loops never offered: ``submit()``
returns an async token iterator (`TokenStream`) fed per token through
the engine's ``on_token`` hook, the engine's step loop runs in a
background driver task (steps execute in a worker thread so the event
loop stays responsive), submission backpressure bounds the admission
queue, slow consumers pause the driver between steps (bounded stream
buffers), cancellation propagates to queued AND running requests, and
``close(drain=True)`` serves every outstanding request before the
driver exits.

Engine-mutation discipline: the engine is single-threaded by design,
so every mutation (add_request, cancel, step) happens from the driver —
``submit()``/``cancel()`` enqueue control actions the driver applies
between steps.  Token callbacks fire inside ``step()`` on the worker
thread and only ever touch the event loop through
``call_soon_threadsafe`` (loop callback order is FIFO, so tokens and
the end-of-stream sentinel can never reorder).

See docs/SERVING.md for the user-facing API walk-through.
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from .. import observability as _obs

__all__ = ["Scheduler", "FIFOScheduler", "SLOScheduler", "make_scheduler",
           "TokenStream", "ServingFrontend"]


# ---------------------------------------------------------------------------
# Schedulers
# ---------------------------------------------------------------------------
class Scheduler:
    """Owns `DecodeEngine._admit`'s between-steps decision.  Bound to
    exactly one engine (`bind`); per step the engine calls `schedule`,
    which admits queued requests through `DecodeEngine._admit_one` (the
    single place the capacity arithmetic lives) and may retire or
    preempt.  Everything runs on the host between steps — a scheduler
    can never change an executable shape."""

    name = "base"

    def __init__(self):
        self.engine = None

    def bind(self, engine):
        if self.engine is not None and self.engine is not engine:
            # scheduler state (starvation fences, budget controller) is
            # per-engine; silently rebinding would cross-wire two queues
            raise ValueError(
                "scheduler is already bound to another engine: construct "
                "one scheduler per DecodeEngine")
        self.engine = engine

    def schedule(self):
        raise NotImplementedError


class FIFOScheduler(Scheduler):
    """Strict arrival order, the historical default: admit the queue
    head while it fits, stop at the first that does not.  No expiry, no
    reordering, no preemption — the bit-exact parity oracle for the SLO
    scheduler (greedy outputs and admission order are identical to the
    pre-scheduler engine)."""

    name = "fifo"

    def schedule(self):
        eng = self.engine
        while eng._queue:
            if not eng._admit_one(eng._queue[0]):
                return


class SLOScheduler(Scheduler):
    """Priority + earliest-deadline-first admission with deadline
    expiry, bounded head-of-line skip, preempt/resume, and an adaptive
    prefill chunk budget.  See the module docstring for the policy;
    every decision routes through the engine's existing primitives
    (`_admit_one`, `_retire_queued`, `preempt`), so the capacity
    arithmetic and telemetry stay in one place.

    Knobs:

    * ``hol_skip_limit`` — how many smaller requests may jump a
      capacity-blocked best candidate before admission freezes until
      the blocked request fits (the anti-starvation fence);
    * ``preempt_min_output`` — a running request only becomes a
      preemption victim after emitting this many tokens ("over
      budget": its TTFT is stamped and its replay pages can register
      in the prefix cache, so resume is cheap).  Mid-prefill requests
      are never preempted;
    * ``adapt_chunk_budget`` — steer the engine's per-step prefill
      budget from the live TTFT/TPOT histograms (chunked engines
      only); ``chunk_budget_min`` floors the shrink.
    """

    name = "slo"

    def __init__(self, hol_skip_limit: int = 4,
                 preempt_min_output: int = 1,
                 adapt_chunk_budget: bool = True,
                 chunk_budget_min: int = 8):
        super().__init__()
        if hol_skip_limit < 0:
            raise ValueError(
                f"hol_skip_limit must be >= 0, got {hol_skip_limit}")
        if preempt_min_output < 1:
            # a victim with zero output has no replay to fold and no
            # pages worth caching — preempting it is pure waste
            raise ValueError(
                f"preempt_min_output must be >= 1, got "
                f"{preempt_min_output}")
        if chunk_budget_min < 1:
            raise ValueError(
                f"chunk_budget_min must be >= 1, got {chunk_budget_min}")
        self.hol_skip_limit = int(hol_skip_limit)
        self.preempt_min_output = int(preempt_min_output)
        self.adapt_chunk_budget = bool(adapt_chunk_budget)
        self.chunk_budget_min = int(chunk_budget_min)
        self._base_budget: Optional[int] = None
        # TTFT/TPOT histogram cursors: the adaptive controller reacts
        # to observations SINCE its last look, not the all-time mean
        self._tpot_seen = (0, 0.0)

    def bind(self, engine):
        super().bind(engine)
        if self._base_budget is None:
            self._base_budget = engine._chunk_budget

    @staticmethod
    def _order_key(req):
        # priority class first, earliest deadline inside a class (no
        # deadline sorts last), arrival id as the stable tie-break —
        # request_id survives preemption, so a resumed request keeps
        # its age-derived position inside its class
        return (req.priority,
                req._deadline_ns if req._deadline_ns is not None
                else float("inf"),
                req.request_id)

    def _expire_deadlines(self, now_ns: int):
        """Retire never-admitted requests whose deadline already
        passed — no slot is ever taken for a request that cannot win.
        A RESUMED request (preempted earlier) is exempt: it already
        held a slot, so it runs to completion and a missed deadline is
        recorded as a violation at finish instead."""
        eng = self.engine
        expired = [r for r in eng._queue
                   if r.t_admit_ns is None and r._deadline_ns is not None
                   and now_ns >= r._deadline_ns]
        for r in expired:
            eng._retire_queued(r, "deadline")

    def _pick_victim(self, candidate):
        """Lowest-priority over-budget running request strictly less
        urgent than ``candidate``, or None.  Among equals: the one
        with the most generation left (it would hold the slot longest,
        so preempting it buys the candidate the most), then newest."""
        eng = self.engine
        victims = [r for r in eng._by_slot
                   if r is not None and r.priority > candidate.priority
                   and len(r.output_ids) >= self.preempt_min_output]
        if not victims:
            return None
        return max(victims, key=lambda r: (
            r.priority, r.max_new_tokens - len(r.output_ids),
            r.request_id))

    def _adapt_budget(self):
        """Steer ``engine._chunk_budget`` from the TTFT/TPOT
        histograms: recent TPOT above the tightest declared target of a
        RUNNING request halves the budget (prefill is stealing decode
        latency); recent TPOT comfortably under target — or no target
        at all — with queued prefill work doubles it back toward the
        configured ceiling.  Data-only: caps arrays change, shapes
        never do.

        The signal is the process-global ``paddle_request_tpot_seconds``
        histogram (it carries no engine label), so in a multi-engine
        process another engine's observations blend into the delta —
        conservative for latency (a slow sibling can only SHRINK this
        engine's budget, trading its own TTFT), but per-engine
        steering needs one engine per process today."""
        eng = self.engine
        if not self.adapt_chunk_budget or not eng._chunked:
            return
        st = _obs.REQUEST_TPOT.series_state()
        if st["count"] < self._tpot_seen[0]:
            # the registry was reset since our last look (bench warmup
            # / test fixtures): re-anchor the cursor instead of acting
            # on a negative delta
            self._tpot_seen = (st["count"], st["sum"])
            return
        d_count = st["count"] - self._tpot_seen[0]
        d_sum = st["sum"] - self._tpot_seen[1]
        if d_count <= 0:
            return  # nothing new observed since the last look
        self._tpot_seen = (st["count"], st["sum"])
        recent_tpot_ms = d_sum / d_count * 1e3
        targets = [r.slo_tpot_ms for r in eng._by_slot
                   if r is not None and r.slo_tpot_ms is not None]
        tightest = min(targets) if targets else None
        floor = min(self.chunk_budget_min, self._base_budget)
        if tightest is not None and recent_tpot_ms > tightest:
            eng._chunk_budget = max(floor, eng._chunk_budget // 2)
        elif eng._queue and (tightest is None
                             or recent_tpot_ms < 0.5 * tightest):
            eng._chunk_budget = min(self._base_budget,
                                    eng._chunk_budget * 2)

    def schedule(self):
        eng = self.engine
        now = _obs.now_ns()
        self._expire_deadlines(now)

        # admission sweep: best-first with bounded head-of-line skip.
        # ``blocked`` is the most urgent candidate that did not fit;
        # every later admission jumps it and costs one skip, and once
        # its fence trips nothing may be admitted past it.
        blocked = None
        for req in sorted(eng._queue, key=self._order_key):
            if blocked is not None and \
                    blocked._hol_skips >= self.hol_skip_limit:
                break
            if eng._admit_one(req):
                if blocked is not None:
                    blocked._hol_skips += 1
                continue
            if not eng._free_slots:
                break  # no slot for anyone: skipping cannot help
            if blocked is None:
                blocked = req  # pool-blocked: smaller ones may still fit

        # preemption: the most urgent still-queued candidate may claim
        # a slot from a strictly less urgent over-budget runner.  One
        # victim at a time, re-testing admission after each, so we
        # never preempt more than the candidate actually needs; a
        # freshly preempted victim re-enters the queue and is only
        # reconsidered NEXT step, which breaks preempt/resume ping-pong
        # inside a single pass.
        if eng._queue:
            top = min(eng._queue, key=self._order_key)
            while True:
                victim = self._pick_victim(top)
                if victim is None:
                    break
                # feasibility gate: preempting EVERY eligible victim
                # must be able to admit `top`, else evicting buys
                # nothing — the victims would resume next step, emit a
                # token, and get preempted again (zero-gain thrash).
                # `freeable` counts each victim's full KV budget (its
                # held pages plus its reservation); pages shared with
                # another live request are an overestimate, which the
                # per-iteration re-check corrects as victims run out.
                freeable = sum(
                    eng._pages_for(v.total_kv_tokens())
                    for v in eng._by_slot
                    if v is not None and v.priority > top.priority
                    and len(v.output_ids) >= self.preempt_min_output)
                if not eng._capacity_ok(top, extra_pages=freeable):
                    break
                eng.preempt(victim)
                if eng._admit_one(top):
                    break

        self._adapt_budget()


_SCHEDULERS = {"fifo": FIFOScheduler, "slo": SLOScheduler}


def make_scheduler(spec) -> Scheduler:
    """Resolve a scheduler: an instance passes through, a name
    constructs with defaults (FLAGS_sched_policy supplies the engine's
    default name)."""
    if isinstance(spec, Scheduler):
        return spec
    try:
        return _SCHEDULERS[spec]()
    except KeyError:
        raise ValueError(
            f"unknown scheduler {spec!r}: pass one of "
            f"{sorted(_SCHEDULERS)} or a Scheduler instance") from None


# ---------------------------------------------------------------------------
# Async streaming front-end
# ---------------------------------------------------------------------------
_DONE = object()  # end-of-stream sentinel on a TokenStream's queue


class TokenStream:
    """Async iterator over one request's generated tokens, produced by
    `ServingFrontend.submit`.  Iterate to stream; after exhaustion
    ``finish_reason`` / ``generated_ids`` read the request's final
    state.  ``cancel()`` stops the request wherever it is (queued or
    running) — already-buffered tokens still drain, then the stream
    ends with ``finish_reason == "cancelled"``."""

    def __init__(self, frontend: "ServingFrontend", request):
        self.request = request
        self._frontend = frontend
        self._queue: asyncio.Queue = asyncio.Queue()
        self._ended = False

    # -- producer side (driver / engine) ------------------------------------
    def _push(self, item):
        # runs as an event-loop callback (call_soon / _threadsafe):
        # put_nowait on an unbounded queue never raises; boundedness is
        # enforced by the driver pausing between steps (_stream_space)
        self._queue.put_nowait(item)

    # -- consumer side -------------------------------------------------------
    def __aiter__(self):
        return self

    async def __anext__(self) -> int:
        if self._ended:
            raise StopAsyncIteration
        item = await self._queue.get()
        self._frontend._notify_drained()
        if item is _DONE:
            self._ended = True
            raise StopAsyncIteration
        return item

    async def collect(self) -> List[int]:
        """Drain the stream to completion and return every token."""
        return [t async for t in self]

    async def cancel(self):
        """Cancel the underlying request (queued or running) and wait
        for the engine to acknowledge; the stream then ends after any
        already-buffered tokens."""
        await self._frontend._cancel(self.request)

    @property
    def pending(self) -> int:
        """Tokens buffered but not yet consumed."""
        return self._queue.qsize()

    @property
    def finish_reason(self) -> Optional[str]:
        return self.request.finish_reason

    @property
    def fault_info(self):
        """Structured fault record (`inference.errors.FaultInfo`) when
        the request was quarantined (``finish_reason == "fault"``),
        rode an engine recovery (``recovered=True`` — it still
        finished normally), or had its callback dropped; None for a
        fault-free request.  The stream itself never raises
        mid-iteration for an engine fault: it ends, and the terminal
        state is read here."""
        return self.request.fault_info

    @property
    def generated_ids(self) -> List[int]:
        return self.request.generated_ids


class ServingFrontend:
    """Asyncio front-end over a `DecodeEngine`: a background driver
    task owns the engine (every mutation happens between steps on the
    driver; steps run in a worker thread so the event loop never
    blocks), ``submit()`` returns a per-token `TokenStream`, and
    shutdown drains or cancels cleanly.

    ::

        async with ServingFrontend(engine) as fe:
            stream = await fe.submit(prompt, max_new_tokens=64,
                                     priority=PRIORITY_INTERACTIVE,
                                     slo_ttft_ms=200.0)
            async for tok in stream:
                ...

    Backpressure, two layers:

    * **admission** — ``submit()`` awaits while the engine's queue
      already holds ``max_queue_depth`` requests (offered load beyond
      that waits in the caller, not in the engine);
    * **streaming** — the driver does not start a step while any open
      stream buffers ``stream_buffer`` or more unconsumed tokens (a
      stalled consumer pauses generation between steps; other
      consumers' buffered tokens stay available throughout).

    ``step_in_thread=False`` runs steps inline on the event loop —
    deterministic for tests, but a long step blocks the loop.
    """

    def __init__(self, engine, max_queue_depth: int = 64,
                 stream_buffer: int = 256, step_in_thread: bool = True,
                 max_recoveries: Optional[int] = None):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if stream_buffer < 1:
            raise ValueError(
                f"stream_buffer must be >= 1, got {stream_buffer}")
        self.engine = engine
        self.max_queue_depth = int(max_queue_depth)
        self.stream_buffer = int(stream_buffer)
        self._step_in_thread = bool(step_in_thread)
        # crash recovery budget (None = FLAGS_engine_recoveries): how
        # many times the driver may rebuild a fatally faulted engine
        # (inference.resilience.recover) before giving up and failing
        # the open streams
        self.max_recoveries = max_recoveries
        self._recoveries = 0
        self._streams: dict = {}  # request -> TokenStream (open only)
        self._control: list = []  # (action, payload, future)
        self._wake: Optional[asyncio.Event] = None
        self._drained: Optional[asyncio.Event] = None
        self._driver: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._closing = False
        self._closed = False
        # ops plane: a frontend-wrapped engine serves the stream-aware
        # debug_dump from /statusz instead of the bare engine statusz
        from ..observability import opsserver as _opsserver

        _opsserver.register_frontend(self)

    # -- lifecycle -----------------------------------------------------------
    async def start(self):
        """Start the background driver (idempotent; ``submit`` starts
        it lazily)."""
        if self._closed:
            raise RuntimeError("frontend is closed")
        if self._driver is None:
            self._loop = asyncio.get_running_loop()
            self._wake = asyncio.Event()
            self._drained = asyncio.Event()
            self._driver = asyncio.create_task(self._drive(),
                                               name="serving-frontend")

    async def __aenter__(self):
        await self.start()
        return self

    async def __aexit__(self, exc_type, exc, tb):
        await self.close(drain=exc_type is None)

    async def close(self, drain: bool = True):
        """Stop the front-end.  ``drain=True`` serves every
        outstanding request to completion first; ``drain=False``
        cancels queued and running requests and returns as soon as the
        engine is idle.  Either way every open stream ends."""
        if self._closed:
            return
        if self._driver is None:
            self._closed = True
            from ..observability import opsserver as _opsserver

            _opsserver.deregister_frontend(self)
            return
        self._closing = True  # reject new submissions from here on
        if not drain:
            # submissions still sitting in the control queue never
            # became engine requests — fail them with the same error a
            # post-close submit() gets, instead of letting the driver
            # apply and serve them to completion during a no-drain
            # close
            keep = []
            for action, payload, fut in self._control:
                if action == "submit" and not fut.done():
                    fut.set_exception(RuntimeError(
                        "frontend is closing; no new requests"))
                else:
                    keep.append((action, payload, fut))
            self._control = keep
            for req in list(self._streams):
                if req.state != "done":
                    await self._cancel(req)
        self._kick()
        await self._driver
        self._closed = True
        from ..observability import opsserver as _opsserver

        _opsserver.deregister_frontend(self)

    # -- submission / cancellation -------------------------------------------
    async def submit(self, prompt_ids, max_new_tokens: int = 32,
                     **request_kwargs) -> TokenStream:
        """Submit one request and stream its tokens.  Keyword
        arguments pass through to `DecodeEngine.add_request`
        (``priority``, ``deadline_ms``, ``slo_ttft_ms``,
        ``slo_tpot_ms``, ``eos_token_id``).  Awaits while the admission
        queue is at ``max_queue_depth`` (submission backpressure) and
        raises whatever ``add_request`` would (validation happens on
        the driver, the error surfaces here)."""
        if self._closing or self._closed:
            raise RuntimeError("frontend is closing; no new requests")
        await self.start()
        # the bound counts not-yet-applied submissions too: N concurrent
        # submit() calls race ahead of the driver's next _apply_control
        # pass, and without the pending term they would all read an
        # empty engine queue and overshoot the bound together
        while len(self.engine._queue) + \
                sum(1 for a, _, _ in self._control
                    if a == "submit") >= self.max_queue_depth:
            # a dead driver will never drain the queue — check BEFORE
            # parking on the event (its final wakeup may already have
            # fired, and nothing else will ever set _drained again)
            self._check_driver()
            # bounded admission queue: wait for a step to drain it
            self._drained.clear()
            await self._drained.wait()
            if self._closing or self._closed:
                raise RuntimeError("frontend is closing; no new requests")
        self._check_driver()
        fut = self._loop.create_future()
        self._control.append(
            ("submit", (prompt_ids, max_new_tokens, request_kwargs), fut))
        self._kick()
        return await fut

    async def adopt(self, journal_dir: str,
                    delivered: Optional[Dict[int, int]] = None,
                    traces: Optional[Dict[int, str]] = None) -> dict:
        """Fleet failover entry: replay a dead sibling replica's
        journal (`durability.adopt_from_dir`) into THIS frontend's
        engine, between steps on the driver like any other mutation,
        and open a `TokenStream` per adopted request.  Returns a dict
        keyed by DONOR request id: ``{"stream": TokenStream,
        "request_id": <fresh id>, "start_index": <tokens the consumer
        already holds>, "backfill": [snapshot-known undelivered
        tokens], "done": bool}`` — the edge relays backfill first,
        then drains the stream, and the reconnected consumer sees
        token-for-token continuity.  ``traces`` (optional) maps donor
        ids to fleet trace ids, the `durability.adopt_from_dir`
        fallback for trace-less journals."""
        if self._closing or self._closed:
            raise RuntimeError("frontend is closing; no new requests")
        await self.start()
        self._check_driver()
        fut = self._loop.create_future()
        self._control.append(
            ("adopt", (journal_dir, delivered, traces), fut))
        self._kick()
        return await fut

    async def _cancel(self, req):
        if self._driver is None or self._driver.done() or \
                req.state == "done":
            # a dead/never-started driver already ended every stream
            # (the _drive finally); there is nothing left to cancel
            return
        fut = self._loop.create_future()
        self._control.append(("cancel", req, fut))
        self._kick()
        await fut

    def _kick(self):
        """Wake the driver wherever it sleeps: ``_wake`` covers the
        idle wait, ``_drained`` covers the stream-backpressure pause —
        a control action (submit/cancel/close) must interrupt BOTH, or
        a cancel aimed at the very stream the driver is paused on would
        deadlock."""
        self._wake.set()
        self._drained.set()

    def _check_driver(self):
        """Surface a dead driver instead of queueing work it will
        never apply (its exception re-raises on `close`)."""
        if self._driver is not None and self._driver.done():
            raise RuntimeError(
                "serving frontend driver has exited; no new requests")

    def _notify_drained(self):
        # a consumer took a token: wake a driver paused on stream
        # backpressure (and submitters waiting on the queue bound)
        if self._drained is not None:
            self._drained.set()

    # -- live introspection ---------------------------------------------------
    def debug_dump(self, flight_records: int = 8) -> dict:
        """Consistent live JSON snapshot of the whole serving stack:
        the frontend's own state (queue bound, open streams with their
        buffered-token counts, pending control actions, recovery
        budget spent) wrapping `DecodeEngine.statusz` — queue, slots,
        degraded modes, health, cache occupancy, SLO burn, and the
        last ``flight_records`` flight records.  Synchronous and
        read-only: callable MID-SERVE from any thread (an operator
        shell, a health endpoint) without perturbing the driver or the
        outputs."""
        streams = {}
        for _ in range(8):
            try:
                for req, s in self._streams.items():
                    streams[req.request_id] = {
                        "state": req.state,
                        "pending_tokens": s.pending,
                    }
                break
            except RuntimeError:  # resized mid-iteration: retry
                streams = {}
        return {
            "frontend": {
                "closing": self._closing,
                "closed": self._closed,
                "driver_alive": self._driver is not None
                and not self._driver.done(),
                "max_queue_depth": self.max_queue_depth,
                "stream_buffer": self.stream_buffer,
                "open_streams": streams,
                "pending_control": len(self._control),
                "recoveries": self._recoveries,
            },
            "engine": self.engine.statusz(
                flight_records=flight_records),
        }

    # -- driver --------------------------------------------------------------
    def _apply_control(self):
        """Apply queued submissions/cancellations — engine idle here
        (between steps, on the loop), the only place besides step()
        that mutates the engine."""
        control, self._control = self._control, []
        for action, payload, fut in control:
            if fut.cancelled():
                continue
            try:
                if action == "submit":
                    prompt_ids, max_new_tokens, kwargs = payload
                    stream_box = []

                    def on_token(tok, _box=stream_box,
                                 _loop=self._loop):
                        # engine worker thread -> event loop; MUST NOT
                        # raise into the serve loop (a closed loop can
                        # only mean shutdown mid-step: drop the token)
                        try:
                            _loop.call_soon_threadsafe(
                                _box[0]._push, tok)
                        except RuntimeError:
                            pass
                    req = self.engine.add_request(
                        prompt_ids, max_new_tokens, on_token=on_token,
                        **kwargs)
                    stream = TokenStream(self, req)
                    stream_box.append(stream)
                    self._streams[req] = stream
                    fut.set_result(stream)
                elif action == "adopt":
                    journal_dir, delivered, traces = payload
                    from . import durability

                    boxes: dict = {}

                    def factory(rid, _boxes=boxes, _loop=self._loop):
                        box: list = []
                        _boxes[rid] = box

                        def on_token(tok, _box=box, _loop=_loop):
                            try:
                                _loop.call_soon_threadsafe(
                                    _box[0]._push, tok)
                            except RuntimeError:
                                pass
                        return on_token
                    # admission happens HERE, between steps on the
                    # driver — no step can emit before the stream
                    # boxes below are filled
                    reqs, meta = durability.adopt_from_dir(
                        journal_dir, self.engine, delivered=delivered,
                        on_token_factory=factory, traces=traces)
                    out = {}
                    for rid, req in reqs.items():
                        stream = TokenStream(self, req)
                        if rid in boxes:
                            boxes[rid].append(stream)
                        # done-state adoptees flush a _DONE on the
                        # next _flush_finished pass like any other
                        # terminal request
                        self._streams[req] = stream
                        out[rid] = {"stream": stream, **meta[rid]}
                    fut.set_result(out)
                else:  # cancel
                    payload.cancel()
                    fut.set_result(None)
            except Exception as e:  # surface on the caller, keep driving
                fut.set_exception(e)

    def _flush_finished(self):
        """End the stream of every request that left the engine
        (finished, cancelled, evicted, deadline-expired).  The sentinel
        goes through ``call_soon`` — the same FIFO callback queue the
        worker thread's token pushes land in — so it can never overtake
        a token emitted by the step that just ran."""
        done = [r for r in self._streams if r.state == "done"]
        for req in done:
            stream = self._streams.pop(req)
            self._loop.call_soon(stream._push, _DONE)

    def _stream_space(self) -> bool:
        """False while any open stream's buffer is at the cap — the
        driver must not step again until a consumer drains."""
        return all(s.pending < self.stream_buffer
                   for s in self._streams.values())

    def _has_work(self) -> bool:
        eng = self.engine
        return bool(eng._queue) or bool(eng._active.any())

    def _recover_engine(self, fault, snapshot=None) -> bool:
        """Supervision: a step fault survived the engine's whole
        containment ladder — rebuild the engine
        (`inference.resilience.recover`, which snapshots the dead
        engine's host state: the fatal raise happens at a between-
        steps-consistent boundary, with every emitted token already
        recorded on its request) and keep every open stream alive:
        the same `Request` objects re-admit with their generated
        tokens folded into the replay prompt, so the ``on_token``
        hooks keep feeding the same `TokenStream`s and no already-
        emitted token is ever re-emitted.  The watchdog's abandon path
        passes its PRE-STEP ``snapshot`` instead (the hung worker may
        still hold the engine mid-step, so its live state cannot be
        trusted); tokens emitted past that snapshot are recomputed
        behind the `_emit` gate — streamed once, never twice.  False
        once the recovery budget (``max_recoveries`` /
        FLAGS_engine_recoveries) is spent — the caller lets the fault
        fail the frontend."""
        from ..core import flags as _flags
        from . import resilience

        limit = int(_flags.flag("engine_recoveries")) \
            if self.max_recoveries is None else int(self.max_recoveries)
        if self._recoveries >= limit:
            return False
        self._recoveries += 1
        self.engine = resilience.recover(self.engine, snapshot=snapshot,
                                         fault=fault)
        # follow the engine generation in the ops registry: /statusz
        # must serve the SUCCESSOR's debug_dump (the dead id is
        # already deregistered by retire_engine_series)
        from ..observability import opsserver as _opsserver

        _opsserver.register_frontend(self)
        return True

    async def _drive(self):
        from .errors import StepFault

        try:
            while True:
                self._apply_control()
                self._flush_finished()  # control may cancel/expire
                if not self._has_work():
                    if self._closing and not self._control:
                        break
                    self._wake.clear()
                    if self._control:
                        continue
                    await self._wake.wait()
                    continue
                if not self._closing and not self._stream_space():
                    # a consumer is behind: pause BETWEEN steps until
                    # it drains (or a control action / close kicks the
                    # event).  A draining shutdown skips the pause —
                    # close() must finish even if nobody consumes, so
                    # the buffers may overshoot the cap there.
                    self._drained.clear()
                    if not self._stream_space():
                        await self._drained.wait()
                    continue
                # hung-step watchdog (FLAGS_step_timeout_ms): once the
                # engine is warm, steps run under an abandon timeout —
                # a worker thread still stuck past the budget is
                # ABANDONED (it may never return; awaiting it would
                # hang the whole frontend) and the engine rebuilds from
                # the pre-step snapshot, streams intact.  The snapshot
                # costs one host-state copy per step and exists only
                # while the watchdog is armed.
                wd = self.engine._watchdog
                arm_abandon = wd is not None and self._step_in_thread \
                    and wd.engine_warm()
                pre = None
                if arm_abandon:
                    from .resilience import EngineSnapshot

                    pre = EngineSnapshot(self.engine)
                try:
                    if arm_abandon:
                        pre_sig = wd.sig()
                        loop = asyncio.get_running_loop()
                        fut = loop.run_in_executor(None, self.engine.step)
                        # the abandoned thread's late raise must not
                        # surface as "exception never retrieved"
                        fut.add_done_callback(
                            lambda f: f.cancelled() or f.exception())
                        try:
                            # shield: wait_for must NOT await the
                            # worker's cancellation — an executor job
                            # cannot be interrupted, so awaiting it
                            # would re-introduce the very hang the
                            # watchdog exists to bound
                            await asyncio.wait_for(asyncio.shield(fut),
                                                   wd.timeout_s)
                        except asyncio.TimeoutError:
                            from . import durability
                            from .errors import HungStep

                            if wd.compiled_since(pre_sig):
                                # a lazily-built executable is
                                # compiling on the worker — an expected
                                # warmup stall, not a hang: wait it out
                                await asyncio.shield(fut)
                            else:
                                from .serving import _stats_add

                                e = HungStep(
                                    f"step still running after "
                                    f"{wd.timeout_ms:.1f}ms — "
                                    f"abandoning the hung worker")
                                _stats_add(hung_steps=1)
                                self.engine._abandon_inflight()
                                durability.set_health(
                                    self.engine._engine_id, "hung")
                                if self._recover_engine(e, snapshot=pre):
                                    continue
                                raise e
                    elif self._step_in_thread:
                        await asyncio.get_running_loop() \
                            .run_in_executor(None, self.engine.step)
                    else:
                        self.engine.step()
                except StepFault as e:
                    if self._recover_engine(e):
                        continue
                    raise
                self._flush_finished()
                self._notify_drained()  # queue may have drained: wake
                # submitters
        except StepFault as e:
            # an UNRECOVERED fatal step fault (the recovery budget is
            # spent — a recovered one was contained above): mark the
            # terminal state BEFORE the finally ends the streams, so a
            # consumer reads finish_reason="fault" + a structured
            # FaultInfo instead of a silently truncated stream (the
            # exception itself re-raises on close()).  Requests that
            # ever held a slot (running, or preempted back to the
            # queue by the containment ladder) died with the engine; a
            # NEVER-admitted queued request keeps its state — it never
            # entered the engine, only its stream ends — but records
            # the fault context too.  Other exception classes
            # (cancellation, sanitizer invariants, host bugs) fall
            # straight to the finally: fabricating a fault verdict for
            # them would misreport what happened.
            from .errors import FaultInfo

            for req in list(self._streams):
                if req.state == "done":
                    continue  # finished normally before the crash
                if req.fault_info is None:
                    req.fault_info = FaultInfo(
                        site=getattr(e, "site", "engine"),
                        recovered=False,
                        message="serving driver died; engine recovery "
                                "budget exhausted")
                else:
                    req.fault_info.recovered = False
                if req.t_admit_ns is not None:
                    req.state = "done"
                    req.finish_reason = "fault"
            raise
        finally:
            # shutdown — clean (drain mode served everything above;
            # cancel mode already retired them) OR an exception out of
            # step(): either way no caller may be left hanging.  Fail
            # whatever control was never applied, end every open
            # stream, and wake blocked submitters so they observe the
            # dead driver (the exception itself re-raises on close()).
            control, self._control = self._control, []
            for _action, _payload, fut in control:
                if not fut.done():
                    fut.set_exception(RuntimeError(
                        "serving frontend driver exited before applying "
                        "this action"))
            self._flush_finished()
            for stream in self._streams.values():
                self._loop.call_soon(stream._push, _DONE)
            self._streams.clear()
            self._notify_drained()
