"""LLM decode serving: paged KV-cache pool + continuous-batching engine.

The serving-side analog of `jit.TrainStep`: the per-step decode —
embedding, per-layer paged-attention over block-table-indexed KV pages,
in-place cache write, sampling — is ONE donated jitted executable with
signature-keyed reuse, so steady-state serving never retraces and the KV
pool buffers are updated in place.  Scheduling (admitting queued
requests into free slots, evicting finished sequences, growing a
sequence's block table page by page) happens on the host *between*
steps, changing only array contents — never shapes — which is what keeps
the executable cache warm.

Layers:

* `KVBlockPool` — host-side page allocator over the device-resident
  K/V page pools (`[layers, kv_heads, num_pages, page_size, head_dim]`),
  doubling as a content-addressed prefix cache (FLAGS_prefix_cache):
  full prompt pages are registered under a chain hash, shared across
  requests at refcount+1, retained on an LRU after their last ref
  drops, and recycled least-recently-released-first under pressure.
  Admission maps the longest page-aligned cached prefix into a new
  request's block table and chunked prefill starts at the first novel
  token; a mid-page divergence is copy-on-write — the partial page is
  recomputed into a fresh private page, cached pages are never
  written;
* `Request` / `DecodeEngine` — continuous batching over a fixed slot
  grid.  With chunked prefill (FLAGS_chunked_prefill, the default)
  admission binds a request to a slot immediately and its prompt is
  consumed chunk by chunk INSIDE the decode step: each step runs one
  fixed-shape ``[slots, Q_max]`` mixed batch (prefilling slots carry a
  prompt chunk as Q>1 ragged rows, decoding slots their usual Q=1 row)
  through a single donated executable, so an admission never stalls
  running decodes and TTFT lands when the last chunk does.  The legacy
  one-shot bucket-padded prefill stays behind ``chunked_prefill=0`` as
  the greedy-parity oracle.  With ``spec_decode_k > 0`` (or
  FLAGS_spec_decode_k) each step becomes a speculative
  propose->verify->accept round (`inference.speculative`) emitting up
  to K+1 tokens per slot;
* telemetry — step latency, batch occupancy, KV-block utilization and
  executable (re)compilation counts, plus speculative acceptance rates
  and per-request finish reasons, surfaced through
  `paddle_tpu.profiler.decode_stats`.

Admission ordering is pluggable (`inference.frontend`): the engine
delegates its between-steps admission decision to a `Scheduler` —
`FIFOScheduler` (the default, bit-exact with the historical strict-
arrival-order behavior) or `SLOScheduler` (priority classes + earliest-
deadline-first + deadline expiry + preempt/resume).  Requests carry
``priority`` / ``deadline_ms`` / TTFT/TPOT SLO targets and an optional
per-token ``on_token`` callback (the streaming hook
`inference.frontend.ServingFrontend` rides).  Preemption
(`DecodeEngine.preempt`) releases a running request's slot and pages
between steps and re-enqueues it with ``prompt_ids + output_ids`` as
the replay prompt — with the prefix cache on, every full page of that
replay was registered at preemption, so resume costs at most one page
of recompute.  All of it is host-side bookkeeping: executable shapes
never change and the zero-warm-retrace contract is untouched.

Numerics deliberately mirror the eager GPT path op for op (same
layer_norm kernel, same sdpa reference, same sampling), so greedy decode
through the engine reproduces `GPT.generate`'s tokens exactly — the
parity contract tests/test_paged_decode.py pins.
"""
from __future__ import annotations

import contextlib
import functools
import hashlib
import heapq
import itertools
import os
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import unwrap
from ..ops.pallas import paged_attention as pa
from .errors import FaultInfo, PoolExhausted, StepFault

__all__ = ["KVBlockPool", "Request", "DecodeEngine", "sample_logits",
           "decode_stats", "reset_decode_stats",
           "PRIORITY_INTERACTIVE", "PRIORITY_BATCH"]


# ---------------------------------------------------------------------------
# Telemetry (profiler.decode_stats).  The key schema lives in profiler
# (DECODE_STAT_COUNTERS) so profiler's not-imported zero fallback and
# this live dict can never diverge.  Mutation and atomic read+reset go
# through the observability registry's lock — the ONE telemetry lock —
# so a stats poller thread can never tear a serve loop's
# read-modify-write updates (or vice versa).
# ---------------------------------------------------------------------------
from ..profiler import (DECODE_STAT_COUNTERS, _decode_stat_zero)
from .. import observability as _obs
from ..analysis import sanitizer as _san
from ..observability import LOCK as _TELEMETRY_LOCK
from ..observability import costmodel as _costmodel

_STATS = {k: _decode_stat_zero(k) for k in DECODE_STAT_COUNTERS}

# reusable no-op context for the flight recorder's phase timers when
# the recorder is off (nullcontext is stateless, so ONE instance
# serves every engine and thread)
_NULL_CTX = contextlib.nullcontext()


def _stats_add(**deltas):
    """Apply counter deltas atomically (one lock round per engine step,
    not one per counter)."""
    with _TELEMETRY_LOCK:
        for k, v in deltas.items():
            _STATS[k] += v


def decode_stats(reset=False):
    """Serving-loop telemetry: decode step latency, batch occupancy,
    KV-block utilization and executable compile counts.
    ``retraces_after_warmup`` must stay 0 in steady state — any nonzero
    value means a step signature changed mid-serve.

    Counters are PROCESS-WIDE aggregates across every DecodeEngine (the
    same contract as ``dispatch_stats``); serving several engines
    concurrently blends their occupancy/utilization averages.
    ``reset=True`` is atomic with the read: counts a concurrent serve
    adds after the snapshot are never lost to the reset."""
    with _TELEMETRY_LOCK:
        out = dict(_STATS)
        if reset:
            reset_decode_stats()
    steps = max(out["steps"], 1)
    out["avg_step_ms"] = out["decode_time_s"] / steps * 1e3
    out["batch_occupancy"] = out["occupancy_sum"] / steps
    out["kv_block_utilization"] = out["kv_util_sum"] / steps
    # speculative decoding: fraction of drafted tokens the verify pass
    # accepted, and tokens emitted per active slot per verify step
    # (1.0 == a classic non-speculative step, K+1 is the ceiling; this
    # number IS the speedup lever)
    out["acceptance_rate"] = out["spec_accepted"] / max(
        out["spec_proposed"], 1)
    out["mean_accepted_per_step"] = out["spec_emitted"] / max(
        out["spec_slot_steps"], 1)
    return out


def reset_decode_stats():
    with _TELEMETRY_LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if isinstance(_STATS[k], float) else 0


# Sampling lives in nn.decode (neutral layer — eager GPT.generate must
# not depend on the serving module); re-exported here for the engine's
# public surface.
from ..nn.decode import sample_logits  # noqa: E402


# ---------------------------------------------------------------------------
# PRNG stream domains.  Every sampling key is
# ``fold_in(engine_key, _fold_counter(counter, domain))``: decode /
# mixed steps fold values in (0, 2^30], legacy one-shot prefill in
# (2^30, 2^31].  The counters themselves are unbounded — after ~2^30
# steps a naive ``fold_in(key, step_no)`` would walk into the prefill
# window and alias its stream, so the fold value WRAPS inside its own
# window (and asserts it stayed there).  Regression-pinned by
# tests/test_chunked_prefill.py::TestRngDomains.
# ---------------------------------------------------------------------------
_RNG_DOMAIN = 1 << 30
RNG_DECODE_DOMAIN = 0   # decode / mixed steps (and speculative rounds)
RNG_PREFILL_DOMAIN = 1  # legacy one-shot prefill


def _fold_counter(counter: int, domain: int) -> int:
    """Map an unbounded 1-based counter into its domain's fold_in
    window ``(domain * 2^30, (domain + 1) * 2^30]``."""
    if counter < 1:
        raise ValueError(f"stream counter must be >= 1, got {counter}")
    v = domain * _RNG_DOMAIN + 1 + (counter - 1) % _RNG_DOMAIN
    assert domain * _RNG_DOMAIN < v <= (domain + 1) * _RNG_DOMAIN, \
        (counter, domain, v)
    return v


class _JitTracker:
    """Retrace telemetry + donation tracking for one jitted step
    executable.  Counts ACTUAL XLA compiles (the jit's own trace-cache
    size) — a dtype/weak_type flapping in the step operands would
    recompile inside the same jitted wrapper and must not go unnoticed.
    Growth after the first call lands in ``retraces_after_warmup``; the
    contract covers the decode step AND the speculative draft/verify
    executables (inference.speculative) identically.

    Invoke the tracker itself (``tracker(*args)``) rather than
    ``tracker.fn``: the call path runs the retrace check after every
    invocation, and under FLAGS_sanitize additionally (a) rejects any
    argument that was DONATED to an earlier tracked call (use-after-
    donate, the error names the donation site), (b) tombstones this
    call's ``donate_argnums`` arguments afterwards — on backends that
    silently ignore donation only the sanitizer makes the "donated
    buffers are dead" contract observable before TPU does — and (c)
    raises `WarmRetraceError` instead of counting a warm retrace."""

    def __init__(self, fn, compile_key, donate_argnums=(), site=None):
        """``fn`` is the PYTHON step callable: the tracker owns the
        ``jax.jit`` wrapping so ``donate_argnums`` has exactly ONE
        source of truth — the tuple XLA donates and the tuple the
        sanitizer tombstones can never drift apart.  (A pre-jitted
        callable is accepted for tests; it must carry no donation or
        the tombstones would not match.)"""
        self.donate_argnums = tuple(donate_argnums)
        is_jitted = hasattr(fn, "lower")  # PjitFunction duck-type
        self.fn = fn if is_jitted else \
            jax.jit(fn, donate_argnums=self.donate_argnums)
        if is_jitted and self.donate_argnums:
            raise ValueError(
                "pass the un-jitted callable when donate_argnums is "
                "set: _JitTracker owns the jax.jit so the donated and "
                "tombstoned argument sets cannot drift")
        self.site = site or compile_key
        # compile_key doubles as the retrace-attribution key:
        # "<kind>_compiles" -> "<kind>_retraces" (decode_stats), so a
        # warm retrace is attributable to ONE executable by counter
        self.compile_key = compile_key
        self._seen = 0
        self._warm = False
        # cost observatory (observability.costmodel): the profile key
        # of this executable's static FLOP/byte profile, stamped at
        # compile time (first invocation) when FLAGS_cost_model is on
        self.cost_sig = None
        _stats_add(**{compile_key: 1})

    def __call__(self, *args):
        san = _san.active()
        if san is not None:
            for a in args:
                san.check_live(a, context=f"argument of {self.site}")
        if not self._warm and _costmodel.enabled():
            # compile-time profile extraction, once per executable:
            # lower the same traced call and read the HLO cost
            # analysis (tracing only — no second compile, no new
            # executable, _cache_size untouched).  BEFORE the call:
            # donated operands are still live here, deleted after.
            try:
                self.cost_sig = _costmodel.note_executable(
                    self.site, self.fn, args)
            except Exception:
                self.cost_sig = None  # analytical fallback covers it
        out = self.fn(*args)
        self.check_retrace()
        if san is not None:
            for i in self.donate_argnums:
                if i < len(args):
                    san.tombstone(args[i], self.site)
        return out

    def check_retrace(self):
        """Runs after every invocation (``__call__`` does it)."""
        try:
            n = self.fn._cache_size()
        except AttributeError:  # older jax without _cache_size
            n = 1
        grew = n - self._seen if self._warm else 0
        was = self._seen
        self._seen = n
        self._warm = True
        if grew > 0:
            san = _san.active()
            if san is not None:
                san.count_warm_retrace(grew)
                raise _san.WarmRetraceError(
                    f"warm retrace of {self.site}: the executable "
                    f"cache grew {was} -> {n} after warmup — a step "
                    f"operand's shape/dtype/weak_type changed "
                    f"mid-serve")
            # aggregate counter + per-executable attribution keyed by
            # compile_key ("<kind>_compiles" -> "<kind>_retraces"); a
            # key outside the schema (tests passing ad-hoc keys) still
            # lands in the aggregate
            per_key = self.compile_key.replace("_compiles", "_retraces")
            if per_key in _STATS:
                _stats_add(retraces_after_warmup=grew,
                           **{per_key: grew})
            else:
                _stats_add(retraces_after_warmup=grew)


# ---------------------------------------------------------------------------
# KV page pool (host-side allocator; device arrays live on the engine)
# ---------------------------------------------------------------------------
class KVBlockPool:
    """Free-list allocator over ``num_pages`` KV pages, extended with a
    content-addressed prefix cache.  Allocation and reservation
    accounting are host-side bookkeeping; the page payloads are the
    engine's donated device arrays.

    A page is in exactly one of four states:

    * **free** — on the free list, payload meaningless;
    * **private** — allocated to exactly one request, writable;
    * **cached, referenced** — registered under a chain-hash key
      (`register_page`), refcount >= 1 requests map it READ-ONLY;
    * **cached, unreferenced** — refcount 0: the payload is retained
      for future prefix hits and the page sits on the eviction LRU.

    `alloc_page` serves from the free list first and falls back to
    evicting the least-recently-released unreferenced cached page; a
    page with a live reference is never evicted and never returns to
    the free list.  Cached pages are immutable by contract: a request
    done with its pages goes through `release_pages` (cached -> unref,
    private -> free), and `free_pages` raises on a cached or already-
    free page — the double-free guard."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._free_set = set(self._free)
        self.reserved = 0  # pages promised to running requests
        # prefix cache: chain hash <-> page, per-page refcounts, and the
        # LRU of refcount-zero cached pages (OrderedDict, oldest first)
        self._hash_to_page: Dict[bytes, int] = {}
        self._page_hash: Dict[int, bytes] = {}
        self._refs: Dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.evictions = 0  # cached pages recycled under pressure

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def cached_count(self) -> int:
        """Pages currently content-addressed (referenced or not)."""
        return len(self._page_hash)

    @property
    def cached_unreferenced_count(self) -> int:
        """Cached pages with no live reference — reclaimable via the
        eviction LRU."""
        return len(self._lru)

    @property
    def available_count(self) -> int:
        """Pages `alloc_page` can hand out right now: the free list
        plus the evictable (unreferenced cached) LRU."""
        return len(self._free) + len(self._lru)

    def utilization(self) -> float:
        """Fraction of the pool a new request CANNOT claim: private +
        cached-referenced pages.  Unreferenced cached pages are
        reclaimable on demand (LRU eviction), so a warm-but-idle cache
        reads 0.0 — an operator alerting on pool pressure sees real
        pressure, not retained prefixes.  With the prefix cache off
        this is exactly used/num_pages, as before."""
        return (self.num_pages - self.available_count) \
            / max(self.num_pages, 1)

    def alloc_page(self) -> int:
        if self._free:
            p = self._free.pop()
            self._free_set.discard(p)
            return p
        if self._lru:
            # eviction under pressure: recycle the least-recently
            # released unreferenced cached page.  Pages with live refs
            # are not in the LRU by invariant, so they can never be
            # handed out from under a running request.
            p, _ = self._lru.popitem(last=False)
            del self._hash_to_page[self._page_hash.pop(p)]
            del self._refs[p]
            self.evictions += 1
            return p
        raise PoolExhausted(
            "KV page pool exhausted: no free page and every cached "
            "page is referenced by a live request")

    def free_pages(self, pages):
        """Return PRIVATE pages to the free list.  Raises on a page
        that is not currently allocated-private: a double free would
        put the same page on the free list twice (handed to two
        requests -> cache corruption), and a cached page must be
        released via `release_pages` (unref) instead."""
        for p in pages:
            p = int(p)
            if not 0 <= p < self.num_pages:
                raise ValueError(
                    f"page {p} outside pool [0, {self.num_pages})")
            if p in self._free_set:
                raise ValueError(f"double free of KV page {p}")
            if p in self._page_hash:
                raise ValueError(
                    f"page {p} is cached (refcount {self._refs[p]}); "
                    f"release_pages unrefs cached pages")
            self._free.append(p)
            self._free_set.add(p)

    def release_pages(self, pages):
        """A request is done with ``pages``: cached pages are unreffed
        (payload retained; refcount 0 parks them on the eviction LRU),
        private pages go back to the free list."""
        for p in pages:
            p = int(p)
            if p in self._page_hash:
                self.unref_page(p)
            else:
                self.free_pages([p])

    # -- content addressing --------------------------------------------------
    def lookup(self, key: bytes) -> Optional[int]:
        """Page registered under chain-hash ``key``, or None."""
        return self._hash_to_page.get(key)

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    def register_page(self, page: int, key: bytes) -> bool:
        """Content-address a full, finally-written PRIVATE page under
        ``key``; the owner's hold becomes refcount 1 (released through
        `release_pages` -> unref, like any other cached ref).  Returns
        False without registering when the key is already taken (a
        concurrent identical prefill computed the same content — the
        duplicate page stays private) or the page is already cached."""
        p = int(page)
        if p in self._free_set:
            raise ValueError(f"cannot register free page {p}")
        if key in self._hash_to_page or p in self._page_hash:
            return False
        self._hash_to_page[key] = p
        self._page_hash[p] = key
        self._refs[p] = 1
        return True

    def ref_page(self, page: int):
        """Map a cached page into one more request (refcount + 1); a
        referenced page leaves the eviction LRU."""
        p = int(page)
        if p not in self._refs:
            raise ValueError(f"page {p} is not cached")
        self._refs[p] += 1
        self._lru.pop(p, None)

    def unref_page(self, page: int):
        """Drop one reference; at zero the page parks on the eviction
        LRU (most-recently released = evicted last), payload intact."""
        p = int(page)
        r = self._refs.get(p)
        if r is None or r <= 0:
            raise ValueError(f"unref of page {p} without a live ref")
        self._refs[p] = r - 1
        if r == 1:
            self._lru[p] = None

    def assert_consistent(self, live_pages=None):
        """Audit the allocator invariants (tests / FLAGS_kv_pool_debug):
        the page universe partitions exactly into free + private +
        cached-referenced + cached-unreferenced, the hash maps are
        mutual inverses, and the LRU is exactly the refcount-zero
        cached set.  With ``live_pages`` — every live request's page
        list, concatenated, WITH multiplicity — additionally checks
        that refcounts equal the number of requests actually holding
        each cached page and every private used page has exactly one
        owner (the ``free + used + cached-unreferenced == num_pages``
        identity made real)."""
        assert len(self._free) == len(self._free_set) == \
            len(set(self._free)), "free list / free set diverged"
        assert len(self._hash_to_page) == len(self._page_hash), \
            "hash->page / page->hash maps diverged"
        for h, p in self._hash_to_page.items():
            assert self._page_hash.get(p) == h, \
                (p, "hash maps are not mutual inverses")
        assert set(self._refs) == set(self._page_hash), \
            "refcounts must exist exactly for cached pages"
        assert not (self._free_set & set(self._page_hash)), \
            "cached page on the free list"
        for p, r in self._refs.items():
            assert r >= 0, (p, r, "negative refcount")
        unref = {p for p, r in self._refs.items() if r == 0}
        assert set(self._lru) == unref, \
            "LRU is not exactly the refcount-zero cached set"
        referenced = len(self._refs) - len(unref)
        private = self.num_pages - self.free_count - self.cached_count
        assert private >= 0, "more free+cached pages than the pool holds"
        assert self.free_count + private + referenced + \
            self.cached_unreferenced_count == self.num_pages
        if live_pages is None:
            return
        from collections import Counter as _Counter

        counts = _Counter(int(p) for p in live_pages)
        for p, c in counts.items():
            assert 0 <= p < self.num_pages, p
            assert p not in self._free_set, (p, "live page is free")
            if p in self._refs:
                assert self._refs[p] == c, \
                    (p, self._refs[p], c, "refcount != live holders")
            else:
                assert c == 1, (p, c, "private page held twice")
        for p, r in self._refs.items():
            if r > 0:
                assert counts.get(p, 0) == r, \
                    (p, r, "referenced page with no live holder")
        live_private = {p for p in counts if p not in self._refs}
        assert len(live_private) == private, \
            (live_private, private, "private page with no owner")


def _chain_hash(prev: bytes, tokens) -> bytes:
    """One link of a prompt's page chain hash: fold the previous page's
    digest with this page's token run.  Page i's key therefore commits
    to tokens 0 .. (i+1)*page-1, so a lookup hit at page i implies the
    whole prefix matched — KV content is a pure function of (model,
    token prefix), which is what makes the cached page bit-reusable."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


# Priority classes (lower value = more urgent; any int works — these
# two name the ends the SLO scheduler is designed around).  The default
# is BATCH so that plain `add_request` calls sort behind explicitly
# interactive traffic under the SLO scheduler while staying pure
# arrival-order under FIFO.
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 10


class Request:
    """One generation request moving through the engine:
    queued -> running (bound to a slot + pages) -> done.

    ``finish_reason`` records WHY a request left the engine — "eos"
    (hit its eos token), "length" (max_new_tokens exhausted),
    "evicted" (`DecodeEngine.evict`), "cancelled" (`Request.cancel`,
    queued or running), "deadline" (its ``deadline_ms`` expired
    while still queued; the SLO scheduler retires it without ever
    taking a slot), or "fault" (the containment ladder quarantined it
    — non-finite logits on its slot, or the batch bisection isolated
    it as the suspect of a persistent step fault; ``fault_info``
    carries the structured record) — so callers can tell a completed
    generation from a truncated one.

    Scheduling metadata: ``priority`` (lower = more urgent;
    `PRIORITY_INTERACTIVE` / `PRIORITY_BATCH` name the classes),
    ``deadline_ms`` (budget from enqueue for the WHOLE request),
    ``slo_ttft_ms`` / ``slo_tpot_ms`` (latency targets — missing one
    increments the SLO-violation counters and flips ``slo_violations``,
    it never aborts the request).  ``on_token`` is the streaming hook:
    called with each generated token id the moment the engine lands it
    (from inside the serve loop — it must be cheap and MUST NOT raise).

    A preempted request (`DecodeEngine.preempt`) goes back to
    "queued" with its generated tokens folded into ``prompt_ids`` for
    replay; ``generated_ids`` always reads the full generation
    regardless of how many times the request was preempted.

    Lifecycle timestamps (``now_ns`` clock, shared with the host
    tracer) are stamped as the request moves enqueue -> admit -> first
    token -> finish; they feed the observability TTFT / TPOT /
    queue-wait / e2e histograms and the per-request chrome-trace
    spans."""

    # itertools.count: id draws are atomic under the GIL, so concurrent
    # enqueues from several threads can never collide (the old
    # read-increment-write raced)
    _next_id = itertools.count()

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 priority=None, deadline_ms=None, slo_ttft_ms=None,
                 slo_tpot_ms=None, on_token=None):
        self.prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.priority = PRIORITY_BATCH if priority is None else \
            int(priority)
        if deadline_ms is not None and float(deadline_ms) <= 0:
            raise ValueError(
                f"deadline_ms must be > 0, got {deadline_ms}")
        self.deadline_ms = None if deadline_ms is None else \
            float(deadline_ms)
        self.slo_ttft_ms = None if slo_ttft_ms is None else \
            float(slo_ttft_ms)
        self.slo_tpot_ms = None if slo_tpot_ms is None else \
            float(slo_tpot_ms)
        self.on_token = on_token
        # stamped at enqueue (t_enqueue_ns + deadline): the instant the
        # request stops being worth admitting
        self._deadline_ns: Optional[int] = None
        # preempt/resume bookkeeping: original prompt length (before
        # any replay folding), generated tokens absorbed into the
        # prompt by preemptions, preemption count, and the scheduler's
        # head-of-line skip counter (anti-starvation fence)
        self.orig_prompt_len = len(self.prompt_ids)
        self._absorbed = 0
        self.preemptions = 0
        self._hol_skips = 0
        # emitted-token gate (inference.durability): > 0 while replay
        # is recomputing tokens an earlier life (pre-crash process, or
        # a watchdog-abandoned step) already streamed — `_emit` lands
        # them on output_ids but never re-fires on_token for them
        self._emit_gate = 0
        # SLO accounting: violation kinds recorded for this request
        # ("ttft" | "tpot" | "deadline")
        self.slo_violations: List[str] = []
        # SLO burn accounting (observability.flight): kinds whose
        # budget burn already crossed 1.0 while live, so the
        # paddle_slo_burn_exceeded counter fires once per kind
        self._burn_noted: set = set()
        self.output_ids: List[int] = []
        self.state = "queued"
        self.finish_reason: Optional[str] = None
        # structured fault record (inference.errors.FaultInfo): set when
        # containment quarantined this request (finish_reason="fault"),
        # when it rode an engine recovery (recovered=True), or when its
        # on_token callback raised and was dropped — instead of a bare
        # exception unwinding through a stream iterator
        self.fault_info: Optional[FaultInfo] = None
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        # prefix cache (FLAGS_prefix_cache): the leading
        # ``cached_page_count`` entries of ``pages`` are shared cached
        # pages (held at refcount+1, never written); chunked prefill
        # starts at token ``cached_prefix_len`` instead of 0
        self.cached_prefix_len = 0
        self.cached_page_count = 0
        # chain hashes of the prompt's full pages, computed lazily at
        # the FIRST admission probe and memoized: a request waiting at
        # the queue head is re-probed every step, and re-hashing a long
        # prompt each time would put O(prompt) host work in the loop
        self._page_hashes: Optional[List[bytes]] = None
        # prefix-cache registration high-water mark: how many of this
        # request's leading FULL pages are content-addressed in the
        # pool — prompt pages at first token, then GENERATED pages as
        # decode crosses page boundaries.  A count of hashes known to
        # the pool, not of pages this life owns, so it survives
        # preempt/resume.
        self._reg_pages = 0
        self.request_id = next(Request._next_id)
        # fleet-scope trace id (observability.fleettrace): minted by
        # the router, carried on every HTTP leg, preserved across
        # failover by the durability journal.  None unless
        # FLAGS_fleet_trace propagated one — span args and flight
        # records tag themselves with it only when set.
        self.trace_id: Optional[str] = None
        self.t_enqueue_ns: Optional[int] = None
        self.t_admit_ns: Optional[int] = None
        self.t_first_token_ns: Optional[int] = None
        self.t_finish_ns: Optional[int] = None
        # chunked prefill: mixed steps that carried one of this
        # request's prompt chunks (1 on the legacy one-shot path)
        self.prefill_chunks = 0
        self._engine = None  # set by DecodeEngine.add_request

    def total_kv_tokens(self) -> int:
        # KV rows ever written: prompt + all generated-token writes except
        # the final sampled token (its KV is never needed).  Invariant
        # under preemption: the replay fold moves tokens from max_new
        # into the prompt one for one.
        return len(self.prompt_ids) + max(self.max_new_tokens - 1, 0)

    @property
    def generated_ids(self) -> List[int]:
        """Every token this request generated, in order — stable across
        preemptions (``output_ids`` only holds the tokens generated
        since the last resume; the earlier ones live in the replay
        prompt)."""
        return self.prompt_ids[self.orig_prompt_len:] + self.output_ids

    def slo_burn(self, now_ns: int) -> Dict[str, float]:
        """Fraction of each declared latency budget this request has
        consumed as of ``now_ns`` — the live SLO burn the flight
        recorder samples every step and `paddle_slo_burn` reports:

        * ``ttft``     — elapsed since enqueue / ``slo_ttft_ms``,
          while the first token is still pending (once it lands the
          budget is settled — met or violated — and stops burning);
        * ``tpot``     — observed per-output-token latency /
          ``slo_tpot_ms``, once at least two tokens exist;
        * ``deadline`` — elapsed since enqueue / the ``deadline_ms``
          budget, while unfinished.

        1.0 means the budget is exactly spent; > 1.0 means the target
        is already missed (the violation counters confirm at finish).
        Empty for a request that declared no targets."""
        out: Dict[str, float] = {}
        if self.t_enqueue_ns is None:
            return out
        if self.slo_ttft_ms is not None and \
                self.t_first_token_ns is None:
            out["ttft"] = ((now_ns - self.t_enqueue_ns) / 1e6) \
                / self.slo_ttft_ms
        if self.slo_tpot_ms is not None and \
                self.t_first_token_ns is not None:
            n_out = len(self.output_ids) + self._absorbed
            if n_out > 1:
                tpot_ms = (now_ns - self.t_first_token_ns) / 1e6 \
                    / (n_out - 1)
                out["tpot"] = tpot_ms / self.slo_tpot_ms
        if self._deadline_ns is not None and self.state != "done":
            budget = self._deadline_ns - self.t_enqueue_ns
            if budget > 0:
                out["deadline"] = (now_ns - self.t_enqueue_ns) / budget
        return out

    @property
    def slo_met(self) -> bool:
        """Did this request complete its generation within every SLO it
        declared?  False while unfinished, for any truncating finish
        (evicted/cancelled/deadline), or when a declared TTFT / TPOT /
        deadline target was missed — the per-request bit behind the
        goodput number `tools/bench_slo.py` reports."""
        return self.state == "done" and \
            self.finish_reason in ("eos", "length") and \
            not self.slo_violations

    def cancel(self):
        """Cancel this request: a still-QUEUED request leaves the
        admission queue without ever taking a slot; a RUNNING request
        gives its slot and pages back between steps (routed through the
        same teardown as `DecodeEngine.evict`).  Either way
        ``finish_reason`` reads "cancelled" — the
        ``finished{reason="cancelled"}`` counter stays distinct from
        "evicted", which is reserved for engine-initiated eviction.
        Cancelling an already-finished request is a no-op."""
        if self.state == "done":
            return
        if self._engine is None:
            raise ValueError("request was never enqueued on an engine")
        if self.state == "queued":
            self._engine._cancel_queued(self)
        else:
            self._engine._cancel_running(self)


def _req_span_args(req: "Request", **extra) -> dict:
    """Span args for a request-carrying span: always the engine
    request id, plus the fleet trace id when one propagated
    (observability.fleettrace) — the key `/tracez/spans` and the
    fleet merge filter on.  No trace id -> byte-identical args to the
    pre-fleet-trace layout."""
    args = {"request": req.request_id}
    if req.trace_id is not None:
        args["trace"] = req.trace_id
    args.update(extra)
    return args


# ---------------------------------------------------------------------------
# Functional GPT forward (pure, jit-compiled once per signature)
# ---------------------------------------------------------------------------
def _extract_gpt_params(model):
    """Pull the weight arrays out of a models.gpt.GPT into a plain pytree
    for the pure step functions."""
    def arr(t):
        return None if t is None else unwrap(t)

    blocks = []
    for blk in model.blocks:
        blocks.append({
            "ln1_w": arr(blk.ln1.weight), "ln1_b": arr(blk.ln1.bias),
            "ln2_w": arr(blk.ln2.weight), "ln2_b": arr(blk.ln2.bias),
            "qkv_w": arr(blk.qkv.weight), "qkv_b": arr(blk.qkv.bias),
            "out_w": arr(blk.out_proj.weight),
            "out_b": arr(blk.out_proj.bias),
            "fc1_w": arr(blk.fc1.weight), "fc1_b": arr(blk.fc1.bias),
            "fc2_w": arr(blk.fc2.weight), "fc2_b": arr(blk.fc2.bias),
        })
    params = {
        "wte": arr(model.wte.weight), "wpe": arr(model.wpe.weight),
        "lnf_w": arr(model.ln_f.weight), "lnf_b": arr(model.ln_f.bias),
        "blocks": blocks,
    }
    if not model.cfg.tie_embeddings:
        params["head_w"] = arr(model.lm_head.weight)
        params["head_b"] = arr(getattr(model.lm_head, "bias", None))
    return params


# Weight leaves `_quantize_gpt_params` folds to int8 storage: every
# [in, out] matmul weight of the step functions.  Embeddings (wte is
# gathered, and the tied head needs its f32 transpose), position
# tables, layernorm params and biases stay f32 — they are a rounding
# error of the per-step weight traffic and some (wte) are read by
# non-matmul ops.
_QUANT_WEIGHT_KEYS = ("qkv_w", "out_w", "fc1_w", "fc2_w")


def _quantize_gpt_params(params):
    """The quantizing twin of `_extract_gpt_params`'s output
    (FLAGS_serve_weights=int8): every matmul weight leaf ``name`` is
    REPLACED by a ``name + "_q"`` int8 leaf (per-out-channel symmetric,
    `quantization.int8.quantize_weight` with quant_axis=1) and a
    ``name + "_s"`` f32 scale leaf holding ``absmax / Q_MAX`` — the
    multiplier the use-site dequant applies AFTER the int8 dot, so
    ``(x @ q) * s == x @ dequant(q)`` exactly (the per-out-channel
    scale commutes past the contraction).  Everything else passes
    through untouched, f32.  Returns ``(params, mats, bytes_saved)``:
    the new tree, the number of weight matrices folded, and the HBM
    bytes the fold reclaimed net of the scale leaves it added."""
    from ..quantization.int8 import Q_MAX, quantize_weight

    def fold(d):
        mats = 0
        saved = 0
        out = dict(d)
        for name in _QUANT_WEIGHT_KEYS + ("head_w",):
            w = out.get(name)
            if w is None:
                continue
            q, scale = quantize_weight(w, quant_axis=1)
            s = (scale / Q_MAX).astype(jnp.float32)
            del out[name]
            out[name + "_q"] = q
            out[name + "_s"] = s
            mats += 1
            saved += w.size * w.dtype.itemsize \
                - q.size * q.dtype.itemsize - s.size * s.dtype.itemsize
        return out, mats, saved

    top, mats, saved = fold(params)
    blocks = []
    for blk in params["blocks"]:
        b, m, s = fold(blk)
        blocks.append(b)
        mats += m
        saved += s
    top["blocks"] = blocks
    return top, mats, saved


def _wmm(x, container, name):
    """Weight matmul, storage-dtype-polymorphic: the ONE use-site shape
    every step function routes its weight matmuls through.  With the
    f32 leaf present (serve_weights=off) this is literally
    ``jnp.matmul`` — the trace emits the exact op it always emitted, so
    off-mode executables stay byte-identical.  With the quantized pair
    present, the dot runs MIXED f32×s8 (`preferred_element_type`
    keeps the accumulator f32) and the per-out-channel scale applies in
    the dot epilogue, where XLA fuses it — the weight streams from HBM
    as int8, and `hot_op_table` sees a distinct ``dot_general[f32xs8]``
    row.  The branch is Python-level on dict membership, resolved at
    trace time: one mode per executable, no in-graph select."""
    w = container.get(name)
    if w is not None:
        return jnp.matmul(x, w)
    acc = jax.lax.dot_general(
        x, container[name + "_q"],
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc * container[name + "_s"]


def _ln(x2d, w, b, eps):
    # the SAME layer_norm implementation the eager path runs on CPU
    # (ops/pallas/layer_norm._fwd_xla) — row-local, so applying it to a
    # single decode row matches the batched eager call bit for bit
    from ..ops.pallas.layer_norm import _fwd_xla

    return _fwd_xla(x2d, w, b, eps)


def _logits_of(params, h):
    if "head_w" in params or "head_w_q" in params:
        out = _wmm(h, params, "head_w")
        if params.get("head_b") is not None:
            out = out + params["head_b"]
        return out
    # tied head: wte stays f32 in every serve_weights mode (it is
    # gathered by the embedding lookup), so the tied logits matmul is
    # always the full-precision transpose
    return jnp.matmul(h, params["wte"].T)


# NaN/inf containment sentinel: a sampled-token value no real vocab can
# produce.  The in-graph guard below replaces the sample of any row
# whose logits went non-finite with it; the host side quarantines
# exactly that slot (finish_reason="fault") instead of streaming
# garbage or killing the batch (inference.resilience).
NAN_TOKEN = -1


def _guard_tokens(logits, tokens):
    """In-graph NaN/inf detection: rows whose logits are not all
    finite sample `NAN_TOKEN` instead of whatever argmax-of-NaN
    returns.  Healthy rows pass through bit-identically, so the guard
    never perturbs parity; the reduce is one pass over logits the
    sampler already read."""
    ok = jnp.isfinite(logits).all(axis=-1)
    return jnp.where(ok, tokens, NAN_TOKEN)


def _gpt_prefill(params, ids, true_len, bt_row, k_pages, v_pages, key, *,
                 num_heads, head_dim, eps, sampler, temperature, top_k,
                 top_p):
    """Prompt pass for ONE request: full causal attention over the
    (bucket-padded) prompt, K/V scattered into the request's pages,
    first token sampled from the last valid position's logits.

    ids: [1, S_pad] int32; true_len: scalar int32; bt_row: [pages_max]
    int32; k_pages/v_pages: [L, Hkv, num_pages, page, D] (donated).
    """
    from ..nn.functional.attention import _sdpa_reference

    s_pad = ids.shape[1]
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]
    pos = jnp.arange(s_pad, dtype=jnp.int32)
    x = params["wte"][ids[0]] + params["wpe"][pos]  # [S, h]

    valid = pos < true_len
    page_idx = jnp.where(valid, bt_row[pos // page], num_pages_total)
    slot = pos % page

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(s_pad, 3, num_heads, head_dim)
        q = qkv[:, 0].transpose(1, 0, 2)[None]  # [1, H, S, D]
        k = qkv[:, 1].transpose(1, 0, 2)[None]
        v = qkv[:, 2].transpose(1, 0, 2)[None]
        # out-of-bounds page index (padded rows) -> scatter drops the
        # row.  The int layer index joins the advanced-index group, so
        # the result dims lead: slice shape is [S, Hkv, D]
        k_pages = k_pages.at[li, :, page_idx, slot, :].set(
            k[0].transpose(1, 0, 2))
        v_pages = v_pages.at[li, :, page_idx, slot, :].set(
            v[0].transpose(1, 0, 2))
        attn = _sdpa_reference(q, k, v, None, 0.0, None, True)[0]
        attn = attn.transpose(1, 0, 2).reshape(s_pad, h)
        x = x + _wmm(attn, blk, "out_w") + blk["out_b"]
        y = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + _wmm(y, blk, "fc2_w") + blk["fc2_b"]

    h_last = jnp.take(x, true_len - 1, axis=0)[None]  # [1, h]
    h_last = _ln(h_last, params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, h_last).astype(jnp.float32)
    token = sample_logits(logits, sampler=sampler, temperature=temperature,
                          top_k=top_k, top_p=top_p, key=key)
    token = _guard_tokens(logits, token)[0]
    return k_pages, v_pages, token


def _gpt_decode_step(params, k_pages, v_pages, block_tables, seq_lens,
                     tokens, active, key, *, num_heads, head_dim, eps,
                     sampler, temperature, top_k, top_p):
    """One batched decode step over every slot: write the incoming
    token's K/V into its page, ragged paged attention over the pool,
    sample the next token.  Donated k_pages/v_pages make the cache
    update in place; inactive slots write nowhere (OOB page index) and
    read length 0."""
    b = tokens.shape[0]
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]

    pos = seq_lens  # the incoming token's position
    x = params["wte"][tokens] + params["wpe"][pos]  # [B, h]
    page_idx = jnp.where(
        active, block_tables[jnp.arange(b), pos // page], num_pages_total)
    slot = pos % page
    lens_now = seq_lens + active.astype(jnp.int32)

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(b, 3, num_heads, head_dim)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        # slice shape [B, Hkv, D] (int layer index joins the advanced
        # group — batch dims lead); inactive rows have an OOB page index
        # and are dropped by the scatter
        k_pages = k_pages.at[li, :, page_idx, slot, :].set(k)
        v_pages = v_pages.at[li, :, page_idx, slot, :].set(v)
        attn = pa.paged_attention(q, k_pages[li], v_pages[li],
                                  block_tables, lens_now)
        x = x + _wmm(attn.reshape(b, h), blk, "out_w") + blk["out_b"]
        y = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + _wmm(y, blk, "fc2_w") + blk["fc2_b"]

    x = _ln(x, params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, x).astype(jnp.float32)
    nxt = sample_logits(logits, sampler=sampler, temperature=temperature,
                        top_k=top_k, top_p=top_p, key=key)
    nxt = _guard_tokens(logits, nxt)
    return k_pages, v_pages, jnp.where(active, nxt, 0)


def _gpt_mixed_step(params, k_pages, v_pages, block_tables, seq_lens,
                    tokens, write_caps, sample_idx, sample_mask, key, *,
                    num_heads, head_dim, eps, sampler, temperature,
                    top_k, top_p):
    """ONE mixed prefill+decode step over every slot: prefilling slots
    contribute a prompt chunk (rows 0..cap-1 of their ``tokens`` row),
    decoding slots contribute their last sampled token (cap 1), stalled
    or inactive slots contribute nothing (cap 0).  K/V for every
    contributed row is scattered into the slot's already-reserved pages
    (write-capped, so padding rows are dropped), attention runs through
    the ragged multi-query paged kernel with per-sequence causal
    offsets (``q_offsets = seq_lens``: each chunk starts at the slot's
    current KV length), and ONE token per slot is sampled from the row
    ``sample_idx`` picks — the last prompt row for a slot finishing its
    prefill this step, row 0 for a decoding slot.  ``sample_mask``
    zeroes the draw for slots still mid-prefill.

    tokens: [B, Q_max] int32; write_caps/sample_idx: [B] int32;
    sample_mask: [B] bool; k_pages/v_pages donated (in-place update).
    Returns (k_pages, v_pages, sampled [B] int32).

    The shapes are fixed per engine, so this compiles ONCE — the pow-2
    bucket zoo of legacy prefill executables collapses into this single
    program, and the `_JitTracker` retrace contract covers it.
    """
    b, qn = tokens.shape
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]

    offs = jnp.arange(qn, dtype=jnp.int32)
    pos = seq_lens[:, None] + offs[None, :]              # [B, Q]
    wpe_max = params["wpe"].shape[0] - 1
    x = params["wte"][tokens] + params["wpe"][jnp.minimum(pos, wpe_max)]
    page_idx, slot = pa.paged_write_indices(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    lens_now = seq_lens + write_caps

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x.reshape(b * qn, h), blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(b, qn, 3, num_heads, head_dim)
        q = qkv[:, :, 0]                                 # [B, Q, H, D]
        # slice shape [B, Q, Hkv, D] (the int layer index joins the
        # advanced group — batch dims lead); capped rows have an OOB
        # page index and are dropped by the scatter
        k_pages = k_pages.at[li, :, page_idx, slot, :].set(qkv[:, :, 1])
        v_pages = v_pages.at[li, :, page_idx, slot, :].set(qkv[:, :, 2])
        attn = pa.paged_attention(q, k_pages[li], v_pages[li],
                                  block_tables, lens_now,
                                  q_offsets=seq_lens)
        x = x + _wmm(attn.reshape(b, qn, h), blk, "out_w") \
            + blk["out_b"]
        y = _ln(x.reshape(b * qn, h), blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + (_wmm(y, blk, "fc2_w") + blk["fc2_b"]
                 ).reshape(b, qn, h)

    # sample ONE row per slot (not all Q like the verify step): the
    # lm-head matmul runs over [B, h], so mixed-step sampling costs the
    # same as a classic decode step's
    sel = x[jnp.arange(b), sample_idx]                   # [B, h]
    sel = _ln(sel, params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, sel).astype(jnp.float32)
    nxt = sample_logits(logits, sampler=sampler, temperature=temperature,
                        top_k=top_k, top_p=top_p, key=key)
    nxt = _guard_tokens(logits, nxt)
    return k_pages, v_pages, jnp.where(sample_mask, nxt, 0)


# ---------------------------------------------------------------------------
# Quantized-KV twins of the step functions (FLAGS_kv_quant=int8).
#
# Pages store int8 with per-page, per-head symmetric scales in parallel
# ``k_scales``/``v_scales`` arrays ([L, Hkv, P] f32) that are donated
# and threaded through every executable exactly like the page pools.
# The write path quantizes the scattered chunk in-graph
# (`pa.paged_quant_write`: per-head absmax folded into the running page
# scale, existing rows re-quantized when the scale grows), and the read
# path fuses dequant into the paged-attention K/V loads — no separate
# materialization pass ever exists.  The sampled-token output is PACKED
# with the step's refold count (one extra int32 row/element) so the
# host learns both from the single blocking fetch the step already
# pays — the sanitizer's one-sync-per-step contract holds in quantized
# mode too.
#
# The unquantized functions above stay byte-identical — they are the
# FLAGS_kv_quant=off path and the bit-exactness oracle; keeping the
# twins separate (rather than a mode flag inside one body) is what
# lets the off path compile the exact same executables as before this
# feature existed (zero new executables in off mode, pinned by
# tools/bench_kv_quant.py).
# ---------------------------------------------------------------------------
def _gpt_prefill_q(params, ids, true_len, bt_row, k_pages, v_pages,
                   k_scales, v_scales, key, *, num_heads, head_dim, eps,
                   sampler, temperature, top_k, top_p):
    """Quantized-storage `_gpt_prefill`: the prompt pass itself attends
    over the in-flight full-precision K/V (same `_sdpa_reference`), but
    every K/V row scattered into the request's pages is quantized via
    the running page scales — later chunked/decode steps read this
    prompt's KV through the fused dequant exactly as if the chunked
    path had written it.  Returns ``(k_pages, v_pages, k_scales,
    v_scales, [token, refolds])``."""
    from ..nn.functional.attention import _sdpa_reference

    s_pad = ids.shape[1]
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]
    pos = jnp.arange(s_pad, dtype=jnp.int32)
    x = params["wte"][ids[0]] + params["wpe"][pos]  # [S, h]

    valid = pos < true_len
    page_idx = jnp.where(valid, bt_row[pos // page], num_pages_total)
    slot = pos % page
    spans = pa.paged_write_spans(
        bt_row[None], jnp.zeros((1,), jnp.int32),
        jnp.reshape(true_len, (1,)), s_pad, num_pages_total, page)
    refolds = jnp.int32(0)

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(s_pad, 3, num_heads, head_dim)
        q = qkv[:, 0].transpose(1, 0, 2)[None]  # [1, H, S, D]
        k = qkv[:, 1].transpose(1, 0, 2)[None]
        v = qkv[:, 2].transpose(1, 0, 2)[None]
        k_pages, k_scales, rk = pa.paged_quant_write(
            k_pages, k_scales, li, k[0].transpose(1, 0, 2), page_idx,
            slot, spans)
        v_pages, v_scales, rv = pa.paged_quant_write(
            v_pages, v_scales, li, v[0].transpose(1, 0, 2), page_idx,
            slot, spans)
        refolds = refolds + rk + rv
        attn = _sdpa_reference(q, k, v, None, 0.0, None, True)[0]
        attn = attn.transpose(1, 0, 2).reshape(s_pad, h)
        x = x + _wmm(attn, blk, "out_w") + blk["out_b"]
        y = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + _wmm(y, blk, "fc2_w") + blk["fc2_b"]

    h_last = jnp.take(x, true_len - 1, axis=0)[None]  # [1, h]
    h_last = _ln(h_last, params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, h_last).astype(jnp.float32)
    token = sample_logits(logits, sampler=sampler, temperature=temperature,
                          top_k=top_k, top_p=top_p, key=key)
    token = _guard_tokens(logits, token)[0]
    out = jnp.stack([token.astype(jnp.int32), refolds])
    return k_pages, v_pages, k_scales, v_scales, out


def _gpt_decode_step_q(params, k_pages, v_pages, k_scales, v_scales,
                       block_tables, seq_lens, tokens, active, key, *,
                       num_heads, head_dim, eps, sampler, temperature,
                       top_k, top_p):
    """Quantized-storage `_gpt_decode_step`: the incoming token's K/V
    quantizes into its page (scale fold + refold), attention reads the
    pool through the fused dequant.  Returns ``(k_pages, v_pages,
    k_scales, v_scales, out)`` with ``out`` = sampled tokens packed
    with the refold count as its last element ([B+1] int32)."""
    b = tokens.shape[0]
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]

    pos = seq_lens  # the incoming token's position
    x = params["wte"][tokens] + params["wpe"][pos]  # [B, h]
    page_idx = jnp.where(
        active, block_tables[jnp.arange(b), pos // page], num_pages_total)
    slot = pos % page
    lens_now = seq_lens + active.astype(jnp.int32)
    refolds = jnp.int32(0)

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(b, 3, num_heads, head_dim)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        k_pages, k_scales, rk = pa.paged_quant_write(
            k_pages, k_scales, li, k, page_idx, slot)
        v_pages, v_scales, rv = pa.paged_quant_write(
            v_pages, v_scales, li, v, page_idx, slot)
        refolds = refolds + rk + rv
        attn = pa.paged_attention(q, k_pages[li], v_pages[li],
                                  block_tables, lens_now,
                                  k_scales=k_scales[li],
                                  v_scales=v_scales[li])
        x = x + _wmm(attn.reshape(b, h), blk, "out_w") + blk["out_b"]
        y = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + _wmm(y, blk, "fc2_w") + blk["fc2_b"]

    x = _ln(x, params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, x).astype(jnp.float32)
    nxt = sample_logits(logits, sampler=sampler, temperature=temperature,
                        top_k=top_k, top_p=top_p, key=key)
    nxt = _guard_tokens(logits, nxt)
    out = jnp.concatenate([jnp.where(active, nxt, 0).astype(jnp.int32),
                           refolds[None]])
    return k_pages, v_pages, k_scales, v_scales, out


def _gpt_mixed_step_q(params, k_pages, v_pages, k_scales, v_scales,
                      block_tables, seq_lens, tokens, write_caps,
                      sample_idx, sample_mask, key, *, num_heads,
                      head_dim, eps, sampler, temperature, top_k, top_p):
    """Quantized-storage `_gpt_mixed_step`: every contributed prompt/
    decode row quantizes into its slot's pages, the ragged multi-query
    attention reads through the fused dequant.  Returns ``(k_pages,
    v_pages, k_scales, v_scales, out)`` with ``out`` [B+1] int32 (the
    sampled token per slot + the refold count)."""
    b, qn = tokens.shape
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]

    offs = jnp.arange(qn, dtype=jnp.int32)
    pos = seq_lens[:, None] + offs[None, :]              # [B, Q]
    wpe_max = params["wpe"].shape[0] - 1
    x = params["wte"][tokens] + params["wpe"][jnp.minimum(pos, wpe_max)]
    page_idx, slot = pa.paged_write_indices(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    flat_idx = page_idx.reshape(-1)                      # [B*Q]
    flat_slot = slot.reshape(-1)
    spans = pa.paged_write_spans(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    lens_now = seq_lens + write_caps
    refolds = jnp.int32(0)

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x.reshape(b * qn, h), blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(b, qn, 3, num_heads, head_dim)
        q = qkv[:, :, 0]                                 # [B, Q, H, D]
        k_pages, k_scales, rk = pa.paged_quant_write(
            k_pages, k_scales, li,
            qkv[:, :, 1].reshape(b * qn, num_heads, head_dim),
            flat_idx, flat_slot, spans)
        v_pages, v_scales, rv = pa.paged_quant_write(
            v_pages, v_scales, li,
            qkv[:, :, 2].reshape(b * qn, num_heads, head_dim),
            flat_idx, flat_slot, spans)
        refolds = refolds + rk + rv
        attn = pa.paged_attention(q, k_pages[li], v_pages[li],
                                  block_tables, lens_now,
                                  q_offsets=seq_lens,
                                  k_scales=k_scales[li],
                                  v_scales=v_scales[li])
        x = x + _wmm(attn.reshape(b, qn, h), blk, "out_w") \
            + blk["out_b"]
        y = _ln(x.reshape(b * qn, h), blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + (_wmm(y, blk, "fc2_w") + blk["fc2_b"]
                 ).reshape(b, qn, h)

    sel = x[jnp.arange(b), sample_idx]                   # [B, h]
    sel = _ln(sel, params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, sel).astype(jnp.float32)
    nxt = sample_logits(logits, sampler=sampler, temperature=temperature,
                        top_k=top_k, top_p=top_p, key=key)
    nxt = _guard_tokens(logits, nxt)
    out = jnp.concatenate(
        [jnp.where(sample_mask, nxt, 0).astype(jnp.int32),
         refolds[None]])
    return k_pages, v_pages, k_scales, v_scales, out


# ---------------------------------------------------------------------------
# The unified ragged step (FLAGS_ragged_step).
#
# ONE executable per KV mode serves every phase of a speculative,
# chunk-prefilling, continuously-batched serve: each slot's row in the
# fixed ``[slots, Q_r]`` grid carries its own query span via
# ``write_caps`` — 1 for a decoding slot, C for a prompt chunk, K+1
# for a verify window, 0 to sit the step out — and the ragged
# multi-query paged-attention kernel (``q_offsets = seq_lens``) gives
# every row its own causal offset.  The host interprets the
# per-position targets by phase: row 0 for a decode slot, row C-1 for
# a slot finishing its prefill, the accept loop for a verify window.
# Collapsing `_gpt_decode_step` / `_gpt_mixed_step` /
# `_gpt_spec_verify` (and the `_q` twins) into this one program means
# one compile, one retrace contract, no compile-time phase branch —
# the "ragged_compiles == 1, {decode,mixed,verify}_compiles == 0"
# counter assertion tests/test_ragged_step.py pins.
#
# The split-path functions above stay byte-identical — they are the
# FLAGS_ragged_step=off path and the greedy-parity oracle; keeping the
# twins separate (rather than a mode flag inside one body) is what
# lets the off path compile the exact same executables as before this
# feature existed (zero new executables in off mode).
# ---------------------------------------------------------------------------
def _mesh_constrain(mesh):
    """Sharding-constraint applicator for the serving mesh: ``None``
    (the single-chip path) returns an identity, so the ragged twins
    trace EXACTLY the ops they always traced — zero sharding machinery
    on the off path.  With a mesh, ``cst(x, *axes)`` pins ``x`` to
    ``PartitionSpec(*axes)`` over it (``cst(x)`` = replicated), the
    GSPMD boundary annotations that turn the one ragged executable
    into a tensor-parallel program: column-split qkv/fc1 compute runs
    head-/feature-local, row-split out/fc2 matmuls end in the
    all-reduce the replicated-residual constraint forces."""
    if mesh is None:
        return lambda x, *spec: x

    def cst(x, *spec):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, PartitionSpec(*spec)))

    return cst


def _gpt_ragged_step(params, k_pages, v_pages, block_tables, seq_lens,
                     tokens, write_caps, key, *, num_heads, head_dim,
                     eps, sampler, temperature, top_k, top_p,
                     mesh=None):
    """The unified ragged step: score up to Q_r incoming tokens per
    slot in ONE pass — write rows ``i < write_caps[b]`` into the slot's
    already-reserved pages (capped rows are dropped by the scatter),
    run ragged multi-query paged attention with per-sequence causal
    offsets, and draw a target token at EVERY position with the
    engine's own `sample_logits`.

    tokens: [B, Q_r] int32 — position ``seq_lens[b] + i`` holds
    ``tokens[b, i]``; write_caps: [B] int32 in [0, Q_r] — the row's
    span (0 = the slot sits this step out; its targets are garbage the
    host ignores); k_pages/v_pages donated (in-place cache update; a
    speculative rejection only shrinks the host's ``seq_lens``).
    Returns (k_pages, v_pages, targets [B, Q_r] int32).

    Positions sample with ``fold_in(key, i)`` (the verify convention);
    greedy ignores the key, which is why greedy tokens are
    bit-identical to the split path — the oracle the parity tests pin.
    Rows past a slot's span cost dense FLOPs but no extra KV traffic
    (K/V pages are gathered once per slot for all Q_r rows), so size
    ``prefill_q_max`` / K to the traffic when decode dominates."""
    b, qn = tokens.shape
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]
    cst = _mesh_constrain(mesh)

    pos = seq_lens[:, None] + jnp.arange(qn, dtype=jnp.int32)[None, :]
    wpe_max = params["wpe"].shape[0] - 1
    x = params["wte"][tokens] + params["wpe"][jnp.minimum(pos, wpe_max)]
    page_idx, slot = pa.paged_write_indices(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    lens_now = seq_lens + write_caps

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x.reshape(b * qn, h), blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        # head axis sharded over 'mp' from here: the KV scatter and the
        # paged-attention gather stay chip-local (each chip owns its
        # head-slice of every page)
        qkv = cst(qkv.reshape(b, qn, 3, num_heads, head_dim),
                  None, None, None, "mp", None)
        q = qkv[:, :, 0]                                 # [B, Q, H, D]
        k_pages = cst(
            k_pages.at[li, :, page_idx, slot, :].set(qkv[:, :, 1]),
            None, "mp", None, None, None)
        v_pages = cst(
            v_pages.at[li, :, page_idx, slot, :].set(qkv[:, :, 2]),
            None, "mp", None, None, None)
        attn = cst(pa.paged_attention(q, k_pages[li], v_pages[li],
                                      block_tables, lens_now,
                                      q_offsets=seq_lens),
                   None, None, "mp", None)
        # row-parallel out proj: replicating the residual forces the
        # cross-chip all-reduce exactly here (heads fuse head-major
        # into h, so the reshape keeps the 'mp' shards contiguous)
        x = cst(x + _wmm(attn.reshape(b, qn, h), blk, "out_w")
                + blk["out_b"])
        y = _ln(x.reshape(b * qn, h), blk["ln2_w"], blk["ln2_b"], eps)
        y = cst(jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                            approximate=True),
                None, "mp")
        # row-parallel fc2: second all-reduce of the block
        x = cst(x + (_wmm(y, blk, "fc2_w") + blk["fc2_b"]
                     ).reshape(b, qn, h))

    xf = _ln(x.reshape(b * qn, h), params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, xf).astype(jnp.float32)
    logits = logits.reshape(b, qn, -1)
    targets = [
        _guard_tokens(
            logits[:, i],
            sample_logits(logits[:, i], sampler=sampler,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, key=jax.random.fold_in(key, i)))
        for i in range(qn)
    ]
    return k_pages, v_pages, jnp.stack(targets, axis=1)


def _gpt_ragged_step_q(params, k_pages, v_pages, k_scales, v_scales,
                       block_tables, seq_lens, tokens, write_caps, key,
                       *, num_heads, head_dim, eps, sampler,
                       temperature, top_k, top_p, mesh=None):
    """Quantized-storage `_gpt_ragged_step` (FLAGS_kv_quant=int8):
    every contributed row quantizes into its slot's pages through
    `pa.paged_quant_write` (span-aware: capped rows never fold a
    scale), attention reads through the fused dequant.  Returns
    ``(k_pages, v_pages, k_scales, v_scales, out)`` with ``out``
    [B+1, Q_r] int32: rows 0..B-1 the per-position targets, row B the
    step's refold count packed in column 0 — the one blocking fetch
    the step already pays carries both."""
    b, qn = tokens.shape
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]
    cst = _mesh_constrain(mesh)

    pos = seq_lens[:, None] + jnp.arange(qn, dtype=jnp.int32)[None, :]
    wpe_max = params["wpe"].shape[0] - 1
    x = params["wte"][tokens] + params["wpe"][jnp.minimum(pos, wpe_max)]
    page_idx, slot = pa.paged_write_indices(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    flat_idx = page_idx.reshape(-1)
    flat_slot = slot.reshape(-1)
    spans = pa.paged_write_spans(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    lens_now = seq_lens + write_caps
    refolds = jnp.int32(0)

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x.reshape(b * qn, h), blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        # head axis sharded over 'mp' from here (see _gpt_ragged_step);
        # the per-head quant scales shard with their pages, so the
        # scale fold/refold reductions over head_dim stay chip-local
        qkv = cst(qkv.reshape(b, qn, 3, num_heads, head_dim),
                  None, None, None, "mp", None)
        q = qkv[:, :, 0]                                 # [B, Q, H, D]
        k_pages, k_scales, rk = pa.paged_quant_write(
            k_pages, k_scales, li,
            qkv[:, :, 1].reshape(b * qn, num_heads, head_dim),
            flat_idx, flat_slot, spans)
        k_pages = cst(k_pages, None, "mp", None, None, None)
        k_scales = cst(k_scales, None, "mp", None)
        v_pages, v_scales, rv = pa.paged_quant_write(
            v_pages, v_scales, li,
            qkv[:, :, 2].reshape(b * qn, num_heads, head_dim),
            flat_idx, flat_slot, spans)
        v_pages = cst(v_pages, None, "mp", None, None, None)
        v_scales = cst(v_scales, None, "mp", None)
        refolds = refolds + rk + rv
        attn = cst(pa.paged_attention(q, k_pages[li], v_pages[li],
                                      block_tables, lens_now,
                                      q_offsets=seq_lens,
                                      k_scales=k_scales[li],
                                      v_scales=v_scales[li]),
                   None, None, "mp", None)
        # row-parallel out proj / fc2: the block's two all-reduces
        x = cst(x + _wmm(attn.reshape(b, qn, h), blk, "out_w")
                + blk["out_b"])
        y = _ln(x.reshape(b * qn, h), blk["ln2_w"], blk["ln2_b"], eps)
        y = cst(jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                            approximate=True),
                None, "mp")
        x = cst(x + (_wmm(y, blk, "fc2_w") + blk["fc2_b"]
                     ).reshape(b, qn, h))

    xf = _ln(x.reshape(b * qn, h), params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, xf).astype(jnp.float32)
    logits = logits.reshape(b, qn, -1)
    targets = [
        _guard_tokens(
            logits[:, i],
            sample_logits(logits[:, i], sampler=sampler,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, key=jax.random.fold_in(key, i)))
        for i in range(qn)
    ]
    out = jnp.stack(targets, axis=1).astype(jnp.int32)
    pack = jnp.zeros((1, qn), jnp.int32).at[0, 0].set(refolds)
    return k_pages, v_pages, k_scales, v_scales, \
        jnp.concatenate([out, pack], axis=0)


def _reset_kv_scales(k_scales, v_scales, fresh_idx):
    """Zero the quant-scale entries of freshly (re)allocated pages —
    one small donated executable the engine runs between steps whenever
    the allocator handed out pages since the last device call, so a
    recycled page's stale scale can never leak into its new owner's
    quantization (the determinism contract `pa.paged_quant_write`
    documents).  ``fresh_idx`` is a fixed-size [num_pages] int32
    buffer padded with ``num_pages`` (out-of-bounds: dropped by the
    scatter)."""
    k_scales = k_scales.at[:, :, fresh_idx].set(0.0)
    v_scales = v_scales.at[:, :, fresh_idx].set(0.0)
    return k_scales, v_scales


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------
class DecodeEngine:
    """Continuous-batching decode over a paged KV cache.

    ``model`` is a `models.gpt.GPT` (dropout must be inactive — call
    ``model.eval()``).  Requests are admitted into ``max_batch_size``
    slots as they arrive and evicted the step they finish; the per-step
    decode is one donated jitted executable reused across the whole
    serve (signature-keyed: shapes never change, so it compiles once).
    """

    # itertools.count for the same reason as Request._next_id: ids
    # label per-engine gauges and trace lanes, and a concurrent
    # construction race would merge two engines onto one lane
    _next_engine_id = itertools.count()

    def __init__(self, model, max_batch_size=4, max_seq_len=None,
                 page_size=None, num_pages=None, sampler="greedy",
                 temperature=1.0, top_k=0, top_p=1.0, seed=0,
                 eos_token_id=None, dtype=None, spec_decode_k=None,
                 drafter=None, chunked_prefill=None,
                 prefill_chunk_tokens=None, prefill_q_max=None,
                 prefix_cache=None, scheduler=None, fault_plan=None,
                 journal_dir=None, step_timeout_ms=None,
                 flight_window=None, flight_dir=None, kv_quant=None,
                 cost_model=None, cost_calibration=None, alerts=None,
                 profile=None, profile_sample_steps=None,
                 ragged_step=None, spec_adaptive_k=None,
                 serve_mesh=None, cache_generated_pages=None,
                 serve_weights=None):
        cfg = model.cfg
        if getattr(cfg, "dropout", 0.0) and model.training:
            # don't silently flip the caller's train/eval mode — dropout
            # is simply not part of the decode step functions
            raise ValueError(
                "DecodeEngine serves inference only: call model.eval() "
                "first (cfg.dropout > 0 and the model is in train mode)")
        self._params = _extract_gpt_params(model)
        self._num_heads = cfg.num_heads
        self._head_dim = cfg.hidden_size // cfg.num_heads
        self._eps = float(getattr(model.ln_f, "_epsilon", 1e-5))
        self._num_layers = cfg.num_layers
        self._slots = int(max_batch_size)
        self._max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self._max_seq_len > cfg.max_seq_len:
            # positions past the wpe table would silently CLAMP in the
            # embedding gather (wrong logits, no error) — refuse instead
            raise ValueError(
                f"max_seq_len {self._max_seq_len} exceeds the model's "
                f"position table ({cfg.max_seq_len})")
        kv_dtype = jnp.dtype(dtype) if dtype is not None else \
            self._params["wte"].dtype
        # quantized KV pages (explicit arg wins, else FLAGS_kv_quant):
        # "int8" stores pages as int8 with per-page, per-head symmetric
        # scales in parallel donated arrays; "off" (default) is the
        # bit-exact full-precision path — it constructs the exact same
        # executables as before the feature existed.
        from ..core import flags as _early_flags

        if kv_quant is None:
            kv_quant = str(_early_flags.flag("kv_quant"))
        kv_quant = str(kv_quant)
        if kv_quant not in ("off", "int8"):
            raise ValueError(
                f"kv_quant must be 'off' or 'int8', got {kv_quant!r}")
        self._kv_quant = kv_quant == "int8"
        self._kv_quant_mode = kv_quant
        # quantized weight storage (explicit arg wins, else
        # FLAGS_serve_weights): "int8" folds every matmul weight of the
        # step executables to per-out-channel symmetric int8 + f32
        # scales (`_quantize_gpt_params`) so weights stream from HBM at
        # a quarter the bytes; "off" (default) keeps the f32 leaves and
        # the step functions trace the exact same ops as before the
        # feature existed — zero new executables, bit-exact tokens.
        if serve_weights is None:
            serve_weights = str(_early_flags.flag("serve_weights"))
        serve_weights = str(serve_weights)
        if serve_weights not in ("off", "int8"):
            raise ValueError(
                f"serve_weights must be 'off' or 'int8', got "
                f"{serve_weights!r}")
        self._weight_quant = serve_weights == "int8"
        self._serve_weights_mode = serve_weights
        # fingerprint sample rows, captured from the F32 tree before
        # any weight fold: `_model_fingerprint`/`config_fingerprint`
        # hash one qkv row per block, and quantization RENAMES that
        # leaf — sampling here keeps both fingerprints a pure function
        # of the model's weights, identical across serve_weights modes
        # (the mode itself folds into config_fingerprint separately)
        self._fp_wrows = [
            np.asarray(jax.device_get(blk["qkv_w"][0]),
                       np.float32).tobytes()
            for blk in self._params["blocks"]]
        # the page-size autotune cache keys on the STORAGE dtype of the
        # pages — an int8 pool must never reuse an fp32-picked page
        # size (a quarter the bytes per page changes the VMEM-fit
        # winner), so the quantized storage dtype drives the pick
        storage_dtype = jnp.dtype(jnp.int8) if self._kv_quant else kv_dtype
        self._page = int(page_size or pa.default_page_size(
            self._max_seq_len, self._head_dim, storage_dtype))
        # block tables round UP: a horizon that doesn't tile just leaves
        # the last page partially used (ragged lengths mask the rest)
        self._pages_per_seq = -(-self._max_seq_len // self._page)
        n_pages = int(num_pages or self._slots * self._pages_per_seq)
        self.pool = KVBlockPool(n_pages)
        shape = (self._num_layers, self._num_heads, n_pages, self._page,
                 self._head_dim)
        self._k_pages = jnp.zeros(shape, storage_dtype)
        self._v_pages = jnp.zeros(shape, storage_dtype)
        # per-page, per-head dequant scales (quantized mode only):
        # donated pool state threaded through every step executable
        # beside the pages — tracecheck's donation pass counts
        # ``*_scales`` params as pool state
        self._k_scales = self._v_scales = None
        self._scale_reset_fn = None
        # pages the allocator handed out since the last scale reset —
        # their (possibly stale) scale entries zero on the next
        # between-steps flush, BEFORE any quantized write sees them
        self._fresh_pages: List[int] = []
        if self._kv_quant:
            sshape = (self._num_layers, self._num_heads, n_pages)
            self._k_scales = jnp.zeros(sshape, jnp.float32)
            self._v_scales = jnp.zeros(sshape, jnp.float32)

        self._bt = np.zeros((self._slots, self._pages_per_seq), np.int32)
        self._lens = np.zeros(self._slots, np.int32)
        self._active = np.zeros(self._slots, bool)
        self._last = np.zeros(self._slots, np.int32)
        self._by_slot: List[Optional[Request]] = [None] * self._slots
        # prompt tokens already consumed per slot (chunked prefill
        # cursor); a slot is mid-prefill while the cursor trails its
        # request's prompt length
        self._prefill_pos = np.zeros(self._slots, np.int32)
        # min-heap of free slot indices: admission pops the lowest slot,
        # _finish pushes it back — O(log slots) per event instead of the
        # old scan over every slot per admitted request
        self._free_slots = list(range(self._slots))
        heapq.heapify(self._free_slots)

        self._sampling = dict(sampler=sampler,
                              temperature=float(temperature),
                              top_k=int(top_k), top_p=float(top_p))
        self._eos = eos_token_id
        self._key = jax.random.PRNGKey(seed)
        self._step_no = 0
        self._prefill_no = 0
        self._queue: "deque[Request]" = deque()
        self._decode_fn = None  # shapes are fixed: ONE jitted step
        self._mixed_fn = None   # ONE mixed prefill+decode executable
        self._prefill_fns = {}
        # engine id = the chrome-trace tid of this engine's step spans
        # (several engines in one process stay on separate lanes)
        self._engine_id = next(DecodeEngine._next_engine_id)
        # FLAGS_metrics_report_interval_s > 0 -> periodic snapshot
        # reporter, started once per process
        _obs.maybe_start_reporter()
        # fold the weights to int8 storage now, before anything
        # downstream consumes the tree: the drafter quantizes against
        # `engine._weight_quant` at bind, and the mesh block shards
        # whatever leaves exist (`gpt_serving_rules` carries the
        # `*_q`/`*_s` pairs on the same axes as their f32 originals)
        if self._weight_quant:
            self._fold_weight_quant()

        from ..core import flags as _flags

        # chunked prefill (explicit args win, else the flags): prompt
        # ingestion rides the decode step as fixed-shape [slots, Q_max]
        # mixed batches instead of one-shot bucket-padded prefills, so
        # an admission never stalls running decodes.  The legacy path
        # (chunked_prefill=0) stays as the greedy-parity oracle.
        if chunked_prefill is None:
            chunked_prefill = bool(_flags.flag("chunked_prefill"))
        self._chunked = bool(chunked_prefill)
        if prefill_chunk_tokens is None:
            prefill_chunk_tokens = int(_flags.flag("prefill_chunk_tokens"))
        if prefill_chunk_tokens < 1:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 1, got "
                f"{prefill_chunk_tokens}")
        # per-step prompt-token budget (never wider than the horizon: a
        # chunk cannot outsize a prompt)
        self._chunk_budget = min(int(prefill_chunk_tokens),
                                 self._max_seq_len)
        # Q_max: the mixed executable's per-slot row width.  Defaults to
        # the budget; setting it SMALLER caps the step's compute (the
        # executable always pays slots x Q_max rows) while the budget
        # still spreads across several prefilling slots per step —
        # decoupling per-step latency from aggregate prefill throughput
        q_max_explicit = prefill_q_max is not None
        if prefill_q_max is None:
            prefill_q_max = self._chunk_budget
        if prefill_q_max < 1:
            raise ValueError(
                f"prefill_q_max must be >= 1, got {prefill_q_max}")
        self._q_max = min(int(prefill_q_max), self._chunk_budget)

        # prefix caching (explicit arg wins, else FLAGS_prefix_cache):
        # full prompt KV pages are content-addressed by a chain hash and
        # shared across requests at refcount+1; admission maps the
        # longest page-aligned cached prefix and chunked prefill starts
        # at the first novel token.  Requires chunked prefill — the
        # legacy one-shot executable cannot start at a nonzero offset
        # (it is the prefix_cache=0 parity oracle's other half).
        if prefix_cache is None:
            prefix_cache = bool(_flags.flag("prefix_cache")) and \
                self._chunked
        elif prefix_cache and not self._chunked:
            raise ValueError(
                "prefix_cache needs chunked prefill: the legacy one-"
                "shot prefill executable cannot start mid-prompt (set "
                "chunked_prefill=1, or drop prefix_cache)")
        self._prefix_cache = bool(prefix_cache)
        self._model_salt = self._model_fingerprint() \
            if self._prefix_cache else b""
        # generated-page registration (explicit arg wins, else
        # FLAGS_cache_generated_pages): extend the prompt's chain hash
        # over the DECODE stream and content-address each generated
        # page the moment it fills, so fanout sharing a decode prefix
        # (and the fleet router's affinity key) prefix-hits it.  Off
        # (default) keeps pool occupancy bit-exact with the
        # prompt-pages-only engine; meaningless without the prefix
        # cache, so it resolves False there rather than refusing (the
        # flag must not break prefix_cache=0 engines).
        if cache_generated_pages is None:
            cache_generated_pages = bool(
                _flags.flag("cache_generated_pages"))
        self._cache_generated = bool(cache_generated_pages) and \
            self._prefix_cache
        self._evictions_seen = 0
        # FLAGS_kv_pool_debug: audit the pool partition + refcounts at
        # every step boundary (engine idle point — host-only cost)
        self._pool_debug = bool(_flags.flag("kv_pool_debug"))

        # speculative decoding (propose K / verify in one multi-query
        # pass): explicit arg wins, else FLAGS_spec_decode_k.  The
        # subsystem lives in inference.speculative; constructed lazily
        # so non-speculative engines never import it.
        if spec_decode_k is None:
            spec_decode_k = int(_flags.flag("spec_decode_k"))
        self._spec = None
        if drafter is not None and not spec_decode_k:
            # a drafter with K == 0 would be silently ignored and the
            # engine would serve classic one-token steps — refuse loudly
            raise ValueError(
                "drafter passed but speculative decoding is off: set "
                "spec_decode_k >= 1 (or FLAGS_spec_decode_k)")
        # adaptive per-slot speculation depth (FLAGS_spec_adaptive_k):
        # an explicit True without speculation is refused like a
        # drafter without K; the flag-resolved value is simply ignored
        # on non-speculative engines (it modifies speculation, it does
        # not imply it)
        if spec_adaptive_k and not spec_decode_k:
            raise ValueError(
                "spec_adaptive_k passed but speculative decoding is "
                "off: set spec_decode_k >= 1 (or FLAGS_spec_decode_k)")
        if spec_adaptive_k is None:
            spec_adaptive_k = bool(_flags.flag("spec_adaptive_k"))
        if spec_decode_k:
            from .speculative import SpeculativeDecoder

            self._spec = SpeculativeDecoder(self, k=int(spec_decode_k),
                                            drafter=drafter,
                                            adaptive=bool(spec_adaptive_k))

        # unified ragged step (explicit arg wins, else
        # FLAGS_ragged_step): decode, mixed prefill+decode, and
        # speculative-verify traffic all dispatch the ONE
        # `_gpt_ragged_step[_q]` executable, each row carrying its own
        # query span.  Off (the default) keeps the split executables
        # byte-identical — the greedy-parity oracle.
        ragged_explicit = ragged_step is not None
        if ragged_step is None:
            ragged_step = bool(_flags.flag("ragged_step"))
        self._ragged = bool(ragged_step)
        self._ragged_fn = None

        # tensor-parallel serving mesh (explicit arg wins, else
        # FLAGS_serve_mesh): 'mp=N' builds a Mesh over N devices,
        # shards the params by the shared regex partition rules
        # (parallel.partition.gpt_serving_rules: column-split qkv/fc1,
        # row-split out/fc2, replicated norms/embeddings/head) and the
        # KV page pool on the HEAD axis — each chip holds its
        # head-slice of every page, so page ids stay logical and the
        # allocator / block tables stay host-global, untouched.  The
        # mesh implies the unified ragged step: it shards the ONE step
        # executable per KV mode rather than three.  '' (default) is
        # the single-chip path: no mesh, no shardings, bit-exact.
        if serve_mesh is None:
            serve_mesh = str(_flags.flag("serve_mesh"))
        serve_mesh = str(serve_mesh or "").strip()
        self._serve_mesh = serve_mesh
        self._mesh = None
        self._mesh_mp = 1
        self._repl_sharding = None
        self._page_sharding = None
        self._scale_sharding = None
        if serve_mesh:
            from ..parallel.partition import (build_mesh,
                                              gpt_serving_rules,
                                              kv_pages_spec,
                                              kv_scales_spec,
                                              match_partition_rules,
                                              parse_mesh_spec)

            axes = parse_mesh_spec(serve_mesh)
            if [a for a, _ in axes] != ["mp"]:
                raise ValueError(
                    f"serve_mesh supports a single tensor-parallel "
                    f"axis 'mp=N', got {serve_mesh!r}")
            mp = axes[0][1]
            if len(jax.devices()) < mp:
                raise ValueError(
                    f"serve_mesh {serve_mesh!r} needs {mp} devices, "
                    f"have {len(jax.devices())}")
            if self._num_heads % mp:
                raise ValueError(
                    f"serve_mesh {serve_mesh!r}: num_heads "
                    f"{self._num_heads} not divisible by mp={mp}")
            if ragged_explicit and not self._ragged:
                raise ValueError(
                    "serve_mesh requires the unified ragged step (the "
                    "mesh shards the ONE step executable per KV "
                    "mode): drop ragged_step=0, or the mesh")
            self._ragged = True
            self._mesh = build_mesh(serve_mesh)
            self._mesh_mp = mp
            self._repl_sharding = NamedSharding(self._mesh,
                                                PartitionSpec())
            self._page_sharding = NamedSharding(self._mesh,
                                                kv_pages_spec())
            self._scale_sharding = NamedSharding(self._mesh,
                                                 kv_scales_spec())
            specs = match_partition_rules(gpt_serving_rules(),
                                          self._params)
            self._params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self._mesh, s)),
                self._params, specs)
            self._k_pages = jax.device_put(self._k_pages,
                                           self._page_sharding)
            self._v_pages = jax.device_put(self._v_pages,
                                           self._page_sharding)
            if self._kv_quant:
                self._k_scales = jax.device_put(self._k_scales,
                                                self._scale_sharding)
                self._v_scales = jax.device_put(self._v_scales,
                                                self._scale_sharding)
        # the unified executable's per-slot row width: wide enough for
        # the widest span any phase contributes — a decode row (1), a
        # prompt chunk (Q_max), a verify window (K+1).  Rows past a
        # slot's span cost dense FLOPs but no extra KV traffic — but
        # EVERY round pays the full grid, so a wide chunk width taxes
        # the steady state (all-decode / all-verify rounds, which
        # dominate any long serve) to speed the transient prefill
        # phase.  When the caller did not pin prefill_q_max, a ragged
        # engine therefore chunks prompts at one KV page of query span
        # per slot (never narrower than the verify window): chunks stay
        # page-aligned for the prefix cache and the steady-state
        # padding is bounded.  An explicit prefill_q_max always wins —
        # it sizes the grid verbatim.
        if self._ragged and self._chunked and not q_max_explicit:
            self._q_max = min(self._q_max, max(
                self._page,
                (self._spec.k + 1) if self._spec is not None else 1))
        self._q_ragged = max(1,
                             self._q_max if self._chunked else 1,
                             (self._spec.k + 1) if self._spec is not None
                             else 1)

        # admission scheduler (explicit arg wins, else FLAGS_sched_policy):
        # owns the between-steps admission ORDER and the preemption /
        # deadline-expiry decisions.  "fifo" reproduces the historical
        # strict-arrival-order admission bit for bit; "slo" adds priority
        # + earliest-deadline-first + preempt/resume (inference.frontend).
        from .frontend import make_scheduler

        if scheduler is None:
            scheduler = str(_flags.flag("sched_policy"))
        self._scheduler = make_scheduler(scheduler)
        self._scheduler.bind(self)

        # fault injection + containment (inference.resilience):
        # explicit arg wins (a FaultPlan or a spec string), else
        # FLAGS_fault_inject.  The manager owns the containment ladder
        # `step()` runs under (retry -> degrade -> bisect-quarantine)
        # and the degraded-mode state; with no plan armed every hook is
        # a single `is None` check.
        from .resilience import FaultPlan, ResilienceManager

        if fault_plan is None:
            fault_plan = FaultPlan.parse(str(_flags.flag("fault_inject")))
        elif isinstance(fault_plan, str):
            fault_plan = FaultPlan.parse(fault_plan)
        self._fault = fault_plan
        self._resilience = ResilienceManager(self)
        # construction-time config the degradation ladder may flip at
        # runtime (legacy fallback) and the re-enable probe restores
        self._chunked_cfg = self._chunked
        self._prefix_cache_cfg = self._prefix_cache

        # durable serving + hung-step watchdog (inference.durability):
        # explicit args win, else the flags.  Disarmed, both are None
        # and every hook on the serve path is a single `is None` check.
        if journal_dir is None:
            journal_dir = str(_flags.flag("journal_dir")) or None
        if step_timeout_ms is None:
            step_timeout_ms = float(_flags.flag("step_timeout_ms"))
        self._journal_dir = journal_dir
        self._step_timeout_ms = float(step_timeout_ms)
        # set True by the watchdog's abandon path: a step still blocked
        # in a worker thread must mutate nothing when it returns
        self._abandoned = False
        self._config_fp: Optional[bytes] = None
        self._durability = None
        self._watchdog = None
        compile_cache = str(_flags.flag("compile_cache_dir"))
        if compile_cache:
            from .durability import enable_compile_cache

            enable_compile_cache(compile_cache)

        # everything `resilience.recover` needs to rebuild THIS engine
        # after a fatal fault: the resolved construction config (flag
        # lookups already applied, so a flag flip mid-serve cannot
        # change the rebuilt engine).  Scheduler/drafter instances are
        # reused — recover() unbinds them first and retires the old
        # engine; the fault plan keeps its occurrence counters so an
        # injected schedule never re-fires after the rebuild.
        self._ctor = dict(
            model=model, max_batch_size=self._slots,
            max_seq_len=self._max_seq_len, page_size=self._page,
            num_pages=self.pool.num_pages,
            sampler=self._sampling["sampler"],
            temperature=self._sampling["temperature"],
            top_k=self._sampling["top_k"],
            top_p=self._sampling["top_p"],
            seed=seed, eos_token_id=self._eos, dtype=kv_dtype,
            spec_decode_k=(self._spec.k if self._spec else 0),
            drafter=(self._spec.drafter if self._spec else None),
            chunked_prefill=self._chunked,
            prefill_chunk_tokens=self._chunk_budget,
            prefill_q_max=self._q_max,
            prefix_cache=self._prefix_cache,
            cache_generated_pages=self._cache_generated,
            scheduler=self._scheduler, fault_plan=self._fault,
            journal_dir=self._journal_dir,
            step_timeout_ms=self._step_timeout_ms,
            kv_quant=self._kv_quant_mode,
            serve_weights=self._serve_weights_mode,
            ragged_step=self._ragged,
            spec_adaptive_k=(self._spec.adaptive
                             if self._spec is not None else False),
            serve_mesh=self._serve_mesh)

        # flight recorder (observability.flight): always-cheap bounded
        # ring of per-step records — batch composition, phase
        # breakdown, ladder events, SLO burn.  flight_window=0 turns
        # it off entirely (the parity/overhead oracle); the dump
        # directory defaults beside the journal.
        if flight_window is None:
            flight_window = int(_flags.flag("flight_window"))
        if flight_dir is None:
            flight_dir = str(_flags.flag("flight_dir")) or None
        self._flight = None
        if int(flight_window) > 0:
            from ..observability.flight import FlightRecorder

            fdir = flight_dir or (
                os.path.join(self._journal_dir, "flight")
                if self._journal_dir else None)
            self._flight = FlightRecorder(self, window=int(flight_window),
                                          flight_dir=fdir)
        self._ctor["flight_window"] = int(flight_window)
        self._ctor["flight_dir"] = flight_dir

        # cost observatory (observability.costmodel): static profiles
        # + calibrated step-cost prediction + HBM ledger + roofline.
        # Explicit arg wins, else FLAGS_cost_model; disarmed = one
        # `is None` check per step and bit-exact serving.
        # ``cost_calibration`` seeds the per-executable calibration
        # from a prior life (recover / restore_from_dir), so a rebuilt
        # engine predicts accurately from its first step.
        if cost_model is not None and bool(cost_model) and \
                not bool(_flags.flag("cost_model")):
            # explicit opt-in AGAINST a disabled flag: arm profile
            # extraction too (the process-global table serves this
            # engine's predictor).  Not latched when the flag is on —
            # recover()/restore pass the resolved cost_model=True of a
            # flag-defaulted engine explicitly, and that must not pin
            # extraction past a later FLAGS_cost_model=0
            _costmodel._force_enable()
        if cost_model is None:
            cost_model = bool(_flags.flag("cost_model"))
        self._cost = None
        if bool(cost_model):
            self._cost = _costmodel.CostModel(
                self, calibration=cost_calibration)
        # cost-gated admission (FLAGS_sched_cost_admission): resolved
        # at construction like every other serving flag — default off
        # keeps _admit_one's decision sequence bit-exact
        self._cost_admission = self._cost is not None and \
            bool(_flags.flag("sched_cost_admission"))
        self._ctor["cost_model"] = bool(cost_model)
        self._ctor["cost_calibration"] = None

        # profiling plane (observability.profiling): sampled device-
        # sync probes + hot-op tables + bounded capture sessions.
        # Explicit arg wins, else FLAGS_profile; disarmed = one
        # `is None` check per serve-loop hook, zero probes, bit-exact.
        from ..observability import profiling as _profiling_mod

        if profile is not None and bool(profile) and \
                not bool(_flags.flag("profile")):
            # explicit opt-in AGAINST a disabled flag: arm hot-op
            # extraction at the costmodel chokepoint too (the
            # costmodel._force_enable pattern — not latched when the
            # flag is on, so recover()/restore re-passing a resolved
            # profile=True cannot pin extraction past a later
            # FLAGS_profile=0)
            _profiling_mod._force_enable()
        if profile is None:
            profile = bool(_flags.flag("profile"))
        self._profiling = None
        if bool(profile):
            self._profiling = _profiling_mod.Profiler(
                self, sample_steps=profile_sample_steps)
        self._ctor["profile"] = bool(profile)
        # the RESOLVED cadence rides wire_config so recover/restore
        # rebuild an armed engine probing at the same rate
        self._ctor["profile_sample_steps"] = (
            self._profiling.sample_steps
            if self._profiling is not None
            else profile_sample_steps)

        # ops plane (observability.opsserver + observability.alerts):
        # the engine always registers with the process-global ops
        # registry (one locked dict insert; retirement deregisters),
        # while the HTTP listener and the between-steps alert engine
        # arm only when FLAGS_ops_port is set — or when ``alerts=``
        # opts in explicitly (True = the shipped default catalog, a
        # rule sequence = a custom table).  Disarmed, the serve loop
        # pays one `is None` check per step and zero alert counters.
        # Resolved BEFORE the durability manager below: the journal's
        # cfg record snapshots wire_config at construction, and a
        # restored engine must rebuild with the same alert table.
        from ..observability import alerts as _alerts_mod
        from ..observability import opsserver as _opsserver

        if alerts is None:
            alerts = int(_flags.flag("ops_port")) != 0
        self._alerts = None
        if alerts is not False and alerts != 0:
            rules = None if alerts is True else alerts
            self._alerts = _alerts_mod.AlertEngine(self, rules=rules)
            self._ctor["alerts"] = tuple(self._alerts.rules)
        else:
            self._ctor["alerts"] = False

        if self._journal_dir:
            from .durability import DurabilityManager

            self._durability = DurabilityManager(self, self._journal_dir)
        if self._step_timeout_ms > 0:
            from .durability import StepWatchdog

            self._watchdog = StepWatchdog(self, self._step_timeout_ms)
        from .durability import set_health

        set_health(self._engine_id, "live", span=False)
        _opsserver.register_engine(self)
        _opsserver.maybe_start_ops_server()

    def _phase(self, name: str):
        """Context manager timing a LEAF flight-recorder phase (device
        dispatch, fetch, cache ops) — a reusable no-op when the
        recorder is off, so call sites read `with self._phase("x"):`
        without repeating the None check."""
        fr = self._flight
        return fr.phase(name) if fr is not None else _NULL_CTX

    def _excl_phase(self, name: str):
        """Like `_phase` for COMPOSITE host phases (admit/draft/emit):
        recorded exclusive of the leaf phases nested inside them."""
        fr = self._flight
        return fr.exclusive_phase(name) if fr is not None else _NULL_CTX

    def _fold_weight_quant(self) -> None:
        """Fold this engine's matmul weights to int8 storage
        (serve_weights=int8): every f32 ``*_w`` matmul leaf of
        ``self._params`` is replaced by the ``*_q``/``*_s`` pair the
        `_wmm` use sites dequantize fused at the dot — the sanctioned
        construction-time param-tree mutation `analysis`'s
        engine-mutation pass names.  Runs ONCE, before any executable
        traces (and before the mesh shards the tree); the counters it
        bumps are how the off mode's zero stays provable."""
        self._params, mats, saved = _quantize_gpt_params(self._params)
        _stats_add(weight_quant_mats=mats,
                   weight_quant_bytes_saved=saved)
        _obs.WEIGHT_QUANT_SAVED_BYTES.set(saved,
                                          engine=self._engine_id)

    def _model_fingerprint(self) -> bytes:
        """Sampling-invariant model identity — the chain-hash root.
        Cached KV is a function of the weights and the token prefix
        ONLY, so sampler/temperature/top-k/top-p are deliberately NOT
        keyed: engines serving different sampling configs over the same
        weights would share prefixes soundly (the pool is per-engine
        today; the key keeps the scheme honest if pools are ever
        shared).  Weight content is represented by the embedding
        table's first row plus one row of EVERY block's qkv projection
        and the architecture dims — a few small host transfers at
        construction.  Two fine-tunes sharing frozen embeddings still
        key differently (their attention weights diverge); this is a
        fingerprint, not a proof — a full-weights digest belongs in
        any future cross-process cache tier."""
        h = hashlib.blake2b(digest_size=16)
        p = self._params
        h.update(np.asarray(jax.device_get(p["wte"][0]),
                            np.float32).tobytes())
        for row in self._fp_wrows:
            # f32 qkv rows sampled at construction, BEFORE any
            # serve_weights fold renamed the leaf — the fingerprint is
            # a function of the model, not of the storage dtype
            h.update(row)
        h.update(str((tuple(p["wte"].shape), len(p["blocks"]),
                      self._num_heads, self._head_dim,
                      self._page)).encode())
        return h.digest()

    def config_fingerprint(self) -> bytes:
        """Digest of everything that determines this engine's
        executable SIGNATURES and numerics: a weight-content sample
        (the `_model_fingerprint` scheme — wte row 0 + one qkv row per
        block), the architecture dims, every shape-determining
        constructor knob, and the sampling config.  Two engines with
        equal fingerprints compile byte-identical step programs, which
        is the gate for `adopt_executables` handoff and for
        `durability.restore_from_dir` validating a rebuilt engine
        against its journal.  Memoized (a few small host transfers on
        first call)."""
        if self._config_fp is None:
            h = hashlib.blake2b(digest_size=16)
            p = self._params
            h.update(np.asarray(jax.device_get(p["wte"][0]),
                                np.float32).tobytes())
            for row in self._fp_wrows:
                # construction-time f32 samples (see _model_fingerprint)
                h.update(row)
            h.update(str((
                tuple(p["wte"].shape), len(p["blocks"]),
                self._num_heads, self._head_dim, self._eps,
                self._slots, self._max_seq_len, self._page,
                self.pool.num_pages, self._q_max,
                int(self._ctor["prefill_chunk_tokens"]),
                # the page STORAGE dtype already separates quantized
                # from full-precision engines (int8 <-> kv_quant is
                # one-to-one); adding the mode string would break
                # fingerprint compatibility with pre-quant journals
                # for off-mode engines whose executables ARE identical
                str(self._k_pages.dtype),
                tuple(sorted(self._sampling.items())),
                self._spec.k if self._spec else 0,
                self._chunked_cfg)).encode())
            if self._ragged:
                # folded CONDITIONALLY so off-path fingerprints stay
                # byte-identical with pre-ragged journals/donors (their
                # executables ARE identical); a ragged engine can never
                # adopt a split-path engine's executables or vice versa
                h.update(str(("ragged", self._q_ragged)).encode())
            if self._mesh is not None:
                # same conditional-fold reason: single-chip
                # fingerprints stay byte-identical with pre-mesh
                # journals/donors, and a sharded engine (whose
                # executables carry mesh shardings) can never adopt a
                # single-chip engine's executables or vice versa
                h.update(str(("mesh", self._serve_mesh)).encode())
            if self._weight_quant:
                # same conditional-fold reason again: off-mode
                # fingerprints stay byte-identical with pre-feature
                # journals/donors (their executables ARE identical),
                # while an int8-weight engine (whose dots read s8
                # operands) can never adopt an f32 engine's
                # executables or vice versa
                h.update(str(("serve_weights",
                              self._serve_weights_mode)).encode())
            self._config_fp = h.digest()
        return self._config_fp

    def wire_config(self) -> dict:
        """The serializable subset of the resolved constructor config —
        what the journal's config record carries so
        `durability.restore_from_dir` can rebuild this engine in a
        fresh process (the caller supplies the model; scheduler /
        drafter / fault-plan objects are process-local and excluded)."""
        kw = {k: v for k, v in self._ctor.items()
              if k not in ("model", "scheduler", "drafter",
                           "fault_plan", "journal_dir")}
        if kw.get("dtype") is not None:
            kw["dtype"] = str(jnp.dtype(kw["dtype"]))
        if kw.get("eos_token_id") is not None:
            kw["eos_token_id"] = int(kw["eos_token_id"])
        if kw.get("alerts"):
            # AlertRule dataclasses -> wire dicts (the ctor accepts
            # either form back); False stays False — a restored engine
            # keeps the resolved arming decision, not the flag's
            kw["alerts"] = [r.to_wire() for r in kw["alerts"]]
        if self._cost is not None:
            # LIVE calibration state, not the construction-time seed:
            # recover() and the durability snapshot carry the learned
            # factors across rebuilds so the successor predicts warm
            kw["cost_calibration"] = self._cost.calibration_wire()
        return kw

    def _trackers(self) -> List[_JitTracker]:
        """Every live `_JitTracker` this engine (and its speculative
        subsystem) currently holds — the watchdog's compile detector
        and the handoff's donor surface."""
        ts = [self._decode_fn, self._mixed_fn, self._ragged_fn,
              self._scale_reset_fn, *self._prefill_fns.values()]
        if self._spec is not None:
            ts.append(self._spec._verify_fn)
            d = self._spec.drafter
            for name in ("_catch_fn", "_step_fn", "_chunk_fn",
                         "_scale_reset_fn"):
                ts.append(getattr(d, name, None))
            ts.extend(getattr(d, "_prefill_fns", {}).values())
        return [t for t in ts if t is not None]

    def adopt_executables(self, donor) -> int:
        """Executable handoff: take a retired engine's live compiled
        step executables instead of recompiling them.  Safe ONLY when
        the config fingerprints match — identical fingerprints mean
        identical executable signatures, so the donor's warm jit
        caches serve this engine's shapes without a retrace; on any
        mismatch nothing is adopted and the executables compile lazily
        as usual (the cold fallback).  Returns the number adopted.
        The drafter instance is REUSED across a recovery (not
        reconstructed), so its executables carry over without passing
        through here."""
        if donor is self or \
                donor.config_fingerprint() != self.config_fingerprint():
            return 0
        n = 0
        if self._decode_fn is None and donor._decode_fn is not None:
            self._decode_fn = donor._decode_fn
            n += 1
        if self._mixed_fn is None and donor._mixed_fn is not None:
            self._mixed_fn = donor._mixed_fn
            n += 1
        if self._ragged_fn is None and \
                getattr(donor, "_ragged_fn", None) is not None:
            self._ragged_fn = donor._ragged_fn
            n += 1
        if self._scale_reset_fn is None and \
                donor._scale_reset_fn is not None:
            self._scale_reset_fn = donor._scale_reset_fn
            n += 1
        for bucket, fn in donor._prefill_fns.items():
            if bucket not in self._prefill_fns:
                self._prefill_fns[bucket] = fn
                n += 1
        if self._spec is not None and donor._spec is not None and \
                self._spec._verify_fn is None and \
                donor._spec._verify_fn is not None:
            self._spec._verify_fn = donor._spec._verify_fn
            n += 1
        if n:
            _stats_add(exec_handoffs=n)
        return n

    def _abandon_inflight(self):
        """Watchdog abandonment: neutralize this engine so a step
        still blocked in a worker thread mutates nothing visible when
        it finally returns — its requests now belong to the rebuilt
        engine.  The host loop after a late-returning executable sees
        no active slot and emits nothing; the slow_step fault site
        (and the containment ladder) re-raise instead of containing.
        Device buffers and pool state are garbage from here on.

        The durability manager detaches FIRST: the successor engine
        owns the journal directory from here, and a late-returning
        step on this engine must neither flush stale records nor
        overwrite the successor's snapshot with this engine's (now
        empty) state."""
        self._abandoned = True
        dur, self._durability = self._durability, None
        if dur is not None:
            try:
                dur.close()
            except Exception:
                pass  # best effort: the hung worker may hold the handle
        self._watchdog = None
        # black box first: the hung worker may never return, so this is
        # the last consistent look at what the engine was doing.  Best
        # effort on BOTH sides: a full disk must not block recovery,
        # and a merely-SLOW (not dead) worker still holding a
        # reference to the open record can mutate it lock-free while
        # the dump serializes — a torn dump is acceptable, a dead
        # driver is not
        if self._alerts is not None:
            # last alert evaluation before the black box dumps: the
            # overload/pressure that preceded the hang should read as
            # FIRING rules in the post-mortem, not raw gauges the
            # reader must re-derive.  (The engine is already marked
            # abandoned, so transitions update the /alertz rule states
            # the dump snapshots but repopulate no retired gauges.)
            try:
                self._alerts.evaluate()
            except Exception:
                pass
        fl = self._flight
        if fl is not None:
            fl.event("abandon", step=int(self._step_no))
            fl.end_step()
            try:
                fl.dump("abandoned")
            except Exception:
                pass
        # close the dead lane: a terminal marker span on this engine's
        # trace track, then retire EVERY engine-labeled series from the
        # scrape surface (the whole-catalog mirror of PR 10's
        # clear_health fix — a dead engine's gauges otherwise read
        # stale levels forever).  The frontend re-flips health to
        # "hung" right after, so an unrecovered abandonment still
        # alerts; a successful recovery retires that too.
        _obs.record_span("engine", "abandoned", _obs.now_ns(), 0,
                         tid=self._engine_id,
                         args={"step": int(self._step_no)})
        from .durability import retire_engine_series

        retire_engine_series(self._engine_id)
        self._by_slot = [None] * self._slots
        self._active = np.zeros(self._slots, bool)
        self._queue.clear()
        self._free_slots = list(range(self._slots))
        heapq.heapify(self._free_slots)

    # -- request lifecycle ---------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=32,
                    eos_token_id=..., priority=None, deadline_ms=None,
                    slo_ttft_ms=None, slo_tpot_ms=None,
                    on_token=None, trace_id=None) -> Request:
        # sentinel default: eos_token_id=None is a real per-request
        # opt-out of the engine-level eos, not "use the default"
        req = Request(prompt_ids, max_new_tokens,
                      self._eos if eos_token_id is ... else eos_token_id,
                      priority=priority, deadline_ms=deadline_ms,
                      slo_ttft_ms=slo_ttft_ms, slo_tpot_ms=slo_tpot_ms,
                      on_token=on_token)
        if trace_id is not None:
            req.trace_id = str(trace_id)
        if not req.prompt_ids:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(req.prompt_ids) + req.max_new_tokens > self._max_seq_len:
            raise ValueError(
                f"prompt ({len(req.prompt_ids)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_seq_len "
                f"{self._max_seq_len}")
        if self._pages_for(req.total_kv_tokens()) > self.pool.num_pages:
            raise ValueError(
                "request needs more KV pages than the pool holds")
        req._engine = self
        req.t_enqueue_ns = _obs.now_ns()
        if req.deadline_ms is not None:
            req._deadline_ns = req.t_enqueue_ns + \
                int(req.deadline_ms * 1e6)
        _obs.REQUESTS_ENQUEUED.inc()
        self._queue.append(req)
        if self._durability is not None:
            self._durability.on_admit(req)
        return req

    def admit_restored(self, req: Request, on_token=None) -> Request:
        """Admit a request another engine's journal materialized
        (`durability.adopt_from_dir` — fleet failover into a LIVE
        survivor).  Unlike the in-place `restore_from_dir` path, the
        adopting engine has its own journal and its own id space: the
        request gets a FRESH id here (the donor's id may collide with
        one this engine already journaled), is validated like any
        admission, and is journaled under its restored identity — the
        ORIGINAL prompt/budget split plus the streamed watermark — so
        a second death of THIS engine replays it correctly too."""
        if req.state == "done":
            raise ValueError(
                "admit_restored takes an in-flight materialized "
                "request, not a finished one")
        if not req.prompt_ids:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(req.prompt_ids) + req.max_new_tokens > self._max_seq_len:
            raise ValueError(
                f"prompt ({len(req.prompt_ids)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_seq_len "
                f"{self._max_seq_len}")
        if self._pages_for(req.total_kv_tokens()) > self.pool.num_pages:
            raise ValueError(
                "request needs more KV pages than the pool holds")
        req.request_id = next(Request._next_id)
        req.on_token = on_token
        req._engine = self
        req.t_enqueue_ns = _obs.now_ns()
        if req.deadline_ms is not None:
            req._deadline_ns = req.t_enqueue_ns + \
                int(req.deadline_ms * 1e6)
        _obs.REQUESTS_ENQUEUED.inc()
        self._queue.append(req)
        if self._durability is not None:
            self._durability.on_admit(req)
            if req._absorbed + req._emit_gate:
                # the adopted watermark must be durable HERE too: a
                # crash of this engine before the next emit would
                # otherwise replay the donor's already-streamed tokens
                # straight into the stream
                self._durability.on_emit(req)
        return req

    # -- fleet export hooks ---------------------------------------------------
    def route_prefix_hashes(self, prompt_ids) -> List[str]:
        """The fleet router's affinity key: hex chain hashes of every
        FULL page of ``prompt_ids`` under THIS engine's salt (same
        digests `_probe_prefix` matches against, so a router keyed on
        them lands a request exactly where its pages are cached).
        Empty when the prefix cache is off or the prompt spans no full
        page."""
        if not self._prefix_cache:
            return []
        return [h.hex() for h in self._prefix_hashes(list(prompt_ids))]

    def journal_info(self) -> Optional[dict]:
        """Where this engine journals (the fleet failover donor
        surface) — directory, record count, on-disk bytes, fsync
        policy; None when durability is off."""
        if self._durability is None:
            return None
        d = self._durability
        try:
            size = os.path.getsize(d.path)
        except OSError:
            size = 0
        return {"dir": d.journal_dir, "path": d.path,
                "records": int(d.seq), "bytes": int(size),
                "fsync": d.fsync}

    def _pages_for(self, tokens: int) -> int:
        return -(-tokens // self._page)  # ceil

    def _alloc_page(self) -> int:
        """THE engine's page-allocation chokepoint: every page the
        engine claims (admission prompt pages, between-steps growth)
        comes through here so quantized mode can mark it fresh — its
        quant-scale entry zeroes on the next `_flush_fresh_scales`
        BEFORE any quantized write folds into it.  A recycled page's
        stale scale leaking into a new owner would silently change the
        quantization (history-dependent outputs: the restore/recovery
        bit-exactness contract breaks)."""
        p = self.pool.alloc_page()
        if self._kv_quant:
            self._fresh_pages.append(p)
        return p

    def _scale_reset_tracker(self) -> _JitTracker:
        fn = self._scale_reset_fn
        if fn is None:
            fn = self._scale_reset_fn = _JitTracker(
                _reset_kv_scales, "kv_quant_compiles",
                donate_argnums=(0, 1),
                site="DecodeEngine scale reset (_reset_kv_scales)")
        return fn

    def _flush_fresh_scales(self):
        """Zero the quant-scale entries of pages allocated since the
        last device call (one fixed-shape donated scatter; the fresh
        buffer pads with an out-of-bounds id so the executable never
        retraces).  Runs between steps, right before the quantized
        step executable — a no-op dict check on every step that
        allocated nothing, and never on the off path."""
        if not self._kv_quant or not self._fresh_pages:
            return
        # churn inside one window (alloc -> unwind -> realloc) can
        # repeat an id; the reset is idempotent but dedupe keeps the
        # fixed-size buffer sufficient by construction
        ids = list(dict.fromkeys(self._fresh_pages))
        self._fresh_pages = []
        buf = np.full(self.pool.num_pages, self.pool.num_pages,
                      np.int32)
        buf[:len(ids)] = ids
        fn = self._scale_reset_tracker()
        with self._phase("cache"):
            self._k_scales, self._v_scales = fn(
                self._k_scales, self._v_scales, self._dev(buf))
            if self._spec is not None and \
                    getattr(self._spec.drafter, "_k_scales", None) \
                    is not None:
                d = self._spec.drafter
                dfn = d._scale_reset_tracker()
                d._k_scales, d._v_scales = dfn(
                    d._k_scales, d._v_scales, jnp.asarray(buf))
        _stats_add(kv_quant_pages=len(ids))
        _obs.KV_QUANT_PAGES.inc(len(ids))

    def _note_refolds(self, n: int):
        """Account one quantized step's scale refolds (the packed
        count the step executable returned with its tokens)."""
        if n:
            _stats_add(kv_quant_refolds=int(n))
            _obs.KV_QUANT_REFOLDS.inc(int(n))

    def _kv_byte_occupancy(self) -> dict:
        """Device bytes the KV pool currently holds in non-free pages
        (payload + quant scales), plus the per-token storage cost —
        the density numbers the flight recorder stamps per step and
        tools/bench_kv_quant.py gates on."""
        per_page_payload = 2 * self._num_layers * self._num_heads * \
            self._page * self._head_dim * self._k_pages.dtype.itemsize
        per_page_scales = 0
        if self._kv_quant:
            per_page_scales = 2 * self._num_layers * self._num_heads * 4
        used = self.pool.used_count
        return {
            "dtype": str(self._k_pages.dtype),
            "payload_bytes": used * per_page_payload,
            "scale_bytes": used * per_page_scales,
            "bytes_per_token": (per_page_payload + per_page_scales)
            / self._page,
        }

    def _prefill_bucket(self, p_len: int) -> int:
        """Pow-2 prompt-length bucket (floor 16, capped at the horizon)
        so prompt lengths share prefill executables.  The draft-model
        drafter buckets with THIS method so target and draft prefill
        always compile the same executable set."""
        bucket = 16
        while bucket < p_len:
            bucket *= 2
        return min(bucket, self._max_seq_len)

    def _prefix_hashes(self, prompt_ids) -> List[bytes]:
        """Chain hashes for every FULL page of the prompt (page i's key
        folds page i-1's digest, so a hit at page i implies the whole
        page-aligned prefix 0..i matched)."""
        page = self._page
        hashes = []
        h = self._model_salt
        for i in range(len(prompt_ids) // page):
            h = _chain_hash(h, prompt_ids[i * page:(i + 1) * page])
            hashes.append(h)
        return hashes

    def _probe_prefix(self, req: Request):
        """Longest page-aligned cached prefix for ``req`` — read-only:
        nothing is referenced until `_bind_slot` commits, so a failed
        admission (capacity) leaves the cache untouched.  At least one
        prompt token is always recomputed (the first sampled token
        needs the last position's logits), so a whole-prompt match is
        capped one page short.  The chain hashes are memoized on the
        request (``req._page_hashes``) for registration and for the
        re-probes a capacity-blocked admission retries every step."""
        if not self._prefix_cache:
            return []
        hashes = req._page_hashes
        if hashes is None:
            hashes = req._page_hashes = \
                self._prefix_hashes(req.prompt_ids)
        limit = (len(req.prompt_ids) - 1) // self._page
        hit_pages = []
        for h in hashes[:limit]:
            p = self.pool.lookup(h)
            if p is None:
                break
            hit_pages.append(p)
        return hit_pages

    def _admit(self):
        """Between-steps admission: delegated to the pluggable
        scheduler (`inference.frontend.Scheduler`).  The default FIFO
        scheduler reproduces the historical strict-arrival-order loop
        exactly; the SLO scheduler re-orders, expires, and preempts.
        Either way the actual bind goes through `_admit_one`, so the
        capacity arithmetic lives in exactly one place."""
        self._scheduler.schedule()

    def _capacity_ok(self, req: Request, extra_pages: int = 0) -> bool:
        """Would the pool see ``req`` through to completion if
        ``extra_pages`` more pages were reclaimable?  ``extra_pages=0``
        is exactly `_admit_one`'s capacity test; a scheduler weighing a
        preemption passes the pages its victims would free to ask
        whether evicting them can possibly admit ``req`` — if not,
        preemption is pure waste.  Read-only (the prefix probe is
        memoized and references nothing)."""
        total_pages = self._pages_for(req.total_kv_tokens())
        hit_pages = self._probe_prefix(req)
        need = total_pages - len(hit_pages)
        avail = self.pool.free_count + \
            self.pool.cached_unreferenced_count + extra_pages - \
            sum(1 for p in hit_pages if self.pool.refcount(p) == 0)
        return avail - self.pool.reserved >= need

    def _admit_one(self, req: Request) -> bool:
        """Admit ONE specific queued request if a slot is free and the
        pool can see it through to completion; returns False (request
        stays queued, cache untouched) otherwise.

        Conservative admission: never admit a request the pool cannot
        see through (running requests' not-yet-allocated pages are
        reserved).  Cached-prefix hits need no allocation, and
        unreferenced cached pages are reclaimable via the eviction LRU
        — but the hit pages themselves must not double-count as
        evictable capacity (`_capacity_ok` carries that arithmetic)."""
        if not self._free_slots:
            return False
        if not self._capacity_ok(req):
            return False
        if self._cost_admission and \
                not self._cost.admission_ok(req):
            # cost-model admission (FLAGS_sched_cost_admission):
            # predicted step cost would blow the tightest declared
            # per-token SLO — the request stays queued and re-probes
            # next step, exactly like a capacity refusal.  Default
            # off: the decision sequence above is bit-exact historical.
            return False
        total_pages = self._pages_for(req.total_kv_tokens())
        hit_pages = self._probe_prefix(req)  # memoized: re-probe is cheap
        if self._queue and self._queue[0] is req:
            self._queue.popleft()  # FIFO fast path (O(1), not a scan)
        else:
            self._queue.remove(req)
        slot = heapq.heappop(self._free_slots)
        try:
            if self._chunked:
                self._bind_slot(req, slot, total_pages, hit_pages)
            else:
                self._prefill_into(req, slot, total_pages)
        except PoolExhausted:
            # typed containment: the pool could not actually deliver
            # what the (conservative) capacity probe promised — or the
            # "pool" fault site fired.  Admission backpressure, never a
            # crash: unwind the partial claim and keep the request
            # QUEUED at the head; it re-probes next step.
            self._unwind_failed_admit(req, slot)
            return False
        return True

    def _unwind_failed_admit(self, req: Request, slot: int):
        """Roll back a bind that raised `PoolExhausted` mid-way: give
        back every page the partial `_alloc_prompt_pages` claimed
        (cached hits unref, fresh allocs free — the reservation is
        only taken after the loop completes, so it was never touched),
        clear the slot, and put the request back at the queue head
        still in state "queued"."""
        self.pool.release_pages(req.pages)
        req.pages = []
        req.cached_page_count = 0
        req.cached_prefix_len = 0
        req.slot = None
        req.state = "queued"
        self._release_slot(slot)
        self._queue.appendleft(req)

    def _release_slot(self, slot: int):
        """Clear every per-slot array for ``slot`` and push it back on
        the free heap — the ONE slot teardown, shared by `_finish`,
        `preempt`, and the admission unwind, so a new per-slot array
        only ever needs resetting here."""
        self._by_slot[slot] = None
        self._active[slot] = False
        self._lens[slot] = 0
        self._last[slot] = 0
        self._bt[slot] = 0
        self._prefill_pos[slot] = 0
        heapq.heappush(self._free_slots, slot)

    def _stamp_admit(self, req: Request):
        first = req.t_admit_ns is None
        req.t_admit_ns = _obs.now_ns()
        if not first:
            # re-admission after a preemption: the request already
            # recorded its queue wait — count the resume instead
            _stats_add(resumes=1)
            if self._flight is not None:
                self._flight.event("resume", request=req.request_id)
            return
        if req.t_enqueue_ns is not None:
            _obs.REQUEST_QUEUE_WAIT.observe(
                (req.t_admit_ns - req.t_enqueue_ns) / 1e9)
            _obs.record_span("requests", "queued", req.t_enqueue_ns,
                             req.t_admit_ns - req.t_enqueue_ns,
                             tid=req.request_id,
                             args=_req_span_args(req))

    def _alloc_prompt_pages(self, req: Request, slot: int,
                            total_pages: int, hit_pages=()):
        """Map the cached prefix (refcount+1, read-only) and allocate
        fresh pages for the rest of the prompt (chunks scatter into
        already-owned pages), reserve the decode tail, and point the
        slot's block-table row at all of them.

        May raise `PoolExhausted` (organically, or via the "pool"
        fault site) — `_admit_one` contains it: the partial claim is
        unwound and the request stays queued."""
        if self._fault is not None:
            self._resilience.fault_point("pool")
        for p in hit_pages:
            self.pool.ref_page(p)
            req.pages.append(p)
        req.cached_page_count = len(req.pages)
        req.cached_prefix_len = len(req.pages) * self._page
        p_len = len(req.prompt_ids)
        for _ in range(len(req.pages), self._pages_for(p_len)):
            req.pages.append(self._alloc_page())
        self.pool.reserved += total_pages - len(req.pages)
        row = np.zeros(self._pages_per_seq, np.int32)
        row[:len(req.pages)] = req.pages
        self._bt[slot] = row

    def _bind_slot(self, req: Request, slot: int, total_pages: int,
                   hit_pages=()):
        """Chunked admission: bind the request to a slot WITHOUT running
        any prompt pass — the next mixed steps feed its prompt chunk by
        chunk under the FLAGS_prefill_chunk_tokens budget (admit-on-
        first-chunk), so running decodes never stall.  With a cached
        prefix mapped, the prefill cursor and KV length start at the
        first NOVEL token: the cached pages' KV is already bit-identical
        to what the chunks would have recomputed.  A divergence that
        lands mid-page is copy-on-write by construction — the partially
        matching page is never mapped, its tokens are recomputed into a
        fresh private page, and the cached page is never written."""
        # alloc BEFORE the admit stamp: a PoolExhausted unwind must
        # leave the request looking never-admitted (a stamped t_admit
        # would make its real admission later count as a resume)
        self._alloc_prompt_pages(req, slot, total_pages, hit_pages)
        self._stamp_admit(req)
        req.state = "running"
        req.slot = slot
        self._by_slot[slot] = req
        start = req.cached_prefix_len
        self._lens[slot] = start
        self._last[slot] = 0
        self._prefill_pos[slot] = start
        self._active[slot] = True
        if self._prefix_cache:
            n_probe = (len(req.prompt_ids) - 1) // self._page
            _stats_add(prefix_hits=len(hit_pages),
                       prefix_misses=n_probe - len(hit_pages),
                       prefix_cached_tokens=start)
            if hit_pages:
                _obs.PREFIX_HITS.inc(len(hit_pages))
            if n_probe > len(hit_pages):
                _obs.PREFIX_MISSES.inc(n_probe - len(hit_pages))
            _obs.PREFIX_CACHED_TOKENS.observe(start)
        if self._spec is not None:
            self._spec.on_admit(slot, req)

    def _is_prefilling(self, slot: int) -> bool:
        req = self._by_slot[slot]
        return req is not None and \
            int(self._prefill_pos[slot]) < len(req.prompt_ids)

    def _prefilling_any(self) -> bool:
        return any(self._is_prefilling(s) for s in range(self._slots)
                   if self._active[s])

    def _prefill_into(self, req: Request, slot: int, total_pages: int):
        # alloc first: a PoolExhausted unwind must see no admit stamp
        # and no stall accounting for an admission that never happened
        self._alloc_prompt_pages(req, slot, total_pages)
        if self._active.any():
            # legacy one-shot prefill runs BETWEEN decode steps: every
            # already-running slot stalls for this whole prompt pass —
            # the cost chunked prefill exists to remove
            _stats_add(stalled_decode_steps=1)
        self._stamp_admit(req)
        p_len = len(req.prompt_ids)

        bucket = self._prefill_bucket(p_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :p_len] = req.prompt_ids

        fn = self._prefill_fns.get(bucket)
        if fn is None:
            # prefill buckets compile on first use by design (a new
            # prompt-length bucket is an expected warmup event, not a
            # steady-state retrace) — only per-bucket recompiles count
            # toward retraces_after_warmup
            if self._kv_quant:
                fn = _JitTracker(
                    functools.partial(_gpt_prefill_q,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._sampling),
                    "prefill_compiles", donate_argnums=(4, 5, 6, 7),
                    site=f"DecodeEngine prefill bucket {bucket} "
                         f"(_gpt_prefill_q)")
            else:
                fn = _JitTracker(
                    functools.partial(_gpt_prefill,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._sampling),
                    "prefill_compiles", donate_argnums=(4, 5),
                    site=f"DecodeEngine prefill bucket {bucket} "
                         f"(_gpt_prefill)")
            self._prefill_fns[bucket] = fn
        t0 = time.perf_counter()
        t0_ns = _obs.now_ns()
        # prefill keys live in the upper fold_in window (decode steps
        # use (0, 2^30]), derived from a PER-ENGINE counter so `seed`
        # actually pins the sampling stream regardless of process-global
        # state; _fold_counter wraps inside the window so the streams
        # can never alias, no matter the uptime
        self._prefill_no += 1
        key = jax.random.fold_in(
            self._key, _fold_counter(self._prefill_no,
                                     RNG_PREFILL_DOMAIN))
        fr = self._flight
        self._flush_fresh_scales()
        with self._phase("prefill"):
            if self._kv_quant:
                (self._k_pages, self._v_pages, self._k_scales,
                 self._v_scales, tok) = fn(
                    self._params, self._dev(ids), jnp.int32(p_len),
                    self._dev(self._bt[slot]), self._k_pages,
                    self._v_pages, self._k_scales, self._v_scales,
                    self._dev(key))
            else:
                self._k_pages, self._v_pages, tok = fn(
                    self._params, self._dev(ids), jnp.int32(p_len),
                    self._dev(self._bt[slot]), self._k_pages,
                    self._v_pages, self._dev(key))
        tok = self._host_fetch(tok)
        if self._kv_quant:
            self._note_refolds(int(tok[1]))
            tok = int(tok[0])
        else:
            tok = int(tok)
        # the pass's wall time is real either way; the token count,
        # prefill count and TTFT stamp wait for the NaN-sentinel check
        # below — a quarantined prefill emitted nothing (mirrors the
        # chunked path, where _on_first_token checks before stamping)
        _stats_add(prefill_time_s=time.perf_counter() - t0)
        _obs.record_span("engine", "prefill", t0_ns,
                         _obs.now_ns() - t0_ns,
                         tid=self._engine_id,
                         args=_req_span_args(req, bucket=bucket,
                                             slot=slot))

        req.state = "running"
        req.slot = slot
        self._by_slot[slot] = req
        self._lens[slot] = p_len
        self._prefill_pos[slot] = p_len  # legacy: prompt consumed whole
        self._last[slot] = max(tok, 0)
        self._active[slot] = True
        if tok < 0:
            # non-finite logits in the prompt pass: quarantine this
            # request only — nothing was emitted, the batch lives on
            self._quarantine_slot(slot, "nan_logits")
            return
        _stats_add(prefills=1, tokens=1)
        self._stamp_first_token(req, prompt_len=p_len, bucket=bucket)
        self._emit(req, [tok])
        if self._spec is not None:
            self._spec.on_admit(slot, req)
        reason = self._done(req, tok)
        if reason:
            self._finish(slot, reason)

    def _done(self, req: Request, tok: int) -> Optional[str]:
        """Finish reason if the request is done after emitting ``tok``,
        else None."""
        if req.eos_token_id is not None and tok == req.eos_token_id:
            return "eos"
        if len(req.output_ids) >= req.max_new_tokens:
            return "length"
        return None

    def _emit(self, req: Request, toks):
        """Land generated tokens on the request and fire its streaming
        callback — the ONE place output_ids grows, so every emission
        path (prefill first token, mixed step, classic decode,
        speculative accept) streams identically.  The callback runs
        inside the serve loop: it must be cheap, and a callback that
        RAISES is contained here (the "host_callback" fault site) —
        the exception is recorded on ``req.fault_info``, the callback
        is dropped for the rest of the request, and the serve loop
        never unwinds mid-step.  Generation continues; only the
        streaming side goes quiet (``output_ids`` stays complete).

        Durable serving rides this chokepoint too: the journal's
        emitted-token watermark is appended (write-ahead — durable
        before the stream sees the token under ``journal_fsync=
        always``), and ``req._emit_gate`` suppresses the callback for
        replay tokens an earlier life already streamed."""
        req.output_ids.extend(toks)
        if self._flight is not None and toks:
            self._flight.note_emit(req.request_id, len(toks))
        gate = req._emit_gate
        if gate:
            skip = min(gate, len(toks))
            req._emit_gate = gate - skip
            toks = toks[skip:]
        if self._durability is not None:
            self._durability.on_emit(req)
        cb = req.on_token
        if cb is None:
            return
        for t in toks:
            try:
                if self._fault is not None:
                    self._resilience.fault_point("host_callback")
                cb(int(t))
            except Exception as e:  # containment, not policy: see above
                req.on_token = None
                if req.fault_info is None:
                    req.fault_info = FaultInfo(
                        site="host_callback", step=self._step_no,
                        recovered=True, message=str(e))
                break

    def _slo_violation(self, req: Request, kind: str):
        """Record one SLO miss ("ttft" | "tpot" | "deadline") — pure
        accounting, the request itself is never aborted for missing a
        latency target."""
        req.slo_violations.append(kind)
        _stats_add(slo_violations=1)
        _obs.SCHED_SLO_VIOLATIONS.inc(kind=kind)

    def _stamp_first_token(self, req: Request, **span_args):
        """Stamp TTFT exactly ONCE per request — shared by the legacy
        one-shot prefill and the chunked first-token path.  A RESUMED
        request (preempted earlier) keeps its original stamp: its
        replay token is mid-generation, not a first token.  Also runs
        the declared-TTFT SLO check and records the per-request
        prefill span."""
        if req.t_first_token_ns is not None:
            return
        req.t_first_token_ns = _obs.now_ns()
        if req.t_enqueue_ns is not None:
            ttft_s = (req.t_first_token_ns - req.t_enqueue_ns) / 1e9
            _obs.REQUEST_TTFT.observe(ttft_s)
            if req.slo_ttft_ms is not None and \
                    ttft_s * 1e3 > req.slo_ttft_ms:
                self._slo_violation(req, "ttft")
        if req.t_admit_ns is not None:
            _obs.record_span("requests", "prefill", req.t_admit_ns,
                             req.t_first_token_ns - req.t_admit_ns,
                             tid=req.request_id,
                             args=_req_span_args(req, **span_args))

    def _register_prompt_pages(self, req: Request):
        """Prefill complete: content-address every freshly computed
        FULL prompt page (beyond the mapped cached prefix) so later
        requests can map it.  The payload is final — all subsequent
        writes for this slot land at positions past the prompt — so
        registering freezes it safely.  First writer wins a hash: a
        concurrent identical prefill keeps its duplicate page private
        (freed normally at finish)."""
        if not self._prefix_cache:
            return
        fr = self._flight
        with self._phase("cache"):
            for i in range(req.cached_page_count, len(req._page_hashes)):
                self.pool.register_page(req.pages[i],
                                        req._page_hashes[i])
        req._reg_pages = len(req._page_hashes)

    def _register_generated_pages(self, slot: int, req: Request):
        """Decode just advanced ``slot``: content-address any GENERATED
        page that became full (ROADMAP quantized-serving rung (d)), so
        beam/agent fanout sharing a decode prefix maps it instead of
        recomputing.  Safe to freeze: KV rows ``< lens`` are final (a
        speculative rejection only ever shrinks lens back to the
        accepted point BEFORE new rows are written, and every later
        write lands at positions ``>= lens`` — past every full page).
        The chain hashes extend the prompt's memoized chain over
        ``prompt_ids + output_ids``; the emit-loop invariant
        ``len(prompt + outputs) == lens + 1`` guarantees the token
        content of every full page is on hand.  O(1) early-out keeps
        the per-token cost of the common (mid-page) case negligible.
        Gated by ``cache_generated_pages`` (default off): prompt-only
        registration is the bit-exact-occupancy parity oracle."""
        if not self._cache_generated or not self._prefix_cache or \
                req.t_first_token_ns is None:
            return
        full = int(self._lens[slot]) // self._page
        if full <= req._reg_pages:
            return
        toks = req.prompt_ids + req.output_ids
        hashes = req._page_hashes
        if hashes is None:
            hashes = req._page_hashes = self._prefix_hashes(
                req.prompt_ids)
        while len(hashes) < full:
            i = len(hashes)
            prev = hashes[-1] if hashes else self._model_salt
            hashes.append(_chain_hash(
                prev, toks[i * self._page:(i + 1) * self._page]))
        with self._phase("cache"):
            for i in range(max(req._reg_pages, req.cached_page_count),
                           full):
                self.pool.register_page(req.pages[i], hashes[i])
        req._reg_pages = full

    def _finish(self, slot: int, reason: str):
        req = self._by_slot[slot]
        self.pool.release_pages(req.pages)
        self.pool.reserved -= max(
            self._pages_for(req.total_kv_tokens()) - len(req.pages), 0)
        req.state = "done"
        req.finish_reason = reason
        req.slot = None
        req.pages = []
        self._release_slot(slot)
        _stats_add(**{{"eos": "finished_eos", "length": "finished_length",
                       "evicted": "evicted", "cancelled": "cancelled",
                       "fault": "finished_fault"}[reason]: 1})
        req.t_finish_ns = _obs.now_ns()
        _obs.REQUESTS_FINISHED.inc(reason=reason)
        if self._durability is not None:
            self._durability.on_finish(req)
        # generated-token count is preemption-stable: tokens folded
        # into the replay prompt still count toward TPOT
        n_out = len(req.output_ids) + req._absorbed
        if req.t_enqueue_ns is not None:
            _obs.REQUEST_E2E.observe(
                (req.t_finish_ns - req.t_enqueue_ns) / 1e9)
        if req.t_first_token_ns is not None:
            if n_out > 1:
                tpot_s = (req.t_finish_ns - req.t_first_token_ns) / 1e9 \
                    / (n_out - 1)
                _obs.REQUEST_TPOT.observe(tpot_s)
                if reason in ("eos", "length") and \
                        req.slo_tpot_ms is not None and \
                        tpot_s * 1e3 > req.slo_tpot_ms:
                    self._slo_violation(req, "tpot")
            _obs.record_span(
                "requests", "decode", req.t_first_token_ns,
                req.t_finish_ns - req.t_first_token_ns,
                tid=req.request_id,
                args=_req_span_args(req, tokens=n_out,
                                    finish_reason=reason))
        if reason in ("eos", "length") and req._deadline_ns is not None \
                and req.t_finish_ns > req._deadline_ns:
            # it ran to completion, but past its deadline: a violation,
            # distinct from queued-expiry (which never takes a slot)
            self._slo_violation(req, "deadline")
        if self._spec is not None:
            self._spec.on_finish(slot, req)
        if self._flight is not None:
            # after the SLO checks above: slo_met is final here
            self._flight.note_finish(req)

    def evict(self, req: Request):
        """Cancel a request: a queued request leaves the queue, a
        running one gives its slot and pages back between steps.  The
        tokens generated so far stay on ``req.output_ids`` and
        ``req.finish_reason`` reads "evicted" — callers can finally tell
        a cancelled generation from one that hit eos."""
        if req.state == "queued":
            self._retire_queued(req, "evicted")
            return
        if req.state == "running" and req.slot is not None and \
                0 <= req.slot < self._slots and \
                self._by_slot[req.slot] is req:
            self._finish(req.slot, "evicted")
            return
        if req.state == "done":
            return  # already finished; nothing to release
        raise ValueError("request is not owned by this engine")

    def preempt(self, req: Request):
        """Preempt a RUNNING request: release its slot and pages
        between steps and re-enqueue it for resume.  The generated
        tokens fold into ``prompt_ids`` (``max_new_tokens`` shrinks one
        for one, so the KV budget is invariant) and the next admission
        replays them as a prompt — with the prefix cache on, every FULL
        page of (prompt + generated) KV is registered here first, so
        the replay maps those pages at refcount+1 and recomputes at
        most one partial page plus the last token.  Streaming is
        seamless: the already-emitted tokens became prompt, so
        ``on_token`` only ever fires for novel tokens, and
        ``generated_ids`` reads the full generation throughout.

        Host-side only — no device transfer, no shape change; the
        preempted KV pages either enter the prefix cache (retained
        payloads) or return to the free list."""
        if req.state != "running" or req.slot is None or \
                self._by_slot[req.slot] is not req:
            raise ValueError(
                f"preempt() is for running requests; this one is "
                f"{req.state!r}")
        slot = req.slot
        total_pages = self._pages_for(req.total_kv_tokens())
        n_gen = len(req.output_ids)
        kv_len = int(self._lens[slot])
        replay_hashes = None
        if self._prefix_cache and req.t_first_token_ns is not None:
            # content-address every fully written page of the replay
            # prompt (prompt pages registered at first token stay; this
            # adds the GENERATED region's full pages).  KV rows
            # < kv_len are final — speculative rollback only ever
            # shrinks lens — so the payloads are safe to freeze.
            replay_hashes = self._prefix_hashes(
                req.prompt_ids + req.output_ids)
            for i in range(req.cached_page_count,
                           min(kv_len // self._page, len(replay_hashes))):
                self.pool.register_page(req.pages[i], replay_hashes[i])
            req._reg_pages = max(
                req._reg_pages,
                min(kv_len // self._page, len(replay_hashes)))
        # fold the generation into the prompt for replay; the KV-budget
        # identity (total_kv_tokens) is preserved exactly
        req.prompt_ids = req.prompt_ids + req.output_ids
        req.max_new_tokens -= n_gen
        req._absorbed += n_gen
        req.output_ids = []
        # the hashes just computed ARE the replay prompt's hashes —
        # keep them memoized so the resume probe (and every re-probe
        # while capacity-blocked) skips the O(prompt+generated) re-hash
        req._page_hashes = replay_hashes
        req.preemptions += 1
        # release the device-side claim (pages + outstanding
        # reservation) and the slot — the same teardown as _finish,
        # minus the finished bookkeeping
        self.pool.release_pages(req.pages)
        self.pool.reserved -= max(total_pages - len(req.pages), 0)
        req.pages = []
        req.cached_page_count = 0
        req.cached_prefix_len = 0
        req.slot = None
        req.state = "queued"
        self._release_slot(slot)
        if self._spec is not None:
            self._spec.on_finish(slot, req)
        # back of the line position-wise, but schedulers order by
        # (priority, deadline, id) anyway and the id is the original
        # (oldest-first within its class); FIFO resumes it first
        self._queue.appendleft(req)
        _stats_add(preemptions=1)
        _obs.SCHED_PREEMPTIONS.inc()
        if self._flight is not None:
            self._flight.event("preempt", request=req.request_id,
                               slot=slot, generated=n_gen)
        if req.t_admit_ns is not None:
            _obs.record_span("requests", "preempted", req.t_admit_ns,
                             _obs.now_ns() - req.t_admit_ns,
                             tid=req.request_id,
                             args=_req_span_args(req, generated=n_gen))

    def _cancel_running(self, req: Request):
        if req.state != "running" or req.slot is None or \
                self._by_slot[req.slot] is not req:
            raise ValueError("request is not running on this engine")
        self._finish(req.slot, "cancelled")

    def _retire_queued(self, req: Request, reason: str):
        """Take a still-queued request out of the admission queue
        (``reason``: "evicted" via `evict`, "cancelled" via
        `Request.cancel`, "deadline" via the SLO scheduler's expiry
        sweep, "fault" via the containment ladder's bisect-quarantine
        — the suspect is preempted back to the queue first, then
        retired here) — it never held a slot or pages at retire time,
        so this is pure queue + telemetry bookkeeping."""
        try:
            self._queue.remove(req)
        except ValueError:
            raise ValueError(
                "request is not queued on this engine") from None
        req.state = "done"
        req.finish_reason = reason
        req.t_finish_ns = _obs.now_ns()
        _stats_add(**{{"evicted": "evicted", "cancelled": "cancelled",
                       "deadline": "deadline_expired",
                       "fault": "finished_fault"}[reason]: 1})
        _obs.REQUESTS_FINISHED.inc(reason=reason)
        if self._durability is not None:
            self._durability.on_finish(req)
        if reason == "deadline":
            _obs.SCHED_DEADLINE_EXPIRED.inc()
        if req.t_enqueue_ns is not None:
            _obs.REQUEST_E2E.observe(
                (req.t_finish_ns - req.t_enqueue_ns) / 1e9)
            _obs.record_span("requests", "queued", req.t_enqueue_ns,
                             req.t_finish_ns - req.t_enqueue_ns,
                             tid=req.request_id,
                             args=_req_span_args(req,
                                                 finish_reason=reason))
        if self._flight is not None:
            self._flight.note_finish(req)

    def _cancel_queued(self, req: Request):
        if req.state != "queued":
            raise ValueError(
                f"cancel() is for still-queued requests; this one is "
                f"{req.state!r} — use DecodeEngine.evict to cancel a "
                f"running request")
        self._retire_queued(req, "cancelled")

    def _grow_block_tables(self, writes=None):
        """Ensure pages exist for every KV row the next step will write:
        positions ``lens[slot] .. lens[slot] + writes[slot] - 1``
        (``writes`` defaults to one token per slot; the speculative
        verify step writes up to K+1).  Slot reuse keeps this a pop from
        the free list, not an allocation; the pages stay with the
        request until it finishes, so a speculative rejection rolls back
        ``seq_lens`` WITHOUT touching the pool.

        May raise `PoolExhausted` ("pool" fault site, or a genuinely
        dry pool): the containment ladder retries and, if pressure
        persists, quarantines a request — which frees pages.  Partial
        growth is consistent state (grown pages belong to their
        requests), so the retry re-enters here idempotently."""
        fr = self._flight
        with self._phase("cache"):
            if self._fault is not None:
                self._resilience.fault_point("pool")
            for slot in range(self._slots):
                if not self._active[slot]:
                    continue
                req = self._by_slot[slot]
                w = 1 if writes is None else int(writes[slot])
                if w == 0:
                    continue  # nothing written this step
                pidx = (int(self._lens[slot]) + w - 1) // self._page
                while pidx >= len(req.pages):
                    req.pages.append(self._alloc_page())
                    self.pool.reserved -= 1
                    self._bt[slot, len(req.pages) - 1] = req.pages[-1]

    def _observe_step(self, t0_ns: int, dt: float, n_active: int,
                      name: str, extra_args=None, observe_hist=True):
        """Per-step observability: a step span on this engine's trace
        lane, the step-latency histogram, and the pool/occupancy
        gauges (levels as of the step that just ran).
        ``observe_hist=False`` skips the step-latency histogram — used
        by the chunk-only mixed step inside a speculative round: the
        round observes a window that OPENS before the chunk step (or,
        when every slot is still prefilling, the chunk step's wall is
        observed directly), so each engine step lands in
        paddle_decode_step_seconds exactly once, chunk time included."""
        if self._abandoned:
            # a late-returning step on a watchdog-abandoned engine must
            # not repopulate the retired gauges or extend the dead lane
            return
        args = {"step": self._step_no, "active": n_active}
        if extra_args:
            args.update(extra_args)
        _obs.record_span("engine", name, t0_ns, int(dt * 1e9),
                         tid=self._engine_id, args=args)
        if observe_hist:
            _obs.STEP_SECONDS.observe(dt)
        # level gauges are engine-labeled: several engines in one
        # process must not clobber each other's pool/occupancy reading
        eid = self._engine_id
        _obs.KV_FREE_PAGES.set(self.pool.free_count, engine=eid)
        _obs.KV_UTIL.set(self.pool.utilization(), engine=eid)
        _obs.SLOT_OCCUPANCY.set(n_active / self._slots, engine=eid)
        _obs.KV_QUANT_BYTES_PER_TOKEN.set(
            self._kv_byte_occupancy()["bytes_per_token"], engine=eid)
        if self._prefix_cache:
            _obs.PREFIX_CACHED_PAGES.set(self.pool.cached_count,
                                         engine=eid)
            d = self.pool.evictions - self._evictions_seen
            if d:
                self._evictions_seen = self.pool.evictions
                _stats_add(prefix_evictions=d)
                _obs.PREFIX_EVICTIONS.inc(d)

    # -- the mixed prefill+decode step ---------------------------------------
    def _mixed_fn_tracker(self) -> _JitTracker:
        fn = self._mixed_fn
        if fn is None:
            if self._kv_quant:
                fn = self._mixed_fn = _JitTracker(
                    functools.partial(_gpt_mixed_step_q,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._sampling),
                    "mixed_compiles", donate_argnums=(1, 2, 3, 4),
                    site="DecodeEngine mixed step (_gpt_mixed_step_q)")
            else:
                fn = self._mixed_fn = _JitTracker(
                    functools.partial(_gpt_mixed_step,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._sampling),
                    "mixed_compiles", donate_argnums=(1, 2),
                    site="DecodeEngine mixed step (_gpt_mixed_step)")
        return fn

    def _ragged_fn_tracker(self) -> _JitTracker:
        """The ONE step executable of the ragged path
        (FLAGS_ragged_step): decode rows, prefill chunks, and
        speculative verify windows all dispatch through this tracker,
        so steady-state serving compiles exactly one executable per KV
        mode (counter: ``ragged_compiles``) and a warm retrace of it is
        attributed to ``ragged_retraces``."""
        fn = self._ragged_fn
        if fn is None:
            if self._kv_quant:
                fn = self._ragged_fn = _JitTracker(
                    functools.partial(_gpt_ragged_step_q,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps,
                                      mesh=self._mesh, **self._sampling),
                    "ragged_compiles", donate_argnums=(1, 2, 3, 4),
                    site="DecodeEngine ragged step (_gpt_ragged_step_q)")
            else:
                fn = self._ragged_fn = _JitTracker(
                    functools.partial(_gpt_ragged_step,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps,
                                      mesh=self._mesh, **self._sampling),
                    "ragged_compiles", donate_argnums=(1, 2),
                    site="DecodeEngine ragged step (_gpt_ragged_step)")
        return fn

    def _mixed_step(self, decode_rows=True) -> bool:
        """One fused prefill+decode step: assemble the fixed-shape
        [slots, Q_max] mixed batch under the chunk-token budget, run the
        single donated mixed executable, land chunks / tokens on the
        host side.  ``decode_rows=False`` (the speculative path) feeds
        ONLY prompt chunks — decoding slots advance through the spec
        round that follows in the same engine step."""
        from ..profiler import RecordEvent

        slots, qmax = self._slots, self._q_max
        # ragged mode widens the grid to Q_r >= Q_max so the ONE
        # executable's token shape also fits verify windows (K+1);
        # chunk spans stay capped by Q_max (the chunk-budget invariant)
        width = self._q_ragged if self._ragged else qmax
        tokens = np.zeros((slots, width), np.int32)
        caps = np.zeros(slots, np.int32)
        sample_idx = np.zeros(slots, np.int32)
        sample_mask = np.zeros(slots, bool)
        prefilling = [s for s in range(slots)
                      if self._active[s] and self._is_prefilling(s)]
        # fair-share chunking: the step's token budget splits evenly
        # across prefilling slots (remainder to the lower slots), so a
        # short prompt admitted next to a long one finishes its prefill
        # in one step instead of queueing behind the long prompt's whole
        # stream — bounded TTFT for everyone, not just slot 0
        budget = self._chunk_budget
        chunk_of = {}
        for i, s in enumerate(prefilling):
            req = self._by_slot[s]
            cur = int(self._prefill_pos[s])
            share = -(-budget // (len(prefilling) - i))  # ceil
            c = min(len(req.prompt_ids) - cur, share, qmax)
            if c == 0:
                continue  # budget spent: the slot waits one step
            budget -= c
            tokens[s, :c] = req.prompt_ids[cur:cur + c]
            caps[s] = c
            chunk_of[s] = c
            if cur + c == len(req.prompt_ids):
                # last chunk: this step produces the first token
                sample_idx[s] = c - 1
                sample_mask[s] = True
        if decode_rows:
            for s in range(slots):
                if self._active[s] and s not in chunk_of and \
                        not self._is_prefilling(s):
                    tokens[s, 0] = self._last[s]
                    caps[s] = 1
                    sample_idx[s] = 0
                    sample_mask[s] = True
        self._grow_block_tables(writes=caps)

        fn = self._ragged_fn_tracker() if self._ragged \
            else self._mixed_fn_tracker()
        if self._fault is not None:
            # fault site BEFORE the invocation (and the step counter):
            # an injected raise leaves no half-donated state, so the
            # containment ladder's retry re-enters cleanly
            self._resilience.step_fault_point("mixed_step")
        self._step_no += 1
        key = jax.random.fold_in(
            self._key, _fold_counter(self._step_no, RNG_DECODE_DOMAIN))
        fr = self._flight
        # phase attribution: chunk-only mixed steps are prompt work
        # ("prefill"), chunk-carrying full steps are fused ("mixed"),
        # chunkless full steps are plain decode through the mixed
        # executable ("decode")
        phase_name = "prefill" if not decode_rows else \
            ("mixed" if chunk_of else "decode")
        self._flush_fresh_scales()
        t0 = time.perf_counter()
        t0_ns = _obs.now_ns()
        with RecordEvent("serving.mixed_step"):
            with self._phase(phase_name):
                if self._ragged:
                    # the unified executable takes no sample_idx /
                    # sample_mask operands — every position draws a
                    # target and the host selects each slot's span-end
                    # row after the fetch below
                    if self._kv_quant:
                        (self._k_pages, self._v_pages, self._k_scales,
                         self._v_scales, toks) = fn(
                            self._params, self._k_pages, self._v_pages,
                            self._k_scales, self._v_scales,
                            self._dev(self._bt),
                            self._dev(self._lens),
                            self._dev(tokens), self._dev(caps),
                            self._dev(key))
                    else:
                        self._k_pages, self._v_pages, toks = fn(
                            self._params, self._k_pages, self._v_pages,
                            self._dev(self._bt),
                            self._dev(self._lens),
                            self._dev(tokens), self._dev(caps),
                            self._dev(key))
                elif self._kv_quant:
                    (self._k_pages, self._v_pages, self._k_scales,
                     self._v_scales, toks) = fn(
                        self._params, self._k_pages, self._v_pages,
                        self._k_scales, self._v_scales,
                        jnp.asarray(self._bt), jnp.asarray(self._lens),
                        jnp.asarray(tokens), jnp.asarray(caps),
                        jnp.asarray(sample_idx),
                        jnp.asarray(sample_mask), key)
                else:
                    self._k_pages, self._v_pages, toks = fn(
                        self._params, self._k_pages, self._v_pages,
                        jnp.asarray(self._bt), jnp.asarray(self._lens),
                        jnp.asarray(tokens), jnp.asarray(caps),
                        jnp.asarray(sample_idx),
                        jnp.asarray(sample_mask), key)
                if self._profiling is not None:
                    # sampled device-sync probe (see _step_inner):
                    # attributed to the DISPATCHED executable (ragged
                    # or mixed) regardless of the flight phase this
                    # step ran under — a chunkless full step runs the
                    # program under the "decode" phase, and scoring it
                    # against the decode profile would poison the
                    # calibration
                    self._profiling.probe(
                        "ragged" if self._ragged else "mixed",
                        toks, t0, t0_ns)
            toks = self._host_fetch(toks)
        if self._kv_quant:
            self._note_refolds(int(toks[-1, 0] if self._ragged
                                   else toks[-1]))
            toks = toks[:-1]
        if self._ragged:
            # host-side span-end selection: a decode row's token sits
            # at column 0, a finishing chunk's at column c-1; padding
            # columns (and sat-out slots) are garbage.  np.where keeps
            # NAN_TOKEN (-1) for masked slots, so per-row quarantine
            # still fires
            toks = np.where(sample_mask,
                            toks[np.arange(slots), sample_idx], 0)
        dt = time.perf_counter() - t0
        if self._fault is not None:
            toks = self._resilience.corrupt_tokens(
                toks, [s for s in range(slots) if sample_mask[s]])

        # the drafter sees the SAME chunks through the same executable
        # shape (speculative path: caps carry only prompt chunks there)
        if self._spec is not None and chunk_of:
            self._spec.drafter.ingest_chunks(tokens, caps)

        n_active = int(self._active.sum())
        chunk_tokens = sum(chunk_of.values())
        if decode_rows:
            # a full mixed step IS this engine-step's decode step
            _stats_add(mixed_steps=1, prefill_chunks=len(chunk_of),
                       steps=1, decode_time_s=dt,
                       occupancy_sum=n_active / slots,
                       kv_util_sum=self.pool.utilization())
        else:
            # chunk-only (speculative path): the spec round that follows
            # accounts the engine step; this wall is prefill work
            _stats_add(mixed_steps=1, prefill_chunks=len(chunk_of),
                       prefill_time_s=dt)
        for c in chunk_of.values():
            _obs.PREFILL_CHUNK_TOKENS.observe(c)
        self._observe_step(t0_ns, dt, n_active, "mixed_step",
                           extra_args={"prefilling": len(chunk_of),
                                       "chunk_tokens": chunk_tokens},
                           observe_hist=decode_rows)

        emitted = 0
        with self._excl_phase("emit"):
            for s in range(slots):
                if not self._active[s]:
                    continue
                req = self._by_slot[s]
                c = chunk_of.get(s)
                if c is not None:
                    self._prefill_pos[s] += c
                    self._lens[s] += c
                    req.prefill_chunks += 1
                    if int(self._prefill_pos[s]) == len(req.prompt_ids):
                        if self._on_first_token(s, req, int(toks[s])):
                            emitted += 1
                elif caps[s] == 1:
                    tok = int(toks[s])
                    if tok < 0:
                        # non-finite logits on this row only:
                        # quarantine the slot, never the batch (lens
                        # stays — the garbage K/V row is released with
                        # the pages)
                        self._quarantine_slot(s, "nan_logits")
                        continue
                    self._lens[s] += 1
                    self._last[s] = tok
                    self._emit(req, [tok])
                    emitted += 1
                    self._register_generated_pages(s, req)
                    reason = self._done(req, tok)
                    if reason:
                        self._finish(s, reason)
        _stats_add(tokens=emitted)
        return True

    def _on_first_token(self, slot: int, req: Request, tok: int) -> bool:
        """A slot's LAST prompt chunk landed: the mixed step sampled its
        first token — stamp TTFT now (not at admission, not at the first
        chunk) and flip the slot into plain decoding.  The prompt's full
        pages are content-final from here on, so they enter the prefix
        cache before any finish-path release can park them.  A RESUMED
        request (preempted earlier) keeps its original TTFT — the token
        sampled here is mid-generation, not its first.  Returns False
        when the token was the NaN sentinel: the slot is quarantined
        and — crucially — its pages are NOT registered (K/V computed
        under non-finite activations must never enter the prefix
        cache)."""
        if tok < 0:
            self._quarantine_slot(slot, "nan_logits")
            return False
        self._register_prompt_pages(req)
        self._emit(req, [tok])
        self._last[slot] = tok
        _stats_add(prefills=1)
        self._stamp_first_token(req, prompt_len=len(req.prompt_ids),
                                chunks=req.prefill_chunks)
        reason = self._done(req, tok)
        if reason:
            self._finish(slot, reason)
        return True

    def _quarantine_slot(self, slot: int, site: str, message: str = ""):
        """Containment verdict for ONE slot: its request leaves the
        engine with ``finish_reason="fault"`` and a structured
        `FaultInfo`, its pages and slot are released through the
        normal `_finish` teardown, and every other slot keeps serving.
        Used by the NaN/inf logit guard (only the offending row is
        poisoned — evicting the batch for one sick request would be
        the availability bug this PR exists to remove)."""
        req = self._by_slot[slot]
        if req.fault_info is None:
            req.fault_info = FaultInfo(
                site=site, step=self._step_no, recovered=False,
                message=message or
                "non-finite logits: slot quarantined")
        else:
            req.fault_info.history.append(req.fault_info.site)
            req.fault_info.site = site
            req.fault_info.recovered = False
        _obs.record_span("engine", "quarantine", _obs.now_ns(), 0,
                         tid=self._engine_id,
                         args=_req_span_args(req, slot=slot, site=site))
        if self._flight is not None:
            self._flight.event("quarantine", request=req.request_id,
                               slot=slot, site=site)
        self._finish(slot, "fault")

    def _debug_check_pool(self):
        """FLAGS_kv_pool_debug / FLAGS_sanitize: full pool-consistency
        audit at an engine idle point (between steps, no device call in
        flight) — every live request's page list cross-checked against
        the pool's free/private/cached partition and refcounts."""
        self.pool.assert_consistent(
            live_pages=[p for r in self._by_slot if r is not None
                        for p in r.pages])

    def _dev(self, x):
        """Host->device for step-executable operands.  Single-chip:
        plain `jnp.asarray` — the bit-exact historical behavior.
        Under a serving mesh: the operand commits to the mesh
        REPLICATED, so every call presents the step executable the
        same input shardings (the jit cache keys on them; uncommitted
        operands would leave placement to GSPMD's per-call whim and
        risk a warm retrace)."""
        if self._mesh is None:
            return jnp.asarray(x)
        return jax.device_put(x, self._repl_sharding)

    def _host_fetch(self, x):
        """THE engine's blocking device->host read.  Every place the
        serve loop materializes device data (sampled tokens, verify
        targets) routes through here so the sanitizer's host-sync
        sentinel (FLAGS_sanitize) can count blocking syncs inside the
        step span — a step that silently grew a second sync shows up as
        ``host_syncs > steps`` in `analysis.sanitizer.get().report()`."""
        san = _san.active()
        if san is not None:
            san.count_host_sync()
        fr = self._flight
        if fr is None:
            return np.asarray(x)
        t0 = time.perf_counter()
        out = np.asarray(x)
        fr.add_phase("fetch", time.perf_counter() - t0)
        return out

    # -- live introspection ---------------------------------------------------
    def _snapshot_queue(self) -> List[Request]:
        """Best-effort copy of the admission queue, safe from a
        non-engine thread (a deque mutated mid-iteration raises; the
        retry makes statusz robust instead of crashy)."""
        for _ in range(8):
            try:
                return list(self._queue)
            except RuntimeError:
                continue
        return []

    def statusz(self, flight_records: int = 8) -> dict:
        """Live JSON-serializable state snapshot: queue, slots,
        degraded modes, health, pool/cache occupancy, SLO burn, and
        the last ``flight_records`` flight records.  Callable
        MID-SERVE from any thread — it only reads (per-field reads are
        atomic under the GIL, the queue copy retries around concurrent
        mutation, and the flight ring is read under its lock), so a
        statusz poller can never perturb outputs.  The fields are the
        machine-readable form of `statusz_text`; `ServingFrontend
        .debug_dump` wraps both with the frontend's own state."""
        from .durability import _health_state

        now = _obs.now_ns()

        def _req(r: Request, slot=None) -> dict:
            d = {
                "request": r.request_id,
                "state": r.state,
                "priority": r.priority,
                "prompt_len": len(r.prompt_ids),
                "out_tokens": len(r.output_ids) + r._absorbed,
                "max_new": r.max_new_tokens,
                # total generation cap, stable across preemption folds
                # (the fold moves budget into _absorbed one for one)
                "out_cap": r._absorbed + r.max_new_tokens,
                "preemptions": r.preemptions,
            }
            if r.t_enqueue_ns is not None:
                d["age_s"] = round((now - r.t_enqueue_ns) / 1e9, 6)
            if slot is not None:
                d["slot"] = slot
                d["phase"] = "prefill" \
                    if int(self._prefill_pos[slot]) < len(r.prompt_ids) \
                    else "decode"
                d["kv_len"] = int(self._lens[slot])
            burn = r.slo_burn(now)
            if burn:
                d["slo_burn"] = {k: round(v, 4)
                                 for k, v in burn.items()}
            if r.finish_reason is not None:
                d["finish_reason"] = r.finish_reason
            return d

        by_slot = list(self._by_slot)
        res = self._resilience
        pool = self.pool
        out = {
            "engine": self._engine_id,
            "step": int(self._step_no),
            "time_ns": now,
            "health": _health_state.get(self._engine_id, "live"),
            "abandoned": bool(self._abandoned),
            "scheduler": self._scheduler.name,
            "degraded": {"spec_off": bool(res.spec_disabled),
                         "legacy_prefill": bool(res.legacy_mode)},
            "config": {
                "slots": self._slots,
                "max_seq_len": self._max_seq_len,
                "page_size": self._page,
                "chunked_prefill": bool(self._chunked),
                "prefix_cache": bool(self._prefix_cache),
                "kv_quant": self._kv_quant_mode,
                "serve_weights": self._serve_weights_mode,
                "chunk_budget": int(self._chunk_budget),
                "spec_k": self._spec.k if self._spec is not None else 0,
                "spec_adaptive_k": bool(
                    self._spec.adaptive if self._spec is not None
                    else False),
                "ragged_step": bool(self._ragged),
                "serve_mesh": self._serve_mesh,
                "mesh_devices": self._mesh_mp if self._mesh is not None
                else 1,
                "sampling": dict(self._sampling),
            },
            "queue": [_req(r) for r in self._snapshot_queue()],
            "slots": [_req(r, slot=s) for s, r in enumerate(by_slot)
                      if r is not None],
            "pool": {
                "num_pages": pool.num_pages,
                "free": pool.free_count,
                "reserved": pool.reserved,
                "cached": pool.cached_count,
                "cached_unreferenced": pool.cached_unreferenced_count,
                "utilization": round(pool.utilization(), 4),
                "evictions": pool.evictions,
            },
            "durability": {
                "journal_dir": self._journal_dir,
                "armed": self._durability is not None,
            },
            "watchdog": {
                "armed": self._watchdog is not None,
                "timeout_ms": self._step_timeout_ms,
            },
        }
        fl = self._flight
        if fl is not None:
            out["flight"] = {
                "totals": fl.window_stats(),
                "records": fl.records(flight_records),
            }
        if self._alerts is not None:
            # the alert engine: rule states, firing set, recent
            # transitions — the same dict /alertz serves
            out["alerts"] = self._alerts.snapshot()
        if self._cost is not None:
            # the cost observatory: static profiles, calibration +
            # error tables, roofline peaks, the HBM ledger, and the
            # capacity-headroom estimate a fleet router admits on
            out["cost"] = self._cost.statusz()
        if self._profiling is not None:
            # the profiling plane: probe accounting, capture status,
            # measured device time / MFU drift, hot-op tables — the
            # same dict the /profilez endpoint serves
            out["profiling"] = self._profiling.statusz()
        return out

    def statusz_text(self, flight_records: int = 4) -> str:
        """Human-readable rendering of `statusz` — the text half of
        the JSON+text introspection surface."""
        z = self.statusz(flight_records=flight_records)
        lines = [
            f"engine {z['engine']} — step {z['step']} — "
            f"health {z['health']}"
            + (" (ABANDONED)" if z["abandoned"] else ""),
            f"scheduler {z['scheduler']} | chunked="
            f"{int(z['config']['chunked_prefill'])} prefix_cache="
            f"{int(z['config']['prefix_cache'])} spec_k="
            f"{z['config']['spec_k']} | degraded: spec_off="
            f"{int(z['degraded']['spec_off'])} legacy="
            f"{int(z['degraded']['legacy_prefill'])}",
            f"pool: {z['pool']['free']}/{z['pool']['num_pages']} free, "
            f"{z['pool']['cached']} cached "
            f"({z['pool']['cached_unreferenced']} reclaimable), "
            f"util {z['pool']['utilization']}, "
            f"{z['pool']['evictions']} evictions",
            f"queue ({len(z['queue'])}):",
        ]
        for q in z["queue"]:
            lines.append(
                f"  req {q['request']} prio {q['priority']} "
                f"age {q.get('age_s', 0):.3f}s "
                f"out {q['out_tokens']}"
                + (f" burn {q['slo_burn']}" if "slo_burn" in q else ""))
        lines.append(f"slots ({len(z['slots'])}/"
                     f"{z['config']['slots']}):")
        for s in z["slots"]:
            lines.append(
                f"  slot {s['slot']} req {s['request']} {s['phase']} "
                f"kv {s['kv_len']} out {s['out_tokens']}/"
                f"{s['out_cap']}"
                + (f" burn {s['slo_burn']}" if "slo_burn" in s else ""))
        fl = z.get("flight")
        if fl:
            t = fl["totals"]
            lines.append(
                f"flight: {t['records']}/{t['window']} records, "
                f"{t['tokens_per_second']:.1f} tok/s over window, "
                f"goodput {t['goodput']}, {t['dumps']} dumps")
            for rec in fl["records"]:
                phases = " ".join(
                    f"{k}={v * 1e3:.2f}ms"
                    for k, v in sorted(rec.get("phases", {}).items()))
                evs = "".join(f" [{e['kind']}]"
                              for e in rec.get("events", []))
                lines.append(
                    f"  step {rec.get('step')} {rec.get('kind')} "
                    f"{rec.get('dur_s', 0) * 1e3:.2f}ms "
                    f"emitted {sum(rec.get('emitted', {}).values())} "
                    f"{phases}{evs}")
        cost = z.get("cost")
        if cost:
            hr = cost["headroom"]
            led = cost["ledger"]
            lines.append(
                f"cost: predicted "
                f"{hr['predicted_step_s'] * 1e3:.2f}ms/step, "
                f"headroom {hr['admissible_slots']} slots, ledger "
                f"{led['attributed_bytes']}B attributed + "
                f"{led['unattributed_bytes']}B unattributed")
        return "\n".join(lines)

    # -- the serve loop ------------------------------------------------------
    def step(self) -> bool:
        """Admit what fits, run one batched step — a fused mixed
        prefill+decode step while any slot is mid-prefill (chunked
        mode), a classic decode step otherwise, or one speculative
        propose->verify->accept round when spec decoding is on.
        Returns False when there is nothing left to do.

        The device step runs under the containment ladder
        (`inference.resilience.ResilienceManager.run_step`): a raising
        step executable is retried with capped exponential backoff,
        then the failing subsystem degrades (speculation off / legacy
        prefill), then the batch is bisected and the suspect request
        quarantined with ``finish_reason="fault"`` — one sick request
        never kills the batch.  A fault that survives the whole ladder
        re-raises as a FATAL `errors.StepFault`; only
        `resilience.recover` (engine rebuild + replay re-admission)
        continues from there."""
        san = _san.active()
        if san is not None:
            # sanitizer mode: audit the pool partition every step and
            # open the step's host-sync accounting window
            san.count_step()
            self._debug_check_pool()
        elif self._pool_debug:
            self._debug_check_pool()
        fr = self._flight
        if fr is not None:
            fr.begin_step()
        if self._profiling is not None:
            # profiling plane: arm any pending capture session (the
            # between-steps engine-thread arming site) and decide
            # whether this step's dispatches probe device time
            self._profiling.note_step_begin()
        try:
            # "admit" phase is EXCLUSIVE of nested leaf phases: a
            # legacy one-shot prefill runs INSIDE admission, and its
            # device/fetch time must not double-count
            with self._excl_phase("admit"):
                self._admit()
            # admission-pressure gauges, sampled every step AFTER
            # admission (what is left queued is the backlog the
            # pool/slots could not absorb).  Not on an ABANDONED
            # engine: a late-returning worker calling step() must not
            # repopulate gauges its retirement just removed.
            if not self._abandoned:
                eid = self._engine_id
                _obs.QUEUE_DEPTH.set(len(self._queue), engine=eid)
                _obs.QUEUE_OLDEST_AGE.set(
                    (_obs.now_ns() - min(r.t_enqueue_ns
                                         for r in self._queue))
                    / 1e9 if self._queue else 0.0, engine=eid)
            if fr is not None:
                fr.note_batch()
            if self._cost is not None and fr is not None and \
                    self._active.any():
                # pre-dispatch cost prediction: stamped onto the open
                # flight record BEFORE the device step runs, so the
                # record's predicted/actual pair is an honest forecast
                self._cost.note_step_begin(fr)
            if not self._active.any():
                if self._durability is not None:
                    self._durability.on_step_boundary()
                if fr is not None:
                    fr.end_step(idle=True)
                if self._alerts is not None:
                    # idle steps keep the cadence: a pool wedged so
                    # badly nothing admits must still reach an
                    # evaluation round
                    self._alerts.maybe_step()
                return bool(self._queue)
            wd = self._watchdog
            if wd is not None:
                wd.arm()
                t0_wd = time.perf_counter()
            try:
                out = self._resilience.run_step()
                if self._durability is not None:
                    self._durability.on_step_boundary()
            finally:
                # the armed window closes on EVERY exit — /readyz's
                # overdue probe must never read a completed (or
                # journal-fault-aborted) step as a live stall
                if wd is not None:
                    dt_wd = time.perf_counter() - t0_wd
                    wd.disarm()
            if wd is not None:
                if wd.classify(dt_wd):
                    # post-hoc hang verdict: the step DID complete (its
                    # tokens are emitted and journaled — recovery folds
                    # them, nothing re-emits), but an engine this slow
                    # is suspect: flip health to hung and hand the
                    # fatal HungStep to the recovery supervision
                    wd.on_hung(dt_wd)
        except StepFault as e:
            # a fault that survived the whole containment ladder is
            # escaping: leave the black box BEFORE the supervisor
            # tears this engine down.  A watchdog-ABANDONED engine
            # skips this — its recorder already dumped at abandonment
            # and its requests belong to the successor.
            if self._alerts is not None and not self._abandoned:
                # forced evaluation on the way out: health already
                # reads hung/the burn gauges already read the overload
                # that killed this step, so the fire transitions land
                # in the ring BEFORE note_fault seals and dumps it —
                # the post-mortem window then SHOWS the alerts firing
                # at death.  Best-effort: an alert bug must never
                # replace the StepFault the supervision is waiting for.
                try:
                    self._alerts.evaluate()
                except Exception:
                    pass
            if fr is not None and not self._abandoned:
                fr.note_fault(e)
            raise
        if self._profiling is not None:
            # stamp the step's probe onto the open record (and retire
            # one captured step) BEFORE the record seals
            self._profiling.note_step_end(fr)
        if fr is not None:
            rec = fr.end_step()
            if self._cost is not None and rec is not None:
                # score the sealed record's prediction against its
                # measured wall: EWMA calibration + error gauge +
                # roofline / periodic ledger gauges (the calibration
                # update site — engine thread, reads the record)
                self._cost.observe(rec)
            if self._profiling is not None and rec is not None:
                # device/host split, measured MFU, and the predicted-
                # vs-measured drift the mfu_regression rule watches
                self._profiling.observe(rec)
        if self._alerts is not None:
            # between-steps alert cadence (FLAGS_alert_interval_steps):
            # the engine thread walks the rule table AFTER the step's
            # record sealed, so every signal it reads is step-boundary
            # consistent and the hot path gained no locks
            self._alerts.maybe_step()
        return out

    def _step_inner(self) -> bool:
        """ONE batched device step over the already-admitted batch —
        the containment ladder's unit of retry (`step` wraps it; never
        call it from outside the ladder).  Dispatches to the
        speculative round, the mixed prefill+decode step, or the
        classic decode step exactly as `step` historically did."""
        from ..profiler import RecordEvent

        if self._fault is not None:
            # "slow_step" site: a deterministic injected stall (the
            # latency-fault class — SLO metrics see it, nothing raises)
            self._resilience.fault_point("slow_step")
        if self._spec is not None and self._resilience.spec_active():
            return self._spec.step()
        if self._chunked and self._prefilling_any():
            return self._mixed_step()
        if self._ragged:
            # ragged unified path: a chunkless step still dispatches
            # the ONE ragged executable (decode rows carry span 1), so
            # steady-state serving never touches _gpt_decode_step
            return self._mixed_step()
        self._grow_block_tables()

        fn = self._decode_fn
        if fn is None:
            if self._kv_quant:
                fn = self._decode_fn = _JitTracker(
                    functools.partial(_gpt_decode_step_q,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._sampling),
                    "decode_compiles", donate_argnums=(1, 2, 3, 4),
                    site="DecodeEngine decode step (_gpt_decode_step_q)")
            else:
                fn = self._decode_fn = _JitTracker(
                    functools.partial(_gpt_decode_step,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._sampling),
                    "decode_compiles", donate_argnums=(1, 2),
                    site="DecodeEngine decode step (_gpt_decode_step)")

        if self._fault is not None:
            self._resilience.step_fault_point("decode_step")
        self._step_no += 1
        key = jax.random.fold_in(
            self._key, _fold_counter(self._step_no, RNG_DECODE_DOMAIN))
        fr = self._flight
        self._flush_fresh_scales()
        t0 = time.perf_counter()
        t0_ns = _obs.now_ns()
        with RecordEvent("serving.decode_step"):
            with self._phase("decode"):
                if self._kv_quant:
                    (self._k_pages, self._v_pages, self._k_scales,
                     self._v_scales, toks) = fn(
                        self._params, self._k_pages, self._v_pages,
                        self._k_scales, self._v_scales,
                        jnp.asarray(self._bt), jnp.asarray(self._lens),
                        jnp.asarray(self._last),
                        jnp.asarray(self._active), key)
                else:
                    self._k_pages, self._v_pages, toks = fn(
                        self._params, self._k_pages, self._v_pages,
                        jnp.asarray(self._bt), jnp.asarray(self._lens),
                        jnp.asarray(self._last),
                        jnp.asarray(self._active), key)
                if self._profiling is not None:
                    # sampled device-sync probe: block on the step's
                    # output INSIDE the phase (the phase wall absorbs
                    # the wait) so dispatch-start -> ready is the
                    # executable's measured device seconds
                    self._profiling.probe("decode", toks, t0, t0_ns)
            toks = self._host_fetch(toks)
        if self._kv_quant:
            self._note_refolds(int(toks[-1]))
            toks = toks[:-1]
        dt = time.perf_counter() - t0
        if self._fault is not None:
            toks = self._resilience.corrupt_tokens(
                toks, [s for s in range(self._slots) if self._active[s]])

        n_active = int(self._active.sum())
        kv_util = self.pool.utilization()  # pre-finish, as historically
        emitted = 0
        self._observe_step(t0_ns, dt, n_active, "decode_step")

        with self._excl_phase("emit"):
            for slot in range(self._slots):
                if not self._active[slot]:
                    continue
                tok = int(toks[slot])
                req = self._by_slot[slot]
                if tok < 0:
                    # non-finite logits on this row: quarantine the
                    # slot only — the rest of the batch emitted
                    # healthy tokens
                    self._quarantine_slot(slot, "nan_logits")
                    continue
                self._lens[slot] += 1
                self._last[slot] = tok
                self._emit(req, [tok])
                emitted += 1
                self._register_generated_pages(slot, req)
                reason = self._done(req, tok)
                if reason:
                    self._finish(slot, reason)
        _stats_add(steps=1, decode_time_s=dt, tokens=emitted,
                   occupancy_sum=n_active / self._slots,
                   kv_util_sum=kv_util)
        return True

    def run(self, max_steps=100000):
        """Drive the loop until every queued/running request finishes.
        ``max_steps`` is a runaway backstop, not a truncation knob:
        exhausting it with work still pending raises instead of
        silently returning half-served requests (every step advances
        each active slot by at least one token, so a healthy serve
        always terminates on its own)."""
        steps = 0
        while self._queue or self._active.any():
            if steps >= max_steps:
                raise RuntimeError(
                    f"run(max_steps={max_steps}) exhausted with "
                    f"{len(self._queue)} queued and "
                    f"{int(self._active.sum())} running requests — "
                    f"raise the cap (or find the scheduling livelock)")
            self.step()
            steps += 1
        return steps

    def generate(self, prompts, max_new_tokens=32, return_meta=False):
        """Convenience batch API: submit all prompts, serve to
        completion, return one token list per prompt (in order).
        ``run()`` already drains the queue (and raises at its step cap
        rather than truncating), so one call is the whole serve.
        Outputs read ``generated_ids`` — stable even if the scheduler
        preempted and resumed a request mid-generation.
        ``return_meta=True`` additionally returns the per-request
        ``finish_reason`` list ("eos" | "length" | "evicted" | ...)."""
        reqs = [self.add_request(p, max_new_tokens) for p in prompts]
        self.run()
        outs = [list(r.generated_ids) for r in reqs]
        if return_meta:
            return outs, [r.finish_reason for r in reqs]
        return outs
