"""Durable serving: a write-ahead request journal + on-disk engine
snapshots that survive PROCESS death, executable handoff for fast
in-process rebuilds, and a hung-step watchdog.

PR 9 (`inference.resilience`) made the engine survive raising steps:
the containment ladder retries/degrades/quarantines, and a fatal fault
rebuilds the engine in-process with every request replayed.  Two holes
remained, and this module closes both plus a third failure class:

* **Process death** — an `EngineSnapshot` lived only in the dying
  process's memory, so a SIGKILL/OOM lost every in-flight request.
  With ``FLAGS_journal_dir`` armed, every admission, emitted-token
  watermark and finish is appended to a crc-framed write-ahead journal
  (``journal.wal``; fsync policy ``FLAGS_journal_fsync``), and every
  ``FLAGS_snapshot_interval_steps`` steps the engine's host state is
  serialized atomically to ``snapshot.json``.  `restore_from_dir`
  rebuilds an engine in a FRESH process: the snapshot supplies each
  in-flight request's generated-token values, the journal replays what
  came after, and every request re-admits through the PR 9 replay fold
  (generated tokens folded into the prompt) — greedy outputs are
  bit-identical to the uninterrupted run, and the journal's streamed
  watermark gates `DecodeEngine._emit` so a token a previous life
  already streamed is recomputed but NEVER re-fired at the stream.

* **Recompile-dominated recovery** — an in-process `recover` rebuilt
  every executable from scratch (recompile dominated recovery latency:
  BENCH_chaos hit TTFT x72 on CPU).  `DecodeEngine.adopt_executables`
  hands the dead engine's live compiled executables to the rebuilt
  engine when the config fingerprints match (identical shapes by
  construction, so the jit caches stay warm — no recompile, no warm
  retrace), falling back to recompile on any mismatch.  Cross-process
  restarts warm-start through JAX's persistent compilation cache
  (``FLAGS_compile_cache_dir``, `enable_compile_cache`).

* **Hung steps** — a step that RAISES rides the containment ladder; a
  step that simply never returns (device wedge, runtime deadlock) used
  to hang the serve forever.  `StepWatchdog` (``FLAGS_step_timeout_ms``)
  classifies a step that outran its wall-clock budget without
  compiling anything as hung, flips the ``paddle_engine_health`` gauge
  (live|degraded|recovering|hung) and raises a fatal `errors.HungStep`
  so the existing recovery supervision rebuilds the engine;
  `frontend.ServingFrontend._drive` additionally ABANDONS a worker
  thread still stuck past the budget and rebuilds from the pre-step
  snapshot with streams intact (tested deterministically through the
  PR 9 ``slow_step`` fault site).

With ``FLAGS_journal_dir`` unset and ``FLAGS_step_timeout_ms`` zero,
every hook on the serve path is a single ``is None`` check — serving
is bit-exact with the PR 9 engine (pinned by tests/test_durability.py).

See docs/RELIABILITY.md for the operator-facing walk-through.
"""
from __future__ import annotations

import itertools
import json
import os
import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import observability as _obs
from .errors import FaultInfo, HungStep

__all__ = ["RequestWire", "SnapshotWire", "DurabilityManager",
           "StepWatchdog", "read_journal", "load_snapshot",
           "restore_from_dir", "enable_compile_cache", "set_health",
           "clear_health", "retire_engine_series", "HEALTH_STATES",
           "JOURNAL_NAME", "SNAPSHOT_NAME", "KV_PAGES_NAME"]

JOURNAL_NAME = "journal.wal"
SNAPSHOT_NAME = "snapshot.json"
# FLAGS_snapshot_kv sidecar: the content-addressed (prefix-cached) KV
# page payloads — int8 + scales under FLAGS_kv_quant — serialized
# beside the snapshot so a restore installs them instead of
# recomputing the whole prompt history (see DurabilityManager)
KV_PAGES_NAME = "kv_pages.npz"


# ---------------------------------------------------------------------------
# Record framing: every journal record (and the snapshot file) is
# "<crc32 hex8> <compact json>\n" — a torn write fails the crc (or has
# no terminator) and the reader stops at the last consistent record
# instead of crashing or trusting garbage.
# ---------------------------------------------------------------------------
def _frame(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    return b"%08x %s\n" % (zlib.crc32(payload), payload)


def _parse_frames(data: bytes) -> Tuple[List[dict], int]:
    """(records, valid_byte_length): decode crc-framed lines, stopping
    at the first torn/corrupt one — everything before it is the last
    consistent state, everything after it is untrusted."""
    events: List[dict] = []
    pos = 0
    while pos < len(data):
        nl = data.find(b"\n", pos)
        if nl < 0:
            break  # unterminated tail record: torn write
        line = data[pos:nl]
        try:
            crc_hex, payload = line.split(b" ", 1)
            if int(crc_hex, 16) != zlib.crc32(payload):
                break
            events.append(json.loads(payload))
        except Exception:
            break
        pos = nl + 1
    return events, pos


def read_journal(path: str) -> Tuple[List[dict], int]:
    """All consistent records of a journal file plus the byte offset
    the last one ends at (a reopening writer truncates to it).  A
    missing file is an empty journal."""
    if not os.path.exists(path):
        return [], 0
    with open(path, "rb") as f:
        return _parse_frames(f.read())


# ---------------------------------------------------------------------------
# Wire forms.  `resilience.EngineSnapshot` holds live `Request` objects
# BY REFERENCE — correct in-process (streams/hooks survive a rebuild),
# wrong on disk (callbacks, engine backrefs and ns timestamps are not
# serializable state).  The wire form is the picklable/JSON-able split:
# original prompt, full generated values, original budget, and the
# streamed watermark — everything a fresh process needs to re-admit the
# request through the replay fold.
# ---------------------------------------------------------------------------
@dataclass
class RequestWire:
    """Serialization-safe form of one in-flight request.

    ``prompt`` is the ORIGINAL prompt (pre any preemption fold) and
    ``max_new`` the ORIGINAL budget, so the wire form is stable no
    matter how many times the live request was preempted or recovered.
    ``streamed`` is the emitted-token watermark: how many generated
    tokens a consumer has already seen — `materialize` turns the
    excess over ``len(generated)`` into an ``_emit_gate`` so replay
    recomputes those tokens without ever re-firing ``on_token``."""

    request_id: int
    prompt: List[int]
    generated: List[int]
    max_new: int
    streamed: int
    eos: Optional[int] = None
    priority: Optional[int] = None
    deadline_ms: Optional[float] = None
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # fleet-scope trace id (observability.fleettrace): persisted so a
    # failover adoption keeps the donor's trace — the one piece of
    # request identity that must survive the process boundary
    trace: Optional[str] = None

    @classmethod
    def from_request(cls, req) -> "RequestWire":
        gen = list(req.generated_ids)
        return cls(
            request_id=req.request_id,
            prompt=list(req.prompt_ids[:req.orig_prompt_len]),
            generated=gen,
            max_new=req.max_new_tokens + req._absorbed,
            streamed=len(gen) + req._emit_gate,
            eos=req.eos_token_id, priority=req.priority,
            deadline_ms=req.deadline_ms, slo_ttft_ms=req.slo_ttft_ms,
            slo_tpot_ms=req.slo_tpot_ms,
            trace=getattr(req, "trace_id", None))

    @classmethod
    def from_record(cls, rec) -> "RequestWire":
        """From a `resilience._ReqRecord` (state AT CAPTURE, not the
        live request, which may have advanced since)."""
        req = rec.request
        gen = list(rec.prompt_ids[rec.orig_len:]) + list(rec.output_ids)
        return cls(
            request_id=req.request_id,
            prompt=list(rec.prompt_ids[:rec.orig_len]),
            generated=gen,
            max_new=rec.max_new + rec.absorbed,
            streamed=rec.streamed,
            eos=req.eos_token_id, priority=req.priority,
            deadline_ms=req.deadline_ms, slo_ttft_ms=req.slo_ttft_ms,
            slo_tpot_ms=req.slo_tpot_ms,
            trace=getattr(req, "trace_id", None))

    def to_obj(self) -> dict:
        obj = {"id": self.request_id, "p": self.prompt,
               "g": self.generated, "mn": self.max_new,
               "sm": self.streamed, "eos": self.eos,
               "pr": self.priority, "dl": self.deadline_ms,
               "tt": self.slo_ttft_ms, "tp": self.slo_tpot_ms}
        if self.trace is not None:
            # conditional so pre-fleet-trace journals stay byte-stable
            obj["tr"] = self.trace
        return obj

    @classmethod
    def from_obj(cls, obj: dict) -> "RequestWire":
        return cls(request_id=int(obj["id"]), prompt=list(obj["p"]),
                   generated=list(obj["g"]), max_new=int(obj["mn"]),
                   streamed=int(obj["sm"]), eos=obj.get("eos"),
                   priority=obj.get("pr"), deadline_ms=obj.get("dl"),
                   slo_ttft_ms=obj.get("tt"), slo_tpot_ms=obj.get("tp"),
                   trace=obj.get("tr"))

    def materialize(self):
        """A fresh `Request` carrying this wire state, re-admittable
        through the replay fold: generated tokens folded into the
        prompt (budget shrinks one for one), the streamed watermark
        turned into an emit gate, the original request id restored."""
        from .serving import Request

        req = Request(
            list(self.prompt) + list(self.generated),
            max_new_tokens=self.max_new - len(self.generated),
            eos_token_id=self.eos, priority=self.priority,
            deadline_ms=self.deadline_ms, slo_ttft_ms=self.slo_ttft_ms,
            slo_tpot_ms=self.slo_tpot_ms)
        req.orig_prompt_len = len(self.prompt)
        req._absorbed = len(self.generated)
        req._emit_gate = max(0, self.streamed - len(self.generated))
        req.request_id = self.request_id
        if self.trace is not None:
            req.trace_id = self.trace
        return req


@dataclass
class SnapshotWire:
    """Serialization-safe form of a whole `EngineSnapshot`:
    ``journal_pos`` anchors it in the journal (replay resumes at that
    record index), the RNG fold counters carry the sampling streams,
    and ``records`` hold every in-flight request in admission order."""

    engine_id: int
    step_no: int
    prefill_no: int
    journal_pos: int
    records: List[RequestWire] = field(default_factory=list)
    # FLAGS_snapshot_kv: metadata anchoring the kv_pages sidecar —
    # file name, crc of its bytes, chain hashes (hex) in array order,
    # and the storage dtype.  None = no sidecar (flag off, no cached
    # pages, or a pre-sidecar snapshot); restore then recomputes
    kv: Optional[dict] = None
    # cost-observatory calibration (observability.costmodel): the
    # per-executable EWMA factors as of the snapshot, so a restored
    # engine predicts step cost warm instead of re-learning from 1.0.
    # None = pre-observatory snapshot or cost model off
    cost: Optional[dict] = None

    def to_obj(self) -> dict:
        obj = {"v": 1, "engine_id": self.engine_id,
               "step_no": self.step_no, "prefill_no": self.prefill_no,
               "journal_pos": self.journal_pos,
               "records": [r.to_obj() for r in self.records]}
        if self.kv is not None:
            obj["kv"] = self.kv
        if self.cost is not None:
            obj["cost"] = self.cost
        return obj

    @classmethod
    def from_obj(cls, obj: dict) -> "SnapshotWire":
        return cls(engine_id=int(obj["engine_id"]),
                   step_no=int(obj["step_no"]),
                   prefill_no=int(obj["prefill_no"]),
                   journal_pos=int(obj["journal_pos"]),
                   records=[RequestWire.from_obj(r)
                            for r in obj["records"]],
                   kv=obj.get("kv"), cost=obj.get("cost"))


def load_snapshot(journal_dir: str) -> Optional[SnapshotWire]:
    """The on-disk snapshot, or None when absent OR torn/corrupt — a
    restore then falls back to replaying the whole journal (the last
    consistent state is never worse than no snapshot)."""
    path = os.path.join(journal_dir, SNAPSHOT_NAME)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        records, _ = _parse_frames(f.read())
    if len(records) != 1:
        return None  # torn/corrupt snapshot: journal-only restore
    try:
        return SnapshotWire.from_obj(records[0])
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Engine health (the watchdog's gauge).  One-hot per engine so a
# dashboard can alert on `paddle_engine_health{state="hung"} == 1`;
# every transition also lands as a `health:*` engine span so the
# sequence (live -> hung -> recovering -> live) is reconstructable.
# ---------------------------------------------------------------------------
HEALTH_STATES = ("live", "degraded", "recovering", "hung")

# current state per engine id: set_health only touches the series a
# transition actually involves (a healthy engine is ONE series, not
# four — engine ids are unbounded and the registry caps cardinality)
_health_state: Dict[int, str] = {}


def set_health(engine_id: int, state: str, span: bool = True):
    """Flip one engine's ``paddle_engine_health`` gauge.  ``span=False``
    records the INITIAL state at construction without a transition
    span, so the span stream reads as the actual transition sequence
    (live -> hung -> recovering -> live) with no construction blips."""
    if state not in HEALTH_STATES:
        raise ValueError(f"unknown health state {state!r}")
    prev = _health_state.get(engine_id)
    if prev == state:
        return
    _health_state[engine_id] = state
    if prev is not None:
        _obs.ENGINE_HEALTH.set(0, engine=engine_id, state=prev)
    _obs.ENGINE_HEALTH.set(1, engine=engine_id, state=state)
    if span:
        _obs.record_span("engine", f"health:{state}", _obs.now_ns(), 0,
                         tid=engine_id)


def clear_health(engine_id: int):
    """Retire an engine from the health gauge: its last state series
    drops to 0 and no state reads 1.  Recovery calls this for the DEAD
    engine — without it a successfully recovered hang would leave
    ``paddle_engine_health{state="hung"} == 1`` (the documented alert
    condition) latched forever on the retired id."""
    prev = _health_state.pop(engine_id, None)
    if prev is not None:
        _obs.ENGINE_HEALTH.set(0, engine=engine_id, state=prev)


def retire_engine_series(engine_id: int) -> int:
    """Retire a DEAD engine's ENTIRE per-engine gauge catalog — the
    whole-catalog generalization of `clear_health`: pool/occupancy/
    queue gauges, degraded-mode and health one-hots, flight
    throughput/goodput/burn gauges.  `resilience.recover` calls this
    for the engine it replaced and `DecodeEngine._abandon_inflight`
    for the engine the watchdog abandoned, so a retired engine id
    leaves the scrape surface (and `statusz` output) instead of
    reading stale levels forever.  Engine ids are never reused
    (`DecodeEngine._next_engine_id` is monotonic), so nothing can race
    a retirement back to life.  Returns the series count removed."""
    clear_health(engine_id)
    # the ops plane's registry retires with the gauges: a dead
    # generation must leave /statusz, /healthz and /readyz the same
    # moment it leaves the scrape surface (recover / restore / abandon
    # all funnel through here)
    from ..observability import opsserver, profiling

    opsserver.deregister_engine(engine_id)
    # likewise the profiling plane's capture registry: request_capture
    # must never arm a session on a retired generation (its
    # paddle_host_overhead_ratio series retires with the label sweep
    # below)
    profiling.deregister(engine_id)
    return _obs.registry.retire_label("engine", engine_id)


# ---------------------------------------------------------------------------
# The write-ahead journal + periodic snapshots
# ---------------------------------------------------------------------------
class DurabilityManager:
    """Owns one engine's journal file and snapshot cadence.

    Record types (crc-framed JSON lines):

    * ``cfg`` — written once when the journal is created: the engine's
      serializable constructor config + config fingerprint (restore
      validates the rebuilding model against it);
    * ``a`` — admission: the request's identity + prompt + budget;
    * ``e`` — emitted-token watermark: total generated tokens the
      stream has consumed for one request.  WRITE-AHEAD: appended (and,
      under ``journal_fsync=always``, fsynced) BEFORE the ``on_token``
      callback fires, so a token the consumer saw is always covered by
      a durable watermark — restore can suppress it, never re-emit it;
    * ``f`` — finish: request id + finish reason.

    Thread discipline: every hook runs on the thread driving the
    engine (the engine is single-threaded by contract; the frontend
    applies control between steps), so the buffer needs no lock.
    Reopening an existing journal truncates a torn tail record first —
    appends after a crash stay parseable."""

    def __init__(self, engine, journal_dir: str, fsync=None,
                 snapshot_interval=None, snapshot_kv=None):
        from ..core import flags as _flags

        self.engine = engine
        self.journal_dir = str(journal_dir)
        os.makedirs(self.journal_dir, exist_ok=True)
        self.snapshot_kv = bool(
            _flags.flag("snapshot_kv") if snapshot_kv is None
            else snapshot_kv)
        self.fsync = str(fsync if fsync is not None
                         else _flags.flag("journal_fsync"))
        if self.fsync not in ("always", "step", "never"):
            raise ValueError(
                f"journal_fsync must be one of always|step|never, got "
                f"{self.fsync!r}")
        self.snapshot_interval = int(
            snapshot_interval if snapshot_interval is not None
            else _flags.flag("snapshot_interval_steps"))
        self.path = os.path.join(self.journal_dir, JOURNAL_NAME)
        events, valid_len = read_journal(self.path)
        self.seq = len(events)
        if os.path.exists(self.path) and \
                os.path.getsize(self.path) > valid_len:
            with open(self.path, "r+b") as f:
                f.truncate(valid_len)
        self._fh = open(self.path, "ab")
        self._buf: List[bytes] = []
        self._steps_since_snapshot = 0
        if self.seq == 0:
            self.append({"t": "cfg", "v": 1,
                         "fp": engine.config_fingerprint().hex(),
                         "cfg": engine.wire_config()})

    # -- record appends ------------------------------------------------------
    def append(self, obj: dict):
        from .serving import _stats_add

        line = _frame(obj)
        self.seq += 1
        _stats_add(journal_records=1)
        if self.fsync == "always":
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        else:
            self._buf.append(line)

    def flush(self):
        if not self._buf:
            return
        self._fh.write(b"".join(self._buf))
        self._buf = []
        self._fh.flush()
        if self.fsync == "step":
            os.fsync(self._fh.fileno())

    # -- engine hooks --------------------------------------------------------
    def on_admit(self, req):
        # journal the ORIGINAL identity (pre-replay-fold prompt,
        # original budget) — identical for a fresh request, and for a
        # MATERIALIZED one (fleet adoption via `admit_restored`) it
        # keeps this journal's own replay correct: the folded prompt
        # would double-count the generated tokens the emitted-token
        # watermark already covers
        eos = req.eos_token_id
        rec = {"t": "a", "id": req.request_id,
               "p": list(req.prompt_ids[:req.orig_prompt_len]),
               "mn": int(req.max_new_tokens + req._absorbed),
               "eos": None if eos is None else int(eos),
               "pr": req.priority, "dl": req.deadline_ms,
               "tt": req.slo_ttft_ms, "tp": req.slo_tpot_ms}
        if getattr(req, "trace_id", None) is not None:
            # fleet trace id rides the admission record (conditional:
            # trace-less journals stay byte-identical) so an adopting
            # engine can stitch donor + adopter spans into one trace
            rec["tr"] = req.trace_id
        self.append(rec)

    def on_emit(self, req):
        # streamed watermark = generated + still-gated (a gated token
        # was streamed by a previous life): monotonic across restores
        self.append({"t": "e", "id": req.request_id,
                     "n": req._absorbed + len(req.output_ids) +
                     req._emit_gate})

    def on_finish(self, req):
        self.append({"t": "f", "id": req.request_id,
                     "r": req.finish_reason})

    def on_step_boundary(self):
        """Between-steps housekeeping (engine idle): flush per the
        fsync policy, write the periodic snapshot."""
        self.flush()
        if self.snapshot_interval > 0:
            self._steps_since_snapshot += 1
            if self._steps_since_snapshot >= self.snapshot_interval:
                self._steps_since_snapshot = 0
                self.write_snapshot()

    def write_snapshot(self):
        """Serialize the engine's between-steps host state atomically:
        write to a temp file, fsync, `os.replace` — a crash mid-write
        leaves the PREVIOUS snapshot intact, never a torn current one.

        With ``FLAGS_snapshot_kv`` (default on) the content-addressed
        KV page payloads write FIRST into their own atomically-replaced
        sidecar; the snapshot record then anchors the sidecar by crc,
        so a crash between the two writes (stale sidecar, new
        snapshot? impossible — snapshot references the NEW crc; new
        sidecar, old snapshot? the old snapshot's crc no longer
        matches) degrades to recompute, never to serving stale KV."""
        from .resilience import EngineSnapshot
        from .serving import _stats_add

        wire = EngineSnapshot(self.engine).to_wire(journal_pos=self.seq)
        if self.snapshot_kv:
            wire.kv = self._write_kv_sidecar()
        if self.engine._cost is not None:
            wire.cost = self.engine._cost.calibration_wire()
        data = _frame(wire.to_obj())
        path = os.path.join(self.journal_dir, SNAPSHOT_NAME)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _stats_add(journal_snapshots=1)

    def _write_kv_sidecar(self) -> Optional[dict]:
        """Gather every content-addressed (prefix-cached) page's K/V
        payload — and its quant scales when the pool is int8
        (FLAGS_kv_quant) — off the device and write them crash-safely
        beside the snapshot.  Returns the anchor metadata the snapshot
        record carries, or None when there is nothing to serialize
        (prefix cache off / no cached pages yet).  Quantized pools
        serialize int8 bytes + f32 scales: roughly a quarter of the
        fp32 sidecar for the same pages — the snapshot-byte and
        restore-I/O halving tools/bench_kv_quant.py pins."""
        import io

        import numpy as np

        eng = self.engine
        if not eng._prefix_cache or not eng.pool._page_hash:
            return None
        if eng._spec is not None and \
                getattr(eng._spec.drafter, "stateful", False):
            # mirror of _install_kv_sidecar's guard: the restore side
            # always refuses a target-pool-only sidecar when a stateful
            # draft-model drafter needs the recompute to repopulate its
            # own cache — don't pay the device fetch + fsync for bytes
            # that can never install
            return None
        import jax

        items = sorted(eng.pool._page_hash.items())  # (page, hash)
        ids = np.asarray([p for p, _ in items], np.int32)
        arrays = {
            "k": np.asarray(jax.device_get(eng._k_pages[:, :, ids])),
            "v": np.asarray(jax.device_get(eng._v_pages[:, :, ids])),
        }
        if eng._kv_quant:
            arrays["ks"] = np.asarray(
                jax.device_get(eng._k_scales[:, :, ids]))
            arrays["vs"] = np.asarray(
                jax.device_get(eng._v_scales[:, :, ids]))
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        payload = buf.getvalue()
        path = os.path.join(self.journal_dir, KV_PAGES_NAME)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return {"file": KV_PAGES_NAME, "crc": zlib.crc32(payload),
                "hashes": [h.hex() for _, h in items],
                "dtype": str(eng._k_pages.dtype),
                "page": int(eng._page), "bytes": len(payload)}

    def close(self):
        self.flush()
        self._fh.close()


# ---------------------------------------------------------------------------
# Fresh-process restore
# ---------------------------------------------------------------------------
def _install_kv_sidecar(journal_dir: str, snap: SnapshotWire,
                        eng) -> int:
    """Load the snapshot's KV sidecar (FLAGS_snapshot_kv) into the
    rebuilt engine's pool: allocate a page per serialized payload,
    scatter the payloads (and quant scales) into the device arrays,
    and register each page under its chain hash at refcount 0 (parked
    on the eviction LRU, exactly as a warm-but-idle cache would hold
    it).  Replay re-admission then prefix-hits these pages instead of
    recomputing the token history they encode — the payloads ARE the
    dead engine's bytes, so quantized pools restore their int8 values
    and scales exactly.

    Defensive by construction: any anchor mismatch (missing/torn file,
    crc fail, dtype or geometry drift) skips the install and restore
    recomputes everything — never worse than the pre-sidecar behavior.
    Returns the number of pages installed."""
    import numpy as np

    meta = snap.kv
    if not meta or not eng._prefix_cache:
        return 0
    if eng._spec is not None and \
            getattr(eng._spec.drafter, "stateful", False):
        # a draft-MODEL drafter keeps its own K/V for the same page
        # ids, and the sidecar only carries the target pool: installing
        # would let replay prefix-hit pages whose DRAFT cache is still
        # zeros — outputs stay correct (verify is authoritative) but
        # acceptance would silently collapse after every restore.  Full
        # recompute feeds the drafter through ingest_chunks exactly as
        # the pre-sidecar path did; serializing the draft pool too is
        # the future upgrade.
        return 0
    path = os.path.join(journal_dir, os.path.basename(
        str(meta.get("file", KV_PAGES_NAME))))
    if not os.path.exists(path):
        return 0
    with open(path, "rb") as f:
        payload = f.read()
    if zlib.crc32(payload) != int(meta.get("crc", -1)):
        return 0  # torn/stale sidecar: recompute instead
    if str(meta.get("dtype")) != str(eng._k_pages.dtype) or \
            int(meta.get("page", -1)) != int(eng._page):
        return 0  # config drift (should be impossible past the
        #         # fingerprint check, but never install wrong bytes)
    import io

    try:
        data = np.load(io.BytesIO(payload))
        k, v = data["k"], data["v"]
    except Exception:
        return 0
    if eng._kv_quant and not ("ks" in data.files and
                              "vs" in data.files):
        # an int8 sidecar without BOTH scale arrays is inconsistent
        # (crc proves the bytes, not the key set): installing would
        # either crash on the missing key or dequantize cached KV
        # with zero scales — fall back to recompute instead
        return 0
    hashes = [bytes.fromhex(h) for h in meta.get("hashes", [])]
    if k.shape[2] != len(hashes) or \
            k.shape[:2] + k.shape[3:] != (eng._num_layers,
                                          eng._num_heads, eng._page,
                                          eng._head_dim):
        return 0
    n = min(len(hashes), eng.pool.free_count)
    if n == 0:
        return 0
    import jax.numpy as jnp

    # raw pool allocs (not the engine's fresh-marking wrapper): the
    # installed pages carry LIVE scales that the between-steps scale
    # reset must not zero
    ids = [eng.pool.alloc_page() for _ in range(n)]
    idx = jnp.asarray(np.asarray(ids, np.int32))
    eng._k_pages = eng._k_pages.at[:, :, idx].set(
        jnp.asarray(k[:, :, :n]))
    eng._v_pages = eng._v_pages.at[:, :, idx].set(
        jnp.asarray(v[:, :, :n]))
    if eng._kv_quant:
        eng._k_scales = eng._k_scales.at[:, :, idx].set(
            jnp.asarray(data["ks"][:, :, :n]))
        eng._v_scales = eng._v_scales.at[:, :, idx].set(
            jnp.asarray(data["vs"][:, :, :n]))
    if getattr(eng, "_mesh", None) is not None:
        # the host-side scatter above ran OUTSIDE the step executables
        # and may have left the pool with whatever sharding GSPMD
        # propagated; re-pin the head-axis layout so the first step
        # after restore sees the exact input shardings it compiled
        # against (a drifted sharding would be a warm retrace)
        import jax

        eng._k_pages = jax.device_put(eng._k_pages, eng._page_sharding)
        eng._v_pages = jax.device_put(eng._v_pages, eng._page_sharding)
        if eng._kv_quant:
            eng._k_scales = jax.device_put(eng._k_scales,
                                           eng._scale_sharding)
            eng._v_scales = jax.device_put(eng._v_scales,
                                           eng._scale_sharding)
    installed = 0
    for pid, key in zip(ids, hashes[:n]):
        if eng.pool.register_page(pid, key):
            eng.pool.unref_page(pid)  # refcount 0: retained, evictable
            installed += 1
        else:  # duplicate hash (cannot happen from one pool) — drop
            eng.pool.free_pages([pid])
    return installed


def _journal_state(journal_dir: str):
    """Resolve ``journal_dir``'s last consistent state:
    ``(cfg_rec, snap, state, finished, events)`` — the shared front
    half of `restore_from_dir`, `adopt_from_dir` and
    `compact_journal`.  ``state`` maps each in-flight request id to
    its `RequestWire` (snapshot values with the journal tail replayed
    on top), ``finished`` maps retired ids to their finish reason."""
    path = os.path.join(journal_dir, JOURNAL_NAME)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no serve journal at {path}")
    events, _ = read_journal(path)
    if not events or events[0].get("t") != "cfg":
        raise ValueError(
            f"{path} has no config record — not a serve journal")
    cfg_rec = events[0]
    snap = load_snapshot(journal_dir)

    state: "OrderedDict[int, RequestWire]" = OrderedDict()
    finished: Dict[int, str] = {}
    start = 1  # past the cfg record
    if snap is not None:
        for w in snap.records:
            state[w.request_id] = w
        # a snapshot can never be AHEAD of the consistent journal
        # prefix unless the journal lost a torn tail — the snapshot is
        # still authoritative for everything it saw
        start = min(max(snap.journal_pos, 1), len(events))
    for ev in events[start:]:
        t = ev.get("t")
        if t == "a":
            state.setdefault(int(ev["id"]), RequestWire(
                request_id=int(ev["id"]), prompt=list(ev["p"]),
                generated=[], max_new=int(ev["mn"]), streamed=0,
                eos=ev.get("eos"), priority=ev.get("pr"),
                deadline_ms=ev.get("dl"), slo_ttft_ms=ev.get("tt"),
                slo_tpot_ms=ev.get("tp"), trace=ev.get("tr")))
        elif t == "e":
            w = state.get(int(ev["id"]))
            if w is not None:
                w.streamed = max(w.streamed, int(ev["n"]))
        elif t == "f":
            state.pop(int(ev["id"]), None)
            finished[int(ev["id"])] = ev.get("r", "")
    return cfg_rec, snap, state, finished, events


def _next_id_floor(cfg_rec, state, finished) -> int:
    """The smallest request id a new life may issue: past every id the
    journal still names AND past the high-water a previous compaction
    recorded (``nid`` — compaction drops finished ids from the
    journal, so without the floor a thrice-restored serve could reuse
    an id a dead life already streamed under)."""
    return max([rid + 1 for rid in (*state, *finished)] +
               [int(cfg_rec.get("nid", 0))], default=0)


def _compact_resolved(journal_dir: str, cfg_rec, snap, state,
                      finished, events) -> dict:
    """Rewrite the journal (and re-anchor the snapshot) down to the
    already-resolved live state.  The compacted journal carries the
    cfg record (plus the ``nid`` id high-water) and, per in-flight
    request, one admission + one watermark — every finished request
    and superseded watermark drops.  Both files replace atomically
    (temp + fsync + `os.replace`): a crash mid-compaction leaves the
    previous consistent pair.  ``snap`` is re-anchored IN PLACE
    (``journal_pos``/``records``) so a caller holding it keeps a view
    consistent with the file.  Returns the size-before/after stats."""
    path = os.path.join(journal_dir, JOURNAL_NAME)
    bytes_before = os.path.getsize(path)
    cfg = dict(cfg_rec)
    cfg["nid"] = _next_id_floor(cfg_rec, state, finished)
    frames = [_frame(cfg)]
    for w in state.values():
        adm = {"t": "a", "id": w.request_id, "p": list(w.prompt),
               "mn": int(w.max_new), "eos": w.eos, "pr": w.priority,
               "dl": w.deadline_ms, "tt": w.slo_ttft_ms,
               "tp": w.slo_tpot_ms}
        if w.trace is not None:
            adm["tr"] = w.trace
        frames.append(_frame(adm))
        if w.streamed:
            frames.append(_frame({"t": "e", "id": w.request_id,
                                  "n": int(w.streamed)}))
    data = b"".join(frames)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if snap is not None:
        # the snapshot's journal_pos anchored into the OLD journal;
        # re-anchor it to the compacted one (records = the post-tail-
        # replay state, strictly newer than what it held) — without
        # this the next restore would mis-align replay
        snap.journal_pos = len(frames)
        snap.records = list(state.values())
        spath = os.path.join(journal_dir, SNAPSHOT_NAME)
        stmp = spath + ".tmp"
        with open(stmp, "wb") as f:
            f.write(_frame(snap.to_obj()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(stmp, spath)
    from .serving import _stats_add

    _stats_add(journal_compactions=1)
    return {"bytes_before": int(bytes_before),
            "bytes_after": len(data),
            "records_before": len(events),
            "records_after": len(frames)}


def compact_journal(journal_dir: str) -> dict:
    """Compact ``journal_dir``'s write-ahead journal to its live
    state (see `_compact_resolved`); standalone entry for tools and
    tests — `restore_from_dir` compacts inline under
    ``FLAGS_journal_compact``."""
    cfg_rec, snap, state, finished, events = _journal_state(journal_dir)
    return _compact_resolved(journal_dir, cfg_rec, snap, state,
                             finished, events)


def restore_from_dir(journal_dir: str, model, scheduler=None,
                     drafter=None, journal: bool = True,
                     compact: Optional[bool] = None, **overrides):
    """Rebuild an engine in a FRESH process from ``journal_dir`` and
    re-admit every request that was in flight when the previous process
    died.  Returns ``(engine, requests)`` — ``requests`` maps each
    journaled request id to its rebuilt `Request` (re-attach
    ``on_token`` hooks there before driving the engine).

    The caller supplies the ``model`` (weights are not journaled); the
    journal's config record supplies every other constructor argument
    and a config fingerprint the rebuilt engine is validated against —
    a different model or config raises instead of silently serving
    garbage.  State resolution: the newest VALID snapshot supplies
    generated-token values and RNG fold counters; journal records after
    its ``journal_pos`` replay admissions / watermarks / finishes on
    top.  A torn tail record or torn snapshot simply falls back to the
    last consistent state — never a crash, and the emitted-token
    watermarks guarantee a previously streamed token is never re-fired
    at a stream (it is recomputed behind the `_emit` gate; greedy
    recompute is bit-identical, which is what the acceptance bench
    pins).

    ``journal=True`` (default) keeps journaling into the same
    directory, so the restored serve survives a SECOND death.
    ``compact`` (default ``FLAGS_journal_compact``) rewrites the
    journal down to its live state BEFORE the rebuilt engine reopens
    it, so a serve that restores repeatedly starts each life from a
    bounded file instead of an ever-growing one.
    ``overrides`` replace individual engine kwargs (tests/benches)."""
    from ..core import flags as _flags
    from .serving import DecodeEngine, Request, _stats_add

    cfg_rec, snap, state, finished, events = _journal_state(journal_dir)
    if compact is None:
        compact = bool(_flags.flag("journal_compact"))
    comp = None
    if journal and compact:
        # BEFORE engine construction: the DurabilityManager the engine
        # builds reopens (and appends to) the compacted file
        comp = _compact_resolved(journal_dir, cfg_rec, snap, state,
                                 finished, events)

    kw = dict(cfg_rec["cfg"])
    if kw.get("dtype") is not None:
        import jax.numpy as jnp

        kw["dtype"] = jnp.dtype(kw["dtype"])
    kw.update(overrides)
    if scheduler is not None:
        kw["scheduler"] = scheduler
    if drafter is not None:
        kw["drafter"] = drafter
    eng = DecodeEngine(model,
                       journal_dir=(journal_dir if journal else None),
                       **kw)
    fp = cfg_rec.get("fp")
    if fp and eng.config_fingerprint().hex() != fp:
        raise ValueError(
            "journal config fingerprint does not match the rebuilt "
            "engine — wrong model weights or construction config")
    if snap is not None:
        # RNG fold counters continue where the dead engine's stopped
        # (greedy ignores them; stochastic streams must not restart)
        eng._step_no = snap.step_no
        eng._prefill_no = snap.prefill_no
        if snap.cost and eng._cost is not None:
            # snapshot calibration is NEWER than the cfg record's
            # (written once at journal creation): the restored
            # predictor starts from the dead engine's learned factors
            eng._cost.load_calibration(snap.cost)
    # install the serialized prefix-cache payloads (FLAGS_snapshot_kv)
    # BEFORE re-admission queues anything: the replay fold's admission
    # probe then maps the installed pages at refcount+1 and recomputes
    # only the uncached tail — same outputs, a fraction of the compute
    installed_pages = _install_kv_sidecar(journal_dir, snap, eng) \
        if snap is not None else 0

    # journaled ids key the watermarks: new requests in this process
    # must never collide with them (nor with ids a previous
    # compaction dropped — the cfg record's ``nid`` high-water)
    Request._next_id = itertools.count(
        max(_next_id_floor(cfg_rec, state, finished),
            next(Request._next_id)))

    t0 = _obs.now_ns()
    reqs: Dict[int, "object"] = {}
    for rid, w in state.items():
        req = w.materialize()
        if w.max_new - len(w.generated) <= 0:
            # fully generated but the finish record was lost with the
            # torn tail: terminal, nothing to recompute or re-emit
            req.state = "done"
            req.finish_reason = "length"
        else:
            req._engine = eng
            req.t_enqueue_ns = _obs.now_ns()
            if req.deadline_ms is not None:
                req._deadline_ns = req.t_enqueue_ns + \
                    int(req.deadline_ms * 1e6)
            req.fault_info = FaultInfo(
                site="restore", step=snap.step_no if snap else 0,
                recovered=True,
                message="restored from the on-disk journal after "
                        "process death")
            eng._queue.append(req)
        reqs[rid] = req
    _stats_add(restores=1)
    _obs.record_span(
        "engine", "restore", t0, _obs.now_ns() - t0,
        tid=eng._engine_id,
        args={"requests": len(reqs), "journal_events": len(events),
              "snapshot": snap is not None,
              "kv_pages_installed": installed_pages,
              **({"compacted_bytes": comp["bytes_after"],
                  "journal_bytes_before": comp["bytes_before"]}
                 if comp else {})})
    if eng._flight is not None:
        eng._flight.event("restore", requests=len(reqs),
                          journal_events=len(events),
                          snapshot=snap is not None)
    return eng, reqs


def adopt_from_dir(journal_dir: str, engine,
                   delivered: Optional[Dict[int, int]] = None,
                   on_token_factory=None,
                   traces: Optional[Dict[int, str]] = None):
    """Fleet failover: replay a DEAD sibling replica's journal into a
    LIVE survivor ``engine`` (contrast `restore_from_dir`, which
    builds a fresh engine around the journal).  Every in-flight
    request materializes through the replay fold and re-admits via
    `DecodeEngine.admit_restored` — fresh ids (the donor's id space
    may collide with the survivor's), validated, and re-journaled
    into the SURVIVOR's journal so a second death loses nothing.

    ``delivered`` maps donor request ids to the number of generated
    tokens the consumer of record actually received.  The journal's
    streamed watermark is written AHEAD of the socket, so a replica
    can die having journaled a token nobody got: tokens past
    ``delivered`` re-deliver — snapshot-known values return
    immediately as ``backfill``, the rest recompute live — while
    everything at or below it stays behind the emit gate and is never
    re-fired.  Omitted ids (or ``delivered=None``) trust the journal
    watermark, the lossless-but-maybe-duplicating default.

    ``on_token_factory(donor_id)`` (optional) returns the ``on_token``
    hook to attach per adopted request.  ``traces`` (optional) maps
    donor ids to fleet trace ids — a fallback for journals written
    before FLAGS_fleet_trace was on; the journal's own ``tr`` record
    wins when present.  Returns ``(requests, meta)`` keyed by DONOR
    ids: ``requests`` the materialized `Request`s (the survivor's
    fresh ids are on them), ``meta`` per-request ``{"request_id",
    "start_index", "backfill", "done"}`` (plus ``"trace"`` when the
    request carries one) — the resume contract the fleet edge serves
    to reconnecting streams."""
    from .serving import _stats_add

    cfg_rec, snap, state, finished, events = _journal_state(journal_dir)
    fp = cfg_rec.get("fp")
    if fp and engine.config_fingerprint().hex() != fp:
        raise ValueError(
            "journal config fingerprint does not match the adopting "
            "engine — fleet replicas must share model weights and "
            "construction config for zero-loss failover")
    delivered = dict(delivered or {})
    t0 = _obs.now_ns()
    reqs: Dict[int, "object"] = {}
    meta: Dict[int, dict] = {}
    for rid, w in state.items():
        d = delivered.get(rid, w.streamed)
        d = max(0, min(int(d), w.streamed))
        # generated values the snapshot preserved past the delivered
        # point need no recompute: hand them straight back
        backfill = [int(t) for t in w.generated[d:]]
        req = w.materialize()
        if req.trace_id is None and traces and rid in traces:
            # router-supplied fallback (observability.fleettrace): a
            # journal written before FLAGS_fleet_trace was flipped has
            # no "tr" record, but the router still knows the stream's
            # trace id — the adoption keeps it either way
            req.trace_id = str(traces[rid])
        # the router's delivered count supersedes the journal
        # watermark: gate exactly what the consumer saw
        req._emit_gate = max(0, d - len(w.generated))
        done = w.max_new - len(w.generated) <= 0
        if done:
            # fully generated before death (finish record lost):
            # terminal — the backfill above is the whole undelivered
            # tail, nothing to recompute
            req.state = "done"
            req.finish_reason = "length"
        else:
            req.fault_info = FaultInfo(
                site="failover", step=snap.step_no if snap else 0,
                recovered=True,
                message="adopted from a dead replica's journal")
            on_token = on_token_factory(rid) if on_token_factory \
                else None
            engine.admit_restored(req, on_token=on_token)
        reqs[rid] = req
        meta[rid] = {"request_id": int(req.request_id),
                     "start_index": int(d), "backfill": backfill,
                     "done": bool(done)}
        if req.trace_id is not None:
            meta[rid]["trace"] = req.trace_id
    _stats_add(adoptions=1)
    _obs.record_span(
        "engine", "adopt", t0, _obs.now_ns() - t0,
        tid=engine._engine_id,
        args={"requests": len(reqs), "journal_events": len(events),
              "donor": journal_dir})
    if engine._flight is not None:
        engine._flight.event("adopt", requests=len(reqs),
                             donor=journal_dir)
    return reqs, meta


# ---------------------------------------------------------------------------
# JAX persistent compilation cache (cross-process executable warm start)
# ---------------------------------------------------------------------------
_compile_cache_applied: Optional[str] = None


def enable_compile_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` so a
    fresh process's executables deserialize from disk instead of
    recompiling (the cross-process half of fast recovery; in-process
    recovery uses `DecodeEngine.adopt_executables`).  Process-global
    and idempotent; returns False when this jax build does not expose
    the cache config."""
    global _compile_cache_applied

    cache_dir = str(cache_dir)
    if _compile_cache_applied == cache_dir:
        return True
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:
        return False
    # CPU compiles are small and fast — without these thresholds the
    # cache would skip exactly the executables a CPU test bed needs
    for opt, val in (
            ("jax_persistent_cache_min_entry_size_bytes", -1),
            ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass
    # jax latches its cache decision at the FIRST compile; anything
    # jitted before this call (model construction, eager dispatch)
    # already concluded "no cache" — reset so the next compile
    # re-initializes against the directory
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)

        _cc.reset_cache()
    except Exception:
        pass
    _compile_cache_applied = cache_dir
    return True


# ---------------------------------------------------------------------------
# The hung-step watchdog
# ---------------------------------------------------------------------------
class StepWatchdog:
    """Monitor armed around `DecodeEngine.step` when
    ``FLAGS_step_timeout_ms`` (or the engine's ``step_timeout_ms``
    argument) is positive.

    Classification: a step is HUNG when it outran the budget AND
    compiled nothing — executable compiles are expected warmup stalls,
    detected by the engine's `_JitTracker` signatures (tracker count /
    trace-cache sizes) changing across the step, so a first-step
    compile never false-positives.  A hung step flips
    ``paddle_engine_health`` to "hung" and raises a fatal
    `errors.HungStep`; the supervisors (`serve_with_recovery`, the
    frontend driver) route it through the existing engine-recovery
    path.  `engine_warm` is the gate the frontend uses before arming
    its harder measure — abandoning a worker thread that never
    returns."""

    def __init__(self, engine, timeout_ms: float):
        self.engine = engine
        self.timeout_ms = float(timeout_ms)
        if self.timeout_ms <= 0:
            raise ValueError(
                f"step_timeout_ms must be > 0 to arm the watchdog, "
                f"got {self.timeout_ms}")
        self._sig = None
        self._armed_t = None

    @property
    def timeout_s(self) -> float:
        return self.timeout_ms / 1e3

    def _tracker_sig(self):
        ts = self.engine._trackers()
        return (len(ts), sum(t._seen for t in ts))

    def engine_warm(self) -> bool:
        """Every executable built so far is warm and at least one step
        completed — arming the frontend's abandon timeout any earlier
        would classify a warmup compile as a hang.  (An executable the
        engine builds LAZILY after this reads True is still safe: the
        frontend re-checks `compiled_since` at timeout before
        abandoning.)"""
        ts = self.engine._trackers()
        return self.engine._step_no > 0 and bool(ts) and \
            all(t._warm for t in ts)

    def sig(self):
        """Opaque compile signature for `compiled_since` (the
        frontend takes it before scheduling a step on the worker)."""
        return self._tracker_sig()

    def compiled_since(self, sig) -> bool:
        """Did an executable compile start or land since ``sig`` was
        taken?  A `_JitTracker` is constructed BEFORE its first jit
        invocation, so a compile still in flight on another thread is
        already visible as a new tracker — the frontend uses this at
        abandon-timeout time to tell a warmup stall from a hang."""
        return self._tracker_sig() != sig

    def arm(self):
        """Called by the engine just before its device step."""
        self._sig = self._tracker_sig()
        self._armed_t = time.perf_counter()

    def disarm(self):
        """Called by the engine after the step returned (either
        verdict) — `overdue` must only ever see an armed window."""
        self._armed_t = None

    # readiness flips at HALF the hang budget: /readyz is a cheap,
    # instantly-reversible routing signal, so it goes early — the
    # router stops sending work while the abandon/rebuild machinery
    # (which pays a snapshot restore) still waits for the full budget.
    # Guarantees the flip PRECEDES abandonment instead of racing it.
    OVERDUE_FRACTION = 0.5

    def overdue(self) -> bool:
        """Is a step CURRENTLY blocked suspiciously long?  Readable
        from any thread while the engine thread is stuck inside its
        device dispatch — the ops plane's `/readyz` consults this so a
        soon-to-be-abandoned engine flips NOT-ready while the step is
        still hanging, not after the post-mortem.  Compiles excuse the
        stall exactly like `classify` (a warmup compile is slow, not
        hung)."""
        t0 = self._armed_t
        if t0 is None or time.perf_counter() - t0 <= \
                self.timeout_s * self.OVERDUE_FRACTION:
            return False
        if not self.engine_warm():
            # a compile IN FLIGHT inside an existing tracker changes
            # nothing observable until it returns (`_seen` bumps after
            # the call) — `classify` excuses it post-hoc, but a LIVE
            # probe must not read a cold engine's warmup compile as a
            # stall, so readiness only trusts the overdue verdict once
            # every built executable is warm
            return False
        return self._tracker_sig() == self._sig

    def classify(self, dt_s: float) -> bool:
        """True iff the step that just completed was hung: over budget
        with no compile to excuse it."""
        if dt_s <= self.timeout_s:
            return False
        return self._tracker_sig() == self._sig

    def on_hung(self, dt_s: float):
        """Record the verdict and raise the fatal `HungStep` the
        recovery supervision consumes."""
        from .serving import _stats_add

        _stats_add(hung_steps=1)
        set_health(self.engine._engine_id, "hung")
        raise HungStep(
            f"step stalled: {dt_s * 1e3:.1f}ms against a "
            f"step_timeout_ms budget of {self.timeout_ms:.1f}ms with "
            f"no executable compile in flight — classifying the "
            f"engine as hung")
