"""Inference engine.

Reference: `paddle/fluid/inference/` — `AnalysisConfig`
(`inference/api/paddle_analysis_config.h`), `AnalysisPredictor::Run/
ZeroCopyRun` (`inference/api/analysis_predictor.cc:381,889`) over
`NaiveExecutor` with an IR-pass optimization pipeline and TensorRT/Lite
subgraph engines.

TPU-native re-design: the deployable artifact is the serialized StableHLO
program + weights that `paddle_tpu.jit.save` emits (replacing
ProgramDesc+params files), and the entire "optimization pipeline"
(fusion passes, memory passes, engine subgraphs) is XLA compilation —
there is nothing to hand-optimize post hoc.  The predictor:

- loads the artifact once, compiles per input-shape signature, and caches
  executables (reference's program/executable cache);
- exposes the zero-copy handle API (`get_input_handle` /
  `copy_from_cpu` / `copy_to_cpu`) so user code ported from the
  reference runs unchanged.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "serving", "speculative",
           "frontend", "resilience", "errors", "durability"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 2
    XPU = 3


class Config:
    """reference `AnalysisConfig` (`paddle_analysis_config.h`): model paths
    + device + optimization switches.  Switches that configure CUDA/TRT/
    MKLDNN specifics are accepted as no-ops (XLA owns those concerns) so
    reference deployment scripts keep working."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_prefix = prog_file
        self._device = "tpu"
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._ir_optim = True
        self._memory_optim = False  # opt-in, as in the reference AnalysisConfig
        self._cpu_math_threads = 1

    # -- model path ---------------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_prefix = prog_file

    def model_dir(self):
        return os.path.dirname(self._model_prefix or "")

    def prog_file(self):
        return (self._model_prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._model_prefix or "") + ".pdiparams"

    # -- device -------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "gpu", device_id

    def enable_tpu(self, device_id=0):
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "gpu"

    # -- switches -----------------------------------------------------------
    def switch_ir_optim(self, x=True):
        # off = interpret the program op-by-op without the whole-graph XLA
        # compile (reference: skip OptimizeInferenceProgram)
        self._ir_optim = bool(x)

    def enable_memory_optim(self, x=True):
        # donate feed buffers to the executable so outputs can alias them
        # (reference: memory_optimize_pass buffer reuse)
        self._memory_optim = bool(x)

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    def enable_tensorrt_engine(self, *args, **kwargs):
        pass  # TRT is a CUDA concern; XLA compiles the whole graph on TPU

    def enable_mkldnn(self):
        pass

    def switch_use_feed_fetch_ops(self, x=False):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    def set_precision(self, p):
        self._precision = p

    def summary(self):
        return (f"Config(model={self._model_prefix!r}, device={self._device}"
                f":{self._device_id}, ir_optim={self._ir_optim})")


class Tensor:
    """Zero-copy input/output handle (reference `ZeroCopyTensor`,
    `inference/api/details/zero_copy_tensor.cc`)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self._name = name
        self._owner = owner
        self._is_input = is_input

    def name(self):
        return self._name

    def copy_from_cpu(self, arr: np.ndarray):
        if not self._is_input:
            raise RuntimeError("copy_from_cpu on an output handle")
        self._owner._inputs[self._name] = np.ascontiguousarray(arr)

    def set_lod(self, lod):
        """reference ZeroCopyTensor::SetLoD.  Accepts the reference's
        offset-based LoD (1 or 2 levels — `framework/lod_tensor.h:109`)
        or a flat per-sequence lengths list.  The INNERMOST level
        becomes the padded+lengths sidecar the lod_* interchange ops
        consume — faithful to the reference sequence kernels, which
        read `lod[lod_level - 1]` (e.g. `math/sequence_pooling.cc:70`);
        the outer level of a 2-level LoD is kept for lod() round-trip.
        Deeper nesting has no consumer in the interchange op set and
        refuses explicitly."""
        if not self._is_input:
            raise RuntimeError("set_lod on an output handle")
        lod = list(lod)
        if lod and isinstance(lod[0], (list, tuple, np.ndarray)):
            if len(lod) > 2:
                raise NotImplementedError(
                    "LoD deeper than 2 levels is not supported by the "
                    f"padded+lengths redesign; got {len(lod)} levels "
                    "(see PARITY.md 'Multi-level LoD')")

            def offsets(level):
                off = np.asarray(level, np.int64)
                if off.size < 2 or off[0] != 0 or \
                        (np.diff(off) < 0).any():
                    raise ValueError(
                        "offset LoD must start at 0 and be "
                        f"non-decreasing (got {off.tolist()})")
                return off

            levels = [offsets(lv) for lv in lod]
            if len(levels) == 2 and \
                    levels[0][-1] != len(levels[1]) - 1:
                raise ValueError(
                    "2-level LoD mismatch: outer level ends at "
                    f"{levels[0][-1]} but the inner level describes "
                    f"{len(levels[1]) - 1} sequences")
            lengths = np.diff(levels[-1])
            self._owner._outer_lods[self._name] = \
                [lv.tolist() for lv in levels[:-1]]
        elif not lod:
            # reference semantics: an empty LoD clears the tensor's
            # sequence structure entirely
            self._owner._lods.pop(self._name, None)
            self._owner._outer_lods.pop(self._name, None)
            return
        else:
            lengths = np.asarray(lod, np.int64)
            self._owner._outer_lods.pop(self._name, None)
        self._owner._lods[self._name] = lengths.astype(np.int32)

    def lod(self):
        """reference ZeroCopyTensor::lod: offset-based levels.  Input
        handles echo what set_lod stored (all levels); output handles
        report the lengths sidecar the program produced for that fetch
        target — the INNERMOST level only, since that is what the
        padded+lengths sidecar carries through ops (outer grouping
        levels of a 2-level input are input-side metadata; see
        PARITY.md 'Multi-level LoD')."""
        if self._is_input:
            lengths = self._owner._lods.get(self._name)
            if lengths is None:
                return []
            outer = self._owner._outer_lods.get(self._name, [])
            off = np.concatenate([[0], np.cumsum(lengths)]).tolist()
            return [list(lv) for lv in outer] + [off]
        lengths = self._owner._output_lods.get(self._name)
        if lengths is None:
            return []
        off = np.concatenate([[0], np.cumsum(np.asarray(lengths))])
        return [[int(v) for v in off]]

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError("copy_to_cpu on an input handle")
        out = self._owner._outputs.get(self._name)
        if out is None:
            raise RuntimeError("run() the predictor before copy_to_cpu")
        return np.asarray(out)

    def shape(self):
        src = self._owner._inputs if self._is_input else self._owner._outputs
        a = src.get(self._name)
        return list(a.shape) if a is not None else None

    def reshape(self, shape):
        pass  # shapes are taken from copy_from_cpu data


class Predictor:
    """reference `AnalysisPredictor`: load once, run many.  Two artifact
    formats:

    * reference interchange (``.pdmodel``+``.pdiparams`` pair or a dir
      with ``__model__``/``__params__``) — parsed by the framework.proto
      codec and interpreted to one XLA computation;
    * the TPU-native StableHLO export from `paddle_tpu.jit.save`."""

    def __init__(self, config: Config):
        import jax

        self._config = config
        self._inputs: Dict[str, np.ndarray] = {}
        self._lods: Dict[str, np.ndarray] = {}
        self._outer_lods: Dict[str, list] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_lods: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []
        prefix = config._model_prefix or ""
        # sniff the artifact: a reference-era .pdmodel parses as a
        # framework.proto ProgramDesc with blocks; the TPU-native export
        # (jit.save) stores StableHLO under the same extension
        is_ref_format = os.path.isdir(prefix) and os.path.exists(
            os.path.join(prefix, "__model__"))
        if not is_ref_format and os.path.exists(prefix + ".pdmodel"):
            from ..static import proto as _proto

            try:
                with open(prefix + ".pdmodel", "rb") as f:
                    parsed = _proto.parse_program(f.read())
                is_ref_format = bool(parsed.get("blocks"))
            except Exception:
                is_ref_format = False
        if is_ref_format:
            from ..static import load_inference_model
            from ..static.interp import ProgramRunner

            program, feeds, fetches = load_inference_model(prefix)
            self._runner = ProgramRunner(
                program, getattr(program, "_param_scope", {}) or {},
                jit=config._ir_optim, donate_feeds=config._memory_optim)
            self._layer = None
            self._input_names = list(self._runner.feed_names)
            self._output_names = [f"output_{i}"
                                  for i in range(len(
                                      self._runner.fetch_names))]
        else:
            from .. import jit as pjit

            self._runner = None
            self._layer = pjit.load(prefix)
            n_in = self._n_model_inputs()
            self._input_names = [f"input_{i}" for i in range(n_in)]
            # enable_memory_optim for the StableHLO artifact: wrap the
            # exported call in a jit whose FEED buffers are donated so
            # outputs may alias them (the ProgramDesc path gets the same
            # via ProgramRunner(donate_feeds=True))
            self._donated_infer = None
            if config._memory_optim:
                import jax as _jax

                ex = self._layer._exported
                self._donated_infer = _jax.jit(
                    lambda parrs, barrs, *ins: ex.call(parrs, barrs, *ins),
                    donate_argnums=tuple(range(2, 2 + n_in)))

    def _n_model_inputs(self) -> int:
        ex = self._layer._exported
        total = len(ex.in_avals)
        return total - len(self._layer._pnames) - len(self._layer._bnames)

    # -- handle API ---------------------------------------------------------
    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        if name not in self._input_names:
            raise KeyError(name)
        return Tensor(name, self, is_input=True)

    def get_output_names(self):
        if not self._output_names:
            raise RuntimeError("run() once to materialize output names")
        return list(self._output_names)

    def get_output_handle(self, name):
        return Tensor(name, self, is_input=False)

    # -- execution ----------------------------------------------------------
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Zero-copy run (reference `ZeroCopyRun` analysis_predictor.cc:889).
        Either pass `inputs` positionally or pre-fill via input handles."""
        if inputs is None:
            inputs = [self._inputs[n] for n in self._input_names]
        out_lods = None
        if self._runner is not None:
            if self._lods:
                outs, out_lods = self._runner.run_with_lods(
                    [np.asarray(i) for i in inputs], self._lods,
                    return_lods=True)
            else:
                outs = self._runner(*[np.asarray(i) for i in inputs])
        else:
            if self._lods:
                raise NotImplementedError(
                    "set_lod applies to reference-format (ProgramDesc) "
                    "models only; the StableHLO export has no LoD inputs")
            if self._donated_infer is not None:
                import jax.numpy as _jnp

                layer = self._layer
                parrs = [layer._param_map[k]._array
                         for k in layer._pnames]
                barrs = [layer._buf_map[k]._array for k in layer._bnames]
                import jax as _jax

                # a caller-owned jax.Array fed directly would itself be
                # donated (deleted) — copy ONLY those; numpy feeds (the
                # normal predictor path) already produce fresh device
                # buffers via asarray, no extra traffic
                feeds = [_jnp.array(i, copy=True)
                         if isinstance(i, _jax.Array) else _jnp.asarray(i)
                         for i in inputs]
                outs = self._donated_infer(parrs, barrs, *feeds)
            else:
                outs = self._layer(*inputs)
            outs = outs if isinstance(outs, tuple) else (outs,)
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        self._outputs = {
            n: np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            for n, o in zip(self._output_names, outs)
        }
        self._output_lods = {}
        if out_lods is not None:
            for n, lv in zip(self._output_names, out_lods):
                if lv is not None:
                    self._output_lods[n] = np.asarray(lv)
        return [self._outputs[n] for n in self._output_names]

    def clone(self):
        """reference AnalysisPredictor::Clone
        (`inference/capi_exp/pd_predictor.h:52` — the documented
        one-predictor-per-thread concurrency model): the clone SHARES
        the loaded program, weights, and compiled-executable cache (no
        reload, no recompile) but owns its input/output/LoD state, so
        each thread runs through its own clone without racing another's
        feeds."""
        twin = object.__new__(Predictor)
        twin._config = self._config
        twin._runner = self._runner
        twin._layer = self._layer
        twin._donated_infer = getattr(self, "_donated_infer", None)
        twin._input_names = list(self._input_names)
        twin._output_names = list(self._output_names)
        twin._inputs = {}
        twin._lods = {}
        twin._outer_lods = {}
        twin._outputs = {}
        twin._output_lods = {}
        return twin


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def __getattr__(name):
    # PEP 562 lazy submodule: `paddle_tpu.inference.serving` resolves on
    # first attribute access without loading the serving engine (and its
    # Pallas kernel chain) into every `import paddle_tpu`.  Must go
    # through importlib — a `from . import serving` here would re-enter
    # this __getattr__ via _handle_fromlist and recurse.
    if name in ("serving", "speculative", "frontend"):
        import importlib

        return importlib.import_module("." + name, __name__)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
