"""Speculative decoding: draft-propose / multi-token-verify on the
paged KV engine.

The continuous-batching engine (`inference.serving.DecodeEngine`)
advances every slot by exactly one token per step, so per-token latency
is one full target-model pass.  Speculative decoding amortizes that
pass: a cheap **drafter** proposes K tokens per slot, and one batched
**verify step** — a single donated jitted executable, the multi-query
sibling of the engine's decode step — scores all K+1 positions at once
through the ragged multi-query paged-attention kernel
(`ops.pallas.paged_attention`, per-sequence causal offsets).

Accept/resample rule (Leviathan et al., specialized to this engine's
samplers): the verify pass draws a *target token* at every position with
the exact `sample_logits` the engine uses (argmax under greedy), then
accepts drafted tokens while they match the targets and emits the first
mismatching target as the correction — or, when every draft survives,
the last target as a bonus token.  Because the emitted tokens ARE the
target model's samples, the output distribution is the target
distribution by construction: token-identical to the non-speculative
engine under greedy, and distribution-preserving under temperature /
top-k / top-p sampling.  For a point-mass drafter (prompt-lookup) this
is exactly the Leviathan rule: accept with probability p(d), resample
from norm(p - p(d)·δ_d) otherwise.

Memory protocol: speculative K/V rows are written into pages the
request already owns (`DecodeEngine._grow_block_tables(writes=...)`
reserves the verify window up front, clamped to the request's token
budget), so rejection is a pure host-side ``seq_lens`` rollback — no
allocation, no free, no retrace.  The page pool cannot distinguish a
speculative serve from a classic one.  Prefix caching
(FLAGS_prefix_cache) carries over for free: a cached prompt page holds
BOTH models' K/V (same page ids, same block tables), so a prefix hit
skips the draft-side prompt ingestion too — `DraftModelDrafter`'s
chunk cursor simply starts at the cached length.

Drafters:

* `PromptLookupDrafter` — model-free n-gram lookup over each request's
  own token history (prompt + generated).  Zero device cost; shines on
  repetition-friendly workloads (code, extraction, chat with quoting).
* `DraftModelDrafter` — a small GPT (see `GPTConfig.draft_config`)
  sharing the engine's page pool: its K/V pages are indexed by the SAME
  block tables and page ids as the target model's, so one allocator
  governs both and the rollback invariants transfer unchanged.

Preemption (`DecodeEngine.preempt`, SLO scheduler) composes for free:
it fires between steps, so a speculative round never sees a half-torn
slot — the preempted slot goes inactive (``on_finish`` resets the
drafter's cursor) and a resume re-enters through ``on_admit`` exactly
like a fresh admission.  Cached replay pages may hold draft K/V the
draft model never wrote (the bonus token of the round before the
preemption, say): drafts over such a page can only be WRONG, never
unsound — the verify pass still emits target-model samples only, so
acceptance may dip after a resume but correctness cannot.

Telemetry lands in `profiler.decode_stats`: ``acceptance_rate``,
``mean_accepted_per_step``, ``draft_time_s`` / ``verify_time_s``, and
the zero-warm-retrace contract extends to the draft and verify
executables via the shared `_JitTracker`.
"""
from __future__ import annotations

import functools
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .serving import (RNG_DECODE_DOMAIN, _JitTracker,
                      _extract_gpt_params, _fold_counter,
                      _gpt_decode_step, _gpt_decode_step_q,
                      _gpt_mixed_step, _gpt_mixed_step_q, _gpt_prefill,
                      _gpt_prefill_q, _guard_tokens, _ln, _logits_of,
                      _quantize_gpt_params, _reset_kv_scales,
                      _stats_add, _wmm, sample_logits)
from .. import observability as _obs
from ..ops.pallas import paged_attention as pa

__all__ = ["Drafter", "PromptLookupDrafter", "DraftModelDrafter",
           "SpeculativeDecoder"]


# ---------------------------------------------------------------------------
# The multi-token verify step (pure, jit-compiled once per engine)
# ---------------------------------------------------------------------------
def _gpt_spec_verify(params, k_pages, v_pages, block_tables, seq_lens,
                     tokens, write_caps, key, *, num_heads, head_dim,
                     eps, sampler, temperature, top_k, top_p):
    """Score Q = K+1 incoming tokens per slot in ONE pass: write their
    K/V into the slots' already-reserved pages (write-capped per
    sequence so rows past a request's token budget are dropped by the
    scatter), run ragged multi-query paged attention with per-sequence
    causal offsets, and draw a target token at every position with the
    engine's own `sample_logits`.

    tokens: [B, Q] int32 — position ``seq_lens[b] + i`` holds
    ``tokens[b, i]`` (the last sampled token followed by the K drafts);
    write_caps: [B] int32 in [0, Q] — rows ``i < write_caps[b]`` are
    written and attendable (0 = inactive slot -> zero logits, target 0
    ignored by the host); k_pages/v_pages donated: the K/V write is in
    place, and a later rejection only shrinks the host's ``seq_lens``.
    Returns (k_pages, v_pages, targets [B, Q] int32).
    """
    b, qn = tokens.shape
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]

    pos = seq_lens[:, None] + jnp.arange(qn, dtype=jnp.int32)[None, :]
    wpe_max = params["wpe"].shape[0] - 1
    x = params["wte"][tokens] + params["wpe"][jnp.minimum(pos, wpe_max)]
    page_idx, slot = pa.paged_write_indices(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    lens_now = seq_lens + write_caps

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x.reshape(b * qn, h), blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(b, qn, 3, num_heads, head_dim)
        q = qkv[:, :, 0]                                 # [B, Q, H, D]
        # slice shape [B, Q, Hkv, D] (the int layer index joins the
        # advanced group — batch dims lead); capped rows have an OOB
        # page index and are dropped by the scatter
        k_pages = k_pages.at[li, :, page_idx, slot, :].set(qkv[:, :, 1])
        v_pages = v_pages.at[li, :, page_idx, slot, :].set(qkv[:, :, 2])
        attn = pa.paged_attention(q, k_pages[li], v_pages[li],
                                  block_tables, lens_now,
                                  q_offsets=seq_lens)
        x = x + _wmm(attn.reshape(b, qn, h), blk, "out_w") \
            + blk["out_b"]
        y = _ln(x.reshape(b * qn, h), blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + (_wmm(y, blk, "fc2_w") + blk["fc2_b"]
                 ).reshape(b, qn, h)

    xf = _ln(x.reshape(b * qn, h), params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, xf).astype(jnp.float32)
    logits = logits.reshape(b, qn, -1)
    # one target draw per position, through the exact engine sampler —
    # the emitted tokens ARE these draws, which is what makes the accept
    # rule distribution-preserving (greedy ignores the key)
    targets = [
        _guard_tokens(
            logits[:, i],
            sample_logits(logits[:, i], sampler=sampler,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, key=jax.random.fold_in(key, i)))
        for i in range(qn)
    ]
    return k_pages, v_pages, jnp.stack(targets, axis=1)


def _gpt_spec_verify_q(params, k_pages, v_pages, k_scales, v_scales,
                       block_tables, seq_lens, tokens, write_caps, key,
                       *, num_heads, head_dim, eps, sampler,
                       temperature, top_k, top_p):
    """Quantized-storage `_gpt_spec_verify` (FLAGS_kv_quant=int8): the
    verify window's K/V rows quantize into the slots' pages through
    `pa.paged_quant_write` (per-head absmax folded into the running
    page scales, existing rows refolded on growth) and the multi-query
    attention reads through the fused dequant.  Returns ``(k_pages,
    v_pages, k_scales, v_scales, out)`` with ``out`` [B+1, Q] int32:
    rows 0..B-1 are the per-position targets, row B packs the step's
    refold count in column 0 — the host learns both from the one fetch
    the round already pays.

    Quantization caveat the docs spell out: a REJECTED draft row's
    absmax may have grown a page scale before the host rolled
    ``seq_lens`` back, so a speculative quantized serve can quantize
    slightly differently than a non-speculative quantized serve over
    the same tokens (greedy equality holds at the off setting and for
    non-speculative quantized engines; speculative quantized mode is
    gated on measured token-match instead)."""
    b, qn = tokens.shape
    h = num_heads * head_dim
    num_pages_total = k_pages.shape[2]
    page = k_pages.shape[3]

    pos = seq_lens[:, None] + jnp.arange(qn, dtype=jnp.int32)[None, :]
    wpe_max = params["wpe"].shape[0] - 1
    x = params["wte"][tokens] + params["wpe"][jnp.minimum(pos, wpe_max)]
    page_idx, slot = pa.paged_write_indices(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    flat_idx = page_idx.reshape(-1)
    flat_slot = slot.reshape(-1)
    spans = pa.paged_write_spans(
        block_tables, seq_lens, write_caps, qn, num_pages_total, page)
    lens_now = seq_lens + write_caps
    refolds = jnp.int32(0)

    for li, blk in enumerate(params["blocks"]):
        y = _ln(x.reshape(b * qn, h), blk["ln1_w"], blk["ln1_b"], eps)
        qkv = _wmm(y, blk, "qkv_w") + blk["qkv_b"]
        qkv = qkv.reshape(b, qn, 3, num_heads, head_dim)
        q = qkv[:, :, 0]                                 # [B, Q, H, D]
        k_pages, k_scales, rk = pa.paged_quant_write(
            k_pages, k_scales, li,
            qkv[:, :, 1].reshape(b * qn, num_heads, head_dim),
            flat_idx, flat_slot, spans)
        v_pages, v_scales, rv = pa.paged_quant_write(
            v_pages, v_scales, li,
            qkv[:, :, 2].reshape(b * qn, num_heads, head_dim),
            flat_idx, flat_slot, spans)
        refolds = refolds + rk + rv
        attn = pa.paged_attention(q, k_pages[li], v_pages[li],
                                  block_tables, lens_now,
                                  q_offsets=seq_lens,
                                  k_scales=k_scales[li],
                                  v_scales=v_scales[li])
        x = x + _wmm(attn.reshape(b, qn, h), blk, "out_w") \
            + blk["out_b"]
        y = _ln(x.reshape(b * qn, h), blk["ln2_w"], blk["ln2_b"], eps)
        y = jax.nn.gelu(_wmm(y, blk, "fc1_w") + blk["fc1_b"],
                        approximate=True)
        x = x + (_wmm(y, blk, "fc2_w") + blk["fc2_b"]
                 ).reshape(b, qn, h)

    xf = _ln(x.reshape(b * qn, h), params["lnf_w"], params["lnf_b"], eps)
    logits = _logits_of(params, xf).astype(jnp.float32)
    logits = logits.reshape(b, qn, -1)
    targets = [
        _guard_tokens(
            logits[:, i],
            sample_logits(logits[:, i], sampler=sampler,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, key=jax.random.fold_in(key, i)))
        for i in range(qn)
    ]
    out = jnp.stack(targets, axis=1).astype(jnp.int32)
    pack = jnp.zeros((1, qn), jnp.int32).at[0, 0].set(refolds)
    return k_pages, v_pages, k_scales, v_scales, \
        jnp.concatenate([out, pack], axis=0)


# ---------------------------------------------------------------------------
# Drafters
# ---------------------------------------------------------------------------
class Drafter:
    """Proposes K draft tokens per active slot each speculative round.

    Lifecycle: ``bind(engine, k)`` once at engine construction, then
    per-request ``on_admit``/``on_finish`` and per-round
    ``propose``/``on_accept``.  ``propose`` runs between engine steps
    (host time there is drafting budget, not device idle time)."""

    name = "base"
    # stateful drafters carry per-slot device state (draft K/V lens);
    # after speculation degrades off (inference.resilience) only a
    # STATELESS drafter can be probed back on mid-serve — a stateful
    # one would need a full per-slot resync its fixed-frame catch-up
    # cannot express, so it stays degraded until recovery/restart
    stateful = False

    def bind(self, engine, k: int):
        if getattr(self, "engine", None) is not None and \
                self.engine is not engine:
            # a drafter carries per-engine state (draft pages, lens
            # bookkeeping); silently rebinding would cross-wire two
            # engines' slot state
            raise ValueError(
                "drafter is already bound to another engine: construct "
                "one drafter per DecodeEngine")
        self.engine = engine
        self.k = int(k)

    def on_admit(self, slot: int, req):
        pass

    def on_finish(self, slot: int, req):
        pass

    def ingest_chunks(self, tokens, caps):
        """Chunked prefill (FLAGS_chunked_prefill): the engine just fed
        these prompt chunks to the target model — ``tokens`` is the
        [slots, Q_max] mixed batch, ``caps[s]`` the chunk length slot
        ``s`` consumed (0 = not prefilling this step).  Model-backed
        drafters ingest the same chunks into their own K/V here; host
        drafters need nothing."""
        pass

    def propose(self, write_caps) -> np.ndarray:
        """Return [slots, K] int32 draft tokens (inactive rows ignored).
        ``write_caps[s]`` is the verify window (K/V writes) slot ``s``
        gets this round — at most ``write_caps[s] - 1`` drafts of it can
        be accepted, so drafters may stop early.  ``write_caps[s] == 0``
        means the slot sits this round out (still prefilling its prompt
        chunks): its row is ignored and must not be advanced."""
        raise NotImplementedError

    def on_accept(self, slot: int, pos_before: int, n_emitted: int):
        """Called per slot after the verify: ``n_emitted`` tokens were
        appended and the slot's KV length moved to
        ``pos_before + n_emitted`` (the rollback, if any, already
        happened on the engine's side)."""
        pass


class PromptLookupDrafter(Drafter):
    """Model-free prompt-lookup (n-gram) drafter: propose the
    continuation of the most recent earlier occurrence of the sequence's
    current n-gram suffix, longest n first.  The LLM serving analog of
    "assume the text repeats itself" — free to compute, surprisingly
    strong on extraction/code/chat workloads, and the q-distribution is
    a point mass so the accept rule is exactly Leviathan's."""

    name = "prompt_lookup"

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"[{ngram_min}, {ngram_max}]")
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)

    def _lookup(self, hist: np.ndarray) -> np.ndarray:
        k = self.k
        ln = len(hist)
        for n in range(min(self.ngram_max, ln - 1), self.ngram_min - 1,
                       -1):
            suffix = hist[ln - n:]
            # candidate starts s <= ln-n-1: the window is strictly
            # earlier than the suffix itself, so a continuation exists
            wins = np.lib.stride_tricks.sliding_window_view(
                hist, n)[:ln - n]
            hits = np.nonzero((wins == suffix).all(axis=1))[0]
            if hits.size:
                s = int(hits[-1])  # most recent occurrence
                cont = hist[s + n: s + n + k]
                if cont.size < k:
                    cont = np.concatenate(
                        [cont, np.full(k - cont.size, hist[-1],
                                       hist.dtype)])
                return cont
        # no n-gram recurs yet: propose a flat repeat of the last token
        # (wrong drafts cost nothing beyond the verify row they ride in)
        return np.full(k, hist[-1], hist.dtype)

    def propose(self, write_caps) -> np.ndarray:
        eng = self.engine
        write_caps = np.asarray(write_caps)
        out = np.zeros((eng._slots, self.k), np.int32)
        for s in range(eng._slots):
            if not eng._active[s] or write_caps[s] == 0:
                continue  # cap 0: still prefilling — skip the slot
            req = eng._by_slot[s]
            hist = np.asarray(req.prompt_ids + req.output_ids, np.int32)
            out[s] = self._lookup(hist)
        return out


class DraftModelDrafter(Drafter):
    """Small-GPT drafter sharing the engine's page pool: the draft
    model's K/V pages are indexed by the SAME block tables and page ids
    the target uses — one allocator governs both caches, so admission,
    growth, rollback, and eviction need no drafter-specific accounting.

    The draft decodes greedily (argmax maximizes the match probability
    against the verify targets).  Per round it runs ONE multi-query
    catch-up pass (ingest the tokens the verify accepted last round —
    the same `_gpt_spec_verify` executable shape, over the draft
    weights) followed by K-1 single-token steps (the engine's own
    `_gpt_decode_step`, over the draft weights).  All draft executables
    ride the `_JitTracker` retrace contract."""

    name = "draft_model"
    stateful = True  # per-slot draft K/V cursors: see Drafter.stateful

    def __init__(self, draft_model):
        cfg = draft_model.cfg
        if getattr(cfg, "dropout", 0.0) and draft_model.training:
            raise ValueError(
                "draft model must be in eval mode (cfg.dropout > 0)")
        self._params = _extract_gpt_params(draft_model)
        self._num_heads = cfg.num_heads
        self._head_dim = cfg.hidden_size // cfg.num_heads
        self._eps = float(getattr(draft_model.ln_f, "_epsilon", 1e-5))
        self._vocab = cfg.vocab_size
        self._max_pos = cfg.max_seq_len

    def bind(self, engine, k: int):
        super().bind(engine, k)
        if self._vocab != engine._params["wte"].shape[0]:
            raise ValueError(
                f"draft vocab {self._vocab} != target vocab "
                f"{engine._params['wte'].shape[0]}: the drafter must "
                f"propose over the target's token space")
        if self._max_pos < engine._max_seq_len:
            raise ValueError(
                f"draft position table ({self._max_pos}) shorter than "
                f"the engine horizon ({engine._max_seq_len})")
        # the draft weights quantize WITH the engine: a serve_weights=
        # int8 target with an f32 drafter would leave the drafter's
        # K-1 steps per round streaming 4-byte weights on the same
        # bandwidth-bound path the fold just relieved.  Guarded so a
        # rebound drafter never quantizes already-int8 leaves.
        if engine._weight_quant and \
                "qkv_w" in self._params["blocks"][0]:
            self._params, mats, saved = _quantize_gpt_params(self._params)
            _stats_add(weight_quant_mats=mats,
                       weight_quant_bytes_saved=saved)
            _obs.WEIGHT_QUANT_SAVED_BYTES.inc(
                saved, engine=engine._engine_id)
        n_layers = len(self._params["blocks"])
        shape = (n_layers, self._num_heads, engine.pool.num_pages,
                 engine._page, self._head_dim)
        # the draft cache quantizes WITH the engine (same page ids,
        # same storage dtype, its own scale arrays): the density win
        # covers both pools, and the drafter's executables follow the
        # same packed-output/donation conventions as the engine's
        self._quant = bool(engine._kv_quant)
        dtype = engine._k_pages.dtype
        self._k_pages = jnp.zeros(shape, dtype)
        self._v_pages = jnp.zeros(shape, dtype)
        self._k_scales = self._v_scales = None
        self._scale_reset_fn = None
        if self._quant:
            sshape = (n_layers, self._num_heads, engine.pool.num_pages)
            self._k_scales = jnp.zeros(sshape, jnp.float32)
            self._v_scales = jnp.zeros(sshape, jnp.float32)
        self._lens = np.zeros(engine._slots, np.int32)
        greedy = dict(sampler="greedy", temperature=1.0, top_k=0,
                      top_p=1.0)
        self._greedy = greedy
        self._chunk_fn = None  # chunked prefill ingest (lazy)
        if self._quant:
            self._catch_fn = _JitTracker(
                functools.partial(_gpt_spec_verify_q,
                                  num_heads=self._num_heads,
                                  head_dim=self._head_dim,
                                  eps=self._eps, **greedy),
                "draft_compiles", donate_argnums=(1, 2, 3, 4),
                site="DraftModelDrafter catch-up (_gpt_spec_verify_q)")
            self._step_fn = _JitTracker(
                functools.partial(_gpt_decode_step_q,
                                  num_heads=self._num_heads,
                                  head_dim=self._head_dim,
                                  eps=self._eps, **greedy),
                "draft_compiles", donate_argnums=(1, 2, 3, 4),
                site="DraftModelDrafter step (_gpt_decode_step_q)")
        else:
            self._catch_fn = _JitTracker(
                functools.partial(_gpt_spec_verify,
                                  num_heads=self._num_heads,
                                  head_dim=self._head_dim,
                                  eps=self._eps, **greedy),
                "draft_compiles", donate_argnums=(1, 2),
                site="DraftModelDrafter catch-up (_gpt_spec_verify)")
            self._step_fn = _JitTracker(
                functools.partial(_gpt_decode_step,
                                  num_heads=self._num_heads,
                                  head_dim=self._head_dim,
                                  eps=self._eps, **greedy),
                "draft_compiles", donate_argnums=(1, 2),
                site="DraftModelDrafter step (_gpt_decode_step)")
        self._prefill_fns = {}

    def _scale_reset_tracker(self) -> _JitTracker:
        """The drafter's OWN scale-reset executable (its layer count
        may differ from the engine's — sharing one tracker across the
        two signatures would read as a warm retrace)."""
        fn = self._scale_reset_fn
        if fn is None:
            fn = self._scale_reset_fn = _JitTracker(
                _reset_kv_scales, "kv_quant_compiles",
                donate_argnums=(0, 1),
                site="DraftModelDrafter scale reset (_reset_kv_scales)")
        return fn

    # -- request lifecycle --------------------------------------------------
    def on_admit(self, slot: int, req):
        """Draft-side prefill: ingest the prompt into the draft's pages
        through the slot's block-table row (the pages the engine just
        allocated for the target's prompt K/V).  Under chunked prefill
        the prompt arrives chunk by chunk via `ingest_chunks` instead —
        admission only resets the slot's draft cursor — to the cached
        prefix length on a prefix-cache hit: the shared pages' DRAFT
        K/V was written when the original request streamed those very
        chunks through `ingest_chunks` (same block-table page ids, and
        greedy draft ingestion is deterministic in the token prefix),
        so the draft cache skips the cached prefix exactly like the
        target does and `ingest_chunks` only ever sees the novel
        tail."""
        eng = self.engine
        if eng._chunked:
            self._lens[slot] = req.cached_prefix_len
            return
        p_len = len(req.prompt_ids)
        bucket = eng._prefill_bucket(p_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :p_len] = req.prompt_ids
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            if self._quant:
                fn = _JitTracker(
                    functools.partial(_gpt_prefill_q,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, sampler="greedy",
                                      temperature=1.0, top_k=0,
                                      top_p=1.0),
                    "draft_compiles", donate_argnums=(4, 5, 6, 7),
                    site=f"DraftModelDrafter prefill bucket {bucket} "
                         f"(_gpt_prefill_q)")
            else:
                fn = _JitTracker(
                    functools.partial(_gpt_prefill,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, sampler="greedy",
                                      temperature=1.0, top_k=0,
                                      top_p=1.0),
                    "draft_compiles", donate_argnums=(4, 5),
                    site=f"DraftModelDrafter prefill bucket {bucket} "
                         f"(_gpt_prefill)")
            self._prefill_fns[bucket] = fn
        t0 = time.perf_counter()
        if self._quant:
            # the sampled-token/refold pack is deliberately NOT fetched
            # (the draft's sample is unused), so no extra host sync —
            # draft-side refolds go uncounted by design
            (self._k_pages, self._v_pages, self._k_scales,
             self._v_scales, _) = fn(
                self._params, jnp.asarray(ids), jnp.int32(p_len),
                jnp.asarray(eng._bt[slot]), self._k_pages,
                self._v_pages, self._k_scales, self._v_scales,
                eng._key)
        else:
            self._k_pages, self._v_pages, _ = fn(
                self._params, jnp.asarray(ids), jnp.int32(p_len),
                jnp.asarray(eng._bt[slot]), self._k_pages,
                self._v_pages, eng._key)
        _stats_add(draft_time_s=time.perf_counter() - t0)
        self._lens[slot] = p_len

    def on_finish(self, slot: int, req):
        self._lens[slot] = 0

    def ingest_chunks(self, tokens, caps):
        """Chunked prefill: run the SAME mixed-step program shape the
        target just ran, over the draft weights — the chunk K/V lands in
        the draft's pages through the shared block tables, no sampling
        (mask all-false), and the draft cursor tracks the engine's
        prefill cursor chunk for chunk."""
        eng = self.engine
        fn = self._chunk_fn
        if fn is None:
            if self._quant:
                fn = self._chunk_fn = _JitTracker(
                    functools.partial(_gpt_mixed_step_q,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._greedy),
                    "draft_compiles", donate_argnums=(1, 2, 3, 4),
                    site="DraftModelDrafter chunk ingest "
                         "(_gpt_mixed_step_q)")
            else:
                fn = self._chunk_fn = _JitTracker(
                    functools.partial(_gpt_mixed_step,
                                      num_heads=self._num_heads,
                                      head_dim=self._head_dim,
                                      eps=self._eps, **self._greedy),
                    "draft_compiles", donate_argnums=(1, 2),
                    site="DraftModelDrafter chunk ingest "
                         "(_gpt_mixed_step)")
        caps = np.asarray(caps, np.int32)
        t0 = time.perf_counter()
        if self._quant:
            (self._k_pages, self._v_pages, self._k_scales,
             self._v_scales, _) = fn(
                self._params, self._k_pages, self._v_pages,
                self._k_scales, self._v_scales,
                jnp.asarray(eng._bt), jnp.asarray(self._lens),
                jnp.asarray(tokens), jnp.asarray(caps),
                jnp.zeros(eng._slots, jnp.int32),
                jnp.zeros(eng._slots, bool), eng._key)
        else:
            self._k_pages, self._v_pages, _ = fn(
                self._params, self._k_pages, self._v_pages,
                jnp.asarray(eng._bt), jnp.asarray(self._lens),
                jnp.asarray(tokens), jnp.asarray(caps),
                jnp.zeros(eng._slots, jnp.int32),
                jnp.zeros(eng._slots, bool), eng._key)
        _stats_add(draft_time_s=time.perf_counter() - t0)
        self._lens = self._lens + caps

    # -- per-round propose ---------------------------------------------------
    def propose(self, write_caps) -> np.ndarray:
        eng = self.engine
        slots = eng._slots
        k = self.k
        # cap 0 = the slot is still prefilling (its chunks flow through
        # ingest_chunks): it must not be caught up or stepped this round
        active = eng._active & (np.asarray(write_caps) > 0)
        drafts = np.zeros((slots, k), np.int32)

        # catch-up: feed the tokens accepted since the draft last saw
        # this slot (positions lens_d .. L, where L = engine seq_len is
        # the last sampled token's position) — at most K+1 of them, in
        # the same fixed [slots, K+1] frame the verify uses, so this is
        # one warm executable, not a shape zoo
        catch = np.zeros((slots, k + 1), np.int32)
        caps = np.zeros(slots, np.int32)
        for s in range(slots):
            if not active[s]:
                continue
            req = eng._by_slot[s]
            full = req.prompt_ids + req.output_ids
            pend = int(eng._lens[s]) + 1 - int(self._lens[s])
            assert 1 <= pend <= k + 1, (pend, k)
            catch[s, :pend] = full[self._lens[s]: self._lens[s] + pend]
            caps[s] = pend
        bt = jnp.asarray(eng._bt)  # invariant across the round
        if self._quant:
            (self._k_pages, self._v_pages, self._k_scales,
             self._v_scales, targets) = self._catch_fn(
                self._params, self._k_pages, self._v_pages,
                self._k_scales, self._v_scales,
                bt, jnp.asarray(self._lens),
                jnp.asarray(catch), jnp.asarray(caps), eng._key)
            targets = eng._host_fetch(targets)
            eng._note_refolds(int(targets[slots, 0]))
            targets = targets[:slots]
        else:
            self._k_pages, self._v_pages, targets = self._catch_fn(
                self._params, self._k_pages, self._v_pages,
                bt, jnp.asarray(self._lens),
                jnp.asarray(catch), jnp.asarray(caps), eng._key)
            targets = eng._host_fetch(targets)
        self._lens[active] += caps[active]
        cur = np.where(
            active,
            np.take_along_axis(
                targets, np.maximum(caps - 1, 0)[:, None], axis=1)[:, 0],
            0).astype(np.int32)
        drafts[:, 0] = cur

        # K-1 greedy single-token steps; a slot only participates while
        # its draft write position stays inside the verify window the
        # engine reserved (write_caps), so the draft can never touch a
        # page the request does not own
        write_caps = np.asarray(write_caps)
        for i in range(1, k):
            step_active = active & (i <= write_caps - 1)
            if not step_active.any():
                break
            if self._quant:
                (self._k_pages, self._v_pages, self._k_scales,
                 self._v_scales, nxt) = self._step_fn(
                    self._params, self._k_pages, self._v_pages,
                    self._k_scales, self._v_scales,
                    bt, jnp.asarray(self._lens),
                    jnp.asarray(cur), jnp.asarray(step_active),
                    eng._key)
                nxt = eng._host_fetch(nxt).astype(np.int32)
                eng._note_refolds(int(nxt[-1]))
                nxt = nxt[:-1]
            else:
                self._k_pages, self._v_pages, nxt = self._step_fn(
                    self._params, self._k_pages, self._v_pages,
                    bt, jnp.asarray(self._lens),
                    jnp.asarray(cur), jnp.asarray(step_active),
                    eng._key)
                nxt = eng._host_fetch(nxt).astype(np.int32)
            self._lens[step_active] += 1
            cur = np.where(step_active, nxt, cur).astype(np.int32)
            drafts[:, i] = np.where(step_active, nxt, 0)
        return drafts

    def on_accept(self, slot: int, pos_before: int, n_emitted: int):
        # draft K/V rows for the accepted drafts (positions
        # pos_before+1 .. pos_before+min(n_emitted, K)-? ) were computed
        # under the accepted prefix, so they are correct and stay; the
        # rejected tail rolls back by the same seq_lens trick as the
        # target cache.  The bonus/correction token was never fed to the
        # draft — next round's catch-up ingests it.
        self._lens[slot] = pos_before + min(n_emitted, self.k)


_DRAFTERS = {"prompt_lookup": PromptLookupDrafter}


def make_drafter(spec) -> Drafter:
    """Resolve a drafter: an instance passes through, a name constructs
    (FLAGS_spec_drafter supplies the default name).  `draft_model`
    drafters cannot be named — they need weights, pass an instance."""
    if isinstance(spec, Drafter):
        return spec
    try:
        return _DRAFTERS[spec]()
    except KeyError:
        raise ValueError(
            f"unknown drafter {spec!r}: pass one of "
            f"{sorted(_DRAFTERS)} or a Drafter instance") from None


# ---------------------------------------------------------------------------
# The propose -> verify -> accept loop
# ---------------------------------------------------------------------------
class SpeculativeDecoder:
    """One speculative round per engine step: reserve the verify window,
    draft K tokens per slot, score them in one donated jitted verify
    call, accept the matching prefix + one target token, roll the rest
    back by shrinking ``seq_lens``.  Every emitted token is a target-
    model sample, so greedy output is bit-identical to the
    non-speculative engine and stochastic output follows the target
    distribution exactly."""

    def __init__(self, engine, k: int, drafter=None, adaptive=False):
        from ..core import flags as _flags

        if k < 1:
            raise ValueError(f"spec_decode_k must be >= 1, got {k}")
        self.engine = engine
        self.k = int(k)
        if drafter is None:
            drafter = str(_flags.flag("spec_drafter"))
        self.drafter = make_drafter(drafter)
        self.drafter.bind(engine, self.k)
        self._verify_fn: Optional[_JitTracker] = None
        # adaptive per-slot speculation depth (FLAGS_spec_adaptive_k):
        # ``k_slot`` is each slot's LIVE depth, capped at the
        # configured k — drafter frames, verify windows, and the
        # ragged grid are all sized by k, so a per-slot depth is just
        # a smaller per-row span, never a new executable shape.
        # Multiplicative decrease on rejection streaks, +1 growth on
        # acceptance runs (gated by the cost model's per-kind
        # calibration via `_grow_ok`).
        self.adaptive = bool(adaptive)
        self.k_min = min(self.k,
                         max(1, int(_flags.flag("spec_k_min"))))
        self._shrink_after = max(
            1, int(_flags.flag("spec_k_shrink_streak")))
        self._grow_after = max(
            1, int(_flags.flag("spec_k_grow_streak")))
        self.k_slot = np.full(engine._slots, self.k, np.int32)
        self._rej_streak = np.zeros(engine._slots, np.int32)
        self._acc_streak = np.zeros(engine._slots, np.int32)

    # engine lifecycle hooks (DecodeEngine._prefill_into / _finish)
    def on_admit(self, slot: int, req):
        self._reset_k(slot)
        self.drafter.on_admit(slot, req)

    def on_finish(self, slot: int, req):
        self._reset_k(slot)
        self.drafter.on_finish(slot, req)

    def _reset_k(self, slot: int):
        """A slot changed hands: its acceptance history (and therefore
        its learned depth) belongs to the request that generated it."""
        self.k_slot[slot] = self.k
        self._rej_streak[slot] = 0
        self._acc_streak[slot] = 0

    def _grow_ok(self) -> bool:
        """Cost-model gate on depth growth: growing a slot's K only
        pays while one verify round costs less than the K+1 decode
        steps it replaces at full acceptance (the only regime growth
        triggers in).  Calibrated per-label seconds when the model has
        learned them ("spec" vs the decode-shaped label), raw roofline
        otherwise; no cost model (or an extraction failure) -> allow —
        the streak policy alone is still safe, just ungated."""
        eng = self.engine
        cost = eng._cost
        if cost is None:
            return True
        try:
            verify_kind = "ragged" if eng._ragged else "verify"
            decode_kind = "ragged" if eng._ragged else "decode"
            v = cost.raw_seconds(cost.profile_for(verify_kind))
            d = cost.raw_seconds(cost.profile_for(decode_kind))
            calib = cost.calibration_wire()
            v *= calib.get("spec", 1.0)
            d *= calib.get("ragged" if eng._ragged else "decode", 1.0)
        except Exception:
            return True
        return v <= d * (self.k + 1)

    def _adapt_k(self, slot: int, m: int, usable: int):
        """Per-slot depth controller, fed by this round's acceptance
        (``m`` of ``usable`` drafts matched): a full rejection extends
        the slot's rejection streak and, at ``spec_k_shrink_streak``,
        halves its depth toward ``spec_k_min`` (multiplicative
        decrease — a mispredicting regime stops paying for dead draft
        rows fast); a full acceptance extends the acceptance run and,
        at ``spec_k_grow_streak``, grows the depth by one (additive,
        cost-gated) back toward the configured K; a partial acceptance
        resets both streaks (the depth is about right)."""
        if usable <= 0:
            return  # depth-0 round (token budget exhausted): no signal
        if m == 0:
            self._acc_streak[slot] = 0
            self._rej_streak[slot] += 1
            if self._rej_streak[slot] >= self._shrink_after and \
                    int(self.k_slot[slot]) > self.k_min:
                self.k_slot[slot] = max(self.k_min,
                                        int(self.k_slot[slot]) // 2)
                self._rej_streak[slot] = 0
                _stats_add(spec_k_shrinks=1)
        elif m >= usable:
            self._rej_streak[slot] = 0
            self._acc_streak[slot] += 1
            if self._acc_streak[slot] >= self._grow_after and \
                    int(self.k_slot[slot]) < self.k:
                self._acc_streak[slot] = 0
                if self._grow_ok():
                    self.k_slot[slot] += 1
                    _stats_add(spec_k_grows=1)
        else:
            self._rej_streak[slot] = 0
            self._acc_streak[slot] = 0

    def step(self) -> bool:
        """One propose->verify->accept round over every active slot.
        Called by `DecodeEngine.step` after admission."""
        from ..profiler import RecordEvent

        eng = self.engine
        slots = eng._slots

        # the round's observation window opens BEFORE any chunk step:
        # paddle_decode_step_seconds must account every engine step's
        # full wall time, chunk ingestion included
        t_round0 = time.perf_counter()
        t_round0_ns = _obs.now_ns()
        if eng._chunked and eng._prefilling_any():
            # feed prompt chunks through the engine's mixed executable
            # first (decoding slots sit that call out — their tokens
            # come from the verify round below); the drafter ingests
            # the same chunks inside _mixed_step.  A slot whose LAST
            # chunk lands there flips to decoding and joins this very
            # round.
            eng._mixed_step(decode_rows=False)

        # verify window per slot, clamped to the request's remaining
        # token budget: KV rows past position prompt+max_new-2 are never
        # needed, and writing them would outrun the pool reservation.
        # Slots still mid-prefill keep cap 0 and skip the round.
        caps = np.zeros(slots, np.int32)
        for s in range(slots):
            if not eng._active[s] or eng._is_prefilling(s):
                continue
            req = eng._by_slot[s]
            need = req.max_new_tokens - len(req.output_ids)
            k_s = int(self.k_slot[s]) if self.adaptive else self.k
            caps[s] = min(k_s + 1, need)
        if not caps.any():
            # every live slot is still prefilling: the chunk step above
            # WAS this engine step — it owns the latency observation
            _obs.STEP_SECONDS.observe(time.perf_counter() - t_round0)
            return True
        eng._grow_block_tables(writes=caps)
        # quantized pools: freshly granted pages' scales zero BEFORE
        # the draft catch-up / verify write into them
        eng._flush_fresh_scales()
        pos_before = eng._lens.copy()

        fr = eng._flight
        t0 = time.perf_counter()
        t0_ns = _obs.now_ns()
        try:
            if eng._fault is not None:
                eng._resilience.fault_point("drafter")
            # "draft" is EXCLUSIVE of the blocking fetches the drafter
            # pays inside propose (those land on the "fetch" phase)
            with eng._excl_phase("draft"):
                drafts = self.drafter.propose(caps)
        except eng._resilience.NONRETRYABLE:
            raise
        except Exception as e:
            # drafter containment: a raising drafter costs this round
            # its speculation, never the step — the verify below runs
            # over zero drafts (all rejected, one genuine target token
            # per slot emitted: exactly a decode step through the
            # verify executable, no new shapes).  Repeated faults
            # degrade speculation off entirely (re-enable probe after
            # FLAGS_degraded_probe_steps clean steps).
            drafts = np.zeros((slots, self.k), np.int32)
            eng._resilience.on_drafter_fault(e)
        t_draft = time.perf_counter() - t0
        _obs.record_span("engine", "draft", t0_ns, int(t_draft * 1e9),
                         tid=eng._engine_id,
                         args={"drafter": self.drafter.name, "k": self.k})

        if eng._ragged:
            # FLAGS_ragged_step: the verify window is just a per-row
            # span on the engine's ONE ragged executable — same
            # program, same shapes as its decode/mixed dispatches, so
            # a speculative engine still compiles exactly one step
            # executable
            fn = eng._ragged_fn_tracker()
        else:
            fn = self._verify_fn
            if fn is None:
                if eng._kv_quant:
                    fn = self._verify_fn = _JitTracker(
                        functools.partial(_gpt_spec_verify_q,
                                          num_heads=eng._num_heads,
                                          head_dim=eng._head_dim,
                                          eps=eng._eps, **eng._sampling),
                        "verify_compiles", donate_argnums=(1, 2, 3, 4),
                        site="SpeculativeDecoder verify "
                             "(_gpt_spec_verify_q)")
                else:
                    fn = self._verify_fn = _JitTracker(
                        functools.partial(_gpt_spec_verify,
                                          num_heads=eng._num_heads,
                                          head_dim=eng._head_dim,
                                          eps=eng._eps, **eng._sampling),
                        "verify_compiles", donate_argnums=(1, 2),
                        site="SpeculativeDecoder verify "
                             "(_gpt_spec_verify)")

        tokens = np.concatenate(
            [eng._last[:, None].astype(np.int32), drafts], axis=1)
        if eng._ragged and tokens.shape[1] < eng._q_ragged:
            # pad the window out to the ragged grid's fixed Q_r (the
            # chunked-prefill width may exceed K+1); padding columns
            # sit past every cap and are never written or read
            tokens = np.concatenate(
                [tokens, np.zeros((slots, eng._q_ragged -
                                   tokens.shape[1]), np.int32)],
                axis=1)
        if eng._fault is not None:
            eng._resilience.step_fault_point("verify")
        eng._step_no += 1
        key = jax.random.fold_in(
            eng._key, _fold_counter(eng._step_no, RNG_DECODE_DOMAIN))
        t0 = time.perf_counter()
        tv_ns = _obs.now_ns()
        with RecordEvent("serving.spec_verify_step"):
            with eng._phase("verify"):
                if eng._kv_quant:
                    (eng._k_pages, eng._v_pages, eng._k_scales,
                     eng._v_scales, targets) = fn(
                        eng._params, eng._k_pages, eng._v_pages,
                        eng._k_scales, eng._v_scales,
                        eng._dev(eng._bt), eng._dev(eng._lens),
                        eng._dev(tokens), eng._dev(caps),
                        eng._dev(key))
                else:
                    eng._k_pages, eng._v_pages, targets = fn(
                        eng._params, eng._k_pages, eng._v_pages,
                        eng._dev(eng._bt), eng._dev(eng._lens),
                        eng._dev(tokens), eng._dev(caps),
                        eng._dev(key))
                if eng._profiling is not None:
                    # sampled device-sync probe (observability.
                    # profiling): the verify executable's measured
                    # device seconds, blocked inside the phase
                    eng._profiling.probe(
                        "ragged" if eng._ragged else "verify",
                        targets, t0, tv_ns)
            targets = eng._host_fetch(targets)
        if eng._kv_quant:
            eng._note_refolds(int(targets[slots, 0]))
            targets = targets[:slots]
        t_verify = time.perf_counter() - t0
        if eng._fault is not None:
            targets = eng._resilience.corrupt_tokens(
                targets, [s for s in range(slots) if caps[s] > 0])
        _obs.record_span("engine", "verify", tv_ns, int(t_verify * 1e9),
                         tid=eng._engine_id, args={"k": self.k})

        n_active = int(eng._active.sum())
        n_verify = int((caps > 0).sum())  # slots this round advanced
        emitted_total = 0
        proposed_total = 0
        accepted_total = 0
        with eng._excl_phase("emit"):
            for s in range(slots):
                if not eng._active[s] or caps[s] == 0:
                    continue
                req = eng._by_slot[s]
                w = int(caps[s])
                usable = min(self.k, w - 1)  # drafts acceptable
                m = 0
                while m < usable and \
                        int(drafts[s, m]) == int(targets[s, m]):
                    m += 1
                emit = [int(t) for t in drafts[s, :m]] + \
                    [int(targets[s, m])]
                if any(t < 0 for t in emit):
                    # non-finite logits somewhere in this slot's verify
                    # window: quarantine the slot without emitting
                    # (lens never advances over the poisoned rows, the
                    # drafter's on_finish resets its cursor) — the
                    # other slots' rounds are untouched
                    eng._quarantine_slot(s, "nan_logits")
                    continue
                if req.eos_token_id is not None:
                    for j, t in enumerate(emit):
                        if t == req.eos_token_id:
                            emit = emit[:j + 1]
                            break
                n_emit = len(emit)
                # accounted AFTER eos truncation so acceptance_rate
                # stays consistent with spec_emitted: drafts that
                # matched but were cut by an earlier eos never reached
                # the output
                proposed_total += usable
                accepted_total += min(m, n_emit)
                # through the engine's single emission point: the
                # streaming on_token hook fires per accepted token
                # exactly like on the classic decode path
                eng._emit(req, emit)
                # accepted rows keep their K/V; the rejected tail is
                # rolled back purely by NOT advancing seq_lens over it
                eng._lens[s] += n_emit
                eng._last[s] = emit[-1]
                emitted_total += n_emit
                eng._register_generated_pages(s, req)
                self.drafter.on_accept(s, int(pos_before[s]), n_emit)
                if self.adaptive:
                    self._adapt_k(s, m, usable)
                reason = eng._done(req, emit[-1])
                if reason:
                    eng._finish(s, reason)

        _stats_add(spec_steps=1, spec_slot_steps=n_verify, steps=1,
                   spec_proposed=proposed_total,
                   spec_accepted=accepted_total,
                   spec_emitted=emitted_total, tokens=emitted_total,
                   draft_time_s=t_draft, verify_time_s=t_verify,
                   decode_time_s=t_draft + t_verify,
                   occupancy_sum=n_active / slots,
                   kv_util_sum=eng.pool.utilization())
        _obs.SPEC_ACCEPTED_LAST.set(emitted_total, engine=eng._engine_id)
        # the round span opens at t_round0 (before any chunk-ingest
        # mixed step) and runs to NOW (draft + verify + the accept
        # loop): measured end-to-end so the chunk/draft/verify child
        # spans nest inside it and STEP_SECONDS sees the whole step
        eng._observe_step(t_round0_ns,
                          (_obs.now_ns() - t_round0_ns) / 1e9, n_active,
                          "spec_step",
                          extra_args={"k": self.k,
                                      "emitted": emitted_total})
        return True
