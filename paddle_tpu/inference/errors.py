"""Typed error taxonomy for the serving stack.

Before this module, serving failures were bare ``RuntimeError``s — a
caller (or the scheduler) could not tell "the KV pool is momentarily
full" (back off, stay queued) from "the step executable is broken"
(contain, quarantine, recover) without string-matching messages.  The
taxonomy makes the distinction typed:

* `ServingError` — base class of every serving-stack failure;
* `PoolExhausted` — `KVBlockPool.alloc_page` found neither a free nor
  an evictable page.  During ADMISSION the scheduler treats this as
  "stay queued" (the request waits for capacity, nothing crashes);
  mid-step it enters the containment ladder (`inference.resilience`)
  where quarantining a request frees pages;
* `StepFault` — a step executable (decode / mixed / verify / drafter)
  raised.  Carries the fault ``site`` and attempt count; raised as
  FATAL only after the whole containment ladder (retry -> degrade ->
  bisect-quarantine) is exhausted;
* `InjectedFault` — a `FaultPlan` fired (FLAGS_fault_inject); subclass
  of `StepFault` so every recovery path handles injected and organic
  faults identically — which is the point of the harness;
* `HungStep` — the watchdog (FLAGS_step_timeout_ms,
  `inference.durability.StepWatchdog`) classified a step as hung: it
  outran its wall-clock budget without compiling anything.  Subclass
  of `StepFault` with ``fatal=True`` so the existing recovery
  supervision (`serve_with_recovery`, `ServingFrontend._drive`)
  rebuilds the engine without a dedicated code path;
* `DegradedMode` — an operation needed a subsystem the engine has
  degraded away (e.g. crash recovery exhausted its rebuild budget).

All of them subclass ``RuntimeError`` so pre-taxonomy callers that
caught ``RuntimeError`` keep working unchanged.

`FaultInfo` is the structured terminal record a faulted request
carries (`Request.fault_info`, surfaced on
`inference.frontend.TokenStream.fault_info`): the fault site, how many
containment attempts were spent, and whether the engine recovered —
instead of a bare exception unwinding through a token iterator.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["ServingError", "PoolExhausted", "StepFault", "InjectedFault",
           "HungStep", "DegradedMode", "FaultInfo"]


class ServingError(RuntimeError):
    """Base class of every typed serving-stack failure."""


class PoolExhausted(ServingError):
    """The KV page pool has neither a free nor an evictable page.

    Admission treats this as backpressure (the request stays queued);
    inside a step it is containable — quarantining or preempting a
    request frees its pages."""


class StepFault(ServingError):
    """A step executable failed.  ``site`` names the failing
    executable/hook (see `inference.resilience.FAULT_SITES`);
    ``attempts`` counts containment attempts already spent when the
    fault was (re-)raised; ``fatal`` marks a fault that survived the
    whole containment ladder — the engine itself is suspect and only
    crash recovery (`inference.resilience.recover`) can continue."""

    def __init__(self, message: str, site: str = "step",
                 attempts: int = 0, fatal: bool = False):
        super().__init__(message)
        self.site = site
        self.attempts = int(attempts)
        self.fatal = bool(fatal)


class InjectedFault(StepFault):
    """A `FaultPlan` fired at a named site (FLAGS_fault_inject).
    Subclasses `StepFault` so containment cannot special-case injected
    faults — the harness proves the real recovery paths."""


class HungStep(StepFault):
    """The hung-step watchdog (`inference.durability.StepWatchdog`,
    FLAGS_step_timeout_ms) classified a step as stalled: it outran its
    wall-clock budget without compiling an executable.  Always
    ``fatal`` — a hang means the device/runtime is suspect, so the
    supervisor abandons the engine and rebuilds through the same
    recovery path a fatal `StepFault` takes (streams stay alive,
    already-emitted tokens are never re-emitted)."""

    def __init__(self, message: str, site: str = "hung",
                 attempts: int = 0):
        super().__init__(message, site=site, attempts=attempts,
                         fatal=True)


class DegradedMode(ServingError):
    """An operation required a subsystem the engine has degraded away,
    or a degradation budget (e.g. FLAGS_engine_recoveries) ran out."""


@dataclass
class FaultInfo:
    """Structured terminal state of a faulted (or fault-recovered)
    request — `Request.fault_info` / `TokenStream.fault_info`.

    ``site``: where the fault hit (containment ladder site name);
    ``attempts``: containment attempts spent on this request's behalf;
    ``step``: the engine step number the verdict landed on;
    ``recovered``: True when the request SURVIVED (e.g. it rode an
    engine rebuild and finished normally), False when it was
    quarantined (``finish_reason == "fault"``);
    ``message``: human-readable detail (the triggering exception)."""

    site: str
    attempts: int = 0
    step: int = 0
    recovered: bool = False
    message: str = ""
    # fault sites this request saw before the verdict (a request can
    # ride several recoveries before finishing)
    history: List[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"site": self.site, "attempts": self.attempts,
                "step": self.step, "recovered": self.recovered,
                "message": self.message, "history": list(self.history)}
