"""Op-version compatibility upgrades for loaded programs.

Reference: `framework/op_version_registry.h:142` — 67 reference ops carry
``REGISTER_OP_VERSION`` checkpoints recording incompatible changes
(new/deleted inputs, changed attribute defaults, bug-fixes that changed
behavior).  A serialized ProgramDesc stores each op type's version in
``op_version_map``; an executor loading an OLDER program must translate
old conventions to current semantics.

Most checkpoints need no action here: ``NewAttr`` entries choose defaults
equal to the old behavior (the checkpoint contract), and our translators
read attrs with those defaults.  The upgraders below cover the cases
where old programs mean something DIFFERENT:

* ``arg_max``/``arg_min`` < 1: the ``dtype`` default changed -1 -> 3
  (int64); old programs carrying -1/missing mean "int64 indices"
  (`operators/arg_max_op.cc:45`).
* ``roi_align`` < 1 / ``generate_proposals`` < 1: the bogus
  RpnRoisLod input/output was deleted
  (`operators/roi_align_op.cc:239`, `detection/generate_proposals_op.cc:305`).
* ``leaky_relu`` < 1: formula was ``max(x, alpha*x)`` (differs from the
  current piecewise form when alpha < 0 or alpha > 1); old programs keep
  the old math via the ``__legacy_formula__`` attr the interp translator
  honors (`operators/activation_op.cc` BugfixWithBehaviorChanged).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Tuple


def program_op_versions(desc: Dict) -> Dict[str, int]:
    """op type -> saved version (absent = 0, the pre-registry era)."""
    out: Dict[str, int] = {}
    vmap = desc.get("op_version_map") or {}
    for pair in vmap.get("pair", []):
        name = pair.get("op_name")
        ver = (pair.get("op_version") or {}).get("version", 0)
        if name:
            out[name] = int(ver)
    return out


def _set_attr(op_desc: Dict, name: str, value, attr_type: int):
    attrs = op_desc.setdefault("attrs", [])
    for a in attrs:
        if a.get("name") == name:
            a.clear()
            a.update(_attr(name, value, attr_type))
            return
    attrs.append(_attr(name, value, attr_type))


def _attr(name, value, attr_type):
    from .proto import AttrType as T

    key = {T.INT: "i", T.BOOLEAN: "b", T.FLOAT: "f",
           T.STRING: "s", T.LONG: "l"}[attr_type]
    return {"name": name, "type": attr_type, key: value}


def _get_attr(op_desc: Dict, name: str):
    for a in op_desc.get("attrs", []):
        if a.get("name") == name:
            return a
    return None


def _up_argmax_dtype(op_desc: Dict):
    from .proto import AttrType as T

    a = _get_attr(op_desc, "dtype")
    if a is None or a.get("i", a.get("l", -1)) in (-1, None):
        _set_attr(op_desc, "dtype", 3, T.INT)  # VarType int64


def _drop_io(slot: str, name: str) -> Callable[[Dict], None]:
    def up(op_desc: Dict):
        op_desc[slot] = [v for v in op_desc.get(slot, [])
                         if v.get("parameter") != name]
    return up


def _up_leaky_relu(op_desc: Dict):
    from .proto import AttrType as T

    _set_attr(op_desc, "__legacy_formula__", True, T.BOOLEAN)


# op type -> [(first_fixed_version, upgrader)]: the upgrader runs when the
# program's saved version is BELOW first_fixed_version
UPGRADERS: Dict[str, List[Tuple[int, Callable[[Dict], None]]]] = {
    "arg_max": [(1, _up_argmax_dtype)],
    "arg_min": [(1, _up_argmax_dtype)],
    "roi_align": [(1, _drop_io("inputs", "RpnRoisLod"))],
    "generate_proposals": [(1, _drop_io("outputs", "RpnRoisLod"))],
    "leaky_relu": [(1, _up_leaky_relu)],
}


def upgrade_program(desc: Dict) -> int:
    """Apply version upgraders in place to every block; returns the
    number of ops touched.  Idempotent (upgraders are)."""
    versions = program_op_versions(desc)
    touched = 0
    for block in desc.get("blocks", []):
        for op_desc in block.get("ops", []):
            ups = UPGRADERS.get(op_desc.get("type"))
            if not ups:
                continue
            saved = versions.get(op_desc["type"], 0)
            for fixed_at, fn in ups:
                if saved < fixed_at:
                    fn(op_desc)
                    touched += 1
    return touched
