"""Program/Block/Variable/Operator mirrors over the ProgramDesc format.

Reference: the Python mirror classes in `fluid/framework.py` (Program,
Block, Variable, Operator) wrapping the C++ descs
(`framework/program_desc.h:31`).  Here the descs are the plain dicts of
`paddle_tpu.static.proto`, and execution happens through the jnp
interpreter (`paddle_tpu.static.interp`) — the whole block traces to one
XLA computation.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import proto
from .proto import AttrType, VarType


class Variable:
    def __init__(self, block: "Block", desc: Dict[str, Any]):
        self.block = block
        self.desc = desc

    @property
    def name(self) -> str:
        return self.desc["name"]

    @property
    def persistable(self) -> bool:
        return bool(self.desc.get("persistable", False))

    @persistable.setter
    def persistable(self, v):
        self.desc["persistable"] = bool(v)

    @property
    def shape(self):
        t = self.desc.get("type", {})
        lt = t.get("lod_tensor")
        if lt:
            return tuple(lt["tensor"].get("dims", []))
        return ()

    @property
    def dtype(self):
        t = self.desc.get("type", {})
        lt = t.get("lod_tensor")
        if lt:
            return proto.vartype_to_np_dtype(lt["tensor"]["data_type"])
        return None

    def __repr__(self):
        return f"Variable({self.name}, shape={self.shape})"


class Operator:
    def __init__(self, block: "Block", desc: Dict[str, Any]):
        self.block = block
        self.desc = desc

    @property
    def type(self):
        return self.desc["type"]

    def input(self, name):
        for v in self.desc.get("inputs", []):
            if v["parameter"] == name:
                return v.get("arguments", [])
        return []

    def output(self, name):
        for v in self.desc.get("outputs", []):
            if v["parameter"] == name:
                return v.get("arguments", [])
        return []

    @property
    def input_arg_names(self):
        return [a for v in self.desc.get("inputs", [])
                for a in v.get("arguments", [])]

    @property
    def output_arg_names(self):
        return [a for v in self.desc.get("outputs", [])
                for a in v.get("arguments", [])]

    def attr(self, name):
        from .interp import _attr_value

        for a in self.desc.get("attrs", []):
            if a["name"] == name:
                return _attr_value(a)
        return None


def _attr_desc(name: str, value) -> Dict[str, Any]:
    """Python value -> OpDesc.Attr dict with the right AttrType."""
    d: Dict[str, Any] = {"name": name}
    if isinstance(value, bool):
        d["type"] = AttrType.BOOLEAN
        d["b"] = value
    elif isinstance(value, (int, np.integer)):
        if -(2 ** 31) <= int(value) < 2 ** 31:
            d["type"] = AttrType.INT
            d["i"] = int(value)
        else:
            d["type"] = AttrType.LONG
            d["l"] = int(value)
    elif isinstance(value, (float, np.floating)):
        d["type"] = AttrType.FLOAT
        d["f"] = float(value)
    elif isinstance(value, str):
        d["type"] = AttrType.STRING
        d["s"] = value
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], bool):
            d["type"] = AttrType.BOOLEANS
            d["bools"] = [bool(v) for v in value]
        elif value and isinstance(value[0], str):
            d["type"] = AttrType.STRINGS
            d["strings"] = list(value)
        elif value and isinstance(value[0], (float, np.floating)):
            d["type"] = AttrType.FLOATS
            d["floats"] = [float(v) for v in value]
        else:
            d["type"] = AttrType.INTS
            d["ints"] = [int(v) for v in value]
    elif isinstance(value, (BlockRef, Block)):
        d["type"] = AttrType.BLOCK
        d["block_idx"] = int(value.idx)
    else:
        raise TypeError(f"unsupported attr value {value!r}")
    return d


class BlockRef:
    """Marker for a BLOCK-typed op attribute (sub_block of
    while/conditional_block/recurrent): `attrs={"sub_block":
    BlockRef(idx)}`."""

    def __init__(self, idx: int):
        self.idx = int(idx)


class Block:
    def __init__(self, program: "Program", desc: Dict[str, Any]):
        self.program = program
        self.desc = desc
        desc.setdefault("vars", [])
        desc.setdefault("ops", [])

    @property
    def idx(self):
        return self.desc.get("idx", 0)

    @property
    def ops(self) -> List[Operator]:
        return [Operator(self, d) for d in self.desc["ops"]]

    def list_vars(self) -> List[Variable]:
        return [Variable(self, d) for d in self.desc["vars"]]

    def var(self, name) -> Variable:
        for d in self.desc["vars"]:
            if d["name"] == name:
                return Variable(self, d)
        raise KeyError(f"variable {name!r} not in block {self.idx}")

    def has_var(self, name) -> bool:
        return any(d["name"] == name for d in self.desc["vars"])

    def create_var(self, name, shape=None, dtype="float32",
                   persistable=False, type=VarType.LOD_TENSOR,
                   lod_level=0, need_check_feed=False) -> Variable:
        if self.has_var(name):
            return self.var(name)
        vt: Dict[str, Any] = {"type": type}
        if type == VarType.LOD_TENSOR:
            vt["lod_tensor"] = {
                "tensor": {
                    "data_type": proto.np_dtype_to_vartype(dtype),
                    "dims": [int(d) for d in (shape or [])],
                },
                "lod_level": lod_level,
            }
        d = {"name": name, "type": vt, "persistable": persistable,
             "need_check_feed": need_check_feed}
        self.desc["vars"].append(d)
        return Variable(self, d)

    def append_op(self, type: str, inputs: Optional[Dict] = None,
                  outputs: Optional[Dict] = None,
                  attrs: Optional[Dict] = None) -> Operator:
        def norm(m):
            out = []
            for param, args in (m or {}).items():
                if isinstance(args, str):
                    args = [args]
                out.append({"parameter": param,
                            "arguments": [str(a) for a in args]})
            return out

        d = {
            "type": type,
            "inputs": norm(inputs),
            "outputs": norm(outputs),
            "attrs": [_attr_desc(k, v)
                      for k, v in sorted((attrs or {}).items())],
        }
        self.desc["ops"].append(d)
        return Operator(self, d)


class Program:
    """A real ProgramDesc (reference `fluid/framework.py` Program)."""

    def __init__(self):
        self.desc: Dict[str, Any] = {
            "blocks": [{"idx": 0, "parent_idx": -1, "vars": [], "ops": []}],
            "version": {"version": 0},
        }
        self.random_seed = None

    # -- blocks --------------------------------------------------------------
    @property
    def blocks(self) -> List[Block]:
        return [Block(self, b) for b in self.desc["blocks"]]

    def global_block(self) -> Block:
        return Block(self, self.desc["blocks"][0])

    def block(self, idx) -> Block:
        return Block(self, self.desc["blocks"][idx])

    def create_block(self, parent_idx: int = 0) -> Block:
        """Append a sub-block (while/conditional_block/recurrent bodies;
        reference `BlockDesc` with parent_idx)."""
        d = {"idx": len(self.desc["blocks"]), "parent_idx": int(parent_idx),
             "vars": [], "ops": []}
        self.desc["blocks"].append(d)
        return Block(self, d)

    def num_blocks(self):
        return len(self.desc["blocks"])

    def list_vars(self):
        return [v for b in self.blocks for v in b.list_vars()]

    # -- serialization (the reference interchange contract) ------------------
    def serialize_to_string(self) -> bytes:
        # Stamp current op versions (reference REGISTER_OP_VERSION
        # registry): a reader of this program must not apply
        # pre-version-1 compat upgrades to ops we emitted with current
        # conventions (static/op_version.py).  Serialization works on a
        # COPY: interpreter-internal attrs are stripped from the wire
        # format, entries for op types outside our registry are
        # preserved verbatim, and ops still carrying legacy semantics
        # (__legacy_formula__ from a v0 load) keep version 0 so any
        # reader re-applies its own compat translation.
        import copy

        from .op_version import UPGRADERS

        desc = copy.deepcopy(self.desc)
        legacy_types = set()
        present = set()
        for b in desc.get("blocks", []):
            for op in b.get("ops", []):
                present.add(op["type"])
                attrs = op.get("attrs", [])
                if any(a.get("name") == "__legacy_formula__"
                       for a in attrs):
                    legacy_types.add(op["type"])
                    op["attrs"] = [a for a in attrs if
                                   a.get("name") != "__legacy_formula__"]
        vmap = desc.get("op_version_map") or {}
        pairs = {p.get("op_name"): p for p in vmap.get("pair", [])}
        for t in sorted(present & set(UPGRADERS)):
            ver = 0 if t in legacy_types else                 max(v for v, _ in UPGRADERS[t])
            pairs[t] = {"op_name": t, "op_version": {"version": ver}}
        if pairs:
            desc["op_version_map"] = {
                "pair": [pairs[k] for k in sorted(pairs)]}
        return proto.serialize_program(desc)

    @classmethod
    def parse_from_string(cls, data: bytes) -> "Program":
        from .op_version import upgrade_program

        p = cls()
        p.desc = proto.parse_program(data)
        p.desc.setdefault("blocks", [])
        # translate old-version op conventions to current semantics
        # (reference op_version_registry checkpoint application)
        upgrade_program(p.desc)
        return p

    def clone(self, for_test=False) -> "Program":
        import copy

        p = Program()
        p.desc = copy.deepcopy(self.desc)
        return p

    # -- feed/fetch discovery ------------------------------------------------
    def feed_target_names(self) -> List[str]:
        outs = []
        for op in self.global_block().ops:
            if op.type == "feed":
                outs.append((op.attr("col") or 0, op.output("Out")[0]))
        return [n for _, n in sorted(outs)]

    def fetch_target_names(self) -> List[str]:
        outs = []
        for op in self.global_block().ops:
            if op.type == "fetch":
                outs.append((op.attr("col") or 0, op.input("X")[0]))
        return [n for _, n in sorted(outs)]

    def persistable_vars(self) -> List[Variable]:
        seen = set()
        out = []
        for v in self.list_vars():
            if v.persistable and v.name not in seen and \
                    v.desc.get("type", {}).get("type") not in (
                        VarType.FEED_MINIBATCH, VarType.FETCH_LIST,
                        VarType.RAW):
                seen.add(v.name)
                out.append(v)
        return out


# ---------------------------------------------------------------------------
# Layer -> Program conversion (sequential topologies)
# ---------------------------------------------------------------------------
def program_from_layer(layer, input_spec, scope: Optional[Dict] = None
                       ) -> "Program":
    """Convert a sequential nn.Layer composition into a ProgramDesc with
    reference op types, collecting parameter values into `scope`.

    Covers the layer set of typical CNN/MLP inference models (Linear,
    Conv2D, BatchNorm2D, LayerNorm, Embedding, ReLU & friends, pooling,
    Flatten, Dropout, Softmax, Sequential/LayerList nesting).  The result
    is loadable by the REFERENCE framework (same op/attr names,
    `operators/*.cc`) and by our own interpreter/Predictor."""
    from .. import nn
    from .input_spec import InputSpec

    prog = Program()
    block = prog.global_block()
    scope = scope if scope is not None else {}
    counter = [0]

    specs = list(input_spec) if isinstance(input_spec, (list, tuple)) \
        else [input_spec]
    if not all(isinstance(s, InputSpec) for s in specs):
        raise TypeError("input_spec must be InputSpec(s)")
    if len(specs) > 1:
        # multi-input models have no sequential-chain reading — capture
        # by tracing (round 4)
        return _program_from_layer_traced_multi(layer, specs, scope)
    spec = specs[0]
    in_name = spec.name or "x"
    in_shape = [(-1 if s is None else int(s)) for s in spec.shape]
    in_dtype = str(spec.dtype or "float32")

    block.create_var("feed", type=VarType.FEED_MINIBATCH, persistable=True)
    block.create_var("fetch", type=VarType.FETCH_LIST, persistable=True)
    block.create_var(in_name, shape=in_shape, dtype=in_dtype,
                     need_check_feed=True)
    block.append_op("feed", {"X": "feed"}, {"Out": in_name}, {"col": 0})

    def fresh(prefix):
        counter[0] += 1
        return f"{prefix}_{counter[0]}.tmp"

    def fresh_var(prefix, dtype="float32"):
        # every op output needs a declared VarDesc: the reference executor
        # creates scope vars from block vars and FindVar-enforces them
        name = fresh(prefix)
        block.create_var(name, dtype=dtype)
        return name

    def add_param(name, tensor):
        arr = np.asarray(tensor.numpy())
        block.create_var(name, shape=list(arr.shape), dtype=str(arr.dtype),
                         persistable=True)
        scope[name] = arr
        return name

    def emit(ly, x):
        nm = getattr(ly, "_full_name", ly.__class__.__name__.lower())
        if isinstance(ly, (nn.Sequential,)):
            for sub in ly:
                x = emit(sub, x)
            return x
        if isinstance(ly, nn.Linear):
            w = add_param(fresh("w"), ly.weight)
            out = fresh("fc")
            block.create_var(out, dtype="float32")
            block.append_op("matmul_v2", {"X": x, "Y": w}, {"Out": out},
                            {"trans_x": False, "trans_y": False})
            if ly.bias is not None:
                b = add_param(fresh("b"), ly.bias)
                out2 = fresh("fc_bias")
                block.create_var(out2, dtype="float32")
                block.append_op("elementwise_add", {"X": out, "Y": b},
                                {"Out": out2}, {"axis": -1})
                out = out2
            return out
        if isinstance(ly, nn.Conv2D):
            w = add_param(fresh("conv_w"), ly.weight)
            out = fresh("conv")
            block.create_var(out, dtype="float32")
            def pair(v, default):
                v = getattr(ly, v, default)
                return [int(v), int(v)] if isinstance(v, int) else \
                    [int(a) for a in v]

            stride = pair("_stride", 1)
            pad = pair("_padding", 0)
            dil = pair("_dilation", 1)
            block.append_op(
                "conv2d", {"Input": x, "Filter": w}, {"Output": out},
                {"strides": stride, "paddings": pad, "dilations": dil,
                 "groups": int(getattr(ly, "_groups", 1)),
                 "padding_algorithm": "EXPLICIT",
                 "data_format": "NCHW"})
            if ly.bias is not None:
                b = add_param(fresh("conv_b"), ly.bias)
                out2 = fresh("conv_bias")
                block.create_var(out2, dtype="float32")
                block.append_op("elementwise_add", {"X": out, "Y": b},
                                {"Out": out2}, {"axis": 1})
                out = out2
            return out
        if isinstance(ly, (nn.BatchNorm2D, nn.BatchNorm1D)):
            scale = add_param(fresh("bn_scale"), ly.weight)
            bias = add_param(fresh("bn_bias"), ly.bias)
            mean = add_param(fresh("bn_mean"), ly._mean)
            var = add_param(fresh("bn_var"), ly._variance)
            out = fresh("bn")
            block.create_var(out, dtype="float32")
            sm = fresh_var("bn_saved_mean")
            sv = fresh_var("bn_saved_var")
            block.append_op(
                "batch_norm",
                {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                 "Variance": var},
                {"Y": out, "MeanOut": mean, "VarianceOut": var,
                 "SavedMean": sm, "SavedVariance": sv},
                {"epsilon": float(ly._epsilon), "is_test": True,
                 "data_layout": "NCHW"})
            return out
        if isinstance(ly, nn.LayerNorm):
            # begin_norm_axis: trailing dims are normalized; without full
            # shape inference we support the common normalize-last-axes
            # placement counted from the input spec's rank
            out = fresh("ln")
            block.create_var(out, dtype="float32")
            ins = {"X": x}
            if ly.weight is not None:
                ins["Scale"] = add_param(fresh("ln_scale"), ly.weight)
            if ly.bias is not None:
                ins["Bias"] = add_param(fresh("ln_bias"), ly.bias)
            nshape = getattr(ly, "_normalized_shape", None) or [0]
            begin = max(1, len(in_shape) - len(nshape))
            block.append_op(
                "layer_norm", ins,
                {"Y": out, "Mean": fresh_var("ln_mean"),
                 "Variance": fresh_var("ln_var")},
                {"epsilon": float(ly._epsilon),
                 "begin_norm_axis": int(begin)})
            return out
        if isinstance(ly, nn.Embedding):
            w = add_param(fresh("emb_w"), ly.weight)
            out = fresh("emb")
            block.create_var(out, dtype="float32")
            block.append_op("lookup_table_v2", {"W": w, "Ids": x},
                            {"Out": out}, {"padding_idx": -1})
            return out
        simple = {
            nn.ReLU: ("relu", {}),
            nn.Sigmoid: ("sigmoid", {}),
            nn.Tanh: ("tanh", {}),
            nn.GELU: ("gelu", {}),
            nn.Softmax: ("softmax", {"axis": -1}),
            nn.ReLU6: ("relu6", {}),
            nn.Silu: ("silu", {}),
            nn.Hardswish: ("hard_swish", {}),
        }
        for cls, (op_type, attrs) in simple.items():
            if isinstance(ly, cls):
                out = fresh(op_type)
                block.create_var(out, dtype="float32")
                block.append_op(op_type, {"X": x}, {"Out": out}, attrs)
                return out
        if isinstance(ly, nn.MaxPool2D) or isinstance(ly, nn.AvgPool2D):
            ptype = "max" if isinstance(ly, nn.MaxPool2D) else "avg"
            out = fresh("pool")
            block.create_var(out, dtype="float32")
            k = ly.ksize if hasattr(ly, "ksize") else ly.kernel_size
            k = [k, k] if isinstance(k, int) else list(k)
            s = getattr(ly, "stride", None) or k
            s = [s, s] if isinstance(s, int) else list(s)
            p = getattr(ly, "padding", 0)
            p = [p, p] if isinstance(p, int) else list(p)
            block.append_op("pool2d", {"X": x}, {"Out": out},
                            {"pooling_type": ptype, "ksize": k,
                             "strides": s, "paddings": p,
                             "global_pooling": False, "adaptive": False,
                             "ceil_mode": False, "exclusive": True})
            return out
        if isinstance(ly, nn.AdaptiveAvgPool2D):
            out = fresh("gap")
            block.create_var(out, dtype="float32")
            block.append_op("pool2d", {"X": x}, {"Out": out},
                            {"pooling_type": "avg", "ksize": [1, 1],
                             "strides": [1, 1], "paddings": [0, 0],
                             "global_pooling": True, "adaptive": False,
                             "ceil_mode": False, "exclusive": True})
            return out
        if isinstance(ly, nn.Flatten):
            out = fresh("flatten")
            block.create_var(out, dtype="float32")
            block.append_op("flatten_contiguous_range", {"X": x},
                            {"Out": out, "XShape": fresh_var("xshape")},
                            {"start_axis": int(getattr(ly, "start_axis",
                                                       1)),
                             "stop_axis": int(getattr(ly, "stop_axis",
                                                      -1))})
            return out
        if isinstance(ly, nn.Dropout):
            out = fresh("dropout")
            block.create_var(out, dtype="float32")
            block.append_op(
                "dropout", {"X": x},
                {"Out": out, "Mask": fresh_var("mask", "uint8")},
                {"dropout_prob": float(getattr(ly, "p", 0.5)),
                 "is_test": True,
                 "dropout_implementation": "upscale_in_train"})
            return out
        raise NotImplementedError(
            f"program_from_layer: no ProgramDesc emitter for "
            f"{ly.__class__.__name__} (wrap unsupported layers or use "
            "paddle_tpu.jit.save for the StableHLO deployable format)")

    # walk: a bare Layer whose children form a pipeline, or one with a
    # custom forward is only convertible if it IS Sequential-like
    if isinstance(layer, nn.Sequential):
        out_name = emit(layer, in_name)
    else:
        # chaining the children is only faithful when forward() IS that
        # chain; a custom forward (functional ops, branching) is
        # captured by TRACING instead (round 4: jaxpr -> ProgramDesc,
        # static/jaxpr_export.py) — any jax-traceable model exports
        if type(layer).forward is not nn.Layer.forward:
            return _program_from_layer_traced(layer, spec, scope,
                                              in_name)
        children = [ly for _, ly in layer.named_children()]
        if not children:
            raise NotImplementedError("layer has no convertible structure")
        out_name = in_name
        for ly in children:
            out_name = emit(ly, out_name)
    block.append_op("fetch", {"X": out_name}, {"Out": "fetch"}, {"col": 0})
    return prog


def _program_from_layer_traced_multi(layer, specs, scope,
                                     names=None):
    """Traced capture for layers with any number of inputs (the ONE
    trace-capture path; the single-input helper delegates here): every
    input becomes a feed target."""
    from ..core.tensor import Tensor, unwrap
    from .jaxpr_export import program_from_traced

    names = list(names) if names else \
        [s.name or f"input_{i}" for i, s in enumerate(specs)]
    reserved = {"feed", "fetch"}
    if len(set(names)) != len(names) or reserved & set(names):
        raise ValueError(
            f"program_from_layer: input names {names} must be unique "
            "and must not use the reserved names 'feed'/'fetch' (a "
            "collision would silently alias feeds)")
    examples = []
    for i, spec in enumerate(specs):
        if any(s in (-1, None) for s in spec.shape):
            raise NotImplementedError(
                "program_from_layer: traced export needs concrete "
                f"shapes; InputSpec[{i}] has a dynamic dim "
                f"{list(spec.shape)}")
        examples.append(np.zeros([int(s) for s in spec.shape],
                                 spec.dtype or "float32"))

    was_training = layer.training
    layer.eval()
    try:
        def fn(*xs):
            out = layer(*[Tensor(x) for x in xs])
            if isinstance(out, (tuple, list)):
                return tuple(unwrap(o) for o in out)
            return unwrap(out)

        prog = program_from_traced(fn, examples, scope,
                                   input_names=names)
    finally:
        if was_training:
            layer.train()
    return prog


def _program_from_layer_traced(layer, spec, scope, in_name):
    """Single-input traced capture — delegates to the multi-input
    helper (one implementation to maintain)."""
    return _program_from_layer_traced_multi(layer, [spec], scope,
                                            names=[in_name])
