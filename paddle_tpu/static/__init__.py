"""`paddle.static` equivalent.

Reference: `python/paddle/static/` re-exports the Program/Executor static
graph stack (`fluid/framework.py`, `fluid/executor.py:916`,
`fluid/backward.py:1369`).

TPU-native stance (SURVEY.md §7): there is no interpreted ProgramDesc — a
"static program" IS a jit-captured pure function.  This module provides the
reference's API shape on top of that: `InputSpec`, a minimal `Program` facade
(a recorded callable + captured state), program_guard/default programs for
source compatibility, and save/load_inference_model mapping onto
`paddle_tpu.jit.save/load` (serialized StableHLO + weights).
"""
from __future__ import annotations

import contextlib
import os

from .input_spec import InputSpec
from ..core.place import CPUPlace, TPUPlace


from . import nn  # noqa: E402  (control-flow + layer surface)
from . import proto  # noqa: E402
from .program import Block, Operator, Program, Variable, \
    program_from_layer  # noqa: E402
from .backward import append_backward, gradients  # noqa: E402


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev = (_default_main, _default_startup)
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev


class Executor:
    """Executor over real ProgramDescs (reference `fluid/executor.py:916` /
    `framework/executor.cc:292`): interprets the block's ops through the
    jnp translator — the whole program traces to one XLA computation.
    Also still accepts a bare python callable for source compatibility."""

    def __init__(self, place=None):
        self.place = place
        self.scope = {}
        self._runners = {}  # id(program) -> compiled ProgramRunner

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            **kwargs):
        feed = feed or {}
        if isinstance(program, Program):
            from .interp import ProgramRunner

            # layer-capture params are the DEFAULTS; the live scope (which
            # receives persistable write-back after each run) overrides
            # them, so training on a program_from_layer program advances
            base = dict(getattr(program, "_param_scope", None) or {})
            base.update(scope if scope is not None else self.scope)
            # key includes the op count across ALL blocks (append_backward
            # /minimize add ops; control-flow sub-block bodies can grow
            # too) and the desc version (set_lr rewrites attrs + bumps
            # it) so program mutations invalidate the compiled runner
            key = (id(program),
                   sum(len(blk["ops"]) for blk in program.desc["blocks"]),
                   program.desc.get("version", {}).get("version", 0))
            runner = self._runners.get(key)
            if runner is None:
                runner = ProgramRunner(program, base)
                self._runners[key] = runner
            import jax.numpy as jnp

            feeds = {k: jnp.asarray(v) for k, v in feed.items()}
            # current scope values override construction-time params so
            # weight updates between runs take effect
            fetch_vals, final_scope = runner.run_with_scope(feeds,
                                                            params=base)
            # persistable state (params, optimizer slots, lr) written by
            # the program flows back into the scope — Executor.run on a
            # minimize()d program is a full training step (reference
            # executor semantics: the Scope owns persistables)
            for v in program.persistable_vars():
                if v.name in final_scope:
                    target = scope if scope is not None else self.scope
                    target[v.name] = final_scope[v.name]
            if fetch_list:
                out = []
                for f in fetch_list:
                    name = getattr(f, "name", f)
                    if name in final_scope:
                        out.append(final_scope[name])
                    else:
                        raise KeyError(
                            f"fetch target {name!r} was not produced by "
                            "the program (known vars: "
                            f"{sorted(final_scope)[:20]}...)")
                return out
            return list(fetch_vals)
        if callable(program):
            outs = program(**feed)
            return outs if isinstance(outs, (list, tuple)) else [outs]
        return []

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Dataset-driven training loop (reference
        `fluid/executor.py:1663` -> MultiTrainer/HogwildWorker;
        `framework/data_set.h:157`).  Iterates the dataset's batches
        through the compiled program; optimizer ops inside the program
        update persistable state between batches."""
        if dataset is None:
            raise ValueError("dataset is required")
        if thread:
            dataset._set_thread(thread)
        names = [getattr(v, "name", str(v)) for v in dataset.use_vars]
        step = 0
        for batch in dataset.iter_batches():
            feed = {n: batch[n] for n in names}
            outs = self.run(program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
            step += 1
            if fetch_list and (debug or step % print_period == 0):
                import numpy as _np

                labels = fetch_info or [getattr(f, "name", f)
                                        for f in fetch_list]
                msg = ", ".join(
                    f"{k}={_np.asarray(v).ravel()[:4]}"
                    for k, v in zip(labels, outs))
                print(f"[train_from_dataset] step {step}: {msg}")
        return None

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """reference `fluid/executor.py:1540`; same loop, caller supplies
        an inference program (no optimizer ops)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)


def _combined_params_bytes(program: Program, scope: dict) -> bytes:
    """Reference `.pdiparams` / `__params__`: concatenated LoDTensor
    streams in LEXICOGRAPHIC var-name order (`inference/io.cc:112` sorts
    before appending load_combine).  Every persistable var must be in
    `scope` — a silent skip would shift every later record onto the
    wrong name at load time (records carry no names)."""
    names = sorted(v.name for v in program.persistable_vars())
    missing = [n for n in names if n not in scope]
    if missing:
        raise ValueError(
            f"save_inference_model: persistable vars missing from scope: "
            f"{missing}")
    return b"".join(proto.write_lod_tensor(scope[n]) for n in names)


def _load_combined_params(program: Program, data: bytes) -> dict:
    names = sorted(v.name for v in program.persistable_vars())
    scope = {}
    pos = 0
    for n in names:
        if pos >= len(data):
            raise ValueError(
                f"params file truncated: no record for var {n!r} "
                f"(expected {len(names)} records)")
        arr, _lod, pos = proto.read_lod_tensor(data, pos)
        # validate against the declared VarDesc shape (-1 = dynamic)
        want = program.global_block().var(n).shape if \
            program.global_block().has_var(n) else ()
        if want and len(want) == arr.ndim and any(
                w != -1 and w != s for w, s in zip(want, arr.shape)):
            raise ValueError(
                f"param {n!r} shape {arr.shape} does not match its "
                f"VarDesc {tuple(want)} — records/vars out of sync")
        scope[n] = arr
    if pos != len(data):
        raise ValueError(
            f"params file has {len(data) - pos} trailing bytes after "
            f"{len(names)} records — program/params mismatch")
    return scope


def save_inference_model(path_prefix, feed_vars=None, fetch_vars=None,
                         executor=None, program=None, layer=None,
                         input_spec=None, scope=None, **kwargs):
    """Write `{prefix}.pdmodel` + `{prefix}.pdiparams` in the REFERENCE
    interchange format (framework.proto ProgramDesc + combined LoDTensor
    records), loadable by reference-era tooling and by our Predictor.

    Accepts either a desc-backed `program` (+ `scope` of param arrays) or
    a sequential `layer` (+ `input_spec`) converted via
    `program_from_layer`."""
    if program is None:
        if layer is None:
            raise ValueError(
                "save_inference_model needs program= (desc Program) or "
                "layer= (+input_spec) to convert")
        scope = {}
        program = program_from_layer(layer, input_spec, scope)
    if scope is None:
        scope = {}
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(program.serialize_to_string())
    with open(path_prefix + ".pdiparams", "wb") as f:
        f.write(_combined_params_bytes(program, scope))
    return program


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Load a reference-format inference model.  Returns
    (program, feed_target_names, fetch_target_names); run it with
    `Executor.run(program, feed={...}, fetch_list=[...])` — params are
    pre-populated into the executor scope.

    Accepts `{prefix}.pdmodel`/`.pdiparams` pairs and legacy
    `dir/__model__` + `dir/__params__` layouts (`inference/io.cc`)."""
    if os.path.isdir(path_prefix):
        model_path = os.path.join(path_prefix, "__model__")
        params_path = os.path.join(path_prefix, "__params__")
    else:
        model_path = path_prefix + ".pdmodel"
        params_path = path_prefix + ".pdiparams"
    with open(model_path, "rb") as f:
        raw = f.read()
    try:
        program = Program.parse_from_string(raw)
        if not program.desc.get("blocks"):
            raise ValueError("no blocks")
    except Exception:
        # same extension, different artifact: paddle_tpu.jit.save stores
        # StableHLO under .pdmodel too — keep the old behavior for it
        from .. import jit

        return jit.load(path_prefix)
    scope = {}
    if os.path.exists(params_path):
        with open(params_path, "rb") as f:
            scope = _load_combined_params(program, f.read())
    if isinstance(executor, Executor):
        executor.scope.update(scope)
    else:
        # stash on the program so Predictor-style callers can reach params
        program._param_scope = scope
    return program, program.feed_target_names(), \
        program.fetch_target_names()


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count=None):
    return [CPUPlace(0)]


def cuda_places(device_ids=None):
    import jax

    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places


class WeightNormParamAttr:
    def __init__(self, *args, **kwargs):
        pass
