"""`paddle.static` equivalent.

Reference: `python/paddle/static/` re-exports the Program/Executor static
graph stack (`fluid/framework.py`, `fluid/executor.py:916`,
`fluid/backward.py:1369`).

TPU-native stance (SURVEY.md §7): there is no interpreted ProgramDesc — a
"static program" IS a jit-captured pure function.  This module provides the
reference's API shape on top of that: `InputSpec`, a minimal `Program` facade
(a recorded callable + captured state), program_guard/default programs for
source compatibility, and save/load_inference_model mapping onto
`paddle_tpu.jit.save/load` (serialized StableHLO + weights).
"""
from __future__ import annotations

import contextlib

from .input_spec import InputSpec
from ..core.place import CPUPlace, TPUPlace


from . import nn  # noqa: E402  (control-flow + layer surface)


class Program:
    """Facade for API parity.  Holds nothing until a function is captured."""

    def __init__(self):
        self.random_seed = None
        self._captured = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy

        return copy.copy(self)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev = (_default_main, _default_startup)
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev


class Executor:
    """API-parity executor (reference `fluid/executor.py:916`): in this
    framework `run` simply invokes a python callable captured via paddle_tpu
    jit; feed/fetch become the callable's inputs/outputs."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            feed = feed or {}
            outs = program(**feed)
            return outs if isinstance(outs, (list, tuple)) else [outs]
        return []


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=...) — the deployable "
        "format is serialized StableHLO + weights"
    )


def load_inference_model(path_prefix, executor=None, **kwargs):
    from .. import jit

    layer = jit.load(path_prefix)
    return layer


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count=None):
    return [CPUPlace(0)]


def cuda_places(device_ids=None):
    import jax

    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places


class WeightNormParamAttr:
    def __init__(self, *args, **kwargs):
        pass
