"""Static-program autodiff: append_backward / gradients over a ProgramDesc.

Reference: `python/paddle/fluid/backward.py:1369` (`append_backward` walks
the forward block in reverse, applies each op's GradOpMaker, and
aggregates duplicate gradients) and `:1964` (`gradients`).

TPU-native twist: instead of ~700 hand-written grad kernels, one generic
grad executor differentiates any translated forward op by re-tracing its
interpreter translation under `jax.vjp` (static/interp.py `run_grad_op`).
The emitted grad ops still follow the reference's program form — op type
`{fwd}_grad`, gradient vars named `X@GRAD`, reverse program order, a
`fill_constant` seeding loss@GRAD = 1 — so the augmented program remains
serializable through the framework.proto codec (the forward op is carried
in a string attr `__forward_op__`).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

from .program import Program, Variable

__all__ = ["append_backward", "gradients"]

GRAD_SUFFIX = "@GRAD"

# op types that never propagate gradients
_NON_DIFF = {
    "feed", "fetch", "fill_constant", "assign_value", "shape",
    "uniform_random", "gaussian_random", "range", "arg_max", "arg_min",
    "accuracy", "top_k", "top_k_v2",
}


def _op_io_args(op_desc: Dict, key: str) -> List[str]:
    return [a for slot in op_desc.get(key, [])
            for a in slot.get("arguments", [])]


def _append_grad_ops(block, target_names: List[str], stop_names: set,
                     target_grad_names: Optional[List[str]] = None
                     ) -> Dict[str, str]:
    """Emit `{type}_grad` ops in reverse program order for every op on the
    path to any of `target_names` (single pass — per-target passes would
    double-count shared subgraphs).  Returns forward-var -> grad-var
    names.  `target_grad_names` supplies user cotangent vars; targets
    without one are seeded with ones."""
    fwd_ops = list(block.desc["ops"])  # snapshot before appending

    needed = set(target_names)
    emit = []
    for op_desc in reversed(fwd_ops):
        if op_desc["type"] in _NON_DIFF or op_desc["type"].endswith("_grad"):
            continue
        outs = _op_io_args(op_desc, "outputs")
        if not any(o in needed for o in outs):
            continue
        ins = _op_io_args(op_desc, "inputs")
        overwritten = set(ins) & set(outs)
        if overwritten:
            # the grad executor recomputes each op from final scope
            # values; an op overwriting its own input would differentiate
            # at the wrong point (the reference renames such vars —
            # backward.py _rename_grad_); require single-assignment form
            raise ValueError(
                f"append_backward: op {op_desc['type']!r} writes its own "
                f"input var(s) {sorted(overwritten)}; use distinct output "
                "names on the path to the loss")
        emit.append(op_desc)
        for i in ins:
            if i not in stop_names:
                needed.add(i)

    # Re-entry guard (advisor round-2 finding): a second
    # append_backward/gradients pass whose grad vars overlap ones already
    # written on this block would silently double-accumulate (the grad
    # executor sums into existing @GRAD scope entries).  Detected
    # statelessly off the persistent desc (Block wrappers are ephemeral —
    # Program.global_block() builds a fresh one per call) by intersecting
    # the @GRAD vars this pass will write with existing op outputs;
    # passes over disjoint subgraphs remain allowed.
    existing_outs = {a for op in fwd_ops for a in _op_io_args(op, "outputs")}
    planned = {t + GRAD_SUFFIX for t in target_names}
    planned |= {a + GRAD_SUFFIX for op_desc in emit
                for a in _op_io_args(op_desc, "inputs")
                if a not in stop_names}
    clash = planned & existing_outs
    if clash:
        raise RuntimeError(
            f"append_backward/gradients: grad var(s) {sorted(clash)} are "
            "already written by earlier ops on this block; a second "
            "backward pass over the same vars would double-accumulate "
            "into them. Build a fresh Program (or clone) to re-derive "
            "gradients.")

    grad_map: Dict[str, str] = {}
    for k, target_name in enumerate(target_names):
        tvar = block.var(target_name)
        seed = target_grad_names[k] if target_grad_names else None
        if seed is not None:
            # honor the user cotangent (reference target_gradients)
            block.append_op("assign", {"X": seed},
                            {"Out": target_name + GRAD_SUFFIX}, {})
        else:
            # seed d(target)/d(target) = 1 (reference fill_constant)
            block.append_op(
                "fill_constant", inputs={},
                outputs={"Out": target_name + GRAD_SUFFIX},
                attrs={"shape": [int(d) for d in (tvar.shape or [1])],
                       "dtype": 5, "value": 1.0})
        block.create_var(target_name + GRAD_SUFFIX, shape=tvar.shape,
                         dtype=tvar.dtype)
        grad_map[target_name] = target_name + GRAD_SUFFIX
    for op_desc in emit:
        ins = {s["parameter"]: list(s.get("arguments", []))
               for s in op_desc.get("inputs", [])}
        outs = {s["parameter"]: list(s.get("arguments", []))
                for s in op_desc.get("outputs", [])}
        g_inputs = dict(ins)
        for p, args in outs.items():
            g_inputs[p] = args
            g_inputs[p + GRAD_SUFFIX] = [a + GRAD_SUFFIX for a in args]
        g_outputs = {}
        for p, args in ins.items():
            grads = []
            for a in args:
                if a in stop_names:
                    continue
                grads.append(a + GRAD_SUFFIX)
                grad_map[a] = a + GRAD_SUFFIX
                if not block.has_var(a + GRAD_SUFFIX):
                    src = block.var(a) if block.has_var(a) else None
                    block.create_var(
                        a + GRAD_SUFFIX,
                        shape=src.shape if src is not None else None,
                        dtype=src.dtype if src is not None else "float32")
            if grads:
                g_outputs[p + GRAD_SUFFIX] = grads
        attrs = {a["name"]: a for a in op_desc.get("attrs", [])}
        block.append_op(op_desc["type"] + "_grad", inputs=g_inputs,
                       outputs=g_outputs,
                       attrs={"__forward_op__": json.dumps(op_desc)})
        # carry the forward attrs verbatim (already proto-shaped dicts)
        block.desc["ops"][-1]["attrs"].extend(attrs.values())
    return grad_map


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    program: Optional[Program] = None):
    """reference `fluid/backward.py:1369`: append grad ops for `loss` and
    return [(parameter, gradient)] Variable pairs."""
    if isinstance(loss, Variable):
        block = loss.block
        loss_name = loss.name
    else:
        from . import default_main_program

        program = program or default_main_program()
        block = program.global_block()
        loss_name = str(loss)
    stop = set(no_grad_set or ())
    grad_map = _append_grad_ops(block, [loss_name], stop)

    if parameter_list is not None:
        params = [p if isinstance(p, str) else p.name
                  for p in parameter_list]
    else:
        params = [v.name for v in block.list_vars()
                  if v.persistable and v.name in grad_map]
    out = []
    for p in params:
        if p in grad_map and block.has_var(grad_map[p]):
            out.append((block.var(p), block.var(grad_map[p])))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference `fluid/backward.py:1964`: grad vars of `targets` w.r.t.
    `inputs` (list of Variables)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    block = targets[0].block
    stop = set(no_grad_set or ())
    tg_names = None
    if target_gradients is not None:
        tgs = target_gradients if isinstance(target_gradients,
                                             (list, tuple)) \
            else [target_gradients]
        tg_names = [None if g is None else
                    (g if isinstance(g, str) else g.name) for g in tgs]
    grad_map = _append_grad_ops(block, [tg.name for tg in targets], stop,
                                tg_names)
    outs = []
    for x in inputs:
        name = x if isinstance(x, str) else x.name
        g = grad_map.get(name)
        outs.append(block.var(g) if g and block.has_var(g) else None)
    return outs
