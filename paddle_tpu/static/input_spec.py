"""InputSpec (reference `python/paddle/static/input.py`)."""
from __future__ import annotations

from ..core import dtype as dtype_mod


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec([batch_size] + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)
