"""Declarative OpDesc -> eager bridge.

Reference counterpart: the reference executor runs ANY registered op out
of a ProgramDesc (`paddle/fluid/framework/executor.cc:166` — OpRegistry
lookup + `op->Run`), so every op in the operator library is reachable
from a serialized program.  Rounds 1-3 hand-wrote ~178 translators; this
module closes the remaining gap *declaratively*: each entry names the
eager function (already implemented under paddle_tpu/*) plus the OpDesc
input / attr / output parameter-name map, with the parameter and attr
names taken from the reference op makers (the interchange schema, e.g.
`paddle/fluid/operators/flip_op.cc` AddInput("X")/AddAttr("axis")).

The generic runner fetches inputs from the interp scope, converts attrs,
calls the eager function inside the interp trace (dispatch handles
tracers transparently — same mechanism as interp._via_functional), and
stores outputs — so a bridged block still compiles to ONE XLA
computation.

Spec DSL
--------
``b("flip reverse", "P:flip", ins="X", attrs="axis")``

* names: space-separated op types sharing one spec
* target: "<mod>:<attr>" resolved lazily (P=paddle_tpu, F=nn.functional,
  ops, seq=ops.sequence, vops=vision.ops, vdet=vision.detection,
  quant=quantization, metric) or a callable ``fn(*arrays, **attrs)``
* ins: tokens ``Name`` (required), ``?Name`` (optional -> omitted),
  ``*Name`` (variadic -> list of arrays)
* attrs: tokens ``name``, ``name->kw`` (rename), with optional ``@conv``
  converter (``dtype`` = VarType code -> numpy dtype string, ``ints`` =
  coerce to list of int).  An attr absent from the OpDesc is not passed,
  so the eager default applies.
* outs: tokens ``Name`` (required), ``?Name`` (skipped when the op desc
  doesn't declare it or the fn returned None), ``*Name`` (fn returns a
  sequence distributed over the output slot's argument list)
"""
from __future__ import annotations

import importlib
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .interp import OP_TRANSLATORS, register

_MODS = {
    "P": "paddle_tpu",
    "F": "paddle_tpu.nn.functional",
    "ops": "paddle_tpu.ops",
    "seq": "paddle_tpu.ops.sequence",
    "vops": "paddle_tpu.vision.ops",
    "vdet": "paddle_tpu.vision.detection",
    "quant": "paddle_tpu.quantization",
    "metric": "paddle_tpu.metric",
    "nnu": "paddle_tpu.nn.utils",
}


def _resolve(target: str) -> Callable:
    mod, _, attr = target.partition(":")
    fn = importlib.import_module(_MODS[mod])
    for part in attr.split("."):
        fn = getattr(fn, part)
    return fn


def _conv_dtype(v):
    from .proto import vartype_to_np_dtype

    return vartype_to_np_dtype(int(v))


_CONVS = {
    "dtype": _conv_dtype,
    "ints": lambda v: [int(x) for x in v],
    "int": int,
    "float": float,
    "bool": bool,
}


def _unwrap(x):
    from ..core.tensor import Tensor

    if isinstance(x, Tensor):
        return x._array
    return x


class _Spec:
    __slots__ = ("target", "ins", "attrs", "outs", "_fn")

    def __init__(self, target, ins, attrs, outs):
        self.target = target
        self.ins = [(t.lstrip("?*"), t[0] if t[0] in "?*" else "")
                    for t in ins.split()] if ins else []
        self.attrs = []
        for tok in (attrs.split() if attrs else []):
            name, _, conv = tok.partition("@")
            src, _, kw = name.partition("->")
            self.attrs.append((src, kw or src,
                               _CONVS[conv] if conv else None))
        self.outs = [(t.lstrip("?*"), t[0] if t[0] in "?*" else "")
                     for t in outs.split()] if outs else []
        self._fn = None

    def fn(self):
        if self._fn is None:
            self._fn = (self.target if callable(self.target)
                        else _resolve(self.target))
        return self._fn


def _run_spec(spec: _Spec, op, scope, feeds, fetches):
    args = []
    for name, mode in spec.ins:
        if mode == "*":
            args.append([scope.fetch(a) for a in op.inputs(name)])
        else:
            arg = op.input(name)
            if not arg:
                if mode == "?":
                    args.append(None)  # keep positional alignment
                    continue
                raise KeyError(
                    f"{op.type}: required input {name!r} missing")
            args.append(scope.fetch(arg))
    kw = {}
    for src, dst, conv in spec.attrs:
        if src in op._attrs:
            v = op._attrs[src]
            kw[dst] = conv(v) if conv else v
    out = spec.fn()(*args, **kw)
    _store_outs(spec, op, scope, out)


def _store_outs(spec, op, scope, out):
    if isinstance(out, (tuple, list)) and not (
            len(spec.outs) == 1 and spec.outs[0][1] != "*"):
        vals = list(out)
    else:
        if isinstance(out, (tuple, list)):
            if len(out) != 1:
                # a silent tuple-into-one-slot store corrupts
                # downstream ops (round-4 sweep caught two) — fail loud
                raise ValueError(
                    f"{op.type}: eager fn returned {len(out)} values "
                    f"but the spec declares one output slot "
                    f"{spec.outs[0][0]!r}; fix the spec's outs or "
                    "index the adapter's return")
            out = out[0]  # 1-tuple: store the value, not the tuple
        vals = [out]
    vi = 0
    for name, mode in spec.outs:
        slots = op.outputs(name)
        if mode == "*":
            seq = vals[vi] if len(spec.outs) > 1 else vals
            if len(seq) == 1 and isinstance(seq[0], (tuple, list)):
                seq = seq[0]
            for slot, v in zip(slots, seq):
                scope[slot] = _unwrap(v)
            vi += 1
            continue
        if not slots:
            if mode == "?":
                vi += 1
                continue
            raise KeyError(f"{op.type}: output slot {name!r} undeclared")
        v = vals[vi] if vi < len(vals) else None
        vi += 1
        if v is None:
            if mode == "?":
                continue
            raise ValueError(f"{op.type}: no value for output {name!r}")
        scope[slots[0]] = _unwrap(v)


BRIDGED: Dict[str, _Spec] = {}


def b(names: str, target, ins="X", attrs="", outs="Out"):
    spec = _Spec(target, ins, attrs, outs)
    for n in names.split():
        if n in OP_TRANSLATORS:  # hand-written translators win
            continue
        BRIDGED[n] = spec

        def _t(op, scope, feeds, fetches, _s=spec):
            _run_spec(_s, op, scope, feeds, fetches)

        OP_TRANSLATORS[n] = _t


# ---------------------------------------------------------------------------
# tensor math / manipulation (reference op makers under
# paddle/fluid/operators/*.cc — names cited per entry where non-obvious)
# ---------------------------------------------------------------------------
b("flip", "P:flip", ins="X", attrs="axis")
b("reverse", "P:flip", ins="X", attrs="axis")  # reverse_op.cc: axis ints
b("roll", "P:roll", ins="X", attrs="shifts axis")
b("strided_slice", lambda x, axes=(), starts=(), ends=(), strides=(),
    decrease_axis=(), infer_flags=():
    _strided_slice(x, axes, starts, ends, strides, decrease_axis),
  ins="Input", attrs="axes starts ends strides decrease_axis infer_flags")
b("index_select", "P:index_select", ins="X Index", attrs="dim->axis")
b("index_sample", "P:index_sample", ins="X Index")
b("tril_triu", lambda x, diagonal=0, lower=True:
    (jnp.tril if lower else jnp.triu)(x, k=int(diagonal)),
  ins="X", attrs="diagonal lower")
b("unbind", "P:unbind", ins="X", attrs="axis", outs="*Out")
b("unstack", "P:unstack", ins="X", attrs="axis num", outs="*Y")
b("meshgrid", "P:meshgrid", ins="*X", outs="*Out")
b("expand", lambda x, expand_times=():
    jnp.tile(x, tuple(int(t) for t in expand_times)),
  ins="X", attrs="expand_times")
b("expand_as", lambda x, y: jnp.tile(
    x, tuple(t // s for t, s in zip(y.shape, x.shape))),
  ins="X target_tensor")  # fluid v1 expand_as tiles by integer multiples
b("expand_as_v2", lambda x, target_shape=():
    jnp.broadcast_to(x, tuple(int(s) for s in target_shape)),
  ins="X", attrs="target_shape")
b("bmm", "P:bmm", ins="X Y")
b("mv", lambda x, vec: jnp.matmul(x, vec), ins="X Vec")
b("dot", "P:dot", ins="X Y")
b("cross", "P:cross", ins="X Y", attrs="dim->axis")
b("kron", "P:kron", ins="X Y")
b("addmm", "P:addmm", ins="Input X Y", attrs="Alpha->alpha Beta->beta")
b("diag_v2", "P:diag", ins="X", attrs="offset padding_value")
b("diag_embed", "P:diag_embed", ins="Input",
  attrs="offset dim1 dim2")
b("diagonal", "P:diagonal", ins="Input", attrs="offset axis1 axis2")
b("trace", "P:trace", ins="Input", attrs="offset axis1 axis2")
b("inverse", "P:inverse", ins="Input", outs="Output")
b("cholesky", "P:cholesky", ins="X", attrs="upper")
b("histogram", "P:histogram", ins="X", attrs="bins min max")
b("masked_select", "P:masked_select", ins="X Mask", outs="Y")
b("multiplex", lambda inputs, ids:
    jnp.take_along_axis(
        jnp.stack(inputs), ids.reshape(1, -1, *([1] * (inputs[0].ndim - 1))
                                       ).astype(jnp.int32), axis=0)[0],
  ins="*X Ids")
b("broadcast_tensors", "P:broadcast_tensors", ins="*X", outs="*Out")
b("allclose", "P:allclose", ins="Input Other",
  attrs="rtol@float atol@float equal_nan")
b("atan2", "P:atan2", ins="X1 X2")
b("digamma", "P:digamma")
b("lgamma", "P:lgamma")
b("expm1", lambda x: jnp.expm1(x))
b("trunc", "P:trunc", ins="X")
b("logsumexp", "P:logsumexp", ins="X",
  attrs="axis keepdim")
b("conj", "P:conj")
b("real", "P:real")
b("imag", "P:imag")
b("arg_min", lambda x, axis=0, keepdims=False, dtype=3, flatten=False:
    jnp.argmin(x.reshape(-1) if flatten else x,
               axis=None if flatten else int(axis),
               keepdims=keepdims and not flatten).astype(_conv_dtype(dtype)),
  ins="X", attrs="axis keepdims dtype flatten")
b("dist", "P:dist", ins="X Y", attrs="p")
b("eye", lambda num_rows=0, num_columns=-1, dtype=5:
    jnp.eye(int(num_rows),
            int(num_columns) if int(num_columns) >= 0 else None,
            dtype=_conv_dtype(dtype)),
  ins="", attrs="num_rows num_columns dtype")
b("size", lambda x: jnp.asarray(int(np.prod(x.shape)), jnp.int64),
  ins="Input")
b("linspace", lambda start, stop, num, dtype=5:
    jnp.linspace(start.reshape(()), stop.reshape(()),
                 int(num.reshape(())),
                 dtype=_conv_dtype(dtype)),
  ins="Start Stop Num", attrs="dtype")
b("crop", lambda x, offsets=(), shape=():
    jax.lax.dynamic_slice(x, [int(o) for o in offsets],
                          [int(s) for s in shape]),
  ins="X", attrs="offsets shape")
b("crop_tensor", lambda x, offsets=(), shape=():
    jax.lax.dynamic_slice(
        x, [int(o) for o in (offsets or [0] * x.ndim)],
        [x.shape[i] if int(s) == -1 else int(s)
         for i, s in enumerate(shape or x.shape)]),
  ins="X", attrs="offsets shape")
b("scatter_nd_add", "P:scatter_nd_add", ins="X Index Updates")
b("gather_tree", "ops:gather_tree", ins="Ids Parents")
b("segment_pool", lambda x, seg, pooltype="SUM":
    _seg_pool(x, seg, pooltype),
  ins="X SegmentIds", attrs="pooltype", outs="Out ?SummedIds")


def _seg_pool(x, seg, pooltype):
    from paddle_tpu import ops as _ops

    return _ops.segment_pool(x, seg, pool_type=pooltype.lower())
b("where_index", lambda x: jnp.stack(jnp.nonzero(x), axis=1)
    .astype(jnp.int64), ins="Condition")
b("minus", lambda x, y: x - y, ins="X Y")
b("grad_add", lambda x, y: x + y, ins="X Y")
b("squared_l2_norm", lambda x: jnp.sum(jnp.square(x)).reshape(1))
b("l1_norm", lambda x: jnp.sum(jnp.abs(x)).reshape(1))
b("frobenius_norm", lambda x, dim=(), keep_dim=False, reduce_all=False:
    jnp.sqrt(jnp.sum(jnp.square(x),
                     axis=None if reduce_all or not dim
                     else tuple(int(d) for d in dim),
                     keepdims=keep_dim)),
  ins="X", attrs="dim keep_dim reduce_all")
b("shard_index", "ops:shard_index", ins="X",
  attrs="index_num nshards shard_id ignore_value")
b("unique", lambda x, dtype=3, return_index=False, return_inverse=False,
    return_counts=False, axis=(), is_sorted=True:
    _unique(x, dtype, return_index, return_inverse, return_counts, axis),
  ins="X", attrs="dtype return_index return_inverse return_counts "
                 "axis is_sorted",
  outs="Out ?Indices ?Index ?Counts")
b("unique_with_counts", lambda x, dtype=2:
    _unique_with_counts(x, dtype),
  ins="X", attrs="dtype", outs="Out Index Count")
b("fill", lambda shape=(), value=0.0, dtype=5:
    jnp.full([int(s) for s in shape], value, _conv_dtype(dtype)),
  ins="", attrs="shape value dtype")
b("fill_constant_batch_size_like",
  lambda x, shape=(), value=0.0, dtype=5, input_dim_idx=0,
  output_dim_idx=0: _batch_size_like(x, shape, input_dim_idx,
                                     output_dim_idx, value,
                                     _conv_dtype(dtype)),
  ins="Input", attrs="shape value dtype input_dim_idx output_dim_idx")
b("empty", lambda shape=(), dtype=5:
    jnp.zeros([int(s) for s in shape], _conv_dtype(dtype)),
  ins="", attrs="shape dtype")
b("seed", lambda seed=0: jnp.asarray(seed or 0, jnp.int32),
  ins="", attrs="seed")


def _strided_slice(x, axes, starts, ends, strides, decrease_axis):
    idx = [slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        ax = int(ax) % x.ndim
        n = x.shape[ax]
        s, e, st = int(s), int(e), int(st)
        # reference clamps INT_MAX/negative bounds (strided_slice_op.h)
        if s < 0:
            s += n
        if e < 0:
            e += n
        if st > 0:
            e = min(e, n)
        elif e < 0:
            # end walked past the front (e.g. ends=[-n-1] or INT_MIN with
            # a negative stride): python slice needs None, -1 would mean
            # "stop before the last element"
            e = None
        idx[ax] = slice(s, e, st)
    out = x[tuple(idx)]
    if decrease_axis:
        out = out.reshape([d for i, d in enumerate(out.shape)
                           if i not in {int(a) for a in decrease_axis}])
    return out


def _unique(x, dtype, return_index, return_inverse, return_counts, axis):
    axis = int(axis[0]) if axis else None
    res = jnp.unique(x, return_index=True, return_inverse=True,
                     return_counts=True, axis=axis)
    out, index, inverse, counts = res
    idt = _conv_dtype(dtype)
    vals = [out]
    vals.append(index.astype(idt) if return_index else None)
    vals.append(inverse.reshape(-1).astype(idt) if return_inverse else None)
    vals.append(counts.astype(idt) if return_counts else None)
    return tuple(vals)


def _unique_with_counts(x, dtype):
    out, inverse, counts = jnp.unique(x, return_inverse=True,
                                      return_counts=True)
    idt = _conv_dtype(dtype)
    return out, inverse.reshape(-1).astype(idt), counts.astype(idt)


def _batch_size_like(x, shape, in_idx, out_idx, value, dtype):
    return jnp.full(_bsl_shape(x, shape, in_idx, out_idx), value, dtype)


# random family: key = PRNGKey(op seed attr) folded with a crc of the
# output var name, so two random ops in one program draw DIFFERENT
# samples (the hand-written uniform_random translator's stance, hardened
# per round-4 review). Program-level reproducibility still holds: same
# program + same seeds -> same draws.
def _op_key(op, seed=0):
    import zlib

    # fold in the op's first declared output name (not every op calls
    # its output "Out" — e.g. dpsgd writes "ParamOut")
    names = [a for args in op._out.values() for a in args]
    tag = names[0] if names else op.type
    return jax.random.fold_in(jax.random.PRNGKey(seed or 0),
                              zlib.crc32(tag.encode()))


def braw(*names):
    """Register a raw translator (full op access) under the bridge's
    'hand over only if unclaimed' rule, and record it as bridged."""
    def deco(fn):
        for n in names:
            if n not in OP_TRANSLATORS:
                OP_TRANSLATORS[n] = fn
                BRIDGED[n] = fn
        return fn
    return deco


@braw("bernoulli")
def _bernoulli(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.random.bernoulli(
        _op_key(op), x.astype(jnp.float32)).astype(x.dtype)


@braw("multinomial")
def _multinomial(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    k = int(op.attr("num_samples", 1))
    logits = jnp.log(x.astype(jnp.float32) + 1e-30)
    if op.attr("replacement", False):
        out = jax.random.categorical(_op_key(op), logits,
                                     shape=x.shape[:-1] + (k,))
    else:
        # Gumbel top-k == sampling without replacement
        g = jax.random.gumbel(_op_key(op), logits.shape)
        _, out = jax.lax.top_k(logits + g, k)
    scope[op.output("Out")] = out.astype(jnp.int64)


@braw("sampling_id")
def _sampling_id(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.random.categorical(
        _op_key(op, op.attr("seed", 0)), jnp.log(x + 1e-30),
        axis=-1).astype(jnp.int64)
@braw("randint")
def _randint_op(op, scope, feeds, fetches):
    scope[op.output("Out")] = jax.random.randint(
        _op_key(op, op.attr("seed", 0)),
        [int(s) for s in op.attr("shape", [])], int(op.attr("low", 0)),
        int(op.attr("high", 1))).astype(
        _conv_dtype(op.attr("dtype", 3)))


@braw("randperm")
def _randperm_op(op, scope, feeds, fetches):
    scope[op.output("Out")] = jax.random.permutation(
        _op_key(op, op.attr("seed", 0)), int(op.attr("n", 0))).astype(
        _conv_dtype(op.attr("dtype", 3)))


@braw("gaussian_random_batch_size_like")
def _gauss_bsl_op(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    shape = _bsl_shape(x, op.attr("shape", []),
                       op.attr("input_dim_idx", 0),
                       op.attr("output_dim_idx", 0))
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.normal(
        _op_key(op, op.attr("seed", 0)), shape, jnp.float32)
    scope[op.output("Out")] = out.astype(_conv_dtype(op.attr("dtype", 5)))


@braw("uniform_random_batch_size_like")
def _unif_bsl_op(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    shape = _bsl_shape(x, op.attr("shape", []),
                       op.attr("input_dim_idx", 0),
                       op.attr("output_dim_idx", 0))
    out = jax.random.uniform(_op_key(op, op.attr("seed", 0)), shape,
                             jnp.float32, op.attr("min", -1.0),
                             op.attr("max", 1.0))
    scope[op.output("Out")] = out.astype(_conv_dtype(op.attr("dtype", 5)))


@braw("truncated_gaussian_random")
def _trunc_gauss_op(op, scope, feeds, fetches):
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * \
        jax.random.truncated_normal(
            _op_key(op, op.attr("seed", 0)), -2.0, 2.0,
            [int(s) for s in op.attr("shape", [])])
    scope[op.output("Out")] = out.astype(_conv_dtype(op.attr("dtype", 5)))


def _bsl_shape(x, shape, in_idx, out_idx):
    shape = [int(s) for s in shape]
    shape[int(out_idx)] = x.shape[int(in_idx)]
    return shape


# ---------------------------------------------------------------------------
# activations / nn functional (reference operators/activation_op.cc +
# individual op makers; loss ops are ELEMENTWISE in fluid — reduction is a
# separate mean/sum op in the program, so adapters pass reduction='none')
# ---------------------------------------------------------------------------
b("elu", "F:elu", ins="X", attrs="alpha")
b("selu", "F:selu", ins="X", attrs="scale alpha")
b("maxout", "F:maxout", ins="X", attrs="groups axis")
b("label_smooth", lambda x, prior=None, epsilon=0.1: _label_smooth(
    x, prior, epsilon), ins="X ?PriorDist", attrs="epsilon")
b("log_loss", lambda p, y, epsilon=1e-4:
    -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
  ins="Predicted Labels", attrs="epsilon", outs="Loss")
b("bce_loss", lambda x, y: -(y * jnp.log(jnp.clip(x, 1e-12))
                             + (1 - y) * jnp.log(jnp.clip(1 - x, 1e-12))),
  ins="X Label")
b("huber_loss", lambda x, y, delta=1.0: (
    y - x,
    jnp.where(jnp.abs(y - x) <= delta, 0.5 * jnp.square(y - x),
              delta * (jnp.abs(y - x) - 0.5 * delta))),
  ins="X Y", attrs="delta", outs="?Residual Out")
b("margin_rank_loss", lambda x1, x2, label, margin=0.0: (
    (margin - label * (x1 - x2)) > 0,
    jnp.maximum(0.0, margin - label * (x1 - x2))),
  ins="X1 X2 Label", attrs="margin", outs="?Activated Out")
b("rank_loss", lambda label, left, right:
    jnp.log(1 + jnp.exp(left - right)) - label * (left - right),
  ins="Label Left Right")
b("hinge_loss", lambda logits, labels:
    jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits),
  ins="Logits Labels", outs="Loss")
b("modified_huber_loss", lambda x, y: _modified_huber(x, y)[::-1],
  ins="X Y", outs="?IntermediateVal Out")
b("teacher_student_sigmoid_loss",
  lambda x, z, soft_max_up_bound=15.0, soft_max_lower_bound=-15.0:
    _teacher_student_loss(x, z),
  ins="X Label", attrs="soft_max_up_bound soft_max_lower_bound",
  outs="Y")
b("bpr_loss", lambda x, label: _bpr_loss(x, label), ins="X Label",
  outs="Y")
b("squared_l2_distance", lambda x, y: (
    x - y, jnp.sum(jnp.square(x - y), axis=tuple(range(1, x.ndim)))
    .reshape(-1, 1)),
  ins="X Y", outs="?sub_result Out")
b("cos_sim", lambda x, y: _cos_sim(x, y), ins="X Y",
  outs="Out ?XNorm ?YNorm")
b("kldiv_loss", lambda x, target, reduction="mean": _unwrap(
    _F().kl_div(x, target, reduction=reduction)),
  ins="X Target", attrs="reduction", outs="Loss")
b("nll_loss", lambda x, label, weight=None, ignore_index=-100,
    reduction="mean": _unwrap(_F().nll_loss(
        x, label, weight=weight, ignore_index=int(ignore_index),
        reduction=reduction)),
  ins="X Label ?Weight", attrs="ignore_index reduction",
  outs="Out ?Total_weight")
b("smooth_l1_loss", lambda x, y, iw=None, ow=None, sigma=1.0:
    _fluid_smooth_l1(x, y, iw, ow, sigma),
  ins="X Y ?InsideWeight ?OutsideWeight", attrs="sigma",
  outs="?Diff Out")
b("sigmoid_focal_loss", lambda x, label, fg=None, gamma=2.0, alpha=0.25:
    _fluid_sigmoid_focal(x, label, fg, gamma, alpha),
  ins="X Label ?FgNum", attrs="gamma alpha")
b("warpctc", lambda logits, label, llen=None, lablen=None, blank=0,
    norm_by_times=False: _warpctc(logits, label, llen, lablen, blank,
                                  norm_by_times),
  ins="Logits Label ?LogitsLength ?LabelLength",
  attrs="blank norm_by_times", outs="Loss ?WarpCTCGrad")
b("lrn", lambda x, n=5, k=2.0, alpha=1e-4, beta=0.75,
    data_format="NCHW": _unwrap(_F().local_response_norm(
        x, int(n), alpha=alpha, beta=beta, k=k,
        data_format=data_format)),
  ins="X", attrs="n k alpha beta data_format", outs="Out ?MidOut")
b("unpool", lambda x, indices, ksize=(2, 2), strides=(2, 2),
    paddings=(0, 0): _unwrap(_F().max_unpool2d(
        x, indices.astype(jnp.int32), [int(v) for v in ksize],
        stride=[int(v) for v in strides],
        padding=[int(v) for v in paddings])),
  ins="X Indices", attrs="ksize strides paddings")
b("spp", lambda x, pyramid_height=1, pooling_type="max": _unwrap(
    _F().spatial_pyramid_pool(x, int(pyramid_height),
                              pool_type=pooling_type.lower())),
  ins="X", attrs="pyramid_height pooling_type")
b("unfold", lambda x, kernel_sizes, strides=(1, 1), paddings=(0, 0),
    dilations=(1, 1): _unwrap(_F().unfold(
        x, [int(v) for v in kernel_sizes],
        strides=[int(v) for v in strides],
        paddings=[int(v) for v in paddings],
        dilations=[int(v) for v in dilations])),
  ins="X", attrs="kernel_sizes strides paddings dilations", outs="Y")
b("affine_channel", lambda x, scale, bias, data_layout="NCHW": _unwrap(
    _P().affine_channel(x, scale, bias, data_layout=data_layout)),
  ins="X Scale Bias", attrs="data_layout")
b("shuffle_channel", lambda x, group=1: _unwrap(
    _F().channel_shuffle(x, int(group))), ins="X", attrs="group")
b("space_to_depth", lambda x, blocksize=1: _unwrap(
    _ops().space_to_depth(x, int(blocksize))),
  ins="X", attrs="blocksize")
b("row_conv", lambda x, w: _unwrap(_P().row_conv(x, w)), ins="X Filter")
b("pad", lambda x, paddings=(), pad_value=0.0:
    jnp.pad(x, [(int(paddings[2 * i]), int(paddings[2 * i + 1]))
                for i in range(x.ndim)], constant_values=pad_value),
  ins="X", attrs="paddings pad_value")
b("pad_constant_like", lambda x, y, pad_value=0.0:
    jnp.pad(y, [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)],
            constant_values=pad_value),
  ins="X Y", attrs="pad_value")
b("temporal_shift", lambda x, seg_num, shift_ratio=0.25,
    data_format="NCHW": _unwrap(_F().temporal_shift(
        x, int(seg_num), shift_ratio, data_format=data_format)),
  ins="X", attrs="seg_num shift_ratio data_format")
b("fsp", lambda x, y: _unwrap(_ops().fsp_matrix(x, y)), ins="X Y")
b("add_position_encoding", lambda x, alpha=1.0, beta=1.0: _unwrap(
    _ops().add_position_encoding(x, alpha, beta)),
  ins="X", attrs="alpha beta")
b("cvm", lambda x, cvm_in, use_cvm=True: _unwrap(
    _ops().cvm(x, cvm_in, use_cvm=use_cvm)),
  ins="X CVM", attrs="use_cvm", outs="Y")
b("conv_shift", lambda x, y: _unwrap(_ops().conv_shift(x, y)),
  ins="X Y")
b("hash", lambda x, num_hash=1, mod_by=100000000: _unwrap(
    _ops().hash_op(x, num_hash=int(num_hash), mod_by=int(mod_by))),
  ins="X", attrs="num_hash mod_by")
b("similarity_focus", lambda x, axis=1, indexes=(): _unwrap(
    _ops().similarity_focus(x, int(axis), [int(i) for i in indexes])),
  ins="X", attrs="axis indexes")
b("batch_fc", lambda x, w, bias=None: _unwrap(
    _ops().batch_fc(x, w, bias)), ins="Input W ?Bias")
b("rank_attention", lambda x, off, par, MaxRank=3, MaxSize=0: _unwrap(
    _ops().rank_attention(x, off, par, max_rank=int(MaxRank),
                          max_size=int(MaxSize))),
  ins="X RankOffset RankParam", attrs="MaxRank MaxSize",
  outs="Out ?InputHelp ?InsRank")
b("lookup_table_dequant", lambda w, ids, padding_idx=-1: _unwrap(
    _ops().lookup_table_dequant(w, ids)), ins="W Ids",
  attrs="padding_idx")
b("edit_distance", lambda hyps, refs, hl=None, rl=None,
    normalized=True: _edit_distance(hyps, refs, hl, rl, normalized),
  ins="Hyps Refs ?HypsLength ?RefsLength", attrs="normalized",
  outs="Out ?SequenceNum")
b("ctc_align", lambda x, xlen=None, blank=0, merge_repeated=True,
    padding_value=0: _ctc_align(x, xlen, blank, merge_repeated,
                                padding_value),
  ins="Input ?InputLength", attrs="blank merge_repeated padding_value",
  outs="Output ?OutputLength")
b("multihead_matmul", lambda inp, w, bias=None, bias_qk=None,
    alpha=1.0, head_number=1, **_: _multihead_matmul(
        inp, w, bias, bias_qk, alpha, int(head_number)),
  ins="Input W ?Bias ?BiasQK", attrs="alpha head_number")
b("im2sequence", lambda x, kernels=(1, 1), strides=(1, 1),
    paddings=(0, 0, 0, 0), out_stride=(1, 1): _im2sequence(
        x, kernels, strides, paddings),
  ins="X", attrs="kernels strides paddings out_stride")
b("bilinear_tensor_product", lambda x, y, w, bias=None:
    _bilinear_tp(x, y, w, bias), ins="X Y Weight ?Bias")
b("mean_iou", lambda pred, label, num_classes=2: _unwrap(
    _metric().mean_iou(pred, label, int(num_classes))),
  ins="Predictions Labels", attrs="num_classes",
  outs="OutMeanIou ?OutWrong ?OutCorrect")


def _P():
    import paddle_tpu

    return paddle_tpu


def _F():
    from paddle_tpu.nn import functional

    return functional


def _ops():
    from paddle_tpu import ops

    return ops


def _metric():
    from paddle_tpu import metric

    return metric


def _label_smooth(x, prior, epsilon):
    if prior is not None:
        return (1 - epsilon) * x + epsilon * prior
    return (1 - epsilon) * x + epsilon / x.shape[-1]


def _modified_huber(x, y):
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z >= -1.0, jnp.square(jnp.maximum(0.0, 1.0 - z)),
                     -4.0 * z)
    return loss, z


def _teacher_student_loss(x, label):
    # delegate to the eager op's 4-case piecewise formula
    # (teacher_student_sigmoid_loss_op.h; the soft_max_*_bound attrs
    # only clip the sigmoid in the reference GRAD kernel) — the bridge
    # previously computed plain sigmoid CE, which is only the label<0
    # half of the reference encoding
    from paddle_tpu import ops as _o

    return _unwrap(_o.teacher_student_sigmoid_loss(x, label))


def _bpr_loss(x, label):
    # reference bpr_loss_op.h: -mean_{j != y} log(sigmoid(x_y - x_j))
    n, c = x.shape
    xy = jnp.take_along_axis(x, label.reshape(-1, 1).astype(jnp.int32), 1)
    diff = xy - x
    logsig = -jnp.log1p(jnp.exp(-diff))
    mask = jnp.ones((n, c)).at[jnp.arange(n),
                               label.reshape(-1).astype(jnp.int32)].set(0)
    return -(logsig * mask).sum(1, keepdims=True) / (c - 1)


def _cos_sim(x, y):
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / (xn * yn)
    return out, xn, yn


def _fluid_smooth_l1(x, y, iw, ow, sigma):
    s2 = float(sigma) * float(sigma)
    diff = (x - y) * (iw if iw is not None else 1.0)
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / s2, 0.5 * s2 * jnp.square(diff),
                    ad - 0.5 / s2)
    if ow is not None:
        val = val * ow
    red = tuple(range(1, x.ndim))
    return diff, jnp.sum(val, axis=red).reshape(-1, 1)


def _fluid_sigmoid_focal(x, label, fg, gamma, alpha):
    # detection variant (operators/detection/sigmoid_focal_loss_op.cc):
    # per-class one-vs-all with fg-count normalization
    num_classes = x.shape[1]
    lab = label.reshape(-1).astype(jnp.int32)
    onehot = (lab[:, None] == jnp.arange(1, num_classes + 1)[None, :])
    onehot = onehot.astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = -(onehot * jnp.log(jnp.clip(p, 1e-12))
           + (1 - onehot) * jnp.log(jnp.clip(1 - p, 1e-12)))
    w = onehot * alpha * jnp.power(1 - p, gamma) + \
        (1 - onehot) * (1 - alpha) * jnp.power(p, gamma)
    out = ce * w
    if fg is not None:
        out = out / jnp.maximum(fg.reshape(()).astype(x.dtype), 1.0)
    return out


def _warpctc(logits, label, llen, lablen, blank, norm_by_times):
    from paddle_tpu.nn import functional as F

    if llen is None:
        llen = jnp.full((logits.shape[1],), logits.shape[0], jnp.int64)
    if lablen is None:
        lablen = jnp.full((label.shape[0],), label.shape[1], jnp.int64)
    loss = F.ctc_loss(jax.nn.log_softmax(logits, -1), label, llen,
                      lablen, blank=int(blank), reduction="none",
                      norm_by_times=norm_by_times)
    return _unwrap(loss).reshape(-1, 1)


def _edit_distance(hyps, refs, hl, rl, normalized):
    from paddle_tpu import ops as _o

    out = _o.edit_distance(hyps, refs, normalized=normalized,
                           input_length=hl, label_length=rl)
    out = _unwrap(out[0] if isinstance(out, tuple) else out)
    return out, jnp.asarray([hyps.shape[0]], jnp.int64)


def _ctc_align(x, xlen, blank, merge_repeated, padding_value):
    from paddle_tpu import ops as _o

    out = _o.ctc_align(x, blank=int(blank),
                       merge_repeated=merge_repeated,
                       padding_value=int(padding_value),
                       input_length=xlen)
    if isinstance(out, tuple):
        return tuple(_unwrap(o) for o in out)
    return _unwrap(out), None


def _multihead_matmul(inp, w, bias, bias_qk, alpha, heads):
    # fused QKV self-attention (operators/fused/multihead_matmul_op.cc):
    # Input [B,S,H], W [H, 3H] (or [3,H,H] packed), Bias [3H]
    bsz, seq, hid = inp.shape
    if w.ndim == 3:
        # packed [3,H,H]: a flat reshape would row-major-interleave the
        # three matrices; the [H,3H] form is their last-axis concat
        w = jnp.concatenate([w[0], w[1], w[2]], axis=-1)
    qkv = inp @ w.reshape(hid, -1)
    if bias is not None:
        qkv = qkv + bias.reshape(-1)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def split_heads(t):
        return t.reshape(bsz, seq, heads, hid // heads).transpose(
            0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    scores = (q @ k.transpose(0, 1, 3, 2)) * alpha
    if bias_qk is not None:
        scores = scores + bias_qk
    out = jax.nn.softmax(scores, -1) @ v
    return out.transpose(0, 2, 1, 3).reshape(bsz, seq, hid)


def _im2sequence(x, kernels, strides, paddings):
    from paddle_tpu.nn import functional as F

    cols = _unwrap(F.unfold(x, [int(k) for k in kernels],
                            strides=[int(s) for s in strides],
                            paddings=[int(p) for p in paddings[:2]]))
    n, ck, L = cols.shape
    return cols.transpose(0, 2, 1).reshape(n * L, ck)


def _bilinear_tp(x, y, w, bias):
    # out[n,k] = x[n,:] @ W[k] @ y[n,:]  (bilinear_tensor_product_op.cc)
    out = jnp.einsum("ni,kij,nj->nk", x, w, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


# ---------------------------------------------------------------------------
# conv3d / pool3d family (shared Conv/PoolOpMaker schemas — same attr
# names as the hand-written conv2d/pool2d translators)
# ---------------------------------------------------------------------------
@braw("conv3d")
def _conv3d_op(op, scope, feeds, fetches):
    from paddle_tpu.nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    pad = op.attr("paddings", [0, 0, 0])
    algo = op.attr("padding_algorithm", "EXPLICIT")
    if algo in ("SAME", "VALID"):
        pad = algo
    out = F.conv3d(x, w, None, stride=op.attr("strides", [1, 1, 1]),
                   padding=pad, dilation=op.attr("dilations", [1, 1, 1]),
                   groups=max(op.attr("groups", 1), 1),
                   data_format=op.attr("data_format", "NCDHW"))
    scope[op.output("Output")] = _unwrap(out)


@braw("conv3d_transpose")
def _conv3d_transpose_op(op, scope, feeds, fetches):
    from paddle_tpu.nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    out = F.conv3d_transpose(
        x, w, None, stride=op.attr("strides", [1, 1, 1]),
        padding=op.attr("paddings", [0, 0, 0]),
        dilation=op.attr("dilations", [1, 1, 1]),
        groups=max(op.attr("groups", 1), 1))
    scope[op.output("Output")] = _unwrap(out)


@braw("depthwise_conv2d_transpose")
def _dw_conv2d_t(op, scope, feeds, fetches):
    from paddle_tpu.nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    g = int(op.attr("groups", 0)) or int(x.shape[1])  # default: depthwise
    out = F.conv2d_transpose(
        x, w, None, stride=op.attr("strides", [1, 1]),
        padding=op.attr("paddings", [0, 0]),
        groups=g)
    scope[op.output("Output")] = _unwrap(out)


@braw("pool3d")
def _pool3d_op(op, scope, feeds, fetches):
    from paddle_tpu.nn import functional as F

    x = scope.fetch(op.input("X"))
    ptype = op.attr("pooling_type", "max")
    if op.attr("global_pooling", False):
        red = (2, 3, 4)
        out = jnp.mean(x, red, keepdims=True) if ptype == "avg" else \
            jnp.max(x, red, keepdims=True)
        scope[op.output("Out")] = out
        return
    kwargs = dict(kernel_size=op.attr("ksize", [1, 1, 1]),
                  stride=op.attr("strides", [1, 1, 1]),
                  padding=op.attr("paddings", [0, 0, 0]),
                  ceil_mode=op.attr("ceil_mode", False))
    if ptype == "avg":
        out = F.avg_pool3d(x, exclusive=op.attr("exclusive", True),
                           **kwargs)
    else:
        out = F.max_pool3d(x, **kwargs)
    scope[op.output("Out")] = _unwrap(out)


@braw("max_pool2d_with_index", "max_pool3d_with_index")
def _pool_with_index(op, scope, feeds, fetches):
    from paddle_tpu.nn import functional as F

    x = scope.fetch(op.input("X"))
    nd = 2 if op.type == "max_pool2d_with_index" else 3
    ksize = op.attr("ksize", [1] * nd)
    if op.attr("global_pooling", False):
        ksize = list(x.shape[2:])
    fn = F.max_pool2d if nd == 2 else F.max_pool3d
    out, mask = _via(fn, x, ksize, stride=op.attr("strides", [1] * nd),
                     padding=op.attr("paddings", [0] * nd),
                     return_mask=True)
    scope[op.output("Out")] = _unwrap(out)
    if op.output("Mask"):
        scope[op.output("Mask")] = _unwrap(mask)


def _via(fn, *a, **kw):
    out = fn(*a, **kw)
    if isinstance(out, tuple):
        return tuple(_unwrap(o) for o in out)
    return _unwrap(out)


@braw("data_norm")
def _data_norm_op(op, scope, feeds, fetches):
    # reference operators/data_norm_op.cc: means = BatchSum/BatchSize,
    # scales = sqrt(BatchSize/BatchSquareSum)
    x = scope.fetch(op.input("X"))
    bsize = scope.fetch(op.input("BatchSize"))
    bsum = scope.fetch(op.input("BatchSum"))
    bsq = scope.fetch(op.input("BatchSquareSum"))
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    y = (x - means) * scales
    if op.attr("enable_scale_and_shift", False):
        y = y * scope.fetch(op.input("scale_w")) + \
            scope.fetch(op.input("bias"))
    scope[op.output("Y")] = y
    if op.output("Means"):
        scope[op.output("Means")] = means
    if op.output("Scales"):
        scope[op.output("Scales")] = scales


@braw("inplace_abn")
def _inplace_abn_op(op, scope, feeds, fetches):
    # activation-fused batch_norm (inplace_abn_op.cc); inference form
    OP_TRANSLATORS["batch_norm"](op, scope, feeds, fetches)
    act = op.attr("activation", "")
    y = scope[op.output("Y")]
    if act == "relu":
        y = jnp.maximum(y, 0)
    elif act == "leaky_relu":
        y = jnp.where(y > 0, y, y * op.attr("alpha", 0.01))
    elif act == "elu":
        a = op.attr("alpha", 1.0)
        y = jnp.where(y > 0, y, a * (jnp.exp(y) - 1))
    scope[op.output("Y")] = y


@braw("spectral_norm")
def _spectral_norm_op(op, scope, feeds, fetches):
    # operators/spectral_norm_op.cc: power iteration on W reshaped with
    # `dim` leading
    w = scope.fetch(op.input("Weight"))
    u = scope.fetch(op.input("U")).reshape(-1)
    v = scope.fetch(op.input("V")).reshape(-1)
    dim = op.attr("dim", 0)
    eps = op.attr("eps", 1e-12)
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
    for _ in range(max(op.attr("power_iters", 1), 0)):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wm @ v
    out = jnp.transpose((wm / sigma).reshape([w.shape[dim]] +
                                             [w.shape[i] for i in perm[1:]]),
                        np.argsort(perm).tolist())
    scope[op.output("Out")] = out


@braw("shuffle_batch")
def _shuffle_batch_op(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    seed_in = op.input("Seed")
    seed = scope.fetch(seed_in).reshape(()) if seed_in else \
        jnp.asarray(op.attr("startup_seed", 0), jnp.int32)
    idx = jax.random.permutation(_op_key(op, int(seed) if
                                 not isinstance(seed, jax.core.Tracer)
                                 else 0), x.shape[0])
    scope[op.output("Out")] = x[idx]
    if op.output("ShuffleIdx"):
        scope[op.output("ShuffleIdx")] = idx.astype(jnp.int64)
    if op.output("SeedOut"):
        scope[op.output("SeedOut")] = jnp.reshape(
            seed.astype(jnp.int64) + 1, (1,))


@braw("filter_by_instag")
def _filter_by_instag_op(op, scope, feeds, fetches):
    from paddle_tpu import ops as _o

    out = _o.filter_by_instag(
        scope.fetch(op.input("Ins")), scope.fetch(op.input("Ins_tag")),
        scope.fetch(op.input("Filter_tag")),
        is_lod=op.attr("is_lod", True),
        out_val_if_empty=op.attr("out_val_if_empty", 0))
    outs = out if isinstance(out, tuple) else (out,)
    names = ["Out", "LossWeight", "IndexMap"]
    for n, v in zip(names, outs):
        if op.output(n):
            scope[op.output(n)] = _unwrap(v)


@braw("set_value")
def _set_value_op(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    x = scope.fetch(op.input("Input"))
    axes = [int(a) for a in op.attr("axes", [])]
    starts = [int(s) for s in op.attr("starts", [])]
    ends = [int(e) for e in op.attr("ends", [])]
    steps = [int(s) for s in op.attr("steps", [])] or [1] * len(axes)
    vt = op.input("ValueTensor")
    if vt:
        value = scope.fetch(vt)
    else:
        shape = [int(s) for s in op.attr("shape", [])]
        value = None
        for key in ("fp32_values", "int32_values", "int64_values",
                    "bool_values", "fp64_values"):
            vals = op.attr(key)
            if vals:
                value = jnp.asarray(np.asarray(vals).reshape(shape))
                break
        if value is None:
            value = jnp.zeros(shape,
                              vartype_to_np_dtype(op.attr("dtype", 5)))
    idx = [slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, steps):
        n = x.shape[ax]
        s += n if s < 0 else 0
        e += n if e < 0 else 0
        idx[ax] = slice(s, min(e, n), st)
    scope[op.output("Out")] = x.at[tuple(idx)].set(
        value.astype(x.dtype))


# ---------------------------------------------------------------------------
# sequence family on the padded+lengths LoD representation (reference
# operators/sequence_ops/*.cc) — each translator reads the `<name>@LOD`
# sidecar (full length when absent) and writes the output's sidecar so
# downstream sequence ops see correct lengths
# ---------------------------------------------------------------------------
def _seq_len(scope, name, x):
    from .interp import _seq_lengths_or_full

    return _seq_lengths_or_full(scope, name, x)


@braw("sequence_concat")
def _sequence_concat_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_concat

    names = op.inputs("X")
    xs = [scope.fetch(n) for n in names]
    lens = [_seq_len(scope, n, x) for n, x in zip(names, xs)]
    out = sequence_concat(xs, lens)
    out, out_len = (out if isinstance(out, tuple)
                    else (out, sum(lens)))
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = _unwrap(out_len)


@braw("sequence_conv")
def _sequence_conv_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_conv

    name = op.input("X")
    x = scope.fetch(name)
    w = scope.fetch(op.input("Filter"))
    lens = _seq_len(scope, name, x)
    ctx_len = op.attr("contextLength", 3)
    # filter arrives [ctx_len*D, out] (reference layout); eager wants it
    # the same way, only the context hyper-params map across
    pad_name = op.input("PaddingData")
    out = sequence_conv(
        x, lens, w, context_length=int(ctx_len),
        context_start=op.attr("contextStart", None),
        padding_data=scope.fetch(pad_name) if pad_name and
        op.attr("paddingTrainable", False) else None)
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = lens


@braw("sequence_enumerate")
def _sequence_enumerate_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_enumerate

    name = op.input("X")
    x = scope.fetch(name)
    lens = _seq_len(scope, name, x)
    out = sequence_enumerate(x, lens, int(op.attr("win_size", 1)),
                             pad_value=op.attr("pad_value", 0))
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = lens


@braw("sequence_erase")
def _sequence_erase_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_erase

    name = op.input("X")
    x = scope.fetch(name)
    lens = _seq_len(scope, name, x)
    out = sequence_erase(x, lens, list(op.attr("tokens", [])))
    out, new_len = out if isinstance(out, tuple) else (out, lens)
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = _unwrap(new_len)


@braw("sequence_expand")
def _sequence_expand_op(op, scope, feeds, fetches):
    # reference: expand rows of X per Y's lod at ref_level.  On
    # padded+lengths: Y's @LOD provides the repeat counts.
    from paddle_tpu import sequence_expand

    xname, yname = op.input("X"), op.input("Y")
    x = scope.fetch(xname)
    y = scope.fetch(yname)
    reps = _seq_len(scope, yname, y)
    out = sequence_expand(x, np.asarray(reps).tolist()
                          if not isinstance(reps, jax.core.Tracer)
                          else reps)
    scope[op.output("Out")] = _unwrap(out)


@braw("sequence_expand_as")
def _sequence_expand_as_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_expand_as

    xname, yname = op.input("X"), op.input("Y")
    x = scope.fetch(xname)
    y = scope.fetch(yname)
    ylen = _seq_len(scope, yname, y)
    scope[op.output("Out")] = _unwrap(sequence_expand_as(x, ylen))
    scope[op.output("Out") + "@LOD"] = ylen


@braw("sequence_reshape")
def _sequence_reshape_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_reshape

    name = op.input("X")
    x = scope.fetch(name)
    lens = _seq_len(scope, name, x)
    out = sequence_reshape(x, lens, int(op.attr("new_dim", x.shape[-1])))
    out, new_len = out if isinstance(out, tuple) else (out, lens)
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = _unwrap(new_len)


@braw("sequence_scatter")
def _sequence_scatter_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_scatter

    ids_name = op.input("Ids")
    ids = scope.fetch(ids_name)
    upd = scope.fetch(op.input("Updates"))
    x = scope.fetch(op.input("X"))
    ilen = _seq_len(scope, ids_name, ids)
    scope[op.output("Out")] = _unwrap(sequence_scatter(x, ids, upd, ilen))


@braw("sequence_slice")
def _sequence_slice_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_slice

    name = op.input("X")
    x = scope.fetch(name)
    lens = _seq_len(scope, name, x)
    off = scope.fetch(op.input("Offset")).reshape(-1)
    ln = scope.fetch(op.input("Length")).reshape(-1)
    out = sequence_slice(x, lens, off, ln)
    if isinstance(out, tuple):  # (padded, new_lengths)
        out, ln = out
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = _unwrap(ln).astype(jnp.int32)


@braw("sequence_unpad")
def _sequence_unpad_op(op, scope, feeds, fetches):
    from paddle_tpu import sequence_unpad

    x = scope.fetch(op.input("X"))
    ln = scope.fetch(op.input("Length")).reshape(-1)
    out = sequence_unpad(x, ln)
    scope[op.output("Out")] = _unwrap(out)
    scope[op.output("Out") + "@LOD"] = ln.astype(jnp.int32)


@braw("sequence_topk_avg_pooling")
def _sequence_topk_avg_pooling_op(op, scope, feeds, fetches):
    from paddle_tpu.ops.sequence import sequence_topk_avg_pooling

    xname = op.input("X")
    x = scope.fetch(xname)
    row_name, col_name = op.input("ROW"), op.input("COLUMN")
    rlen = _seq_len(scope, row_name, scope.fetch(row_name)) \
        if row_name else _seq_len(scope, xname, x)
    clen = _seq_len(scope, col_name, scope.fetch(col_name)) \
        if col_name else jnp.full((x.shape[0],), x.shape[-1], jnp.int32)
    out = sequence_topk_avg_pooling(
        x, rlen, clen, [int(k) for k in op.attr("topks", [1])],
        channel_num=int(op.attr("channel_num", 1)))
    scope[op.output("Out")] = _unwrap(out)
    if op.output("pos"):
        scope[op.output("pos")] = jnp.zeros((1,), jnp.int32)


# ---------------------------------------------------------------------------
# vision / detection family (reference operators/detection/*.cc).  RoI
# batching: reference passes LoD rois or a RoisNum tensor; adapters take
# RoisNum when present, else the `@LOD` sidecar, else all-rois-in-image-0.
# ---------------------------------------------------------------------------
def _rois_num(op, scope, rois, param="RoisNum"):
    name = op.input(param)
    if name:
        return scope.fetch(name).reshape(-1).astype(jnp.int32)
    key = op.input("ROIs") + "@LOD"
    if key in scope:
        return jnp.asarray(scope[key]).reshape(-1).astype(jnp.int32)
    return jnp.asarray([rois.shape[0]], jnp.int32)


@braw("roi_pool")
def _roi_pool_op(op, scope, feeds, fetches):
    from paddle_tpu.vision.ops import roi_pool

    x = scope.fetch(op.input("X"))
    rois = scope.fetch(op.input("ROIs"))
    out = roi_pool(x, rois, _rois_num(op, scope, rois),
                   (int(op.attr("pooled_height", 1)),
                    int(op.attr("pooled_width", 1))),
                   spatial_scale=op.attr("spatial_scale", 1.0))
    scope[op.output("Out")] = _unwrap(out)
    if op.output("Argmax"):
        scope[op.output("Argmax")] = jnp.zeros(
            _unwrap(out).shape, jnp.int64)


b("psroi_pool", lambda x, rois, output_channels=1, spatial_scale=1.0,
    pooled_height=1, pooled_width=1: _unwrap(_vops().psroi_pool(
        x, rois, jnp.asarray([rois.shape[0]], jnp.int32),
        int(output_channels), spatial_scale, int(pooled_height),
        int(pooled_width))),
  ins="X ROIs", attrs="output_channels spatial_scale pooled_height "
                      "pooled_width")
b("prroi_pool", lambda x, rois, rois_num=None, spatial_scale=1.0,
    pooled_height=1, pooled_width=1: _unwrap(_vops().prroi_pool(
        x, rois, rois_num if rois_num is not None else
        jnp.asarray([rois.shape[0]], jnp.int32),
        int(pooled_height), int(pooled_width), spatial_scale)),
  ins="X ROIs ?BatchRoINums",
  attrs="spatial_scale pooled_height pooled_width")
# deformable_conv (v2, modulated: Mask input) vs deformable_conv_v1
# (no Mask in the maker — deformable_conv_v1_op.cc; absent optionals
# keep positional alignment via the None append in _run_spec, so the
# split is for maker-schema fidelity, caught by
# tools/validate_bridge_specs.py).
def _deform_conv(x, offset, w, mask=None, strides=(1, 1),
                 paddings=(0, 0), dilations=(1, 1), groups=1,
                 deformable_groups=1, im2col_step=1):
    return _unwrap(_vops().deform_conv2d(
        x, offset, w, stride=[int(s) for s in strides],
        padding=[int(p) for p in paddings],
        dilation=[int(d) for d in dilations],
        deformable_groups=int(deformable_groups), groups=int(groups),
        mask=mask))


b("deformable_conv", _deform_conv, ins="Input Offset Filter ?Mask",
  attrs="strides paddings dilations groups deformable_groups "
        "im2col_step", outs="Output")
b("deformable_conv_v1", _deform_conv, ins="Input Offset Filter",
  attrs="strides paddings dilations groups deformable_groups "
        "im2col_step", outs="Output")
b("deformable_psroi_pooling",
  lambda x, rois, trans, no_trans=False, spatial_scale=1.0,
  output_dim=None, group_size=(1,), pooled_height=1, pooled_width=1,
  part_size=(), sample_per_part=4, trans_std=0.1:
    _unwrap(_vops().deformable_psroi_pooling(
        x, rois, trans, no_trans=no_trans,
        spatial_scale=spatial_scale, output_channels=output_dim,
        group_size=int(group_size[0]) if group_size else 1,
        pooled_height=int(pooled_height),
        pooled_width=int(pooled_width),
        part_size=[int(p) for p in part_size] or None,
        sample_per_part=int(sample_per_part), trans_std=trans_std)),
  ins="Input ROIs ?Trans",
  attrs="no_trans spatial_scale output_dim group_size pooled_height "
        "pooled_width part_size sample_per_part trans_std",
  outs="Output ?TopCount")
b("box_clip", lambda x, im_info: _unwrap(_vops().box_clip(x, im_info)),
  ins="Input ImInfo", outs="Output")
b("iou_similarity", lambda x, y, box_normalized=True: _unwrap(
    _vops().iou_similarity(x, y, box_normalized=box_normalized)),
  ins="X Y", attrs="box_normalized")
b("correlation", lambda x1, x2, pad_size, kernel_size,
    max_displacement, stride1, stride2, corr_type_multiply=1:
    _unwrap(_vops().correlation(
        x1, x2, int(pad_size), int(kernel_size), int(max_displacement),
        int(stride1), int(stride2), int(corr_type_multiply))),
  ins="Input1 Input2",
  attrs="pad_size kernel_size max_displacement stride1 stride2 "
        "corr_type_multiply", outs="Output")
b("bilateral_slice", lambda x, grid, guide, has_offset=False: _unwrap(
    _vops().bilateral_slice(x, grid, guide, has_offset=has_offset)),
  ins="X Grid Guide", attrs="has_offset")
b("polygon_box_transform", lambda x: _unwrap(
    _vdet().polygon_box_transform(x)), ins="Input", outs="Output")
b("bipartite_match", lambda dist, match_type="bipartite",
    dist_threshold=0.5: _via(_P().bipartite_match, dist,
                             match_type=match_type,
                             dist_threshold=dist_threshold),
  ins="DistMat", attrs="match_type dist_threshold",
  outs="ColToRowMatchIndices ?ColToRowMatchDist")
b("anchor_generator", lambda x, anchor_sizes, aspect_ratios, variances,
    stride, offset=0.5: _via(_P().anchor_generator, x,
                             [float(a) for a in anchor_sizes],
                             [float(a) for a in aspect_ratios],
                             [float(v) for v in variances],
                             [float(s) for s in stride], offset),
  ins="Input",
  attrs="anchor_sizes aspect_ratios variances stride offset",
  outs="Anchors Variances")
b("target_assign", lambda x, mi, ni=None, mismatch_value=0:
    _via(_vdet().target_assign, x, mi, negative_indices=ni,
         mismatch_value=mismatch_value),
  ins="X MatchIndices ?NegIndices", attrs="mismatch_value",
  outs="Out ?OutWeight")
b("mine_hard_examples", lambda cls_loss, loc_loss, mi, md,
    neg_pos_ratio=3.0, neg_dist_threshold=0.5, sample_size=0,
    mining_type="max_negative": _via(
        _vdet().mine_hard_examples, cls_loss, mi, md,
        loc_loss=loc_loss, neg_pos_ratio=neg_pos_ratio,
        neg_dist_threshold=neg_dist_threshold,
        sample_size=int(sample_size), mining_type=mining_type),
  ins="ClsLoss ?LocLoss MatchIndices MatchDist",
  attrs="neg_pos_ratio neg_dist_threshold sample_size mining_type",
  outs="NegIndices ?UpdatedMatchIndices")
b("retinanet_detection_output", lambda bb, sc, an, im,
    score_threshold=0.05, nms_top_k=1000, keep_top_k=100,
    nms_threshold=0.3, nms_eta=1.0: _via(
        _vops().retinanet_detection_output, bb, sc, an, im,
        score_threshold=score_threshold, nms_top_k=int(nms_top_k),
        keep_top_k=int(keep_top_k), nms_threshold=nms_threshold,
        nms_eta=nms_eta),
  ins="*BBoxes *Scores *Anchors ImInfo",
  attrs="score_threshold nms_top_k keep_top_k nms_threshold nms_eta")
b("locality_aware_nms", lambda bb, sc, score_threshold=0.05,
    nms_top_k=1000, keep_top_k=100, nms_threshold=0.3, normalized=True,
    nms_eta=1.0, background_label=-1: _via(
        _vops().locality_aware_nms, bb, sc, score_threshold,
        int(nms_top_k), int(keep_top_k), nms_threshold=nms_threshold,
        normalized=normalized, nms_eta=nms_eta,
        background_label=int(background_label))[0],
  ins="BBoxes Scores",
  attrs="score_threshold nms_top_k keep_top_k nms_threshold "
        "normalized nms_eta background_label")
b("density_prior_box", lambda x, img, densities=(), fixed_sizes=(),
    fixed_ratios=(), variances=(0.1, 0.1, 0.2, 0.2), clip=False,
    step_w=0.0, step_h=0.0, offset=0.5, flatten_to_2d=False: _via(
        _vops().density_prior_box, x, img,
        [int(d) for d in densities], [float(s) for s in fixed_sizes],
        [float(r) for r in fixed_ratios],
        variance=[float(v) for v in variances], clip=clip,
        step_w=float(step_w[0]) if isinstance(step_w, (list, tuple))
        and step_w else float(step_w or 0.0),
        step_h=float(step_h[0]) if isinstance(step_h, (list, tuple))
        and step_h else float(step_h or 0.0),
        offset=offset, flatten_to_2d=flatten_to_2d),
  ins="Input Image",
  attrs="densities fixed_sizes fixed_ratios variances clip step_w "
        "step_h offset flatten_to_2d", outs="Boxes Variances")
b("yolov3_loss", lambda x, gtbox, gtlabel, gtscore=None, class_num=1,
    anchors=(), anchor_mask=(), downsample_ratio=32,
    ignore_thresh=0.7, use_label_smooth=True, scale_x_y=1.0: _via(
        _vops().yolov3_loss, x, gtbox, gtlabel,
        [int(a) for a in anchors], [int(m) for m in anchor_mask],
        int(class_num), ignore_thresh, int(downsample_ratio),
        gt_score=gtscore, use_label_smooth=use_label_smooth,
        scale_x_y=scale_x_y),
  ins="X GTBox GTLabel ?GTScore",
  attrs="class_num anchors anchor_mask downsample_ratio ignore_thresh "
        "use_label_smooth scale_x_y",
  outs="Loss ?ObjectnessMask ?GTMatchMask")
b("matrix_nms", lambda bb, sc, score_threshold=0.05,
    post_threshold=0.0, nms_top_k=1000, keep_top_k=100,
    use_gaussian=False, gaussian_sigma=2.0, background_label=-1,
    normalized=True: _via(
        _vops().matrix_nms, bb, sc, score_threshold, post_threshold,
        int(nms_top_k), int(keep_top_k), use_gaussian=use_gaussian,
        gaussian_sigma=gaussian_sigma,
        background_label=int(background_label), normalized=normalized,
        return_index=True),
  ins="BBoxes Scores",
  attrs="score_threshold post_threshold nms_top_k keep_top_k "
        "use_gaussian gaussian_sigma background_label normalized",
  outs="Out ?Index ?RoisNum")
b("box_decoder_and_assign", lambda pb, pbv, tb, bs, box_clip=4.135:
    _via(_vops().box_decoder_and_assign, pb, pbv, tb, bs,
         box_clip=float(box_clip)),
  ins="PriorBox PriorBoxVar TargetBox BoxScore", attrs="box_clip",
  outs="DecodeBox OutputAssignBox")
# generate_proposals (v1: ImInfo [N,3] = H,W,scale, always offset) vs
# generate_proposals_v2 (ImShape [N,2], pixel_offset attr) — the two
# makers differ (generate_proposals_op.cc vs
# detection/generate_proposals_v2_op.cc), caught by
# tools/validate_bridge_specs.py
def _gen_proposals(scores, deltas, im, anchors, var, pre_nms_topN=6000,
                   post_nms_topN=1000, nms_thresh=0.5, min_size=0.1,
                   eta=1.0, pixel_offset=True):
    # im passes through unsliced: v1's ImInfo carries [H, W, scale] and
    # the eager fn divides box sizes by the scale column during
    # min-size filtering when present (reference bbox_util.h
    # FilterBoxes is_scale=true); v2's ImShape is just [H, W]
    return _via(
        _vops().generate_proposals, scores, deltas, im,
        anchors, var, pre_nms_top_n=int(pre_nms_topN),
        post_nms_top_n=int(post_nms_topN), nms_thresh=nms_thresh,
        min_size=min_size, eta=eta, pixel_offset=pixel_offset)


b("generate_proposals", _gen_proposals,
  ins="Scores BboxDeltas ImInfo Anchors Variances",
  attrs="pre_nms_topN post_nms_topN nms_thresh min_size eta",
  outs="RpnRois RpnRoiProbs ?RpnRoisNum")
b("generate_proposals_v2", _gen_proposals,
  ins="Scores BboxDeltas ImShape Anchors Variances",
  attrs="pre_nms_topN post_nms_topN nms_thresh min_size eta "
        "pixel_offset",
  outs="RpnRois RpnRoiProbs ?RpnRoisNum")
b("distribute_fpn_proposals", lambda rois, rois_num=None, min_level=2,
    max_level=5, refer_level=4, refer_scale=224, pixel_offset=True:
    _distribute_fpn(rois, rois_num, min_level, max_level, refer_level,
                    refer_scale, pixel_offset),
  ins="FpnRois ?RoisNum",
  attrs="min_level max_level refer_level refer_scale pixel_offset",
  outs="*MultiFpnRois RestoreIndex *MultiLevelRoIsNum")
b("collect_fpn_proposals", lambda rois, scores, rois_num=None,
    post_nms_topN=100: _via(
        _vops().collect_fpn_proposals, rois, scores, 2,
        2 + len(rois) - 1, int(post_nms_topN),
        rois_num_per_level=rois_num or None),
  ins="*MultiLevelRois *MultiLevelScores *MultiLevelRoIsNum",
  attrs="post_nms_topN", outs="FpnRois ?RoisNum")
b("roi_perspective_transform", lambda x, rois, transformed_height=1,
    transformed_width=1, spatial_scale=1.0: _via(
        _vdet().roi_perspective_transform, x, rois,
        int(transformed_height), int(transformed_width),
        spatial_scale),
  ins="X ROIs",
  attrs="transformed_height transformed_width spatial_scale",
  outs="Out ?Mask ?TransformMatrix ?Out2InIdx ?Out2InWeights")
b("rpn_target_assign", lambda anchor, gt, is_crowd, im_info,
    rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
    rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
    rpn_negative_overlap=0.3, use_random=False: _via(
        _vdet().rpn_target_assign, None, None, anchor, None, gt,
        is_crowd, im_info,
        rpn_batch_size_per_im=int(rpn_batch_size_per_im),
        rpn_straddle_thresh=rpn_straddle_thresh,
        rpn_fg_fraction=rpn_fg_fraction,
        rpn_positive_overlap=rpn_positive_overlap,
        rpn_negative_overlap=rpn_negative_overlap,
        use_random=use_random),
  ins="Anchor GtBoxes IsCrowd ImInfo",
  attrs="rpn_batch_size_per_im rpn_straddle_thresh rpn_fg_fraction "
        "rpn_positive_overlap rpn_negative_overlap use_random",
  outs="LocationIndex ScoreIndex TargetBBox TargetLabel "
       "?BBoxInsideWeight")
b("retinanet_target_assign", lambda anchor, gt, gtl, is_crowd, im_info,
    positive_overlap=0.5, negative_overlap=0.4: _via(
        _vdet().retinanet_target_assign, None, None, anchor, None, gt,
        gtl, is_crowd, im_info, positive_overlap=positive_overlap,
        negative_overlap=negative_overlap),
  ins="Anchor GtBoxes GtLabels IsCrowd ImInfo",
  attrs="positive_overlap negative_overlap",
  outs="LocationIndex ScoreIndex TargetBBox TargetLabel "
       "?BBoxInsideWeight ?ForegroundNumber")
b("generate_proposal_labels", lambda rois, gtc, crowd, gtb, im,
    batch_size_per_im=256, fg_fraction=0.25, fg_thresh=0.5,
    bg_thresh_hi=0.5, bg_thresh_lo=0.0,
    bbox_reg_weights=(0.1, 0.1, 0.2, 0.2), class_nums=81,
    use_random=False, is_cls_agnostic=False: _via(
        _vdet().generate_proposal_labels, rois, gtc, crowd, gtb, im,
        batch_size_per_im=int(batch_size_per_im),
        fg_fraction=fg_fraction, fg_thresh=fg_thresh,
        bg_thresh_hi=bg_thresh_hi, bg_thresh_lo=bg_thresh_lo,
        bbox_reg_weights=[float(w) for w in bbox_reg_weights],
        class_nums=int(class_nums), use_random=use_random,
        is_cls_agnostic=is_cls_agnostic),
  ins="RpnRois GtClasses IsCrowd GtBoxes ImInfo",
  attrs="batch_size_per_im fg_fraction fg_thresh bg_thresh_hi "
        "bg_thresh_lo bbox_reg_weights class_nums use_random "
        "is_cls_agnostic",
  outs="Rois LabelsInt32 BboxTargets BboxInsideWeights "
       "BboxOutsideWeights ?MaxOverlapWithGT")
b("generate_mask_labels", lambda im, gtc, crowd, segms, rois, lab,
    num_classes=81, resolution=14: _via(
        _vdet().generate_mask_labels, im, gtc, crowd, segms, rois,
        lab, int(num_classes), int(resolution)),
  ins="ImInfo GtClasses IsCrowd GtSegms Rois LabelsInt32",
  attrs="num_classes resolution",
  outs="MaskRois RoiHasMaskInt32 MaskInt32")


def _vops():
    from paddle_tpu.vision import ops

    return ops


def _vdet():
    from paddle_tpu.vision import detection

    return detection


def _distribute_fpn(rois, rois_num, min_level, max_level, refer_level,
                    refer_scale, pixel_offset):
    from paddle_tpu.vision.ops import distribute_fpn_proposals

    out = distribute_fpn_proposals(
        rois, int(min_level), int(max_level), int(refer_level),
        int(refer_scale), pixel_offset=pixel_offset,
        rois_num=rois_num)
    multi, restore = out[0], out[1]
    nums = out[2] if len(out) > 2 else [
        jnp.asarray([r.shape[0]], jnp.int32) for r in multi]
    return list(multi), _unwrap(restore), list(nums or [])


# ---------------------------------------------------------------------------
# industrial / CRF / quant-runtime ops
# ---------------------------------------------------------------------------
b("crf_decoding", lambda em, tr, label=None, length=None: _via(
    _P().crf_decoding, em, tr, label=label, length=length),
  ins="Emission Transition ?Label ?Length", outs="ViterbiPath")
b("linear_chain_crf", lambda em, tr, label, length=None:
    _linear_chain_crf(em, tr, label, length),
  ins="Emission Transition Label ?Length",
  outs="LogLikelihood ?Alpha ?EmissionExps ?TransitionExps")
b("tdm_child", lambda x, tree, child_nums=1, dtype=3: _via(
    _ops().tdm_child, x, tree, int(child_nums),
    dtype=_conv_dtype(dtype)),
  ins="X TreeInfo", attrs="child_nums dtype", outs="Child ?LeafMask")
b("tdm_sampler", lambda x, travel, layer, output_positive=True,
    neg_samples_num_list=(), layer_offset_lod=(), seed=0, dtype=3:
    _via(_ops().tdm_sampler, x, travel, layer,
         [int(n) for n in neg_samples_num_list],
         [int(o) for o in layer_offset_lod],
         output_positive=output_positive, seed=int(seed)),
  ins="X Travel Layer",
  attrs="output_positive neg_samples_num_list layer_offset_lod seed "
        "dtype",
  outs="Out ?Labels ?Mask")
b("pyramid_hash", lambda x, w, wl=None, bl=None, num_emb=8,
    space_len=1000, pyramid_layer=2, rand_len=4, drop_out_percent=0.0,
    is_training=False, seed=0, **_:
    _via(_ops().pyramid_hash, x, w, num_emb=int(num_emb),
         space_len=int(space_len), pyramid_layer=int(pyramid_layer),
         rand_len=int(rand_len), drop_out_percent=drop_out_percent,
         is_training=bool(is_training), seed=int(seed)),
  ins="X W ?WhiteList ?BlackList",
  attrs="num_emb space_len pyramid_layer rand_len drop_out_percent "
        "is_training seed",
  outs="Out ?DropPos ?X_Temp_Out")
b("tree_conv", lambda nodes, edges, filt, max_depth=2: _via(
    _ops().tree_conv, nodes, edges, filt, int(max_depth)),
  ins="NodesVector EdgeSet Filter", attrs="max_depth")
b("nce", lambda x, label, w, bias=None, sw=None, num_total_classes=2,
    num_neg_samples=10, sampler=0, seed=0, **_: _via(
        _F().nce, x, label, w, bias=bias,
        num_total_classes=int(num_total_classes),
        num_neg_samples=int(num_neg_samples),
        sampler=["uniform", "log_uniform", "custom_dist"][int(sampler)]
        if not isinstance(sampler, str) else sampler,
        sample_weight=sw, seed=int(seed)),
  ins="Input Label Weight ?Bias ?SampleWeight",
  attrs="num_total_classes num_neg_samples sampler seed",
  outs="Cost ?SampleLogits ?SampleLabels")
b("hierarchical_sigmoid", lambda x, w, label, pt=None, pc=None,
    bias=None, num_classes=2, **_: _via(
        _F().hsigmoid_loss, x, label, int(num_classes), w, bias=bias,
        path_table=pt, path_code=pc),
  ins="X W Label ?PathTable ?PathCode ?Bias", attrs="num_classes",
  outs="Out ?PreOut ?W_Out")
b("center_loss", lambda x, label, centers, rate, cluster_num=2,
    need_update=True: _center_loss(x, label, centers, rate,
                                   need_update),
  ins="X Label Centers CenterUpdateRate",
  attrs="cluster_num need_update",
  outs="CentersOut ?SampleCenterDiff Loss")
b("sample_logits", lambda logits, labels, cs=None, cp=None,
    num_samples=1, uniq=True, remove_accidental_hits=True,
    use_customized_samples=False, seed=0: _via(
        _ops().sample_logits, logits, labels, int(num_samples),
        uniq=uniq, remove_accidental_hits=remove_accidental_hits,
        use_customized_samples=use_customized_samples,
        customized_samples=cs, customized_probabilities=cp,
        seed=int(seed)),
  ins="Logits Labels ?CustomizedSamples ?CustomizedProbabilities",
  attrs="num_samples uniq remove_accidental_hits "
        "use_customized_samples seed",
  outs="SampledLogits SampledLabels ?Samples ?Probabilities")
b("match_matrix_tensor", lambda x, y, w, dim_t=1: _via(
    _ops().match_matrix_tensor, x, y, w, dim_t=int(dim_t)),
  ins="X Y W", attrs="dim_t", outs="Out ?Tmp")
b("var_conv_2d", lambda x, w, row, col, InputChannel=1,
    OutputChannel=1, KernelH=1, KernelW=1, StrideH=1, StrideW=1: _via(
        _ops().var_conv_2d, x, w, row, col, int(InputChannel),
        int(OutputChannel), int(KernelH), int(KernelW), int(StrideH),
        int(StrideW)),
  ins="X W ?ROW ?COLUMN",
  attrs="InputChannel OutputChannel KernelH KernelW StrideH StrideW",
  outs="Out ?Col")
b("lstmp", lambda x, h0, c0, w, pw, bias=None, use_peepholes=True,
    is_reverse=False, gate_activation="sigmoid",
    cell_activation="tanh", candidate_activation="tanh",
    proj_activation="tanh", **_: _via(
        _ops().lstmp, x, w, pw, bias=bias, h0=h0, c0=c0,
        use_peepholes=use_peepholes, is_reverse=is_reverse,
        gate_activation=gate_activation,
        cell_activation=cell_activation,
        candidate_activation=candidate_activation,
        proj_activation=proj_activation),
  ins="Input ?H0 ?C0 Weight ProjWeight ?Bias",
  attrs="use_peepholes is_reverse gate_activation cell_activation "
        "candidate_activation proj_activation",
  outs="Projection ?Cell ?BatchGate ?BatchCellPreAct ?BatchHidden")
b("dequantize_abs_max", lambda x, scale, max_range=127.0: _via(
    _quant().dequantize_abs_max, x, scale, float(max_range)),
  ins="X Scale", attrs="max_range")
b("dequantize_log", lambda x, table: _via(
    _quant().dequantize_log, x, table), ins="X Dict")
b("moving_average_abs_max_scale", lambda x, accum=None, state=None,
    moving_rate=0.9, is_test=False: _moving_avg_scale(
        x, accum, state, moving_rate),
  ins="X ?InAccum ?InState", attrs="moving_rate is_test",
  outs="?Out OutScale ?OutState ?OutAccum")


def _quant():
    from paddle_tpu import quantization

    return quantization


def _linear_chain_crf(em, tr, label, length):
    from paddle_tpu import linear_chain_crf as f

    out = f(em, tr, label, length)
    if isinstance(out, tuple):
        return tuple(_unwrap(o) for o in out)
    return (_unwrap(out),)


def _center_loss(x, label, centers, rate, need_update):
    lab = label.reshape(-1).astype(jnp.int32)
    csel = centers[lab]
    diff = x - csel
    loss = 0.5 * jnp.sum(jnp.square(diff), -1, keepdims=True)
    if need_update:
        # reference center_loss_op.h: centers -= rate * mean-per-center
        counts = jnp.zeros((centers.shape[0],)).at[lab].add(1.0)
        upd = jnp.zeros_like(centers).at[lab].add(diff)
        centers = centers + rate.reshape(()) * upd / jnp.maximum(
            counts[:, None], 1.0)
    return centers, diff, loss


def _moving_avg_scale(x, accum, state, rate):
    from paddle_tpu import quantization as q

    out = q.moving_average_abs_max_scale(x, state=state, accum=accum,
                                         moving_rate=rate)
    # eager returns (x, scale, new_state, new_accum)
    _, scale, new_state, new_accum = out
    ns = _unwrap(new_state) if new_state is not None else None
    na = _unwrap(new_accum) if new_accum is not None else None
    return x, _unwrap(scale), ns, na


# ---------------------------------------------------------------------------
# in-program optimizer ops (reference operators/optimizers/*).  Slot vars
# (moments, pows) default sensibly when the program hasn't initialized
# them (same stance as the hand-written momentum translator), so a
# minimize()d program trains from step one.
# ---------------------------------------------------------------------------
def _opt_common(op, scope):
    p = scope.fetch(op.input("Param"))
    g = scope.fetch(op.input("Grad"))
    lr_in = op.input("LearningRate")
    lr = jnp.reshape(scope.fetch(lr_in), ()) if lr_in else None
    return p, g, lr


def _slot(op, scope, name, like, fill=0.0):
    vname = op.input(name)
    if vname and vname in scope:
        return scope[vname]
    return jnp.full_like(like, fill)


def _scalar_slot(op, scope, name, default):
    vname = op.input(name)
    if vname and vname in scope:
        return jnp.reshape(scope[vname], ()).astype(jnp.float32)
    return jnp.asarray(default, jnp.float32)


@braw("adam", "adamw")
def _adam_op(op, scope, feeds, fetches):
    # reference operators/optimizers/adam_op.h AdamFunctor; adamw adds
    # decoupled decay (adamw_op.h: p -= lr*coeff*p before the adam step)
    p, g, lr = _opt_common(op, scope)
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    m = _slot(op, scope, "Moment1", p)
    v = _slot(op, scope, "Moment2", p)
    b1p = _scalar_slot(op, scope, "Beta1Pow", b1)
    b2p = _scalar_slot(op, scope, "Beta2Pow", b2)
    if op.type == "adamw" and op.attr("with_decay", True):
        p = p - lr * op.attr("coeff", 0.01) * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    new_p = p - lr_t * m / (jnp.sqrt(v) + eps * jnp.sqrt(1 - b2p))
    scope[op.output("ParamOut")] = new_p.astype(p.dtype)
    scope[op.output("Moment1Out")] = m
    scope[op.output("Moment2Out")] = v
    if op.output("Beta1PowOut") and not op.attr("use_global_beta_pow",
                                                False):
        scope[op.output("Beta1PowOut")] = jnp.reshape(b1p * b1, (1,))
        scope[op.output("Beta2PowOut")] = jnp.reshape(b2p * b2, (1,))
    mp = op.input("MasterParam")
    if mp and op.output("MasterParamOut"):
        scope[op.output("MasterParamOut")] = new_p.astype(jnp.float32)


@braw("adamax")
def _adamax_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    m = _slot(op, scope, "Moment", p)
    inf = _slot(op, scope, "InfNorm", p)
    b1p = _scalar_slot(op, scope, "Beta1Pow", b1)
    m = b1 * m + (1 - b1) * g
    inf = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    new_p = p - (lr / (1 - b1p)) * m / inf
    scope[op.output("ParamOut")] = new_p
    scope[op.output("MomentOut")] = m
    scope[op.output("InfNormOut")] = inf
    if op.output("Beta1PowOut"):
        scope[op.output("Beta1PowOut")] = jnp.reshape(b1p * b1, (1,))


@braw("adagrad", "decayed_adagrad", "proximal_adagrad")
def _adagrad_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    eps = op.attr("epsilon", 1e-6)
    mom = _slot(op, scope, "Moment", p)
    if op.type == "decayed_adagrad":
        decay = op.attr("decay", 0.95)
        mom = decay * mom + (1 - decay) * g * g
    else:
        mom = mom + g * g
    step = lr * g / (jnp.sqrt(mom) + eps)
    if op.type == "proximal_adagrad":
        l1 = op.attr("l1", 0.0)
        l2 = op.attr("l2", 0.0)
        prox = p - step
        lr_eff = lr / (jnp.sqrt(mom) + eps)
        new_p = jnp.sign(prox) * jnp.maximum(
            0.0, jnp.abs(prox) - lr_eff * l1) / (1.0 + lr_eff * l2)
    else:
        new_p = p - step
    scope[op.output("ParamOut")] = new_p
    scope[op.output("MomentOut")] = mom


@braw("adadelta")
def _adadelta_op(op, scope, feeds, fetches):
    p = scope.fetch(op.input("Param"))
    g = scope.fetch(op.input("Grad"))
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    asg = _slot(op, scope, "AvgSquaredGrad", p)
    asu = _slot(op, scope, "AvgSquaredUpdate", p)
    asg = rho * asg + (1 - rho) * g * g
    upd = -jnp.sqrt((asu + eps) / (asg + eps)) * g
    asu = rho * asu + (1 - rho) * upd * upd
    scope[op.output("ParamOut")] = p + upd
    scope[op.output("AvgSquaredGradOut")] = asg
    scope[op.output("AvgSquaredUpdateOut")] = asu


@braw("rmsprop")
def _rmsprop_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    rho = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    mu = op.attr("momentum", 0.0)
    ms = _slot(op, scope, "MeanSquare", p)
    mom = _slot(op, scope, "Moment", p)
    ms = rho * ms + (1 - rho) * g * g
    if op.attr("centered", False):
        mg = _slot(op, scope, "MeanGrad", p)
        mg = rho * mg + (1 - rho) * g
        denom = ms - mg * mg
        if op.output("MeanGradOut"):
            scope[op.output("MeanGradOut")] = mg
    else:
        denom = ms
    mom = mu * mom + lr * g / jnp.sqrt(denom + eps)
    scope[op.output("ParamOut")] = p - mom
    scope[op.output("MeanSquareOut")] = ms
    scope[op.output("MomentOut")] = mom


@braw("lamb")
def _lamb_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    m = _slot(op, scope, "Moment1", p)
    v = _slot(op, scope, "Moment2", p)
    b1p = _scalar_slot(op, scope, "Beta1Pow", b1)
    b2p = _scalar_slot(op, scope, "Beta2Pow", b2)
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    m_hat = m / (1 - b1p)
    v_hat = v / (1 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    scope[op.output("ParamOut")] = p - lr * trust * r
    scope[op.output("Moment1Out")] = m
    scope[op.output("Moment2Out")] = v
    if op.output("Beta1PowOut"):
        scope[op.output("Beta1PowOut")] = jnp.reshape(b1p * b1, (1,))
    if op.output("Beta2PowOut"):
        scope[op.output("Beta2PowOut")] = jnp.reshape(b2p * b2, (1,))


@braw("lars_momentum")
def _lars_momentum_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    mu = op.attr("mu", 0.9)
    coeff = op.attr("lars_coeff", 0.001)
    wd_list = op.attr("lars_weight_decay", [0.0005])
    wd = float(wd_list[0]) if isinstance(wd_list, (list, tuple)) else \
        float(wd_list)
    v = _slot(op, scope, "Velocity", p)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * p_norm / (
        g_norm + wd * p_norm + op.attr("epsilon", 0.0) + 1e-30)
    v = mu * v + local_lr * (g + wd * p)
    scope[op.output("ParamOut")] = p - v
    scope[op.output("VelocityOut")] = v


@braw("ftrl")
def _ftrl_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    lr_power = op.attr("lr_power", -0.5)
    sq = _slot(op, scope, "SquaredAccumulator", p)
    lin = _slot(op, scope, "LinearAccumulator", p)
    new_sq = sq + g * g
    sigma = (jnp.power(new_sq, -lr_power) -
             jnp.power(sq, -lr_power)) / lr
    lin = lin + g - sigma * p
    quad = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(lin, -l1, l1) - lin
    scope[op.output("ParamOut")] = pre / quad
    scope[op.output("SquaredAccumOut")] = new_sq
    scope[op.output("LinearAccumOut")] = lin


@braw("dpsgd")
def _dpsgd_op(op, scope, feeds, fetches):
    # differential-privacy sgd (dpsgd_op.h): clip grad to clip-norm,
    # add gaussian noise sigma, then sgd
    p, g, lr = _opt_common(op, scope)
    clip = op.attr("clip", 10.0)
    sigma = op.attr("sigma", 1.0)
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g * jnp.minimum(1.0, clip / (gn + 1e-30))
    noise = sigma * clip * jax.random.normal(_op_key(op), g.shape)
    bsz = op.attr("batch_size", 1.0) or 1.0
    scope[op.output("ParamOut")] = p - lr * (g + noise / bsz)


@braw("proximal_gd")
def _proximal_gd_op(op, scope, feeds, fetches):
    p, g, lr = _opt_common(op, scope)
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    prox = p - lr * g
    scope[op.output("ParamOut")] = jnp.sign(prox) * jnp.maximum(
        0.0, jnp.abs(prox) - lr * l1) / (1.0 + lr * l2)


@braw("average_accumulates")
def _average_accumulates_op(op, scope, feeds, fetches):
    # reference operators/average_accumulates_op.h window accounting
    p = scope.fetch(op.input("param"))
    s1 = _slot(op, scope, "in_sum_1", p)
    s2 = _slot(op, scope, "in_sum_2", p)
    s3 = _slot(op, scope, "in_sum_3", p)
    num_acc = _scalar_slot(op, scope, "in_num_accumulates", 0)
    old_num = _scalar_slot(op, scope, "in_old_num_accumulates", 0)
    num_upd = _scalar_slot(op, scope, "in_num_updates", 0)
    avg_window = op.attr("average_window", 0.0)
    max_avg = op.attr("max_average_window", 10000)
    min_avg = op.attr("min_average_window", 10000)
    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p
    window = jnp.maximum(min_avg, jnp.minimum(
        float(max_avg), avg_window * num_upd))
    roll = num_acc >= window
    s3 = jnp.where(roll, s1 + s2, s3)
    old_num = jnp.where(roll, num_acc, old_num)
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)
    s2 = jnp.where(roll, jnp.zeros_like(s2), s2)
    num_acc = jnp.where(roll, 0.0, num_acc)
    scope[op.output("out_sum_1")] = s1
    scope[op.output("out_sum_2")] = s2
    scope[op.output("out_sum_3")] = s3
    scope[op.output("out_num_accumulates")] = jnp.reshape(
        num_acc, (1,)).astype(jnp.int64)
    scope[op.output("out_old_num_accumulates")] = jnp.reshape(
        old_num, (1,)).astype(jnp.int64)
    scope[op.output("out_num_updates")] = jnp.reshape(
        num_upd, (1,)).astype(jnp.int64)


# ---------------------------------------------------------------------------
# AMP ops (reference operators/amp/*.cc) — the static-program mixed
# precision protocol
# ---------------------------------------------------------------------------
@braw("check_finite_and_unscale")
def _check_finite_and_unscale_op(op, scope, feeds, fetches):
    scale = jnp.reshape(scope.fetch(op.input("Scale")), ())
    inv = 1.0 / scale
    found = jnp.asarray(False)
    outs = op.outputs("Out")
    for name, oname in zip(op.inputs("X"), outs):
        x = scope.fetch(name)
        found = found | ~jnp.all(jnp.isfinite(x))
        scope[oname] = x.astype(jnp.float32) * inv
    scope[op.output("FoundInfinite")] = jnp.reshape(found, (1,))


@braw("update_loss_scaling")
def _update_loss_scaling_op(op, scope, feeds, fetches):
    found = jnp.reshape(scope.fetch(op.input("FoundInfinite")), ())
    scale = jnp.reshape(scope.fetch(op.input("PrevLossScaling")), ())
    good = _scalar_slot(op, scope, "InGoodSteps", 0)
    bad = _scalar_slot(op, scope, "InBadSteps", 0)
    incr_n = op.attr("incr_every_n_steps", 1000)
    decr_n = op.attr("decr_every_n_nan_or_inf", 2)
    incr_ratio = op.attr("incr_ratio", 2.0)
    decr_ratio = op.attr("decr_ratio", 0.5)
    new_bad = jnp.where(found, bad + 1, 0)
    new_good = jnp.where(found, 0, good + 1)
    decr = new_bad >= decr_n
    incr = new_good >= incr_n
    new_scale = jnp.where(decr, scale * decr_ratio,
                          jnp.where(incr, scale * incr_ratio, scale))
    new_scale = jnp.maximum(new_scale, 1e-9)
    new_bad = jnp.where(decr, 0, new_bad)
    new_good = jnp.where(incr, 0, new_good)
    if not op.attr("stop_update", False):
        scope[op.output("LossScaling")] = jnp.reshape(new_scale, (1,))
        scope[op.output("OutGoodSteps")] = jnp.reshape(
            new_good, (1,)).astype(jnp.int32)
        scope[op.output("OutBadSteps")] = jnp.reshape(
            new_bad, (1,)).astype(jnp.int32)
    else:
        scope[op.output("LossScaling")] = jnp.reshape(scale, (1,))
        scope[op.output("OutGoodSteps")] = jnp.reshape(
            good, (1,)).astype(jnp.int32)
        scope[op.output("OutBadSteps")] = jnp.reshape(
            bad, (1,)).astype(jnp.int32)
    # grads zeroed on overflow so the optimizer step is a no-op
    for name, oname in zip(op.inputs("X"), op.outputs("Out")):
        x = scope.fetch(name)
        scope[oname] = jnp.where(found, jnp.zeros_like(x), x)


# ---------------------------------------------------------------------------
# collective ops (reference operators/collective/*.cc) lowered onto mesh
# axes.  ring_id -> axis-name mapping comes from `collective_axes(...)`;
# outside that context the program is treated as world-size-1 (identity
# semantics), matching a distributed-rewritten program run single-process.
# ---------------------------------------------------------------------------
import contextlib as _ctx
import threading as _thr

_COLL_TLS = _thr.local()


@_ctx.contextmanager
def collective_axes(mapping=None, default=None):
    """Map ring_id -> mesh axis name for c_* ops interpreted inside a
    shard_map/pmap region.  `default` applies to any unmapped ring."""
    prev = getattr(_COLL_TLS, "cfg", None)
    _COLL_TLS.cfg = (dict(mapping or {}), default)
    try:
        yield
    finally:
        _COLL_TLS.cfg = prev


def _ring_axis(op):
    cfg = getattr(_COLL_TLS, "cfg", None)
    if cfg is None:
        return None
    mapping, default = cfg
    return mapping.get(op.attr("ring_id", 0), default)


def _coll(op, scope, fn_with_axis, identity=lambda x: x,
          in_name="X", out_name="Out"):
    x = scope.fetch(op.input(in_name))
    ax = _ring_axis(op)
    scope[op.output(out_name)] = (identity(x) if ax is None
                                  else fn_with_axis(x, ax))


@braw("c_allreduce_sum", "allreduce", "mp_allreduce_sum")
def _c_allreduce_sum_op(op, scope, feeds, fetches):
    _coll(op, scope, lambda x, ax: jax.lax.psum(x, ax))


@braw("c_allreduce_max")
def _c_allreduce_max_op(op, scope, feeds, fetches):
    _coll(op, scope, lambda x, ax: jax.lax.pmax(x, ax))


@braw("c_allreduce_min")
def _c_allreduce_min_op(op, scope, feeds, fetches):
    _coll(op, scope, lambda x, ax: jax.lax.pmin(x, ax))


def _psum_prod(x, ax):
    # product via logs is sign/zero-UNSAFE; carry magnitude, sign parity
    # and zero-presence separately
    mag = jnp.exp(jax.lax.psum(jnp.log(jnp.maximum(jnp.abs(x), 1e-38)),
                               ax))
    neg = jax.lax.psum((x < 0).astype(jnp.int32), ax)
    has_zero = jax.lax.pmax((x == 0).astype(jnp.int32), ax)
    signed = jnp.where(neg % 2 == 1, -mag, mag)
    return jnp.where(has_zero > 0, jnp.zeros_like(signed), signed)


@braw("c_allreduce_prod")
def _c_allreduce_prod_op(op, scope, feeds, fetches):
    _coll(op, scope, _psum_prod)


@braw("c_reduce_sum", "c_reduce_max", "c_reduce_min", "c_reduce_prod")
def _c_reduce_op(op, scope, feeds, fetches):
    # SPMD stance: reduce == allreduce (every device holds the root
    # value; the reference only guarantees the root's buffer)
    kind = op.type.rsplit("_", 1)[1]
    fns = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
           "prod": _psum_prod}
    _coll(op, scope, lambda x, ax: fns[kind](x, ax))


@braw("c_broadcast", "broadcast")
def _c_broadcast_op(op, scope, feeds, fetches):
    root = op.attr("root", op.attr("root_id", 0))

    def bcast(x, ax):
        keep = jnp.equal(jax.lax.axis_index(ax), root)
        return jax.lax.psum(jnp.where(keep, x, jnp.zeros_like(x)), ax)

    _coll(op, scope, bcast)


@braw("c_identity")
def _c_identity_op(op, scope, feeds, fetches):
    scope[op.output("Out")] = scope.fetch(op.input("X"))


@braw("c_allgather")
def _c_allgather_op(op, scope, feeds, fetches):
    _coll(op, scope,
          lambda x, ax: jax.lax.all_gather(x, ax, axis=0, tiled=True))


@braw("c_reducescatter")
def _c_reducescatter_op(op, scope, feeds, fetches):
    _coll(op, scope,
          lambda x, ax: jax.lax.psum_scatter(x, ax, scatter_dimension=0,
                                             tiled=True))


@braw("c_concat")
def _c_concat_op(op, scope, feeds, fetches):
    # mp gather along the LAST axis (operators/collective/c_concat_op.cc)
    _coll(op, scope,
          lambda x, ax: jax.lax.all_gather(x, ax, axis=x.ndim - 1,
                                           tiled=True))


@braw("c_split")
def _c_split_op(op, scope, feeds, fetches):
    nranks = op.attr("nranks", 1)

    def split(x, ax):
        i = jax.lax.axis_index(ax)
        w = x.shape[-1] // nranks
        return jax.lax.dynamic_slice_in_dim(x, i * w, w, x.ndim - 1)

    _coll(op, scope, split)


@braw("c_scatter")
def _c_scatter_op(op, scope, feeds, fetches):
    nranks = op.attr("nranks", 1)

    def scatter(x, ax):
        i = jax.lax.axis_index(ax)
        rows = x.shape[0] // nranks
        return jax.lax.dynamic_slice_in_dim(x, i * rows, rows, 0)

    _coll(op, scope, scatter,
          identity=lambda x: x)


@braw("c_embedding")
def _c_embedding_op(op, scope, feeds, fetches):
    # vocab-parallel embedding (c_embedding_op.cc): rows outside this
    # shard contribute zeros; psum combines shards
    w = scope.fetch(op.input("W"))
    ids = scope.fetch(op.input("Ids")).astype(jnp.int32)
    start = op.attr("start_index", 0)
    local = ids - start
    in_range = (local >= 0) & (local < w.shape[0])
    emb = w[jnp.clip(local, 0, w.shape[0] - 1)]
    emb = jnp.where(in_range[..., None], emb, 0)
    ax = _ring_axis(op)
    if ax is not None:
        emb = jax.lax.psum(emb, ax)
    scope[op.output("Out")] = emb


@braw("c_softmax_with_cross_entropy")
def _c_softmax_ce_op(op, scope, feeds, fetches):
    # vocab-parallel CE (c_softmax_with_cross_entropy_op.cc): global
    # max/logsumexp via collectives, label logit from the owning shard
    logits = scope.fetch(op.input("Logits"))
    label = scope.fetch(op.input("Label")).astype(jnp.int32)
    ax = _ring_axis(op)
    if ax is None:
        lse = jax.nn.logsumexp(logits, -1, keepdims=True)
        soft = jnp.exp(logits - lse)
        picked = jnp.take_along_axis(logits, label.reshape(
            label.shape[0], 1), -1)
        loss = lse.reshape(label.shape[0], 1) - picked
    else:
        rank = jax.lax.axis_index(ax)
        vocab_local = logits.shape[-1]
        start = rank * vocab_local
        gmax = jax.lax.pmax(jnp.max(logits, -1, keepdims=True), ax)
        ex = jnp.exp(logits - gmax)
        denom = jax.lax.psum(jnp.sum(ex, -1, keepdims=True), ax)
        soft = ex / denom
        local = label.reshape(-1, 1) - start
        owned = (local >= 0) & (local < vocab_local)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, vocab_local - 1), -1)
        picked = jnp.where(owned, picked, 0.0)
        picked = jax.lax.psum(picked, ax)
        loss = jnp.log(denom) + gmax - picked
    scope[op.output("Softmax")] = soft
    scope[op.output("Loss")] = loss


@braw("alltoall")
def _alltoall_op(op, scope, feeds, fetches):
    _coll(op, scope,
          lambda x, ax: jax.lax.all_to_all(x, ax, split_axis=0,
                                           concat_axis=0, tiled=True))


@braw("barrier")
def _barrier_op(op, scope, feeds, fetches):
    # XLA programs are globally scheduled; a barrier is the identity on
    # its token input
    if op.input("X") and op.output("Out"):
        scope[op.output("Out")] = scope.fetch(op.input("X"))


# ---------------------------------------------------------------------------
# fleet-inserted bootstrap/sync ops (SURVEY §3.3 steps 3-4): a genuinely
# distributed-rewritten reference program carries NCCL bootstrap ops in
# its startup program and stream-sync/fusion ops in its main program.
# All are TPU-obsolete as *work* (PJRT coordination replaces rendezvous;
# XLA's global schedule replaces stream syncs; XLA fusion replaces
# buffer coalescing) but must still CONSUME in program form so the real
# fleet output loads — no-op / identity-alias semantics.
# ---------------------------------------------------------------------------
@braw("c_gen_nccl_id", "c_gen_bkcl_id", "c_gen_hccl_id")
def _c_gen_comm_id_op(op, scope, feeds, fetches):
    # reference c_gen_nccl_id_op.cc:107 writes an opaque UniqueId RAW
    # var consumed only by c_comm_init; PJRT's coordination service is
    # the rendezvous here, so the id is a placeholder token
    scope[op.output("Out")] = jnp.zeros((1,), jnp.int32)


@braw("gen_nccl_id", "gen_bkcl_id", "gen_hccl_id")
def _gen_comm_id_op(op, scope, feeds, fetches):
    # legacy spelling (gen_nccl_id_op.cc:215): output slot is NCCLID
    for slot in ("NCCLID", "Out"):
        if op.output(slot):
            scope[op.output(slot)] = jnp.zeros((1,), jnp.int32)


@braw("c_comm_init", "c_comm_init_all", "c_comm_init_hccl",
      "c_comm_init_multitrainer", "comm_init")
def _c_comm_init_op(op, scope, feeds, fetches):
    # communicator construction (c_comm_init_op.cc:105 consumes the
    # UniqueId); mesh axes are bound by `collective_axes(...)` instead —
    # nothing to do, and no outputs to write
    return


@braw("c_sync_comm_stream", "c_sync_calc_stream", "c_wait_comm",
      "c_wait_compute")
def _c_stream_sync_op(op, scope, feeds, fetches):
    # stream fences (c_sync_comm_stream_op.cc etc.): X -> Out are the
    # same vars in fleet programs (a dependency edge, not a compute);
    # alias every pair so a differently-named Out still resolves.  Copy
    # the RAW scope entry (not through __getitem__): a coalesced
    # component must stay a live FusedSlice view, not freeze into its
    # pre-allreduce snapshot
    xs = op.inputs("X")
    outs = op.outputs("Out")
    for x_name, out_name in zip(xs, outs):
        if out_name != x_name and x_name in scope:
            scope[out_name] = dict.__getitem__(scope, x_name)


@braw("marker")
def _marker_op(op, scope, feeds, fetches):
    # profiler span marker (marker_op.cc): no inputs, no outputs
    return


def _partial_cols(op, scope):
    # partial_concat/partial_sum (operators/partial_concat_op.h):
    # columns [start, start+length) of each 2-D input (length=-1: to
    # the end; negative start wraps)
    xs = [jnp.asarray(scope.fetch(n)) for n in op.inputs("X")]
    start = int(op.attr("start_index", 0))
    length = int(op.attr("length", -1))
    size = xs[0].shape[1]
    if start < 0:
        start += size
    stop = size if length < 0 else start + length
    return [x[:, start:stop] for x in xs]


@braw("partial_concat")
def _partial_concat_op(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.concatenate(_partial_cols(op, scope),
                                              axis=1)


@braw("partial_sum")
def _partial_sum_op(op, scope, feeds, fetches):
    cols = _partial_cols(op, scope)
    out = cols[0]
    for c in cols[1:]:
        out = out + c
    scope[op.output("Out")] = out


@braw("coalesce_tensor")
def _coalesce_tensor_op(op, scope, feeds, fetches):
    """reference `operators/coalesce_tensor_op.cc`: pack Input tensors
    into one contiguous FusedOutput whose sub-ranges ALIAS the Output
    vars (the fleet then allreduces the fused buffer once and the
    optimizer reads the component grads through the aliases).  The
    functional redesign packs with jnp.concatenate and registers
    `FusedSlice` views for the outputs — reads of a component var
    resolve against the CURRENT fused buffer, so the post-allreduce
    values flow through exactly as the reference's sub-tensor aliasing
    does.  Alignment padding (use_align/align_size) only moves offsets;
    tight packing is observably equivalent through the views and is
    what we emit."""
    from .interp import FusedSlice, _current_blocks
    from .proto import vartype_to_np_dtype

    in_names = op.inputs("Input")
    out_names = op.outputs("Output")
    fused_name = op.output("FusedOutput")
    dtype = np.dtype(vartype_to_np_dtype(op.attr("dtype", 5)))
    copy_data = op.attr("copy_data", True) and \
        not op.attr("set_constant", False)

    def shape_of(name):
        # the fuse-grad-space layout coalesces BEFORE the backward ops
        # first write the components — sizes then come from the block's
        # static var descs, not from (absent) scope values
        if name in scope:
            return tuple(jnp.asarray(scope[name]).shape)
        for blk in _current_blocks():
            for v in blk.get("vars", []):
                if v.get("name") == name:
                    dims = (v.get("type", {}).get("lod_tensor", {})
                            .get("tensor", {}).get("dims", []))
                    if dims and all(int(d) >= 0 for d in dims):
                        return tuple(int(d) for d in dims)
        raise KeyError(
            f"coalesce_tensor: component {name!r} has neither a scope "
            "value nor a statically-shaped var desc to size the fused "
            "buffer from")

    shapes = [shape_of(n) for n in in_names]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    if copy_data:
        flat = jnp.concatenate(
            [jnp.ravel(jnp.asarray(scope.fetch(n))).astype(dtype)
             for n in in_names]) if in_names else jnp.zeros((0,), dtype)
    else:
        const = float(op.attr("constant", 0.0)) \
            if op.attr("set_constant", False) else 0.0
        flat = jnp.full((sum(sizes),), const, dtype)
    scope[fused_name] = flat
    offset = 0
    for out_name, shp, n in zip(out_names, shapes, sizes):
        # plain dict write: establishing the view must not write-through
        # into a previous aliasing of the same name
        dict.__setitem__(scope, out_name,
                         FusedSlice(fused_name, offset, shp))
        offset += n


# ---------------------------------------------------------------------------
# fake-quant family (reference operators/fake_quantize_op.cc /
# fake_dequantize_op.cc): QAT/PTQ simulation ops
# ---------------------------------------------------------------------------
def _qmax(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


@braw("fake_quantize_abs_max")
def _fake_q_abs_max(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    qm = _qmax(op.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    scope[op.output("Out")] = jnp.round(x / scale * qm)
    scope[op.output("OutScale")] = jnp.reshape(scale, (1,))


@braw("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    qm = _qmax(op.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    scope[op.output("Out")] = jnp.round(x / scale * qm) * scale / qm
    scope[op.output("OutScale")] = jnp.reshape(scale, (1,))


@braw("fake_channel_wise_quantize_abs_max",
      "fake_channel_wise_quantize_dequantize_abs_max")
def _fake_cw_q(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    qm = _qmax(op.attr("bit_length", 8))
    axis = op.attr("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    q = jnp.round(x / scale * qm)
    if "dequantize" in op.type:
        q = q * scale / qm
    scope[op.output("Out")] = q
    scope[op.output("OutScale")] = scale.reshape(-1)


@braw("fake_quantize_range_abs_max")
def _fake_q_range(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    qm = _qmax(op.attr("bit_length", 8))
    in_scale = jnp.reshape(scope.fetch(op.input("InScale")), ())
    cur = jnp.max(jnp.abs(x))
    if op.attr("is_test", False):
        scale = in_scale
    else:
        scale = jnp.maximum(cur, in_scale)
    scope[op.output("Out")] = jnp.round(
        jnp.clip(x, -scale, scale) / scale * qm)
    scope[op.output("OutScale")] = jnp.reshape(scale, (1,))
    if op.output("OutScales"):
        scope[op.output("OutScales")] = jnp.reshape(scale, (1,))


@braw("fake_quantize_moving_average_abs_max",
      "fake_quantize_dequantize_moving_average_abs_max")
def _fake_q_moving(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    qm = _qmax(op.attr("bit_length", 8))
    rate = op.attr("moving_rate", 0.9)
    state = _scalar_slot(op, scope, "InState", 1.0)
    accum = _scalar_slot(op, scope, "InAccum", 0.0)
    if op.attr("is_test", False):
        scale = jnp.reshape(scope.fetch(op.input("InScale")), ())
    else:
        state = rate * state + 1.0
        accum = rate * accum + jnp.max(jnp.abs(x))
        scale = accum / state
        if op.output("OutState"):
            scope[op.output("OutState")] = jnp.reshape(state, (1,))
        if op.output("OutAccum"):
            scope[op.output("OutAccum")] = jnp.reshape(accum, (1,))
    q = jnp.round(jnp.clip(x, -scale, scale) / scale * qm)
    if "dequantize" in op.type:
        q = q * scale / qm
    scope[op.output("Out")] = q
    if op.output("OutScale"):
        scope[op.output("OutScale")] = jnp.reshape(scale, (1,))


@braw("fake_dequantize_max_abs")
def _fake_dq_max_abs(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scale = jnp.reshape(scope.fetch(op.input("Scale")), ())
    scope[op.output("Out")] = x.astype(jnp.float32) * scale / op.attr(
        "max_range", 127.0)


@braw("fake_channel_wise_dequantize_max_abs")
def _fake_cw_dq(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X")).astype(jnp.float32)
    scales = [scope.fetch(n) for n in op.inputs("Scales")]
    qsteps = op.attr("quant_bits", [8, 8])
    axis = op.attr("quant_axis", 0)
    s0 = scales[0].reshape([-1 if i == axis else 1
                            for i in range(x.ndim)])
    out = x * s0 / _qmax(qsteps[0])
    if len(scales) > 1:
        out = out * scales[1].reshape(()) / _qmax(
            qsteps[1] if len(qsteps) > 1 else 8)
    scope[op.output("Out")] = out


@braw("fake_init")
def _fake_init_op(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    scope[op.output("Out")] = jnp.zeros(
        shape, vartype_to_np_dtype(op.attr("dtype", 5)))


# ---------------------------------------------------------------------------
# metric / misc / host ops
# ---------------------------------------------------------------------------
@braw("auc")
def _auc_op(op, scope, feeds, fetches):
    # reference operators/metrics/auc_op.h: histogram accumulation over
    # num_thresholds buckets + trapezoid area
    pred = scope.fetch(op.input("Predict"))
    label = scope.fetch(op.input("Label")).reshape(-1).astype(jnp.int32)
    n_th = op.attr("num_thresholds", 4095)
    pos_in = _slot_vec(op, scope, "StatPos", n_th + 1)
    neg_in = _slot_vec(op, scope, "StatNeg", n_th + 1)
    p1 = pred[:, -1] if pred.ndim == 2 else pred.reshape(-1)
    idx = jnp.clip((p1 * n_th).astype(jnp.int32), 0, n_th)
    pos = pos_in.at[idx].add(jnp.where(label > 0, 1.0, 0.0))
    neg = neg_in.at[idx].add(jnp.where(label > 0, 0.0, 1.0))
    # area sweeping thresholds from high to low
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros((1,)), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros((1,)), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc = jnp.where((tot_pos > 0) & (tot_neg > 0),
                    area / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0)
    scope[op.output("AUC")] = jnp.reshape(auc, ())
    if op.output("StatPosOut"):
        scope[op.output("StatPosOut")] = pos.astype(jnp.int64)
    if op.output("StatNegOut"):
        scope[op.output("StatNegOut")] = neg.astype(jnp.int64)


def _slot_vec(op, scope, name, n):
    vname = op.input(name)
    if vname and vname in scope:
        return jnp.asarray(scope[vname]).reshape(-1).astype(
            jnp.float32)[:n]
    return jnp.zeros((n,), jnp.float32)


@braw("precision_recall")
def _precision_recall_op(op, scope, feeds, fetches):
    # reference operators/metrics/precision_recall_op.h: per-class
    # TP/FP/TN/FN accumulation + macro/micro metrics
    idx = scope.fetch(op.input("Indices")).reshape(-1).astype(jnp.int32)
    label = scope.fetch(op.input("Labels")).reshape(-1).astype(jnp.int32)
    c = op.attr("class_number", 2)
    states_in = op.input("StatesInfo")
    st = scope[states_in].astype(jnp.float32) if states_in and \
        states_in in scope else jnp.zeros((c, 4), jnp.float32)
    onehot_p = jax.nn.one_hot(idx, c)
    onehot_l = jax.nn.one_hot(label, c)
    tp = jnp.sum(onehot_p * onehot_l, 0)
    fp = jnp.sum(onehot_p * (1 - onehot_l), 0)
    fn = jnp.sum((1 - onehot_p) * onehot_l, 0)
    tn = idx.shape[0] - tp - fp - fn
    batch = jnp.stack([tp, fp, tn, fn], 1)
    acc = st + batch

    def metrics(m):
        tp_, fp_, _, fn_ = m[:, 0], m[:, 1], m[:, 2], m[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1),
                         0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1),
                        0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec /
                       jnp.maximum(prec + rec, 1e-12), 0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mprec = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1),
                          0.0)
        mrec = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1),
                         0.0)
        mf1 = jnp.where(mprec + mrec > 0, 2 * mprec * mrec /
                        jnp.maximum(mprec + mrec, 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mprec, mrec, mf1])])

    scope[op.output("BatchMetrics")] = metrics(batch)
    scope[op.output("AccumMetrics")] = metrics(acc)
    scope[op.output("AccumStatesInfo")] = acc


@braw("print")
def _print_op(op, scope, feeds, fetches):
    x = scope.fetch(op.input("In"))
    msg = op.attr("message", "")
    jax.debug.print(msg + " {}", x)
    if op.output("Out"):
        scope[op.output("Out")] = x


@braw("assert")
def _assert_op(op, scope, feeds, fetches):
    cond = scope.fetch(op.input("Cond"))

    def _chk(c):
        if not np.asarray(c).all():
            raise AssertionError("Assert op failed")

    jax.debug.callback(_chk, cond)


@braw("bicubic_interp", "bicubic_interp_v2", "linear_interp",
      "linear_interp_v2", "trilinear_interp", "trilinear_interp_v2")
def _interp_extra_op(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    kind = op.type.split("_")[0]
    if kind == "linear":  # [N, C, W]
        out_w = op.attr("out_w", -1)
        if out_w <= 0:
            sc = op.attr("scale", [])
            sc = sc[0] if isinstance(sc, (list, tuple)) and sc else sc
            out_w = int(x.shape[2] * float(sc))
        shape = x.shape[:2] + (out_w,)
        method = "linear"
    elif kind == "bicubic":
        out_h, out_w = op.attr("out_h", -1), op.attr("out_w", -1)
        if out_h <= 0 or out_w <= 0:
            sc = op.attr("scale", [])
            if isinstance(sc, (int, float)):
                sc = [sc, sc]
            out_h = int(x.shape[2] * sc[0])
            out_w = int(x.shape[3] * sc[1])
        shape = x.shape[:2] + (out_h, out_w)
        method = "cubic"
    else:  # trilinear [N, C, D, H, W]
        out_d = op.attr("out_d", -1)
        out_h = op.attr("out_h", -1)
        out_w = op.attr("out_w", -1)
        if out_d <= 0:
            sc = op.attr("scale", [])
            if isinstance(sc, (int, float)):
                sc = [sc] * 3
            out_d = int(x.shape[2] * sc[0])
            out_h = int(x.shape[3] * sc[1])
            out_w = int(x.shape[4] * sc[2])
        shape = x.shape[:2] + (out_d, out_h, out_w)
        method = "trilinear"
    scope[op.output("Out")] = jax.image.resize(
        x, shape, "linear" if method == "trilinear" else method
    ).astype(x.dtype)


@braw("affine_grid")
def _affine_grid_op(op, scope, feeds, fetches):
    from paddle_tpu.nn import functional as F

    theta = scope.fetch(op.input("Theta"))
    shape_in = op.input("OutputShape")
    if shape_in:
        out_shape = [int(v) for v in np.asarray(scope.fetch(shape_in))]
    else:
        out_shape = [int(v) for v in op.attr("output_shape", [])]
    scope[op.output("Output")] = _unwrap(F.affine_grid(
        theta, out_shape,
        align_corners=op.attr("align_corners", True)))


@braw("diag")
def _diag_v1_op(op, scope, feeds, fetches):
    # fluid v1 diag: vector -> square diagonal matrix (diag_op.cc)
    scope[op.output("Out")] = jnp.diag(
        scope.fetch(op.input("Diagonal")).reshape(-1))


@braw("gru_unit")
def _gru_unit_op(op, scope, feeds, fetches):
    # single GRU step (operators/gru_unit_op.h): Input [B, 3D] packed
    # (update, reset, candidate), HiddenPrev [B, D], Weight [D, 3D]
    x = scope.fetch(op.input("Input"))
    hp = scope.fetch(op.input("HiddenPrev"))
    w = scope.fetch(op.input("Weight"))
    d = hp.shape[-1]
    bias_in = op.input("Bias")
    if bias_in:
        x = x + scope.fetch(bias_in).reshape(1, -1)
    gates = x[:, :2 * d] + hp @ w[:, :2 * d]
    u = jax.nn.sigmoid(gates[:, :d])
    rst = jax.nn.sigmoid(gates[:, d:])
    c_in = x[:, 2 * d:] + (rst * hp) @ w[:, 2 * d:]
    c = jnp.tanh(c_in)
    if op.attr("origin_mode", False):
        h = u * hp + (1 - u) * c
    else:
        h = (1 - u) * hp + u * c
    scope[op.output("Hidden")] = h
    if op.output("Gate"):
        scope[op.output("Gate")] = jnp.concatenate([u, rst, c], -1)
    if op.output("ResetHiddenPrev"):
        scope[op.output("ResetHiddenPrev")] = rst * hp


@braw("lstm_unit")
def _lstm_unit_op(op, scope, feeds, fetches):
    # single LSTM step (operators/lstm_unit_op.h): X [B, 4D] {i,g,f,o}
    x = scope.fetch(op.input("X"))
    c_prev = scope.fetch(op.input("C_prev"))
    d = c_prev.shape[-1]
    fb = op.attr("forget_bias", 0.0)
    i = jax.nn.sigmoid(x[:, :d])
    g = jnp.tanh(x[:, d:2 * d])
    f = jax.nn.sigmoid(x[:, 2 * d:3 * d] + fb)
    o = jax.nn.sigmoid(x[:, 3 * d:])
    c = f * c_prev + i * g
    scope[op.output("C")] = c
    scope[op.output("H")] = o * jnp.tanh(c)


@braw("random_crop")
def _random_crop_op(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    shape = [int(s) for s in op.attr("shape", [])]
    key = _op_key(op, op.attr("startup_seed", 0))
    full = list(x.shape)
    tgt = full[:len(full) - len(shape)] + shape
    starts = []
    for i, (fs, ts) in enumerate(zip(full, tgt)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, fs - ts + 1)
                      if fs > ts else 0)
    scope[op.output("Out")] = jax.lax.dynamic_slice(x, starts, tgt)
    if op.output("SeedOut"):
        scope[op.output("SeedOut")] = jnp.reshape(
            jnp.asarray(op.attr("startup_seed", 0), jnp.int64), (1,))


# ---------------------------------------------------------------------------
# ops with NO program-form translation, each with the reason and the
# API that delivers the capability instead.  tools/op_inventory.py
# cross-checks: implemented op => translator OR an entry here.
# ---------------------------------------------------------------------------
PROGRAM_FORM_NA = {
    # parameter-server trainer/server ops execute in the fleet PS
    # runtime (distributed/ps native client+server over TCP), not in
    # the XLA-traced program; fleet.distributed_optimizer rewires
    # programs onto the PS client at the Python layer
    "listen_and_serv": "distributed.ps.PSServer",
    "heter_listen_and_serv": "distributed.ps.HeterServer",
    "send": "distributed.ps.Communicator",
    "send_and_recv": "distributed.ps.Communicator",
    "send_barrier": "distributed.ps.PSClient.barrier",
    "fetch_barrier": "distributed.ps.PSClient.barrier",
    "distributed_lookup_table": "distributed.ps.PSClient.pull_sparse",
    "pull_sparse": "distributed.ps.PSClient.pull_sparse",
    "pull_sparse_v2": "distributed.ps.PSClient.pull_sparse",
    "push_sparse": "distributed.ps.PSClient.push_sparse_grad",
    "push_sparse_v2": "distributed.ps.PSClient.push_sparse_grad",
    "pull_box_sparse": "distributed.ps.PSClient.pull_sparse",
    "pull_box_extended_sparse": "distributed.ps.PSClient.pull_sparse",
    "push_box_sparse": "distributed.ps.PSClient.push_sparse_grad",
    "push_box_extended_sparse":
        "distributed.ps.PSClient.push_sparse_grad",
    "push_dense": "distributed.ps.PSClient.push_dense_grad",
    # host-python callback backed by a class registry the interchange
    # format cannot carry (reference py_layer is eager-only anyway)
    "py_layer": "autograd.PyLayer (eager)",
    # a program-in-program trampoline for dy2static; jit.StaticFunction
    # IS that mechanism here (run_program_op.cc)
    "run_program": "jit.StaticFunction",
}


# ---------------------------------------------------------------------------
# persistence ops — REAL file IO in the reference LoDTensor wire format
# (operators/save_op.cc, load_op.cc, save_combine_op.cc:1,
# load_combine_op.cc).  File IO needs concrete values, so these ops ride
# the op-by-op execution path (DYNAMIC set): the runner drops the
# whole-graph XLA compile for programs containing them, exactly like the
# reference's imperative op loop.
# ---------------------------------------------------------------------------
@braw("save")
def _save_op(op, scope, feeds, fetches):
    from .proto import write_lod_tensor
    import os

    path = op.attr("file_path", "")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    x = np.asarray(jax.device_get(scope.fetch(op.input("X"))))
    with open(path, "wb") as f:
        f.write(write_lod_tensor(x))


@braw("load")
def _load_op(op, scope, feeds, fetches):
    from .proto import read_lod_tensor

    with open(op.attr("file_path", ""), "rb") as f:
        data = f.read()
    arr, _lod, _pos = read_lod_tensor(data, 0)
    scope[op.output("Out")] = jnp.asarray(arr)


@braw("save_combine")
def _save_combine_op(op, scope, feeds, fetches):
    from .proto import write_lod_tensor
    import os

    path = op.attr("file_path", "")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        for name in op.inputs("X"):
            x = np.asarray(jax.device_get(scope.fetch(name)))
            f.write(write_lod_tensor(x))


@braw("load_combine")
def _load_combine_op(op, scope, feeds, fetches):
    from .proto import read_lod_tensor

    with open(op.attr("file_path", ""), "rb") as f:
        data = f.read()
    pos = 0
    for name in op.outputs("Out"):
        arr, _lod, pos = read_lod_tensor(data, pos)
        scope[name] = jnp.asarray(arr)


# ---------------------------------------------------------------------------
# DGC family (operators/dgc_op.h, dgc_momentum_op.h,
# dgc_clip_by_norm_op.h): gradient top-k compression.  The comm side is
# TPU-obsolete (XLA collectives), but the NUMERICS (momentum correction
# + top-k masking + local accumulation) translate faithfully.
# ---------------------------------------------------------------------------
@braw("dgc_clip_by_norm")
def _dgc_clip_by_norm_op(op, scope, feeds, fetches):
    # clip only after rampup_begin_step (current_step input)
    x = scope.fetch(op.input("X"))
    step = jnp.reshape(scope.fetch(op.input("current_step")), ())
    begin = op.attr("rampup_begin_step", 0.0)
    max_norm = op.attr("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    clipped = jnp.where(norm > max_norm, x * (max_norm / norm), x)
    scope[op.output("Out")] = jnp.where(step < begin, x, clipped)


@braw("dgc_momentum")
def _dgc_momentum_op(op, scope, feeds, fetches):
    # before rampup: plain SGD; after: momentum (dgc_momentum_op.h)
    p, g, lr = _opt_common(op, scope)
    step = jnp.reshape(scope.fetch(op.input("current_step")), ())
    begin = op.attr("rampup_begin_step", 0.0)
    mu = op.attr("mu", 0.9)
    v = _slot(op, scope, "Velocity", p)
    v_new = mu * v + g
    p_mom = p - lr * (g + mu * v_new) if op.attr("use_nesterov", False) \
        else p - lr * v_new
    p_sgd = p - lr * g
    use_mom = step >= begin
    scope[op.output("ParamOut")] = jnp.where(use_mom, p_mom, p_sgd)
    scope[op.output("VelocityOut")] = jnp.where(use_mom, v_new, v)


@braw("dgc")
def _dgc_op(op, scope, feeds, fetches):
    # top-k sparsification with momentum correction (dgc_op.h):
    # U = m*U + g; V = V + U; mask = |V| in top-k; encode = V*mask;
    # U,V keep the unsent residual.  k uses the FINAL sparsity ratio
    # (static shape requirement; the reference ramps k with steps).
    g = scope.fetch(op.input("Grad"))
    u = _slot(op, scope, "U", g)
    v = _slot(op, scope, "V", g)
    m = op.attr("m", 0.9)
    ratios = op.attr("sparsity", [0.999])
    ratio = float(ratios[-1]) if ratios else 0.999
    k = max(1, int(round(g.size * (1.0 - ratio))))
    u = m * u + g
    v = v + u
    flat = v.reshape(-1)
    thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
    mask = jnp.abs(v) >= thresh
    encode = jnp.where(mask, v, 0)
    scope[op.output("U_out")] = jnp.where(mask, 0, u)
    scope[op.output("V_out")] = jnp.where(mask, 0, v)
    scope[op.output("EncodeGrad")] = encode
    if op.output("Grad_out"):
        scope[op.output("Grad_out")] = encode
    if op.output("GatherBuff"):
        scope[op.output("GatherBuff")] = encode


@braw("positive_negative_pair")
def _positive_negative_pair_op(op, scope, feeds, fetches):
    # metrics/positive_negative_pair_op.h: per-query ordered-pair counts
    score = scope.fetch(op.input("Score")).reshape(-1)
    label = scope.fetch(op.input("Label")).reshape(-1)
    qid = scope.fetch(op.input("QueryID")).reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), 1)
    pair = same_q & (upper > 0) & (label[:, None] != label[None, :])
    hi_label = label[:, None] > label[None, :]
    hi_score = score[:, None] > score[None, :]
    eq_score = score[:, None] == score[None, :]
    pos = jnp.sum(pair & (hi_label == hi_score) & ~eq_score)
    neu = jnp.sum(pair & eq_score)
    neg = jnp.sum(pair) - pos - neu

    def acc(name, val):
        prev_in = op.input("Acc" + name)
        prev = jnp.reshape(scope[prev_in], ()) if prev_in and \
            prev_in in scope else 0.0
        scope[op.output(name)] = jnp.reshape(
            prev + val, (1,)).astype(jnp.float32)

    acc("PositivePair", pos)
    acc("NegativePair", neg)
    acc("NeutralPair", neu)


for _n in ("save", "load", "save_combine", "load_combine", "dgc"):
    from .interp import DYNAMIC_SHAPE_OPS as _DSO

    _DSO.add(_n)



# ---------------------------------------------------------------------------
# chunk_eval (operators/metrics/chunk_eval_op.h): IOB-family chunk
# extraction + batch precision/recall/F1.  The reference runs this
# CPU-side; here the extraction runs as a host callback
# (jax.pure_callback is jit-compatible), so the op is a real translator
# in both execution modes.
# ---------------------------------------------------------------------------
def _chunk_counts(inf, lab, lengths, scheme, num_chunk_types, excluded):
    inf = np.asarray(inf)
    lab = np.asarray(lab)
    if inf.ndim == 1:
        inf, lab = inf[None], lab[None]
    n_inf = n_lab = n_cor = 0
    for row in range(inf.shape[0]):
        ln = int(lengths[row]) if lengths is not None else inf.shape[1]
        from paddle_tpu.metric import extract_chunk_spans

        ci = extract_chunk_spans(inf[row, :ln], scheme,
                                 num_chunk_types, excluded)
        cl = extract_chunk_spans(lab[row, :ln], scheme,
                                 num_chunk_types, excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(set(ci) & set(cl))
    return (np.asarray([n_inf], np.int32), np.asarray([n_lab], np.int32),
            np.asarray([n_cor], np.int32))


@braw("chunk_eval")
def _chunk_eval_op(op, scope, feeds, fetches):
    inf = scope.fetch(op.input("Inference"))
    lab = scope.fetch(op.input("Label"))
    seq_in = op.input("SeqLength")
    lengths = scope.fetch(seq_in).reshape(-1) if seq_in else None
    scheme = op.attr("chunk_scheme", "IOB")
    nct = int(op.attr("num_chunk_types", 1))
    excl = set(int(e) for e in op.attr("excluded_chunk_types", []))

    def host(i_, l_, ln_):
        return _chunk_counts(i_, l_, ln_, scheme, nct, excl)

    # int32 shapes: x64 is disabled in this stack (callback results
    # must match); counts cast up for the declared int64 outputs after
    shapes = (jax.ShapeDtypeStruct((1,), jnp.int32),) * 3
    if lengths is not None:
        n_inf, n_lab, n_cor = jax.pure_callback(
            host, shapes, inf, lab, lengths)
    else:
        n_inf, n_lab, n_cor = jax.pure_callback(
            lambda i_, l_: host(i_, l_, None), shapes, inf, lab)
    fi = n_inf.astype(jnp.float32)
    fl = n_lab.astype(jnp.float32)
    fc = n_cor.astype(jnp.float32)
    p = jnp.where(fi > 0, fc / jnp.maximum(fi, 1), 0.0)
    r = jnp.where(fl > 0, fc / jnp.maximum(fl, 1), 0.0)
    f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    scope[op.output("Precision")] = p
    scope[op.output("Recall")] = r
    scope[op.output("F1-Score")] = f1
    if op.output("NumInferChunks"):
        scope[op.output("NumInferChunks")] = n_inf.astype(jnp.int64)
    if op.output("NumLabelChunks"):
        scope[op.output("NumLabelChunks")] = n_lab.astype(jnp.int64)
    if op.output("NumCorrectChunks"):
        scope[op.output("NumCorrectChunks")] = n_cor.astype(jnp.int64)



@braw("detection_map")
def _detection_map_op(op, scope, feeds, fetches):
    """operators/detection/detection_map_op.cc on the padded+lengths
    representation: DetectRes/Label are [B, M, 6] with `@LOD` sidecars
    (full length when absent); the reference's growing LoD state
    tensors become fixed-capacity dense buffers (PosCount [C],
    TruePos/FalsePos [C, CAP, 2] + valid-count vectors) accumulated
    across calls.  Matching + AP run on host via pure_callback (the
    reference kernel is CPU-side too)."""
    from .interp import _seq_lengths_or_full
    from paddle_tpu.metric import detection_map_update

    dname, lname = op.input("DetectRes"), op.input("Label")
    det = scope.fetch(dname)
    gt = scope.fetch(lname)
    det_lens = _seq_lengths_or_full(scope, dname, det)
    gt_lens = _seq_lengths_or_full(scope, lname, gt)
    C = int(op.attr("class_num", 1))
    cap = int(op.attr("state_capacity", 1024))

    def state(name, shape, dtype):
        vname = op.input(name)
        if vname and vname in scope:
            return jnp.asarray(scope[vname], dtype).reshape(shape)
        return jnp.zeros(shape, dtype)

    pos_count = state("PosCount", (C,), jnp.int32)
    true_pos = state("TruePos", (C, cap, 2), jnp.float32)
    tp_count = state("TruePosCount", (C,), jnp.int32)
    false_pos = state("FalsePos", (C, cap, 2), jnp.float32)
    fp_count = state("FalsePosCount", (C,), jnp.int32)
    overlap = op.attr("overlap_threshold", 0.5)
    ap_type = op.attr("ap_type", "11point")
    eval_diff = op.attr("evaluate_difficult", True)

    def host(d_, dl_, g_, gl_, pc_, tp_, tc_, fp_, fc_):
        out = detection_map_update(
            d_, dl_, g_, gl_, pc_, tp_, tc_, fp_, fc_, C,
            overlap_threshold=overlap, ap_type=ap_type,
            evaluate_difficult=eval_diff)
        pc, tpb, tcn, fpb, fcn, m = out
        return (pc.astype(np.int32), tpb.astype(np.float32),
                tcn.astype(np.int32), fpb.astype(np.float32),
                fcn.astype(np.int32), m.astype(np.float32))

    shapes = (jax.ShapeDtypeStruct((C,), jnp.int32),
              jax.ShapeDtypeStruct((C, cap, 2), jnp.float32),
              jax.ShapeDtypeStruct((C,), jnp.int32),
              jax.ShapeDtypeStruct((C, cap, 2), jnp.float32),
              jax.ShapeDtypeStruct((C,), jnp.int32),
              jax.ShapeDtypeStruct((1,), jnp.float32))
    pc, tpb, tcn, fpb, fcn, m_ap = jax.pure_callback(
        host, shapes, det, det_lens, gt, gt_lens, pos_count, true_pos,
        tp_count, false_pos, fp_count)
    scope[op.output("MAP")] = m_ap
    if op.output("AccumPosCount"):
        scope[op.output("AccumPosCount")] = pc
    if op.output("AccumTruePos"):
        scope[op.output("AccumTruePos")] = tpb
        if op.output("AccumTruePosCount"):
            scope[op.output("AccumTruePosCount")] = tcn
    if op.output("AccumFalsePos"):
        scope[op.output("AccumFalsePos")] = fpb
        if op.output("AccumFalsePosCount"):
            scope[op.output("AccumFalsePosCount")] = fcn



# ---------------------------------------------------------------------------
# host IO ops (operators/read_file_op.cc, decode_jpeg_op.cc) — concrete
# file IO with data-dependent output shapes: real translators on the
# op-by-op path (DYNAMIC set), exactly how the reference executes them
# (CPU-side, imperative op loop)
# ---------------------------------------------------------------------------
@braw("read_file")
def _read_file_op(op, scope, feeds, fetches):
    from paddle_tpu.vision.transforms import read_file

    scope[op.output("Out")] = _unwrap(
        read_file(op.attr("filename", "")))


@braw("decode_jpeg")
def _decode_jpeg_op(op, scope, feeds, fetches):
    from paddle_tpu.vision.transforms import decode_jpeg

    scope[op.output("Out")] = _unwrap(decode_jpeg(
        scope.fetch(op.input("X")), mode=op.attr("mode", "unchanged")))


# ---------------------------------------------------------------------------
# py_func (operators/py_func_op.cc): the reference stores a PROCESS-LOCAL
# registry index in `forward_callable_id` — in-process programs (built
# with this API in the same interpreter) run their callable through a
# host callback; a program deserialized in another process raises with
# the reason, same as the reference (its registry is process-local too).
# ---------------------------------------------------------------------------
PY_FUNC_REGISTRY: list = []


def register_py_func(fn) -> int:
    """Register a python callable for `py_func` ops; returns the id the
    op's `forward_callable_id` attr must carry (reference
    `layers/nn.py py_func` registration contract)."""
    PY_FUNC_REGISTRY.append(fn)
    return len(PY_FUNC_REGISTRY) - 1


@braw("py_func")
def _py_func_op(op, scope, feeds, fetches):
    cid = op.attr("forward_callable_id", -1)
    if not 0 <= cid < len(PY_FUNC_REGISTRY):
        raise NotImplementedError(
            f"py_func: forward_callable_id={cid} is not registered in "
            "this process (the registry is process-local, as in the "
            "reference py_func_op.cc); rebuild the program with "
            "op_bridge.register_py_func in this interpreter")
    fn = PY_FUNC_REGISTRY[cid]
    ins = [scope.fetch(n) for n in op.inputs("X")]
    outs = op.outputs("Out")
    if any(isinstance(v, jax.core.Tracer) for v in ins):
        # py_func is in DYNAMIC_SHAPE_OPS so the runner de-jits the
        # program; a traced value here means someone bypassed that path
        raise NotImplementedError(
            "py_func requires concrete inputs (op-by-op execution); "
            "it cannot run under an XLA trace")
    # inputs are concrete: run the callable ONCE (a pure_callback would
    # need a shape probe, executing stateful callables twice per step)
    res = fn(*[np.asarray(jax.device_get(v)) for v in ins])
    res = res if isinstance(res, (tuple, list)) else (res,)
    if len(res) != len(outs):
        raise ValueError(
            f"py_func callable returned {len(res)} values but the op "
            f"declares {len(outs)} outputs {outs}")
    for name, v in zip(outs, res):
        scope[name] = jnp.asarray(np.asarray(v))


for _n in ("read_file", "decode_jpeg", "py_func"):
    from .interp import DYNAMIC_SHAPE_OPS as _DSO2

    _DSO2.add(_n)


# paddle-2.x scalar ops the jaxpr exporter can emit
b("log1p", lambda x: jnp.log1p(x))
b("isfinite isfinite_v2", lambda x: jnp.isfinite(x))


# ---------------------------------------------------------------------------
# cudnn_lstm (operators/cudnn_lstm_op.cc / fluid.layers.lstm): the flat
# packed weight W follows cuDNN's canonical parameter order — for every
# layer, for every direction: 4 input-weight matrices then 4 recurrent
# matrices (gate order i, f, g, o); after ALL matrices, the biases in
# the same traversal order (4 input biases + 4 recurrent biases per
# layer/direction).  Total size matches fluid/layers/rnn.py:2564-2575.
# Input is TIME-MAJOR [T, B, in]; inference form (is_test) — dropout
# between layers is identity.
# ---------------------------------------------------------------------------
@braw("cudnn_lstm")
def _cudnn_lstm_op(op, scope, feeds, fetches):
    from .interp import OP_TRANSLATORS as _T, OpView

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("W")).reshape(-1)
    hidden = int(op.attr("hidden_size", 0))
    layers = int(op.attr("num_layers", 1))
    ndir = 2 if bool(op.attr("is_bidirec", False)) else 1
    t_len, bsz, in_sz = x.shape

    expected = 0
    for layer in range(layers):
        isz = in_sz if layer == 0 else hidden * ndir
        expected += (isz * hidden + hidden * hidden) * 4 * ndir
        expected += hidden * 8 * ndir
    if int(w.shape[0]) != expected:
        raise ValueError(
            f"cudnn_lstm: flat weight has {w.shape[0]} elements, the "
            f"layout for hidden={hidden} layers={layers} ndir={ndir} "
            f"input={in_sz} needs {expected}")

    # unpack into the unified rnn op's WeightList order ([w_ih, w_hh
    # per (layer, dir)] then [b_ih, b_hh per (layer, dir)]) and
    # DELEGATE to the `rnn` translator — one scan implementation, and
    # SequenceLength masking + the train-dropout guard come with it
    uid = f"__cudnn_lstm_{op.output('Out')}"
    wnames, bnames = [], []
    off = 0
    for layer in range(layers):
        isz = in_sz if layer == 0 else hidden * ndir
        for d in range(ndir):
            n_ih = f"{uid}_wih_{layer}_{d}"
            scope[n_ih] = w[off: off + 4 * hidden * isz].reshape(
                4 * hidden, isz)
            off += 4 * hidden * isz
            n_hh = f"{uid}_whh_{layer}_{d}"
            scope[n_hh] = w[off: off + 4 * hidden * hidden].reshape(
                4 * hidden, hidden)
            off += 4 * hidden * hidden
            wnames += [n_ih, n_hh]
    for layer in range(layers):
        for d in range(ndir):
            n_bi = f"{uid}_bih_{layer}_{d}"
            scope[n_bi] = w[off: off + 4 * hidden]
            off += 4 * hidden
            n_bh = f"{uid}_bhh_{layer}_{d}"
            scope[n_bh] = w[off: off + 4 * hidden]
            off += 4 * hidden
            bnames += [n_bi, n_bh]

    h0_in, c0_in = op.input("InitH"), op.input("InitC")
    h0_name, c0_name = f"{uid}_h0", f"{uid}_c0"
    scope[h0_name] = scope.fetch(h0_in) if h0_in else jnp.zeros(
        (layers * ndir, bsz, hidden), x.dtype)
    scope[c0_name] = scope.fetch(c0_in) if c0_in else jnp.zeros(
        (layers * ndir, bsz, hidden), x.dtype)

    lh = op.output("LastH") or f"{uid}_lh"
    lc = op.output("LastC") or f"{uid}_lc"
    inputs = [
        {"parameter": "Input", "arguments": [op.input("Input")]},
        {"parameter": "WeightList", "arguments": wnames + bnames},
        {"parameter": "PreState", "arguments": [h0_name, c0_name]},
    ]
    if op.input("SequenceLength"):
        inputs.append({"parameter": "SequenceLength",
                       "arguments": [op.input("SequenceLength")]})
    outputs = [
        {"parameter": "Out", "arguments": [op.output("Out")]},
        {"parameter": "State", "arguments": [lh, lc]},
    ]
    from .proto import AttrType as _AT

    desc = {
        "type": "rnn", "inputs": inputs, "outputs": outputs,
        "attrs": [
            {"name": "mode", "type": _AT.STRING, "s": "LSTM"},
            {"name": "hidden_size", "type": _AT.INT, "i": hidden},
            {"name": "num_layers", "type": _AT.INT, "i": layers},
            {"name": "is_bidirec", "type": _AT.BOOLEAN,
             "b": ndir == 2},
            {"name": "is_test", "type": _AT.BOOLEAN,
             "b": bool(op.attr("is_test", True))},
            {"name": "dropout_prob", "type": _AT.FLOAT,
             "f": float(op.attr("dropout_prob", 0.0))},
        ],
    }
    _T["rnn"](OpView(desc), scope, feeds, fetches)
    for aux in ("Reserve", "StateOut"):
        if op.output(aux):
            scope[op.output(aux)] = jnp.zeros((1,), jnp.uint8)
