"""Declarative OpDesc -> eager bridge.

Reference counterpart: the reference executor runs ANY registered op out
of a ProgramDesc (`paddle/fluid/framework/executor.cc:166` — OpRegistry
lookup + `op->Run`), so every op in the operator library is reachable
from a serialized program.  Rounds 1-3 hand-wrote ~178 translators; this
module closes the remaining gap *declaratively*: each entry names the
eager function (already implemented under paddle_tpu/*) plus the OpDesc
input / attr / output parameter-name map, with the parameter and attr
names taken from the reference op makers (the interchange schema, e.g.
`paddle/fluid/operators/flip_op.cc` AddInput("X")/AddAttr("axis")).

The generic runner fetches inputs from the interp scope, converts attrs,
calls the eager function inside the interp trace (dispatch handles
tracers transparently — same mechanism as interp._via_functional), and
stores outputs — so a bridged block still compiles to ONE XLA
computation.

Spec DSL
--------
``b("flip reverse", "P:flip", ins="X", attrs="axis")``

* names: space-separated op types sharing one spec
* target: "<mod>:<attr>" resolved lazily (P=paddle_tpu, F=nn.functional,
  ops, seq=ops.sequence, vops=vision.ops, vdet=vision.detection,
  quant=quantization, metric) or a callable ``fn(*arrays, **attrs)``
* ins: tokens ``Name`` (required), ``?Name`` (optional -> omitted),
  ``*Name`` (variadic -> list of arrays)
* attrs: tokens ``name``, ``name->kw`` (rename), with optional ``@conv``
  converter (``dtype`` = VarType code -> numpy dtype string, ``ints`` =
  coerce to list of int).  An attr absent from the OpDesc is not passed,
  so the eager default applies.
* outs: tokens ``Name`` (required), ``?Name`` (skipped when the op desc
  doesn't declare it or the fn returned None), ``*Name`` (fn returns a
  sequence distributed over the output slot's argument list)
"""
from __future__ import annotations

import importlib
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .interp import OP_TRANSLATORS, register

_MODS = {
    "P": "paddle_tpu",
    "F": "paddle_tpu.nn.functional",
    "ops": "paddle_tpu.ops",
    "seq": "paddle_tpu.ops.sequence",
    "vops": "paddle_tpu.vision.ops",
    "vdet": "paddle_tpu.vision.detection",
    "quant": "paddle_tpu.quantization",
    "metric": "paddle_tpu.metric",
    "nnu": "paddle_tpu.nn.utils",
}


def _resolve(target: str) -> Callable:
    mod, _, attr = target.partition(":")
    fn = importlib.import_module(_MODS[mod])
    for part in attr.split("."):
        fn = getattr(fn, part)
    return fn


def _conv_dtype(v):
    from .proto import vartype_to_np_dtype

    return vartype_to_np_dtype(int(v))


_CONVS = {
    "dtype": _conv_dtype,
    "ints": lambda v: [int(x) for x in v],
    "int": int,
    "float": float,
    "bool": bool,
}


def _unwrap(x):
    from ..core.tensor import Tensor

    if isinstance(x, Tensor):
        return x._array
    return x


class _Spec:
    __slots__ = ("target", "ins", "attrs", "outs", "_fn")

    def __init__(self, target, ins, attrs, outs):
        self.target = target
        self.ins = [(t.lstrip("?*"), t[0] if t[0] in "?*" else "")
                    for t in ins.split()] if ins else []
        self.attrs = []
        for tok in (attrs.split() if attrs else []):
            name, _, conv = tok.partition("@")
            src, _, kw = name.partition("->")
            self.attrs.append((src, kw or src,
                               _CONVS[conv] if conv else None))
        self.outs = [(t.lstrip("?*"), t[0] if t[0] in "?*" else "")
                     for t in outs.split()] if outs else []
        self._fn = None

    def fn(self):
        if self._fn is None:
            self._fn = (self.target if callable(self.target)
                        else _resolve(self.target))
        return self._fn


def _run_spec(spec: _Spec, op, scope, feeds, fetches):
    args = []
    for name, mode in spec.ins:
        if mode == "*":
            args.append([scope.fetch(a) for a in op.inputs(name)])
        else:
            arg = op.input(name)
            if not arg:
                if mode == "?":
                    args.append(None)  # keep positional alignment
                    continue
                raise KeyError(
                    f"{op.type}: required input {name!r} missing")
            args.append(scope.fetch(arg))
    kw = {}
    for src, dst, conv in spec.attrs:
        if src in op._attrs:
            v = op._attrs[src]
            kw[dst] = conv(v) if conv else v
    out = spec.fn()(*args, **kw)
    _store_outs(spec, op, scope, out)


def _store_outs(spec, op, scope, out):
    if isinstance(out, (tuple, list)) and not (
            len(spec.outs) == 1 and spec.outs[0][1] != "*"):
        vals = list(out)
    else:
        vals = [out]
    vi = 0
    for name, mode in spec.outs:
        slots = op.outputs(name)
        if mode == "*":
            seq = vals[vi] if len(spec.outs) > 1 else vals
            if len(seq) == 1 and isinstance(seq[0], (tuple, list)):
                seq = seq[0]
            for slot, v in zip(slots, seq):
                scope[slot] = _unwrap(v)
            vi += 1
            continue
        if not slots:
            if mode == "?":
                vi += 1
                continue
            raise KeyError(f"{op.type}: output slot {name!r} undeclared")
        v = vals[vi] if vi < len(vals) else None
        vi += 1
        if v is None:
            if mode == "?":
                continue
            raise ValueError(f"{op.type}: no value for output {name!r}")
        scope[slots[0]] = _unwrap(v)


BRIDGED: Dict[str, _Spec] = {}


def b(names: str, target, ins="X", attrs="", outs="Out"):
    spec = _Spec(target, ins, attrs, outs)
    for n in names.split():
        if n in OP_TRANSLATORS:  # hand-written translators win
            continue
        BRIDGED[n] = spec

        def _t(op, scope, feeds, fetches, _s=spec):
            _run_spec(_s, op, scope, feeds, fetches)

        OP_TRANSLATORS[n] = _t


# ---------------------------------------------------------------------------
# tensor math / manipulation (reference op makers under
# paddle/fluid/operators/*.cc — names cited per entry where non-obvious)
# ---------------------------------------------------------------------------
b("flip", "P:flip", ins="X", attrs="axis")
b("reverse", "P:flip", ins="X", attrs="axis")  # reverse_op.cc: axis ints
b("roll", "P:roll", ins="X", attrs="shifts axis")
b("strided_slice", lambda x, axes=(), starts=(), ends=(), strides=(),
    decrease_axis=(), infer_flags=():
    _strided_slice(x, axes, starts, ends, strides, decrease_axis),
  ins="Input", attrs="axes starts ends strides decrease_axis infer_flags")
b("index_select", "P:index_select", ins="X Index", attrs="dim->axis")
b("index_sample", "P:index_sample", ins="X Index")
b("tril_triu", lambda x, diagonal=0, lower=True:
    (jnp.tril if lower else jnp.triu)(x, k=int(diagonal)),
  ins="X", attrs="diagonal lower")
b("unbind", "P:unbind", ins="X", attrs="axis", outs="*Out")
b("unstack", "P:unstack", ins="X", attrs="axis num", outs="*Y")
b("meshgrid", "P:meshgrid", ins="*X", outs="*Out")
b("expand", lambda x, expand_times=():
    jnp.tile(x, tuple(int(t) for t in expand_times)),
  ins="X", attrs="expand_times")
b("expand_as", lambda x, y: jnp.tile(
    x, tuple(t // s for t, s in zip(y.shape, x.shape))),
  ins="X target_tensor")  # fluid v1 expand_as tiles by integer multiples
b("expand_as_v2", lambda x, target_shape=():
    jnp.broadcast_to(x, tuple(int(s) for s in target_shape)),
  ins="X", attrs="target_shape")
b("bmm", "P:bmm", ins="X Y")
b("mv", lambda x, vec: jnp.matmul(x, vec), ins="X Vec")
b("dot", "P:dot", ins="X Y")
b("cross", "P:cross", ins="X Y", attrs="dim->axis")
b("kron", "P:kron", ins="X Y")
b("addmm", "P:addmm", ins="Input X Y", attrs="Alpha->alpha Beta->beta")
b("diag_v2", "P:diag", ins="X", attrs="offset padding_value")
b("diag_embed", "P:diag_embed", ins="Input",
  attrs="offset dim1 dim2")
b("diagonal", "P:diagonal", ins="Input", attrs="offset axis1 axis2")
b("trace", "P:trace", ins="Input", attrs="offset axis1 axis2")
b("inverse", "P:inverse", ins="Input", outs="Output")
b("cholesky", "P:cholesky", ins="X", attrs="upper")
b("histogram", "P:histogram", ins="X", attrs="bins min max")
b("masked_select", "P:masked_select", ins="X Mask", outs="Y")
b("multiplex", lambda inputs, ids:
    jnp.take_along_axis(
        jnp.stack(inputs), ids.reshape(1, -1, *([1] * (inputs[0].ndim - 1))
                                       ).astype(jnp.int32), axis=0)[0],
  ins="*X Ids")
b("broadcast_tensors", "P:broadcast_tensors", ins="*X", outs="*Out")
b("allclose", "P:allclose", ins="Input Other",
  attrs="rtol@float atol@float equal_nan")
b("atan2", "P:atan2", ins="X1 X2")
b("digamma", "P:digamma")
b("lgamma", "P:lgamma")
b("expm1", lambda x: jnp.expm1(x))
b("trunc", "P:trunc", ins="X")
b("logsumexp", "P:logsumexp", ins="X",
  attrs="axis keepdim")
b("conj", "P:conj")
b("real", "P:real")
b("imag", "P:imag")
b("arg_min", lambda x, axis=0, keepdims=False, dtype=3, flatten=False:
    jnp.argmin(x.reshape(-1) if flatten else x,
               axis=None if flatten else int(axis),
               keepdims=keepdims and not flatten).astype(_conv_dtype(dtype)),
  ins="X", attrs="axis keepdims dtype flatten")
b("dist", "P:dist", ins="X Y", attrs="p")
b("eye", lambda num_rows=0, num_columns=-1, dtype=5:
    jnp.eye(int(num_rows),
            int(num_columns) if int(num_columns) >= 0 else None,
            dtype=_conv_dtype(dtype)),
  ins="", attrs="num_rows num_columns dtype")
b("size", lambda x: jnp.asarray(int(np.prod(x.shape)), jnp.int64),
  ins="Input")
b("linspace", lambda start, stop, num, dtype=5:
    jnp.linspace(start.reshape(()), stop.reshape(()),
                 int(num.reshape(())),
                 dtype=_conv_dtype(dtype)),
  ins="Start Stop Num", attrs="dtype")
b("crop", lambda x, offsets=(), shape=():
    jax.lax.dynamic_slice(x, [int(o) for o in offsets],
                          [int(s) for s in shape]),
  ins="X", attrs="offsets shape")
b("crop_tensor", lambda x, offsets=(), shape=():
    jax.lax.dynamic_slice(
        x, [int(o) for o in (offsets or [0] * x.ndim)],
        [x.shape[i] if int(s) == -1 else int(s)
         for i, s in enumerate(shape or x.shape)]),
  ins="X", attrs="offsets shape")
b("scatter_nd_add", "P:scatter_nd_add", ins="X Index Updates")
b("gather_tree", "ops:gather_tree", ins="Ids Parents")
b("segment_pool", lambda x, seg, pooltype="SUM":
    _seg_pool(x, seg, pooltype),
  ins="X SegmentIds", attrs="pooltype", outs="Out ?SummedIds")


def _seg_pool(x, seg, pooltype):
    from paddle_tpu import ops as _ops

    return _ops.segment_pool(x, seg, pool_type=pooltype.lower())
b("where_index", lambda x: jnp.stack(jnp.nonzero(x), axis=1)
    .astype(jnp.int64), ins="Condition")
b("minus", lambda x, y: x - y, ins="X Y")
b("grad_add", lambda x, y: x + y, ins="X Y")
b("squared_l2_norm", lambda x: jnp.sum(jnp.square(x)).reshape(1))
b("l1_norm", lambda x: jnp.sum(jnp.abs(x)).reshape(1))
b("frobenius_norm", lambda x, dim=(), keep_dim=False, reduce_all=False:
    jnp.sqrt(jnp.sum(jnp.square(x),
                     axis=None if reduce_all or not dim
                     else tuple(int(d) for d in dim),
                     keepdims=keep_dim)),
  ins="X", attrs="dim keep_dim reduce_all")
b("shard_index", "ops:shard_index", ins="X",
  attrs="index_num nshards shard_id ignore_value")
b("unique", lambda x, dtype=3, return_index=False, return_inverse=False,
    return_counts=False, axis=(), is_sorted=True:
    _unique(x, dtype, return_index, return_inverse, return_counts, axis),
  ins="X", attrs="dtype return_index return_inverse return_counts "
                 "axis is_sorted",
  outs="Out ?Indices ?Index ?Counts")
b("unique_with_counts", lambda x, dtype=2:
    _unique_with_counts(x, dtype),
  ins="X", attrs="dtype", outs="Out Index Count")
b("fill", lambda shape=(), value=0.0, dtype=5:
    jnp.full([int(s) for s in shape], value, _conv_dtype(dtype)),
  ins="", attrs="shape value dtype")
b("fill_constant_batch_size_like",
  lambda x, shape=(), value=0.0, dtype=5, input_dim_idx=0,
  output_dim_idx=0: _batch_size_like(x, shape, input_dim_idx,
                                     output_dim_idx, value,
                                     _conv_dtype(dtype)),
  ins="Input", attrs="shape value dtype input_dim_idx output_dim_idx")
b("empty", lambda shape=(), dtype=5:
    jnp.zeros([int(s) for s in shape], _conv_dtype(dtype)),
  ins="", attrs="shape dtype")
b("seed", lambda seed=0: jnp.asarray(seed or 0, jnp.int32),
  ins="", attrs="seed")


def _strided_slice(x, axes, starts, ends, strides, decrease_axis):
    idx = [slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        ax = int(ax) % x.ndim
        n = x.shape[ax]
        s, e, st = int(s), int(e), int(st)
        # reference clamps INT_MAX/negative bounds (strided_slice_op.h)
        if s < 0:
            s += n
        if e < 0:
            e += n
        if st > 0:
            e = min(e, n)
        elif e < 0:
            # end walked past the front (e.g. ends=[-n-1] or INT_MIN with
            # a negative stride): python slice needs None, -1 would mean
            # "stop before the last element"
            e = None
        idx[ax] = slice(s, e, st)
    out = x[tuple(idx)]
    if decrease_axis:
        out = out.reshape([d for i, d in enumerate(out.shape)
                           if i not in {int(a) for a in decrease_axis}])
    return out


def _unique(x, dtype, return_index, return_inverse, return_counts, axis):
    axis = int(axis[0]) if axis else None
    res = jnp.unique(x, return_index=True, return_inverse=True,
                     return_counts=True, axis=axis)
    out, index, inverse, counts = res
    idt = _conv_dtype(dtype)
    vals = [out]
    vals.append(index.astype(idt) if return_index else None)
    vals.append(inverse.reshape(-1).astype(idt) if return_inverse else None)
    vals.append(counts.astype(idt) if return_counts else None)
    return tuple(vals)


def _unique_with_counts(x, dtype):
    out, inverse, counts = jnp.unique(x, return_inverse=True,
                                      return_counts=True)
    idt = _conv_dtype(dtype)
    return out, inverse.reshape(-1).astype(idt), counts.astype(idt)


def _batch_size_like(x, shape, in_idx, out_idx, value, dtype):
    return jnp.full(_bsl_shape(x, shape, in_idx, out_idx), value, dtype)


# random family: key = PRNGKey(op seed attr) folded with a crc of the
# output var name, so two random ops in one program draw DIFFERENT
# samples (the hand-written uniform_random translator's stance, hardened
# per round-4 review). Program-level reproducibility still holds: same
# program + same seeds -> same draws.
def _op_key(op, seed=0):
    import zlib

    return jax.random.fold_in(jax.random.PRNGKey(seed or 0),
                              zlib.crc32(op.output("Out").encode()))


def braw(*names):
    """Register a raw translator (full op access) under the bridge's
    'hand over only if unclaimed' rule, and record it as bridged."""
    def deco(fn):
        for n in names:
            if n not in OP_TRANSLATORS:
                OP_TRANSLATORS[n] = fn
                BRIDGED[n] = fn
        return fn
    return deco


@braw("bernoulli")
def _bernoulli(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.random.bernoulli(
        _op_key(op), x.astype(jnp.float32)).astype(x.dtype)


@braw("multinomial")
def _multinomial(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    k = int(op.attr("num_samples", 1))
    logits = jnp.log(x.astype(jnp.float32) + 1e-30)
    if op.attr("replacement", False):
        out = jax.random.categorical(_op_key(op), logits,
                                     shape=x.shape[:-1] + (k,))
    else:
        # Gumbel top-k == sampling without replacement
        g = jax.random.gumbel(_op_key(op), logits.shape)
        _, out = jax.lax.top_k(logits + g, k)
    scope[op.output("Out")] = out.astype(jnp.int64)


@braw("sampling_id")
def _sampling_id(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.random.categorical(
        _op_key(op, op.attr("seed", 0)), jnp.log(x + 1e-30),
        axis=-1).astype(jnp.int64)
b("randint", lambda shape=(), low=0, high=0, dtype=3, seed=0:
    jax.random.randint(jax.random.PRNGKey(seed or 0),
                       [int(s) for s in shape], int(low), int(high)
                       ).astype(_conv_dtype(dtype)),
  ins="", attrs="shape low high dtype seed")
b("randperm", lambda n=0, dtype=3, seed=0:
    jax.random.permutation(jax.random.PRNGKey(seed or 0), int(n)
                           ).astype(_conv_dtype(dtype)),
  ins="", attrs="n dtype seed")
b("gaussian_random_batch_size_like",
  lambda x, shape=(), input_dim_idx=0, output_dim_idx=0, mean=0.0,
  std=1.0, seed=0, dtype=5: mean + std * jax.random.normal(
      jax.random.PRNGKey(seed or 0),
      _bsl_shape(x, shape, input_dim_idx, output_dim_idx),
      jnp.float32).astype(_conv_dtype(dtype)),
  ins="Input", attrs="shape input_dim_idx output_dim_idx mean std "
                     "seed dtype")
b("uniform_random_batch_size_like",
  lambda x, shape=(), input_dim_idx=0, output_dim_idx=0, min=-1.0,
  max=1.0, seed=0, dtype=5: jax.random.uniform(
      jax.random.PRNGKey(seed or 0),
      _bsl_shape(x, shape, input_dim_idx, output_dim_idx),
      jnp.float32, min, max).astype(_conv_dtype(dtype)),
  ins="Input", attrs="shape input_dim_idx output_dim_idx min max "
                     "seed dtype")
b("truncated_gaussian_random", lambda shape=(), mean=0.0, std=1.0,
    seed=0, dtype=5: mean + std * jax.random.truncated_normal(
        jax.random.PRNGKey(seed or 0), -2.0, 2.0,
        [int(s) for s in shape]).astype(_conv_dtype(dtype)),
  ins="", attrs="shape mean std seed dtype")


def _bsl_shape(x, shape, in_idx, out_idx):
    shape = [int(s) for s in shape]
    shape[int(out_idx)] = x.shape[int(in_idx)]
    return shape
