"""Bit-compatible reader/writer for the reference ProgramDesc format.

Reference interchange contract: `paddle/fluid/framework/framework.proto`
(proto2, package paddle.framework.proto) — ProgramDesc -> BlockDesc ->
OpDesc/VarDesc with the AttrType and VarType.Type enums (SURVEY.md
Appendix C).  Reference-era `.pdmodel` / `__model__` files and the
LoDTensor payloads of `.pdiparams` / `__params__` must round-trip through
here byte-for-byte.

Implementation: a small hand-rolled protobuf *wire format* codec (varint /
64-bit / length-delimited / 32-bit) plus the message schemas as data
tables keyed by field number.  No generated code, no protobuf runtime
dependency — the field numbers ARE the contract, the schema tables below
restate them.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# enums (framework.proto values)
# ---------------------------------------------------------------------------


class AttrType:
    INT = 0
    FLOAT = 1
    STRING = 2
    INTS = 3
    FLOATS = 4
    STRINGS = 5
    BOOLEAN = 6
    BOOLEANS = 7
    BLOCK = 8
    LONG = 9
    BLOCKS = 10
    LONGS = 11
    FLOAT64S = 12


class VarType:
    # POD dtypes
    BOOL = 0
    INT16 = 1
    INT32 = 2
    INT64 = 3
    FP16 = 4
    FP32 = 5
    FP64 = 6
    SIZE_T = 19
    UINT8 = 20
    INT8 = 21
    BF16 = 22
    COMPLEX64 = 23
    COMPLEX128 = 24
    # container types
    LOD_TENSOR = 7
    SELECTED_ROWS = 8
    FEED_MINIBATCH = 9
    FETCH_LIST = 10
    STEP_SCOPES = 11
    LOD_RANK_TABLE = 12
    LOD_TENSOR_ARRAY = 13
    PLACE_LIST = 14
    READER = 15
    RAW = 17
    TUPLE = 18


# numpy dtype <-> VarType POD code
_NP_TO_VT = {
    "bool": VarType.BOOL, "int16": VarType.INT16, "int32": VarType.INT32,
    "int64": VarType.INT64, "float16": VarType.FP16,
    "float32": VarType.FP32, "float64": VarType.FP64,
    "uint8": VarType.UINT8, "int8": VarType.INT8,
    "bfloat16": VarType.BF16, "complex64": VarType.COMPLEX64,
    "complex128": VarType.COMPLEX128,
}
_VT_TO_NP = {v: k for k, v in _NP_TO_VT.items()}


def np_dtype_to_vartype(dtype) -> int:
    return _NP_TO_VT[str(dtype)]


def vartype_to_np_dtype(vt: int) -> str:
    return _VT_TO_NP[vt]


# ---------------------------------------------------------------------------
# wire-format primitives
# ---------------------------------------------------------------------------
_WT_VARINT, _WT_I64, _WT_LEN, _WT_I32 = 0, 1, 2, 5


def _w_varint(out: bytearray, v: int):
    if v < 0:  # proto int32/int64 negative -> 10-byte two's complement
        v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _r_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _signed32(v: int) -> int:
    v &= (1 << 64) - 1
    v = v & 0xFFFFFFFF if v < (1 << 32) else v & (1 << 64) - 1
    v = _signed64(v)
    if v > 0x7FFFFFFF:
        v -= 1 << 32
    return v


def _w_tag(out: bytearray, field: int, wt: int):
    _w_varint(out, (field << 3) | wt)


# ---------------------------------------------------------------------------
# schema tables: field -> (name, kind, repeated, [submessage])
# kinds: int32 int64 uint64 bool float double string enum msg
# ---------------------------------------------------------------------------
_S = {
    "Version": {1: ("version", "int64", False)},
    "OpDesc.Attr": {
        1: ("name", "string", False),
        2: ("type", "enum", False),
        3: ("i", "int32", False),
        4: ("f", "float", False),
        5: ("s", "string", False),
        6: ("ints", "int32", True),
        7: ("floats", "float", True),
        8: ("strings", "string", True),
        10: ("b", "bool", False),
        11: ("bools", "bool", True),
        12: ("block_idx", "int32", False),
        13: ("l", "int64", False),
        14: ("blocks_idx", "int32", True),
        15: ("longs", "int64", True),
        16: ("float64s", "double", True),
    },
    "OpDesc.Var": {
        1: ("parameter", "string", False),
        2: ("arguments", "string", True),
    },
    "OpDesc": {
        1: ("inputs", "msg", True, "OpDesc.Var"),
        2: ("outputs", "msg", True, "OpDesc.Var"),
        3: ("type", "string", False),
        4: ("attrs", "msg", True, "OpDesc.Attr"),
        5: ("is_target", "bool", False),
    },
    "VarType.TensorDesc": {
        1: ("data_type", "enum", False),
        2: ("dims", "int64", True),
    },
    "VarType.LoDTensorDesc": {
        1: ("tensor", "msg", False, "VarType.TensorDesc"),
        2: ("lod_level", "int32", False),
    },
    "VarType.LoDTensorArrayDesc": {
        1: ("tensor", "msg", False, "VarType.TensorDesc"),
        2: ("lod_level", "int32", False),
    },
    "VarType.ReaderDesc": {
        1: ("lod_tensor", "msg", True, "VarType.LoDTensorDesc"),
    },
    "VarType.Tuple": {1: ("element_type", "enum", True)},
    "VarType": {
        1: ("type", "enum", False),
        2: ("selected_rows", "msg", False, "VarType.TensorDesc"),
        3: ("lod_tensor", "msg", False, "VarType.LoDTensorDesc"),
        4: ("tensor_array", "msg", False, "VarType.LoDTensorArrayDesc"),
        5: ("reader", "msg", False, "VarType.ReaderDesc"),
        7: ("tuple", "msg", False, "VarType.Tuple"),
    },
    "VarDesc": {
        1: ("name", "string", False),
        2: ("type", "msg", False, "VarType"),
        3: ("persistable", "bool", False),
        4: ("need_check_feed", "bool", False),
    },
    "BlockDesc": {
        1: ("idx", "int32", False),
        2: ("parent_idx", "int32", False),
        3: ("vars", "msg", True, "VarDesc"),
        4: ("ops", "msg", True, "OpDesc"),
        5: ("forward_block_idx", "int32", False),
    },
    "OpVersion": {1: ("version", "int32", False)},
    "OpVersionMap.OpVersionPair": {
        1: ("op_name", "string", False),
        2: ("op_version", "msg", False, "OpVersion"),
    },
    "OpVersionMap": {
        1: ("pair", "msg", True, "OpVersionMap.OpVersionPair"),
    },
    "ProgramDesc": {
        1: ("blocks", "msg", True, "BlockDesc"),
        4: ("version", "msg", False, "Version"),
        5: ("op_version_map", "msg", False, "OpVersionMap"),
    },
}

# field emission order: proto encoders conventionally write by ascending
# field number; the reference's C++ protobuf does the same, which keeps
# our bytes comparable with protoc-generated ones
_ORDERED = {m: sorted(f.items()) for m, f in _S.items()}


def decode(msg_name: str, buf: bytes, start: int = 0,
           end: Optional[int] = None) -> Dict[str, Any]:
    """Parse wire bytes into a dict (repeated fields become lists)."""
    schema = _S[msg_name]
    out: Dict[str, Any] = {}
    pos = start
    end = len(buf) if end is None else end
    while pos < end:
        key, pos = _r_varint(buf, pos)
        field, wt = key >> 3, key & 7
        spec = schema.get(field)
        if spec is None:  # unknown field: skip per wire type
            if wt == _WT_VARINT:
                _, pos = _r_varint(buf, pos)
            elif wt == _WT_I64:
                pos += 8
            elif wt == _WT_LEN:
                ln, pos = _r_varint(buf, pos)
                pos += ln
            elif wt == _WT_I32:
                pos += 4
            else:
                raise ValueError(f"bad wire type {wt} in {msg_name}")
            continue
        name, kind, repeated = spec[0], spec[1], spec[2]
        if kind == "msg":
            ln, pos = _r_varint(buf, pos)
            val = decode(spec[3], buf, pos, pos + ln)
            pos += ln
        elif kind == "string":
            ln, pos = _r_varint(buf, pos)
            val = buf[pos:pos + ln].decode("utf-8", errors="surrogateescape")
            pos += ln
        elif kind == "float":
            if wt == _WT_LEN:  # packed
                ln, pos = _r_varint(buf, pos)
                vals = list(struct.unpack_from(f"<{ln // 4}f", buf, pos))
                pos += ln
                out.setdefault(name, []).extend(vals)
                continue
            val = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        elif kind == "double":
            if wt == _WT_LEN:
                ln, pos = _r_varint(buf, pos)
                vals = list(struct.unpack_from(f"<{ln // 8}d", buf, pos))
                pos += ln
                out.setdefault(name, []).extend(vals)
                continue
            val = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        else:  # varint family: int32 int64 bool enum
            if wt == _WT_LEN and repeated:  # packed repeated varints
                ln, pos = _r_varint(buf, pos)
                stop = pos + ln
                while pos < stop:
                    raw, pos = _r_varint(buf, pos)
                    out.setdefault(name, []).append(
                        _coerce_varint(kind, raw))
                continue
            raw, pos = _r_varint(buf, pos)
            val = _coerce_varint(kind, raw)
        if repeated:
            out.setdefault(name, []).append(val)
        else:
            out[name] = val
    return out


def _coerce_varint(kind: str, raw: int):
    if kind == "bool":
        return bool(raw)
    if kind == "int32":
        return _signed32(raw)
    if kind == "int64":
        return _signed64(raw)
    return raw  # enum / uint


def encode(msg_name: str, obj: Dict[str, Any]) -> bytes:
    """Serialize a dict (as produced by decode) back to wire bytes."""
    out = bytearray()
    for field, spec in _ORDERED[msg_name]:
        name, kind, repeated = spec[0], spec[1], spec[2]
        if name not in obj or obj[name] is None:
            continue
        vals = obj[name] if repeated else [obj[name]]
        for v in vals:
            if kind == "msg":
                sub = encode(spec[3], v)
                _w_tag(out, field, _WT_LEN)
                _w_varint(out, len(sub))
                out += sub
            elif kind == "string":
                data = v.encode("utf-8", errors="surrogateescape") \
                    if isinstance(v, str) else bytes(v)
                _w_tag(out, field, _WT_LEN)
                _w_varint(out, len(data))
                out += data
            elif kind == "float":
                _w_tag(out, field, _WT_I32)
                out += struct.pack("<f", float(v))
            elif kind == "double":
                _w_tag(out, field, _WT_I64)
                out += struct.pack("<d", float(v))
            elif kind == "bool":
                _w_tag(out, field, _WT_VARINT)
                _w_varint(out, 1 if v else 0)
            else:  # int32/int64/enum
                _w_tag(out, field, _WT_VARINT)
                _w_varint(out, int(v))
    return bytes(out)


def parse_program(data: bytes) -> Dict[str, Any]:
    return decode("ProgramDesc", data)


def serialize_program(prog: Dict[str, Any]) -> bytes:
    return encode("ProgramDesc", prog)


# ---------------------------------------------------------------------------
# LoDTensor payload streams (save_op / .pdiparams records)
# ---------------------------------------------------------------------------
def write_lod_tensor(arr, lod: Optional[List[List[int]]] = None) -> bytes:
    """Serialize one array in the reference `SerializeToStream` layout:
    u32 version | u64 lod_level | per-level (u64 nbytes + u64 offsets) |
    u32 version | i32 desc_len | TensorDesc proto | raw data
    (`framework/lod_tensor.cc:244`, `tensor_util.cc:771`)."""
    import numpy as np

    arr = np.ascontiguousarray(arr)
    out = bytearray()
    out += struct.pack("<I", 0)  # LoDTensor version
    lod = lod or []
    out += struct.pack("<Q", len(lod))
    for level in lod:
        level_arr = np.asarray(level, np.uint64)
        out += struct.pack("<Q", level_arr.nbytes)
        out += level_arr.tobytes()
    out += struct.pack("<I", 0)  # Tensor version
    desc = encode("VarType.TensorDesc", {
        "data_type": np_dtype_to_vartype(arr.dtype),
        "dims": [int(d) for d in arr.shape],
    })
    out += struct.pack("<i", len(desc))
    out += desc
    out += arr.tobytes()
    return bytes(out)


def read_lod_tensor(buf: bytes, pos: int = 0):
    """Parse one SerializeToStream record; returns (array, lod, new_pos)."""
    import numpy as np

    (ver,) = struct.unpack_from("<I", buf, pos)
    pos += 4
    if ver != 0:
        raise ValueError(f"unsupported LoDTensor version {ver}")
    (lod_level,) = struct.unpack_from("<Q", buf, pos)
    pos += 8
    lod = []
    for _ in range(lod_level):
        (nbytes,) = struct.unpack_from("<Q", buf, pos)
        pos += 8
        level = np.frombuffer(buf, np.uint64, count=nbytes // 8,
                              offset=pos)
        lod.append([int(x) for x in level])
        pos += nbytes
    (tver,) = struct.unpack_from("<I", buf, pos)
    pos += 4
    if tver != 0:
        raise ValueError(f"unsupported Tensor version {tver}")
    (desc_len,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    desc = decode("VarType.TensorDesc", buf, pos, pos + desc_len)
    pos += desc_len
    dtype = np.dtype(vartype_to_np_dtype(desc["data_type"]))
    dims = desc.get("dims", [])
    count = 1
    for d in dims:
        count *= int(d)
    arr = np.frombuffer(buf, dtype, count=count, offset=pos).reshape(dims)
    pos += count * dtype.itemsize
    return arr.copy(), lod, pos
