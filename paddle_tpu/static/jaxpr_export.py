"""jaxpr -> ProgramDesc exporter: serialize ANY traceable model to the
reference interchange format.

Reference counterpart: the ProgramTranslator/`jit.save` path — the
reference captures arbitrary dygraph models into a ProgramDesc via
source transform + trace (`dygraph/jit.py`, `TranslatedLayer`).  The
TPU-native equivalent traces the function to a JAXPR (the IR we already
have for free) and maps each primitive onto the reference op set, so
`save_inference_model(layer=...)` is no longer limited to sequential
layer compositions: custom `forward()`s with residuals, means, custom
math — anything jax can trace — round-trips into a `.pdmodel` the
reference-era tooling (and our own Predictor) can load.

Unmapped primitives raise with the primitive name (explicit coverage
boundary, same stance as the interp's unknown-op error).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from . import proto

# jax dtype name -> proto VarType code handled by proto helpers


class _Emitter:
    def __init__(self, program, block, scope: Dict[str, np.ndarray]):
        self.program = program
        self.block = block
        self.scope = scope
        # keyed on the jaxpr Var OBJECTS (identity hash): an id(v) key
        # is unstable — inner-jaxpr vars are garbage-collected after
        # their pjit region inlines and CPython reuses the addresses,
        # silently cross-binding variables (found via a BERT export
        # feeding token ids into the token-type table).  Var keys also
        # pin the objects alive.
        self.names: Dict[object, str] = {}
        self.known: Dict[object, np.ndarray] = {}
        self.counter = 0

    # -- naming -------------------------------------------------------------
    def fresh(self, tag="tmp"):
        self.counter += 1
        return f"jx_{tag}_{self.counter}"

    def var_of(self, v) -> str:
        if v not in self.names:
            if v in self.known:
                # constant-folded value used as a real input here:
                # materialize it once
                self.names[v] = self.emit_constant(self.known[v])
                return self.names[v]
            raise KeyError(f"unbound jaxpr var {v}")
        return self.names[v]

    def bind(self, v, name: str):
        self.names[v] = name
        self.known.pop(v, None)  # a cached-region var may be re-bound

    def declare(self, name, aval, persistable=False):
        self.block.create_var(name, list(aval.shape), str(aval.dtype),
                              persistable=persistable)

    def emit(self, optype, ins, outs, attrs):
        self.block.append_op(optype, ins, outs, attrs)

    # -- values -------------------------------------------------------------
    def emit_constant(self, val: np.ndarray, tag="lit") -> str:
        """Emit a constant as fill_constant/assign_value; the ONE
        dtype->attr-key mapping (shared by literals and iota)."""
        val = np.asarray(val)
        name = self.fresh(tag)
        self.declare(name, jax.ShapeDtypeStruct(val.shape, val.dtype))
        if val.ndim == 0:
            self.emit("fill_constant", {}, {"Out": name},
                      {"shape": [1],
                       "dtype": proto.np_dtype_to_vartype(val.dtype),
                       "value": float(val)})
        else:
            key = {"float32": "fp32_values", "int32": "int32_values",
                   "int64": "int64_values",
                   "bool": "bool_values"}.get(str(val.dtype))
            if key is None:
                raise NotImplementedError(
                    f"jaxpr export: constant dtype {val.dtype} has no "
                    "assign_value attr key")
            self.emit("assign_value", {}, {"Out": name},
                      {"shape": list(val.shape),
                       "dtype": proto.np_dtype_to_vartype(val.dtype),
                       key: val.reshape(-1).tolist()})
        return name

    def literal_or_var(self, a):
        """Return the program var name holding atom `a` (emit a
        constant for literals)."""
        from jax.extend.core import Literal

        if isinstance(a, Literal):
            return self.emit_constant(np.asarray(a.val))
        return self.var_of(a)

    def const_value(self, a):
        """Concrete value of atom `a` when statically known (a Literal,
        or a var bound to a captured const/param in scope); else None."""
        from jax.extend.core import Literal

        if isinstance(a, Literal):
            return np.asarray(a.val)
        if a in self.known:
            return self.known[a]
        name = self.names.get(a)
        if name is not None and name in self.scope:
            return np.asarray(self.scope[name])
        return None


def _elementwise(em, eqn, optype):
    x, y = eqn.invars
    out = em.fresh("ew")
    em.declare(out, eqn.outvars[0].aval)
    xn, yn = em.literal_or_var(x), em.literal_or_var(y)
    # reference elementwise ops broadcast trailing-aligned (axis=-1)
    em.emit(optype, {"X": xn, "Y": yn}, {"Out": out}, {"axis": -1})
    em.bind(eqn.outvars[0], out)


def _unary(em, eqn, optype, attrs=None):
    out = em.fresh(optype)
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out}, attrs or {})
    em.bind(eqn.outvars[0], out)


def _dot_general(em, eqn):
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    x, y = eqn.invars
    xa, ya = x.aval, y.aval
    xn, yn = em.literal_or_var(x), em.literal_or_var(y)
    # common matmul forms: contract last-of-x with one dim of y, batch
    # dims leading and aligned
    if (len(lc) == 1 and len(rc) == 1
            and tuple(lb) == tuple(range(len(lb)))
            and tuple(rb) == tuple(range(len(rb)))):
        trans_x = lc[0] != xa.ndim - 1
        trans_y = rc[0] != ya.ndim - 2 and ya.ndim >= 2
        # verify the transposed interpretation is exactly a matmul
        ok_x = lc[0] in (xa.ndim - 1, xa.ndim - 2)
        ok_y = rc[0] in (ya.ndim - 2, ya.ndim - 1) or ya.ndim == 1
        if ok_x and ok_y:
            out = em.fresh("mm")
            em.declare(out, eqn.outvars[0].aval)
            em.emit("matmul_v2", {"X": xn, "Y": yn}, {"Out": out},
                    {"trans_x": bool(trans_x), "trans_y": bool(trans_y)})
            em.bind(eqn.outvars[0], out)
            return
    raise NotImplementedError(
        f"jaxpr export: dot_general with dimension_numbers {dnums} has "
        "no matmul_v2 form (general tensor contraction)")


def _conv(em, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    if (dn.lhs_spec != tuple(range(len(dn.lhs_spec)))
            or dn.rhs_spec != tuple(range(len(dn.rhs_spec)))):
        raise NotImplementedError(
            "jaxpr export: conv with non-NCHW/OIHW layout")
    if len(p["window_strides"]) != 2:
        raise NotImplementedError("jaxpr export: only 2-D convs")
    if any(int(d) != 1 for d in p.get("lhs_dilation", ())):
        raise NotImplementedError(
            "jaxpr export: conv with lhs_dilation (transposed conv) has "
            "no plain conv2d form")
    if int(p.get("batch_group_count", 1)) != 1:
        raise NotImplementedError(
            "jaxpr export: conv with batch_group_count != 1")
    pads = p["padding"]
    if any(a != b for a, b in pads):
        raise NotImplementedError("jaxpr export: asymmetric conv pad")
    out = em.fresh("conv")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("conv2d",
            {"Input": em.literal_or_var(eqn.invars[0]),
             "Filter": em.literal_or_var(eqn.invars[1])},
            {"Output": out},
            {"strides": [int(s) for s in p["window_strides"]],
             "paddings": [int(a) for a, _ in pads],
             "dilations": [int(d) for d in p["rhs_dilation"]],
             "groups": int(p["feature_group_count"]),
             "padding_algorithm": "EXPLICIT", "data_format": "NCHW"})
    em.bind(eqn.outvars[0], out)


def _reduce(em, eqn, optype):
    axes = [int(a) for a in eqn.params["axes"]]
    nd = eqn.invars[0].aval.ndim
    out = em.fresh("red")
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"dim": axes, "keep_dim": False,
             "reduce_all": len(axes) == nd})
    em.bind(eqn.outvars[0], out)


def _check_window_dilations(p):
    for key in ("window_dilation", "base_dilation"):
        if any(int(d) != 1 for d in p.get(key, ())):
            raise NotImplementedError(
                f"jaxpr export: reduce_window with {key} != 1 has no "
                "pool2d form")


def _reduce_window(em, eqn):
    """lax pooling: window over the trailing two dims -> pool2d."""
    p = eqn.params
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pads = p.get("padding", ((0, 0),) * len(wd))
    _check_window_dilations(p)
    if len(wd) != 4 or wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError(
            f"jaxpr export: reduce_window dims {wd} is not NCHW pooling")
    if any(a != b for a, b in pads):
        raise NotImplementedError(
            f"jaxpr export: asymmetric pooling pad {pads} (pool2d "
            "paddings are symmetric per dim)")
    kind = str(eqn.params.get("computation", ""))
    prim = eqn.primitive.name
    ptype = "max" if "max" in prim else "avg"
    out = em.fresh("pool")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("pool2d", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"pooling_type": ptype, "ksize": [int(wd[2]), int(wd[3])],
             "strides": [int(ws[2]), int(ws[3])],
             "paddings": [int(pads[2][0]), int(pads[3][0])],
             "ceil_mode": False, "global_pooling": False,
             "exclusive": True, "adaptive": False})
    em.bind(eqn.outvars[0], out)


def _broadcast_in_dim(em, eqn):
    tgt = [int(s) for s in eqn.params["shape"]]
    bdims = [int(d) for d in eqn.params["broadcast_dimensions"]]
    xa = eqn.invars[0].aval
    xn = em.literal_or_var(eqn.invars[0])
    # insert size-1 dims so ranks match, then expand_v2
    mid_shape = [1] * len(tgt)
    for i, d in enumerate(bdims):
        mid_shape[d] = int(xa.shape[i]) if i < xa.ndim else 1
    cur = xn
    if list(xa.shape) != mid_shape:
        rname = em.fresh("bcast_r")
        em.declare(rname, jax.ShapeDtypeStruct(tuple(mid_shape),
                                               xa.dtype))
        em.emit("reshape2", {"X": cur}, {"Out": rname},
                {"shape": mid_shape})
        cur = rname
    out = em.fresh("bcast")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("expand_v2", {"X": cur}, {"Out": out}, {"shape": tgt})
    em.bind(eqn.outvars[0], out)


def _transpose(em, eqn):
    out = em.fresh("tr")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("transpose2", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"axis": [int(a) for a in eqn.params["permutation"]]})
    em.bind(eqn.outvars[0], out)


def _reshape(em, eqn):
    out = em.fresh("rs")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("reshape2", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"shape": [int(s) for s in eqn.outvars[0].aval.shape]})
    em.bind(eqn.outvars[0], out)


def _convert(em, eqn):
    out = em.fresh("cast")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("cast", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"in_dtype": proto.np_dtype_to_vartype(
                np.dtype(eqn.invars[0].aval.dtype)),
             "out_dtype": proto.np_dtype_to_vartype(
                 np.dtype(eqn.params["new_dtype"]))})
    em.bind(eqn.outvars[0], out)


def _slice(em, eqn):
    p = eqn.params
    if p.get("strides") and any(int(s) != 1 for s in p["strides"]):
        axes = list(range(eqn.invars[0].aval.ndim))
        attrs = {"axes": axes,
                 "starts": [int(s) for s in p["start_indices"]],
                 "ends": [int(e) for e in p["limit_indices"]],
                 "strides": [int(s) for s in p["strides"]],
                 "infer_flags": [1] * len(axes), "decrease_axis": []}
        optype, inname = "strided_slice", "Input"
    else:
        axes = list(range(eqn.invars[0].aval.ndim))
        attrs = {"axes": axes,
                 "starts": [int(s) for s in p["start_indices"]],
                 "ends": [int(e) for e in p["limit_indices"]],
                 "infer_flags": [1] * len(axes), "decrease_axis": []}
        optype, inname = "slice", "Input"
    out = em.fresh("sl")
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {inname: em.literal_or_var(eqn.invars[0])},
            {"Out": out}, attrs)
    em.bind(eqn.outvars[0], out)


def _concatenate(em, eqn):
    out = em.fresh("cc")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("concat",
            {"X": [em.literal_or_var(v) for v in eqn.invars]},
            {"Out": out}, {"axis": int(eqn.params["dimension"])})
    em.bind(eqn.outvars[0], out)


def _select_n(em, eqn):
    if len(eqn.invars) != 3:
        raise NotImplementedError("jaxpr export: select_n arity != 3")
    pred, on_false, on_true = eqn.invars
    out = em.fresh("where")
    em.declare(out, eqn.outvars[0].aval)
    # lax.select_n(pred, false_case, true_case); reference `where` is
    # (Condition ? X : Y)
    em.emit("where", {"Condition": em.literal_or_var(pred),
                      "X": em.literal_or_var(on_true),
                      "Y": em.literal_or_var(on_false)},
            {"Out": out}, {})
    em.bind(eqn.outvars[0], out)


def _gather_as_lookup(em, eqn):
    """Embedding pattern: gather(table[V, H], ids[...,1]) along dim 0
    with full trailing slice -> lookup_table_v2; anything else is
    unsupported (explicitly)."""
    p = eqn.params
    dn = p["dimension_numbers"]
    table, idx = eqn.invars
    ta = table.aval
    if (ta.ndim == 2 and tuple(dn.start_index_map) == (0,)
            and tuple(dn.collapsed_slice_dims) == (0,)
            and tuple(p["slice_sizes"]) == (1, ta.shape[1])):
        ids_name = em.literal_or_var(idx)
        ia = idx.aval
        # ids arrive [..., 1]; lookup_table_v2 takes [...] int ids
        if ia.shape and ia.shape[-1] == 1:
            rs = em.fresh("ids")
            em.declare(rs, jax.ShapeDtypeStruct(tuple(ia.shape[:-1]),
                                                ia.dtype))
            em.emit("reshape2", {"X": ids_name}, {"Out": rs},
                    {"shape": [int(s) for s in ia.shape[:-1]]})
            ids_name = rs
        out = em.fresh("emb")
        em.declare(out, eqn.outvars[0].aval)
        em.emit("lookup_table_v2",
                {"W": em.literal_or_var(table), "Ids": ids_name},
                {"Out": out}, {"padding_idx": -1})
        em.bind(eqn.outvars[0], out)
        return
    raise NotImplementedError(
        "jaxpr export: general lax.gather (only the embedding pattern "
        "maps to lookup_table_v2)")


def _bool_elementwise(em, eqn, optype):
    if not all(str(v.aval.dtype) == "bool" for v in eqn.invars):
        raise NotImplementedError(
            f"jaxpr export: bitwise {eqn.primitive.name!r} on "
            f"non-bool operands has no reference logical_* equivalent "
            "(logical ops bool-cast)")
    _elementwise(em, eqn, optype)


def _cbrt(em, eqn):
    # real cube root: sign(x) * |x|^(1/3) — pow(1/3) alone NaNs on
    # negatives
    x = em.literal_or_var(eqn.invars[0])
    aval = eqn.outvars[0].aval
    sgn, ab, pw = em.fresh("sgn"), em.fresh("abs"), em.fresh("pw")
    for n in (sgn, ab, pw):
        em.declare(n, aval)
    em.emit("sign", {"X": x}, {"Out": sgn}, {})
    em.emit("abs", {"X": x}, {"Out": ab}, {})
    em.emit("pow", {"X": ab}, {"Out": pw}, {"factor": 1.0 / 3.0})
    out = em.fresh("cbrt")
    em.declare(out, aval)
    em.emit("elementwise_mul", {"X": sgn, "Y": pw}, {"Out": out},
            {"axis": -1})
    em.bind(eqn.outvars[0], out)


def _atan2(em, eqn):
    # the atan2 op's input slots are X1/X2 (atan2_op.cc), not X/Y
    out = em.fresh("atan2")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("atan2", {"X1": em.literal_or_var(eqn.invars[0]),
                      "X2": em.literal_or_var(eqn.invars[1])},
            {"Out": out}, {})
    em.bind(eqn.outvars[0], out)


def _cumsum(em, eqn):
    if eqn.params.get("reverse"):
        raise NotImplementedError("jaxpr export: reverse cumsum")
    _unary(em, eqn, "cumsum",
           {"axis": int(eqn.params["axis"]), "flatten": False,
            "exclusive": False, "reverse": False})


def _argminmax(em, eqn, optype):
    axes = eqn.params["axes"]
    if len(axes) != 1:
        raise NotImplementedError(
            f"jaxpr export: {optype} over multiple axes")
    out = em.fresh(optype)
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"axis": int(axes[0]), "keepdims": False, "flatten": False,
             "dtype": proto.np_dtype_to_vartype(
                 np.dtype(eqn.params["index_dtype"]))})
    em.bind(eqn.outvars[0], out)


def _clamp(em, eqn):
    lo_atom, x, hi_atom = eqn.invars
    lo, hi = em.const_value(lo_atom), em.const_value(hi_atom)
    if lo is None or hi is None:
        raise NotImplementedError(
            "jaxpr export: clamp with runtime tensor bounds (clip "
            "takes scalar attrs)")
    out = em.fresh("clip")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("clip", {"X": em.literal_or_var(x)}, {"Out": out},
            {"min": float(lo), "max": float(hi)})
    em.bind(eqn.outvars[0], out)


def _iota(em, eqn):
    # static shape: materialize as a constant (range/eye/linspace all
    # reduce to this for a serialized inference program)
    aval = eqn.outvars[0].aval
    dim = int(eqn.params["dimension"])
    arr = np.asarray(np.broadcast_to(
        np.arange(aval.shape[dim],
                  dtype=np.dtype(aval.dtype)).reshape(
            [-1 if i == dim else 1 for i in range(aval.ndim)]),
        aval.shape))
    em.bind(eqn.outvars[0], em.emit_constant(arr, tag="iota"))


def _pad(em, eqn):
    cfg = eqn.params["padding_config"]
    if any(int(i) != 0 for _, _, i in cfg):
        raise NotImplementedError("jaxpr export: interior (dilating) pad")
    if any(int(lo) < 0 or int(hi) < 0 for lo, hi, _ in cfg):
        raise NotImplementedError("jaxpr export: negative pad")
    pval = em.const_value(eqn.invars[1])
    if pval is None:
        raise NotImplementedError(
            "jaxpr export: pad value is a runtime tensor (the pad op "
            "takes a scalar attr)")
    out = em.fresh("pad")
    em.declare(out, eqn.outvars[0].aval)
    paddings = []
    for lo, hi, _ in cfg:
        paddings += [int(lo), int(hi)]
    em.emit("pad", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"paddings": paddings, "pad_value": float(pval)})
    em.bind(eqn.outvars[0], out)


def _top_k(em, eqn):
    out_v = em.fresh("topk_v")
    out_i = em.fresh("topk_i")
    em.declare(out_v, eqn.outvars[0].aval)
    em.declare(out_i, eqn.outvars[1].aval)
    em.emit("top_k_v2", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out_v, "Indices": out_i},
            {"k": int(eqn.params["k"]), "axis": -1, "largest": True,
             "sorted": True})
    em.bind(eqn.outvars[0], out_v)
    em.bind(eqn.outvars[1], out_i)


def _reduce_window_sum(em, eqn):
    """sum-pool window -> pool2d avg un-divided (scale by the window
    size); the avg-pool pattern (reduce_window_sum + div) then stays
    numerically exact."""
    p = eqn.params
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pads = p.get("padding", ((0, 0),) * len(wd))
    _check_window_dilations(p)
    if len(wd) != 4 or wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError(
            f"jaxpr export: reduce_window_sum dims {wd} is not NCHW "
            "pooling")
    if any(a != b for a, b in pads):
        raise NotImplementedError(
            f"jaxpr export: asymmetric pooling pad {pads}")
    mid = em.fresh("avgpool")
    em.declare(mid, eqn.outvars[0].aval)
    em.emit("pool2d", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": mid},
            {"pooling_type": "avg", "ksize": [int(wd[2]), int(wd[3])],
             "strides": [int(ws[2]), int(ws[3])],
             "paddings": [int(pads[2][0]), int(pads[3][0])],
             "ceil_mode": False, "global_pooling": False,
             "exclusive": False, "adaptive": False})
    out = em.fresh("sumpool")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("scale", {"X": mid}, {"Out": out},
            {"scale": float(int(wd[2]) * int(wd[3])), "bias": 0.0,
             "bias_after_scale": True})
    em.bind(eqn.outvars[0], out)


def _erfc(em, eqn):
    mid = em.fresh("erf")
    em.declare(mid, eqn.outvars[0].aval)
    em.emit("erf", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": mid}, {})
    out = em.fresh("erfc")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("scale", {"X": mid}, {"Out": out},
            {"scale": -1.0, "bias": 1.0, "bias_after_scale": True})
    em.bind(eqn.outvars[0], out)


def _rsqrt(em, eqn):
    _unary(em, eqn, "rsqrt")


def _pow(em, eqn):
    y = int(eqn.params["y"])
    out = em.fresh("pow")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("pow", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out}, {"factor": float(y)})
    em.bind(eqn.outvars[0], out)


_HANDLERS = {
    "add": lambda em, e: _elementwise(em, e, "elementwise_add"),
    "sub": lambda em, e: _elementwise(em, e, "elementwise_sub"),
    "mul": lambda em, e: _elementwise(em, e, "elementwise_mul"),
    "div": lambda em, e: _elementwise(em, e, "elementwise_div"),
    "max": lambda em, e: _elementwise(em, e, "elementwise_max"),
    "min": lambda em, e: _elementwise(em, e, "elementwise_min"),
    "pow": lambda em, e: _elementwise(em, e, "elementwise_pow"),
    "rem": lambda em, e: _elementwise(em, e, "elementwise_mod"),
    "eq": lambda em, e: _elementwise(em, e, "equal"),
    "ne": lambda em, e: _elementwise(em, e, "not_equal"),
    "lt": lambda em, e: _elementwise(em, e, "less_than"),
    "le": lambda em, e: _elementwise(em, e, "less_equal"),
    "gt": lambda em, e: _elementwise(em, e, "greater_than"),
    "ge": lambda em, e: _elementwise(em, e, "greater_equal"),
    "and": lambda em, e: _bool_elementwise(em, e, "logical_and"),
    "or": lambda em, e: _bool_elementwise(em, e, "logical_or"),
    "xor": lambda em, e: _bool_elementwise(em, e, "logical_xor"),
    "exp": lambda em, e: _unary(em, e, "exp"),
    "log": lambda em, e: _unary(em, e, "log"),
    "tanh": lambda em, e: _unary(em, e, "tanh"),
    "logistic": lambda em, e: _unary(em, e, "sigmoid"),
    "sqrt": lambda em, e: _unary(em, e, "sqrt"),
    "rsqrt": _rsqrt,
    "abs": lambda em, e: _unary(em, e, "abs"),
    "floor": lambda em, e: _unary(em, e, "floor"),
    "ceil": lambda em, e: _unary(em, e, "ceil"),
    "sign": lambda em, e: _unary(em, e, "sign"),
    "erf": lambda em, e: _unary(em, e, "erf"),
    # erfc(x) = 1 - erf(x): erf then scale(-1, bias 1)
    "erfc": lambda em, e: _erfc(em, e),
    "square": lambda em, e: _unary(em, e, "square"),
    "log1p": lambda em, e: _unary(em, e, "log1p"),
    "cbrt": lambda em, e: _cbrt(em, e),
    "is_finite": lambda em, e: _unary(em, e, "isfinite"),
    "sin": lambda em, e: _unary(em, e, "sin"),
    "cos": lambda em, e: _unary(em, e, "cos"),
    "not": lambda em, e: _unary(em, e, "logical_not"),
    "neg": lambda em, e: _unary(em, e, "scale",
                                {"scale": -1.0, "bias": 0.0,
                                 "bias_after_scale": True}),
    "integer_pow": _pow,
    "dot_general": _dot_general,
    "conv_general_dilated": _conv,
    "reduce_sum": lambda em, e: _reduce(em, e, "reduce_sum"),
    "reduce_max": lambda em, e: _reduce(em, e, "reduce_max"),
    "reduce_min": lambda em, e: _reduce(em, e, "reduce_min"),
    "reduce_prod": lambda em, e: _reduce(em, e, "reduce_prod"),
    "reduce_and": lambda em, e: _reduce(em, e, "reduce_all"),
    "reduce_or": lambda em, e: _reduce(em, e, "reduce_any"),
    "reduce_window_max": _reduce_window,
    "cumsum": _cumsum,
    "argmax": lambda em, e: _argminmax(em, e, "arg_max"),
    "argmin": lambda em, e: _argminmax(em, e, "arg_min"),
    "clamp": _clamp,
    "iota": _iota,
    "pad": _pad,
    "atan2": _atan2,
    "expm1": lambda em, e: _unary(em, e, "expm1"),
    "top_k": _top_k,
    "reduce_window_sum": _reduce_window_sum,

    "broadcast_in_dim": _broadcast_in_dim,
    "transpose": _transpose,
    "reshape": _reshape,
    "squeeze": _reshape,
    "expand_dims": _reshape,
    "convert_element_type": _convert,
    "slice": _slice,
    "concatenate": _concatenate,
    "select_n": _select_n,
    "gather": _gather_as_lookup,
    "rev": lambda em, e: _unary(
        em, e, "flip",
        {"axis": [int(d) for d in e.params["dimensions"]]}),
    "stop_gradient": lambda em, e: _unary(em, e, "assign"),
    "copy": lambda em, e: _unary(em, e, "assign"),
}


def _try_const_fold(em, eqn) -> bool:
    """When every input is statically known, evaluate the primitive
    eagerly and record the result — no ops emitted (materialized on
    demand by var_of).  Keeps pad/clip attr resolution working when
    values route through convert/broadcast chains, and exports leaner
    programs."""
    if eqn.primitive.name in ("pjit", "jit", "closed_call"):
        return False
    vals = [em.const_value(a) for a in eqn.invars]
    if any(v is None for v in vals):
        return False
    # only fold small constants: folding a big computed tensor would
    # bloat the program with assign_value blobs
    if any(np.asarray(v).size > 4096 for v in vals):
        return False
    try:
        out = eqn.primitive.bind(*[jnp.asarray(v) for v in vals],
                                 **eqn.params)
    except Exception:
        return False
    outs = out if isinstance(out, (tuple, list)) else (out,)
    if len(outs) != len(eqn.outvars):
        return False
    for v, val in zip(eqn.outvars, outs):
        em.names.pop(v, None)  # cached-region var may be re-bound
        em.known[v] = np.asarray(val)
    return True


def _walk(em: _Emitter, jaxpr):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if _try_const_fold(em, eqn):
            continue
        if prim in ("pjit", "jit", "closed_call", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr",
                    "remat", "checkpoint"):
            inner = eqn.params.get("jaxpr") or eqn.params.get(
                "call_jaxpr")
            closed = getattr(inner, "jaxpr", inner)
            consts = getattr(inner, "consts", [])
            for cv, cval in zip(closed.constvars, consts):
                name = em.fresh("const")
                arr = np.asarray(cval)
                em.declare(name, jax.ShapeDtypeStruct(arr.shape,
                                                      arr.dtype),
                           persistable=True)
                em.scope[name] = arr
                em.bind(cv, name)
            # NOTE: jax CACHES identical inner jaxprs, so the same Var
            # objects recur across different pjit eqns (two
            # structurally-equal embedding wraps share one jaxpr) — a
            # re-bind must clear the var's previous-region state or a
            # stale name wins over the new const (found via BERT's
            # token-type ids resolving to the word-ids chain)
            for outer, innerv in zip(eqn.invars, closed.invars):
                em.names.pop(innerv, None)
                em.known.pop(innerv, None)
                cv = em.const_value(outer)
                if cv is not None:
                    # keep constants foldable across the jit boundary
                    em.known[innerv] = cv
                else:
                    em.bind(innerv, em.literal_or_var(outer))
            _walk(em, closed)
            from jax.extend.core import Literal

            for outer, innerv in zip(eqn.outvars, closed.outvars):
                cv = em.const_value(innerv)
                # Literal outvars (inner region returns a constant) are
                # unhashable — guard before any dict membership test
                inner_named = (not isinstance(innerv, Literal)
                               and innerv in em.names)
                if cv is not None and not inner_named:
                    em.names.pop(outer, None)  # stale walk-1 binding
                    em.known[outer] = cv
                else:
                    em.bind(outer, em.literal_or_var(innerv))
            continue
        handler = _HANDLERS.get(prim)
        if handler is None:
            raise NotImplementedError(
                f"jaxpr export: no ProgramDesc mapping for primitive "
                f"{prim!r} (op set: {sorted(_HANDLERS)})")
        handler(em, eqn)


def program_from_traced(fn, example_inputs: List, scope: Dict,
                        input_names: List[str] = None):
    """Trace `fn(*example_inputs)` and export the jaxpr as a Program.

    Closure constants (e.g. layer parameters) become persistable vars
    with their live values collected into `scope`.  Returns the
    Program; feed targets are the positional inputs, fetch targets the
    outputs.
    """
    from .program import Program
    from .proto import VarType

    specs = [jax.ShapeDtypeStruct(np.shape(x),
                                  np.asarray(x).dtype if not
                                  hasattr(x, "dtype") else x.dtype)
             for x in example_inputs]
    closed = jax.make_jaxpr(fn)(*specs)

    program = Program()
    block = program.global_block()
    block.create_var("feed", type=VarType.FEED_MINIBATCH,
                     persistable=True)
    block.create_var("fetch", type=VarType.FETCH_LIST, persistable=True)
    em = _Emitter(program, block, scope)

    for cv, cval in zip(closed.jaxpr.constvars, closed.consts):
        arr = np.asarray(cval)
        name = em.fresh("param")
        em.declare(name, jax.ShapeDtypeStruct(arr.shape, arr.dtype),
                   persistable=True)
        scope[name] = arr
        em.bind(cv, name)

    names = input_names or [f"input_{i}" for i in range(len(specs))]
    for i, (v, spec, name) in enumerate(zip(closed.jaxpr.invars, specs,
                                            names)):
        block.create_var(name, list(spec.shape), str(spec.dtype),
                         need_check_feed=True)
        em.emit("feed", {"X": "feed"}, {"Out": name}, {"col": i})
        em.bind(v, name)

    _walk(em, closed.jaxpr)

    for i, v in enumerate(closed.jaxpr.outvars):
        out_name = f"output_{i}"
        aval = v.aval
        block.create_var(out_name, list(aval.shape), str(aval.dtype))
        em.emit("assign", {"X": em.literal_or_var(v)},
                {"Out": out_name}, {})
        em.emit("fetch", {"X": out_name}, {"Out": "fetch"}, {"col": i})
    return program
