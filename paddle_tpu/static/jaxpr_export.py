"""jaxpr -> ProgramDesc exporter: serialize ANY traceable model to the
reference interchange format.

Reference counterpart: the ProgramTranslator/`jit.save` path — the
reference captures arbitrary dygraph models into a ProgramDesc via
source transform + trace (`dygraph/jit.py`, `TranslatedLayer`).  The
TPU-native equivalent traces the function to a JAXPR (the IR we already
have for free) and maps each primitive onto the reference op set, so
`save_inference_model(layer=...)` is no longer limited to sequential
layer compositions: custom `forward()`s with residuals, means, custom
math — anything jax can trace — round-trips into a `.pdmodel` the
reference-era tooling (and our own Predictor) can load.

Unmapped primitives raise with the primitive name (explicit coverage
boundary, same stance as the interp's unknown-op error).

Control flow (round 5): `lax.while_loop`/`lax.scan`/`lax.cond` serialize
as the reference's sub-block ops — `while_op` with the carry written
back each step and the Condition recomputed at the end of the body
(`operators/controlflow/while_op.cc:59`), `scan` as a counter `while`
whose per-step outputs land in `write_to_array` TensorArrays and stack
via `tensor_array_to_tensor` after the loop (the exact program shape
the reference's dy2static loop transformer emits —
`dygraph_to_static/loop_transformer.py`), and `cond`/`switch` as
one `conditional_block` per branch reconciled by `select_input`
(`conditional_block_op.cc:29`, `select_input_op.cc`).  nn.LSTM/GRU/
SimpleRNN lower to the unified `rnn` op (`operators/rnn_op.cc`) via the
export-time marker primitive in `export_marker.py` — the reference's
dygraph RNN layers likewise serialize to that single fused op.
"""
from __future__ import annotations

import contextlib

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from . import proto

# jax dtype name -> proto VarType code handled by proto helpers


class _Emitter:
    def __init__(self, program, block, scope: Dict[str, np.ndarray]):
        self.program = program
        self.block = block
        self.scope = scope
        # keyed on the jaxpr Var OBJECTS (identity hash): an id(v) key
        # is unstable — inner-jaxpr vars are garbage-collected after
        # their pjit region inlines and CPython reuses the addresses,
        # silently cross-binding variables (found via a BERT export
        # feeding token ids into the token-type table).  Var keys also
        # pin the objects alive.
        self.names: Dict[object, str] = {}
        self.known: Dict[object, np.ndarray] = {}
        self.counter = 0
        # ClosedJaxpr id -> bound const names (a cond_jaxpr is walked
        # once outside the loop and once per body; its consts bind once)
        self.closed_consts: Dict[int, List[str]] = {}
        # vars that must never materialize (PRNG keys closed over by a
        # jitted eval-mode forward: dead unless an op actually consumes
        # them, in which case this carries the refusal message)
        self.poison: Dict[object, str] = {}
        # vars produced by an unfolded iota eqn -> its dimension (lets
        # the sort handler recognize the argsort index payload without
        # materializing it)
        self.iota_axes: Dict[object, int] = {}

    def bind_const_value(self, cv, cval, tag, persistable=True):
        """Bind a closed-over constant.  Extended-dtype values (PRNG
        keys) are poisoned rather than materialized: an eval-mode
        forward jitted through StaticFunction closes over its rng key,
        which is dead in the inference program unless a random op
        actually consumes it."""
        import jax.dtypes as jdt

        dt = getattr(cval, "dtype", None)
        if dt is not None and jdt.issubdtype(dt, jdt.extended):
            self.names.pop(cv, None)
            self.known.pop(cv, None)
            self.poison[cv] = (
                f"jaxpr export: a constant of extended dtype {dt} "
                "(PRNG key / RNG state) feeds a serialized op — "
                "inference programs cannot carry RNG state; export "
                "with the layer in eval() mode")
            return None
        arr = np.asarray(cval)
        name = self.fresh(tag)
        self.declare_global(name, jax.ShapeDtypeStruct(arr.shape,
                                                       arr.dtype),
                            persistable=persistable)
        self.scope[name] = arr
        self.names.pop(cv, None)
        self.known.pop(cv, None)
        self.bind(cv, name)
        return name

    # -- naming -------------------------------------------------------------
    def fresh(self, tag="tmp"):
        self.counter += 1
        return f"jx_{tag}_{self.counter}"

    def var_of(self, v) -> str:
        if v in self.poison:
            raise NotImplementedError(self.poison[v])
        if v not in self.names:
            if v in self.known:
                # constant-folded value used as a real input here:
                # materialize it once
                self.names[v] = self.emit_constant(self.known[v])
                return self.names[v]
            raise KeyError(f"unbound jaxpr var {v}")
        return self.names[v]

    def bind(self, v, name: str):
        self.names[v] = name
        self.known.pop(v, None)  # a cached-region var may be re-bound

    def declare(self, name, aval, persistable=False):
        self.block.create_var(name, list(aval.shape), str(aval.dtype),
                              persistable=persistable)

    def declare_global(self, name, aval, persistable=True):
        """Persistables (params, closed-jaxpr consts) live in the global
        block regardless of which sub-block is being emitted (reference
        layout: `framework.py` puts parameters in block 0)."""
        self.program.global_block().create_var(
            name, list(aval.shape), str(aval.dtype),
            persistable=persistable)

    def emit(self, optype, ins, outs, attrs):
        self.block.append_op(optype, ins, outs, attrs)

    @contextlib.contextmanager
    def in_block(self, block):
        """Emit into a sub-block.  Names materialized for lazily-known
        constants while inside are forgotten on exit: the defining op
        lives in the sub-block (whose scope is discarded per reference
        step-scope semantics), so a later outer-block use must
        re-materialize in a block that's actually visible there."""
        prev = self.block
        before = set(self.names)
        self.block = block
        try:
            yield
        finally:
            self.block = prev
            for v in [v for v in list(self.names)
                      if v not in before and v in self.known]:
                del self.names[v]

    # -- values -------------------------------------------------------------
    def emit_constant(self, val: np.ndarray, tag="lit") -> str:
        """Emit a constant as fill_constant/assign_value; the ONE
        dtype->attr-key mapping (shared by literals and iota)."""
        val = np.asarray(val)
        name = self.fresh(tag)
        self.declare(name, jax.ShapeDtypeStruct(val.shape, val.dtype))
        if val.ndim == 0:
            self.emit("fill_constant", {}, {"Out": name},
                      {"shape": [1],
                       "dtype": proto.np_dtype_to_vartype(val.dtype),
                       "value": float(val)})
        else:
            key = {"float32": "fp32_values", "int32": "int32_values",
                   "int64": "int64_values",
                   "bool": "bool_values"}.get(str(val.dtype))
            if key is None:
                raise NotImplementedError(
                    f"jaxpr export: constant dtype {val.dtype} has no "
                    "assign_value attr key")
            self.emit("assign_value", {}, {"Out": name},
                      {"shape": list(val.shape),
                       "dtype": proto.np_dtype_to_vartype(val.dtype),
                       key: val.reshape(-1).tolist()})
        return name

    def literal_or_var(self, a):
        """Return the program var name holding atom `a` (emit a
        constant for literals)."""
        from jax.extend.core import Literal

        if isinstance(a, Literal):
            return self.emit_constant(np.asarray(a.val))
        return self.var_of(a)

    def const_value(self, a):
        """Concrete value of atom `a` when statically known (a Literal,
        or a var bound to a captured const/param in scope); else None."""
        from jax.extend.core import Literal

        if isinstance(a, Literal):
            return np.asarray(a.val)
        if a in self.known:
            return self.known[a]
        name = self.names.get(a)
        if name is not None and name in self.scope:
            return np.asarray(self.scope[name])
        return None


def _elementwise(em, eqn, optype):
    x, y = eqn.invars
    out = em.fresh("ew")
    em.declare(out, eqn.outvars[0].aval)
    xn, yn = em.literal_or_var(x), em.literal_or_var(y)
    # reference elementwise ops broadcast trailing-aligned (axis=-1)
    em.emit(optype, {"X": xn, "Y": yn}, {"Out": out}, {"axis": -1})
    em.bind(eqn.outvars[0], out)


def _unary(em, eqn, optype, attrs=None):
    out = em.fresh(optype)
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out}, attrs or {})
    em.bind(eqn.outvars[0], out)


def _dot_general(em, eqn):
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    x, y = eqn.invars
    xa, ya = x.aval, y.aval
    xn, yn = em.literal_or_var(x), em.literal_or_var(y)
    # common matmul forms: contract last-of-x with one dim of y, batch
    # dims leading and aligned
    if (len(lc) == 1 and len(rc) == 1
            and tuple(lb) == tuple(range(len(lb)))
            and tuple(rb) == tuple(range(len(rb)))):
        trans_x = lc[0] != xa.ndim - 1
        trans_y = rc[0] != ya.ndim - 2 and ya.ndim >= 2
        # verify the transposed interpretation is exactly a matmul
        ok_x = lc[0] in (xa.ndim - 1, xa.ndim - 2)
        ok_y = rc[0] in (ya.ndim - 2, ya.ndim - 1) or ya.ndim == 1
        if ok_x and ok_y:
            out = em.fresh("mm")
            em.declare(out, eqn.outvars[0].aval)
            em.emit("matmul_v2", {"X": xn, "Y": yn}, {"Out": out},
                    {"trans_x": bool(trans_x), "trans_y": bool(trans_y)})
            em.bind(eqn.outvars[0], out)
            return
    _dot_general_contraction(em, eqn)


def _dot_general_contraction(em, eqn):
    """General tensor contraction: canonicalize both operands to
    batched 3-D via transpose2+reshape2, one matmul_v2, reshape to the
    dot_general output layout (batch dims, lhs free, rhs free — which
    is exactly the [B, M, N] reshape order, so no output transpose)."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    x, y = eqn.invars
    xa, ya = x.aval, y.aval

    def prod(dims, shape):
        out = 1
        for d in dims:
            out *= int(shape[d])
        return out

    lfree = [d for d in range(xa.ndim) if d not in lc and d not in lb]
    rfree = [d for d in range(ya.ndim) if d not in rc and d not in rb]
    bsz = prod(lb, xa.shape)
    m, k = prod(lfree, xa.shape), prod(lc, xa.shape)
    n = prod(rfree, ya.shape)

    def canon(atom, aval, perm, shape3):
        name = em.literal_or_var(atom)
        if list(perm) != list(range(aval.ndim)):
            t = em.fresh("dg_t")
            em.declare(t, jax.ShapeDtypeStruct(
                tuple(int(aval.shape[p]) for p in perm), aval.dtype))
            em.emit("transpose2", {"X": name}, {"Out": t},
                    {"axis": [int(p) for p in perm]})
            name = t
        r = em.fresh("dg_r")
        em.declare(r, jax.ShapeDtypeStruct(tuple(shape3), aval.dtype))
        em.emit("reshape2", {"X": name}, {"Out": r},
                {"shape": list(shape3)})
        return r

    xr = canon(x, xa, list(lb) + lfree + list(lc), [bsz, m, k])
    yr = canon(y, ya, list(rb) + list(rc) + rfree, [bsz, k, n])
    mm = em.fresh("dg_mm")
    em.declare(mm, jax.ShapeDtypeStruct((bsz, m, n), eqn.outvars[0]
                                        .aval.dtype))
    em.emit("matmul_v2", {"X": xr, "Y": yr}, {"Out": mm},
            {"trans_x": False, "trans_y": False})
    out = em.fresh("dg")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("reshape2", {"X": mm}, {"Out": out},
            {"shape": [int(s) for s in eqn.outvars[0].aval.shape]})
    em.bind(eqn.outvars[0], out)


def _conv(em, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    if (dn.lhs_spec != tuple(range(len(dn.lhs_spec)))
            or dn.rhs_spec != tuple(range(len(dn.rhs_spec)))):
        raise NotImplementedError(
            "jaxpr export: conv with non-NCHW/OIHW layout")
    if len(p["window_strides"]) != 2:
        raise NotImplementedError("jaxpr export: only 2-D convs")
    if any(int(d) != 1 for d in p.get("lhs_dilation", ())):
        raise NotImplementedError(
            "jaxpr export: conv with lhs_dilation (transposed conv) has "
            "no plain conv2d form")
    if int(p.get("batch_group_count", 1)) != 1:
        raise NotImplementedError(
            "jaxpr export: conv with batch_group_count != 1")
    pads = p["padding"]
    if any(a != b for a, b in pads):
        raise NotImplementedError("jaxpr export: asymmetric conv pad")
    out = em.fresh("conv")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("conv2d",
            {"Input": em.literal_or_var(eqn.invars[0]),
             "Filter": em.literal_or_var(eqn.invars[1])},
            {"Output": out},
            {"strides": [int(s) for s in p["window_strides"]],
             "paddings": [int(a) for a, _ in pads],
             "dilations": [int(d) for d in p["rhs_dilation"]],
             "groups": int(p["feature_group_count"]),
             "padding_algorithm": "EXPLICIT", "data_format": "NCHW"})
    em.bind(eqn.outvars[0], out)


def _reduce(em, eqn, optype):
    axes = [int(a) for a in eqn.params["axes"]]
    nd = eqn.invars[0].aval.ndim
    out = em.fresh("red")
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"dim": axes, "keep_dim": False,
             "reduce_all": len(axes) == nd})
    em.bind(eqn.outvars[0], out)


def _check_window_dilations(p):
    for key in ("window_dilation", "base_dilation"):
        if any(int(d) != 1 for d in p.get(key, ())):
            raise NotImplementedError(
                f"jaxpr export: reduce_window with {key} != 1 has no "
                "pool2d form")


def _reduce_window(em, eqn):
    """lax pooling: window over the trailing two dims -> pool2d."""
    p = eqn.params
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pads = p.get("padding", ((0, 0),) * len(wd))
    _check_window_dilations(p)
    if len(wd) != 4 or wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError(
            f"jaxpr export: reduce_window dims {wd} is not NCHW pooling")
    if any(a != b for a, b in pads):
        raise NotImplementedError(
            f"jaxpr export: asymmetric pooling pad {pads} (pool2d "
            "paddings are symmetric per dim)")
    kind = str(eqn.params.get("computation", ""))
    prim = eqn.primitive.name
    ptype = "max" if "max" in prim else "avg"
    out = em.fresh("pool")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("pool2d", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"pooling_type": ptype, "ksize": [int(wd[2]), int(wd[3])],
             "strides": [int(ws[2]), int(ws[3])],
             "paddings": [int(pads[2][0]), int(pads[3][0])],
             "ceil_mode": False, "global_pooling": False,
             "exclusive": True, "adaptive": False})
    em.bind(eqn.outvars[0], out)


def _broadcast_in_dim(em, eqn):
    tgt = [int(s) for s in eqn.params["shape"]]
    bdims = [int(d) for d in eqn.params["broadcast_dimensions"]]
    xa = eqn.invars[0].aval
    xn = em.literal_or_var(eqn.invars[0])
    # insert size-1 dims so ranks match, then expand_v2
    mid_shape = [1] * len(tgt)
    for i, d in enumerate(bdims):
        mid_shape[d] = int(xa.shape[i]) if i < xa.ndim else 1
    cur = xn
    if list(xa.shape) != mid_shape:
        rname = em.fresh("bcast_r")
        em.declare(rname, jax.ShapeDtypeStruct(tuple(mid_shape),
                                               xa.dtype))
        em.emit("reshape2", {"X": cur}, {"Out": rname},
                {"shape": mid_shape})
        cur = rname
    out = em.fresh("bcast")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("expand_v2", {"X": cur}, {"Out": out}, {"shape": tgt})
    em.bind(eqn.outvars[0], out)


def _transpose(em, eqn):
    out = em.fresh("tr")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("transpose2", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"axis": [int(a) for a in eqn.params["permutation"]]})
    em.bind(eqn.outvars[0], out)


def _reshape(em, eqn):
    out = em.fresh("rs")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("reshape2", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"shape": [int(s) for s in eqn.outvars[0].aval.shape]})
    em.bind(eqn.outvars[0], out)


def _convert(em, eqn):
    out = em.fresh("cast")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("cast", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"in_dtype": proto.np_dtype_to_vartype(
                np.dtype(eqn.invars[0].aval.dtype)),
             "out_dtype": proto.np_dtype_to_vartype(
                 np.dtype(eqn.params["new_dtype"]))})
    em.bind(eqn.outvars[0], out)


def _slice(em, eqn):
    p = eqn.params
    if p.get("strides") and any(int(s) != 1 for s in p["strides"]):
        axes = list(range(eqn.invars[0].aval.ndim))
        attrs = {"axes": axes,
                 "starts": [int(s) for s in p["start_indices"]],
                 "ends": [int(e) for e in p["limit_indices"]],
                 "strides": [int(s) for s in p["strides"]],
                 "infer_flags": [1] * len(axes), "decrease_axis": []}
        optype, inname = "strided_slice", "Input"
    else:
        axes = list(range(eqn.invars[0].aval.ndim))
        attrs = {"axes": axes,
                 "starts": [int(s) for s in p["start_indices"]],
                 "ends": [int(e) for e in p["limit_indices"]],
                 "infer_flags": [1] * len(axes), "decrease_axis": []}
        optype, inname = "slice", "Input"
    out = em.fresh("sl")
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {inname: em.literal_or_var(eqn.invars[0])},
            {"Out": out}, attrs)
    em.bind(eqn.outvars[0], out)


def _concatenate(em, eqn):
    out = em.fresh("cc")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("concat",
            {"X": [em.literal_or_var(v) for v in eqn.invars]},
            {"Out": out}, {"axis": int(eqn.params["dimension"])})
    em.bind(eqn.outvars[0], out)


def _select_n(em, eqn):
    if len(eqn.invars) == 3:
        pred, on_false, on_true = eqn.invars
        out = em.fresh("where")
        em.declare(out, eqn.outvars[0].aval)
        # lax.select_n(pred, false_case, true_case); reference `where`
        # is (Condition ? X : Y)
        em.emit("where", {"Condition": em.literal_or_var(pred),
                          "X": em.literal_or_var(on_true),
                          "Y": em.literal_or_var(on_false)},
                {"Out": out}, {})
        em.bind(eqn.outvars[0], out)
        return
    # arity > 3: integer selector; fold right as nested `where`
    # (out = pred==0 ? c0 : (pred==1 ? c1 : ... c_{n-1}))
    pred, cases = eqn.invars[0], eqn.invars[1:]
    pa = pred.aval
    pn = em.literal_or_var(pred)
    if np.dtype(pa.dtype) != np.dtype(np.int32):
        # selector may be int8/uint8/int64; compare in int32 — the
        # reference compare kernels require matching operand dtypes
        # (and assign_value has no small-int attr key)
        c = em.fresh("selcast")
        em.declare(c, jax.ShapeDtypeStruct(pa.shape, np.int32))
        em.emit("cast", {"X": pn}, {"Out": c},
                {"in_dtype": proto.np_dtype_to_vartype(np.dtype(pa.dtype)),
                 "out_dtype": proto.np_dtype_to_vartype(np.dtype(np.int32))})
        pn = c
    aval = eqn.outvars[0].aval
    cur = em.literal_or_var(cases[-1])
    for k in range(len(cases) - 2, -1, -1):
        kname = em.emit_constant(
            np.full([1] if pa.ndim == 0 else list(pa.shape), k,
                    np.int32), tag="selk")
        mask = em.fresh("selmask")
        em.declare(mask, jax.ShapeDtypeStruct(pa.shape, np.bool_))
        em.emit("equal", {"X": pn, "Y": kname}, {"Out": mask}, {"axis": -1})
        out = em.fresh("sel")
        em.declare(out, aval)
        em.emit("where", {"Condition": mask,
                          "X": em.literal_or_var(cases[k]), "Y": cur},
                {"Out": out}, {})
        cur = out
    if pa.ndim == 0:
        # the per-case constants were emitted shape (1,) (assign_value
        # has no 0-d form), so the folded equal/where chain is (1,) while
        # the outvar's declared aval is scalar — reshape back (ADVICE
        # round 5), mirroring the dynamic_slice tail
        rs = em.fresh("sel_rs")
        em.declare(rs, aval)
        em.emit("reshape2", {"X": cur}, {"Out": rs},
                {"shape": [int(s) for s in aval.shape]})
        cur = rs
    em.bind(eqn.outvars[0], cur)


def _gather_as_lookup(em, eqn):
    """Embedding pattern: gather(table[V, H], ids[...,1]) along dim 0
    with full trailing slice -> lookup_table_v2; anything else is
    unsupported (explicitly)."""
    p = eqn.params
    dn = p["dimension_numbers"]
    table, idx = eqn.invars
    ta = table.aval
    if (ta.ndim == 2 and tuple(dn.start_index_map) == (0,)
            and tuple(dn.collapsed_slice_dims) == (0,)
            and tuple(p["slice_sizes"]) == (1, ta.shape[1])):
        ids_name = em.literal_or_var(idx)
        ia = idx.aval
        # ids arrive [..., 1]; lookup_table_v2 takes [...] int ids
        if ia.shape and ia.shape[-1] == 1:
            rs = em.fresh("ids")
            em.declare(rs, jax.ShapeDtypeStruct(tuple(ia.shape[:-1]),
                                                ia.dtype))
            em.emit("reshape2", {"X": ids_name}, {"Out": rs},
                    {"shape": [int(s) for s in ia.shape[:-1]]})
            ids_name = rs
        out = em.fresh("emb")
        em.declare(out, eqn.outvars[0].aval)
        em.emit("lookup_table_v2",
                {"W": em.literal_or_var(table), "Ids": ids_name},
                {"Out": out}, {"padding_idx": -1})
        em.bind(eqn.outvars[0], out)
        return
    raise NotImplementedError(
        "jaxpr export: general lax.gather (only the embedding pattern "
        "maps to lookup_table_v2)")


def _bool_elementwise(em, eqn, optype):
    if not all(str(v.aval.dtype) == "bool" for v in eqn.invars):
        raise NotImplementedError(
            f"jaxpr export: bitwise {eqn.primitive.name!r} on "
            f"non-bool operands has no reference logical_* equivalent "
            "(logical ops bool-cast)")
    _elementwise(em, eqn, optype)


def _cbrt(em, eqn):
    # real cube root: sign(x) * |x|^(1/3) — pow(1/3) alone NaNs on
    # negatives
    x = em.literal_or_var(eqn.invars[0])
    aval = eqn.outvars[0].aval
    sgn, ab, pw = em.fresh("sgn"), em.fresh("abs"), em.fresh("pw")
    for n in (sgn, ab, pw):
        em.declare(n, aval)
    em.emit("sign", {"X": x}, {"Out": sgn}, {})
    em.emit("abs", {"X": x}, {"Out": ab}, {})
    em.emit("pow", {"X": ab}, {"Out": pw}, {"factor": 1.0 / 3.0})
    out = em.fresh("cbrt")
    em.declare(out, aval)
    em.emit("elementwise_mul", {"X": sgn, "Y": pw}, {"Out": out},
            {"axis": -1})
    em.bind(eqn.outvars[0], out)


def _atan2(em, eqn):
    # the atan2 op's input slots are X1/X2 (atan2_op.cc), not X/Y
    out = em.fresh("atan2")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("atan2", {"X1": em.literal_or_var(eqn.invars[0]),
                      "X2": em.literal_or_var(eqn.invars[1])},
            {"Out": out}, {})
    em.bind(eqn.outvars[0], out)


def _cumsum(em, eqn):
    # the reference cumsum op carries reverse/exclusive attrs
    # (`operators/cum_op.cc` CumOpMaker), so both forms serialize
    _unary(em, eqn, "cumsum",
           {"axis": int(eqn.params["axis"]), "flatten": False,
            "exclusive": False,
            "reverse": bool(eqn.params.get("reverse", False))})


def _argminmax(em, eqn, optype):
    axes = eqn.params["axes"]
    if len(axes) != 1:
        raise NotImplementedError(
            f"jaxpr export: {optype} over multiple axes")
    out = em.fresh(optype)
    em.declare(out, eqn.outvars[0].aval)
    em.emit(optype, {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out},
            {"axis": int(axes[0]), "keepdims": False, "flatten": False,
             "dtype": proto.np_dtype_to_vartype(
                 np.dtype(eqn.params["index_dtype"]))})
    em.bind(eqn.outvars[0], out)


def _clamp(em, eqn):
    lo_atom, x, hi_atom = eqn.invars
    lo, hi = em.const_value(lo_atom), em.const_value(hi_atom)
    if lo is None or hi is None:
        raise NotImplementedError(
            "jaxpr export: clamp with runtime tensor bounds (clip "
            "takes scalar attrs)")
    out = em.fresh("clip")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("clip", {"X": em.literal_or_var(x)}, {"Out": out},
            {"min": float(lo), "max": float(hi)})
    em.bind(eqn.outvars[0], out)


def _iota(em, eqn):
    # static shape: materialize as a constant (range/eye/linspace all
    # reduce to this for a serialized inference program)
    aval = eqn.outvars[0].aval
    dim = int(eqn.params["dimension"])
    em.iota_axes[eqn.outvars[0]] = dim
    arr = np.asarray(np.broadcast_to(
        np.arange(aval.shape[dim],
                  dtype=np.dtype(aval.dtype)).reshape(
            [-1 if i == dim else 1 for i in range(aval.ndim)]),
        aval.shape))
    em.bind(eqn.outvars[0], em.emit_constant(arr, tag="iota"))


def _pad(em, eqn):
    cfg = eqn.params["padding_config"]
    if any(int(i) != 0 for _, _, i in cfg):
        raise NotImplementedError("jaxpr export: interior (dilating) pad")
    pval = em.const_value(eqn.invars[1])
    if pval is None:
        raise NotImplementedError(
            "jaxpr export: pad value is a runtime tensor (the pad op "
            "takes a scalar attr)")
    xa = eqn.invars[0].aval
    cur = em.literal_or_var(eqn.invars[0])
    if any(int(lo) < 0 or int(hi) < 0 for lo, hi, _ in cfg):
        # lax semantics: negative pad trims; serialize as slice of the
        # negative components, then a plain pad of the positive ones
        starts = [max(0, -int(lo)) for lo, _, _ in cfg]
        ends = [int(xa.shape[d]) + min(0, int(hi))
                for d, (_, hi, _) in enumerate(cfg)]
        sl_shape = tuple(e - s for s, e in zip(starts, ends))
        sl = em.fresh("padtrim")
        em.declare(sl, jax.ShapeDtypeStruct(sl_shape, xa.dtype))
        em.emit("slice", {"Input": cur}, {"Out": sl},
                {"axes": list(range(xa.ndim)), "starts": starts,
                 "ends": ends, "infer_flags": [1] * xa.ndim,
                 "decrease_axis": []})
        cur = sl
        cfg = [(max(0, int(lo)), max(0, int(hi)), 0) for lo, hi, _ in cfg]
        if all(lo == 0 and hi == 0 for lo, hi, _ in cfg):
            em.bind(eqn.outvars[0], cur)
            return
    out = em.fresh("pad")
    em.declare(out, eqn.outvars[0].aval)
    paddings = []
    for lo, hi, _ in cfg:
        paddings += [int(lo), int(hi)]
    em.emit("pad", {"X": cur}, {"Out": out},
            {"paddings": paddings, "pad_value": float(pval)})
    em.bind(eqn.outvars[0], out)


def _top_k(em, eqn):
    out_v = em.fresh("topk_v")
    out_i = em.fresh("topk_i")
    em.declare(out_v, eqn.outvars[0].aval)
    em.declare(out_i, eqn.outvars[1].aval)
    em.emit("top_k_v2", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out_v, "Indices": out_i},
            {"k": int(eqn.params["k"]), "axis": -1, "largest": True,
             "sorted": True})
    em.bind(eqn.outvars[0], out_v)
    em.bind(eqn.outvars[1], out_i)


def _reduce_window_sum(em, eqn):
    """sum-pool window -> pool2d avg un-divided (scale by the window
    size); the avg-pool pattern (reduce_window_sum + div) then stays
    numerically exact."""
    p = eqn.params
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pads = p.get("padding", ((0, 0),) * len(wd))
    _check_window_dilations(p)
    if len(wd) != 4 or wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError(
            f"jaxpr export: reduce_window_sum dims {wd} is not NCHW "
            "pooling")
    if any(a != b for a, b in pads):
        raise NotImplementedError(
            f"jaxpr export: asymmetric pooling pad {pads}")
    mid = em.fresh("avgpool")
    em.declare(mid, eqn.outvars[0].aval)
    em.emit("pool2d", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": mid},
            {"pooling_type": "avg", "ksize": [int(wd[2]), int(wd[3])],
             "strides": [int(ws[2]), int(ws[3])],
             "paddings": [int(pads[2][0]), int(pads[3][0])],
             "ceil_mode": False, "global_pooling": False,
             "exclusive": False, "adaptive": False})
    out = em.fresh("sumpool")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("scale", {"X": mid}, {"Out": out},
            {"scale": float(int(wd[2]) * int(wd[3])), "bias": 0.0,
             "bias_after_scale": True})
    em.bind(eqn.outvars[0], out)


def _erfc(em, eqn):
    mid = em.fresh("erf")
    em.declare(mid, eqn.outvars[0].aval)
    em.emit("erf", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": mid}, {})
    out = em.fresh("erfc")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("scale", {"X": mid}, {"Out": out},
            {"scale": -1.0, "bias": 1.0, "bias_after_scale": True})
    em.bind(eqn.outvars[0], out)


def _rsqrt(em, eqn):
    _unary(em, eqn, "rsqrt")


def _sort_prim(em, eqn):
    """lax.sort -> reference `argsort` op (`operators/argsort_op.cc`,
    which emits BOTH the sorted values and the indices).  jnp.sort is
    the 1-operand form; jnp.argsort arrives as (x, iota) with
    num_keys=1 — the iota operand IS the index payload, so the op's
    Indices output binds to it."""
    p = eqn.params
    if int(p.get("num_keys", 1)) != 1:
        raise NotImplementedError(
            "jaxpr export: multi-key lax.sort has no argsort form")
    axis = int(p["dimension"])
    x = eqn.invars[0]
    va = x.aval
    payload_is_iota = False
    if len(eqn.invars) == 2:
        pay = eqn.invars[1]
        pv = em.const_value(pay)
        if pv is not None:
            # the jnp.argsort iota usually const-folds: verify it IS
            # the axis iota, not an arbitrary sort_key_val payload
            expect = np.broadcast_to(
                np.arange(va.shape[axis]).reshape(
                    [-1 if i == axis else 1
                     for i in range(len(va.shape))]),
                va.shape)
            payload_is_iota = (
                np.issubdtype(np.asarray(pv).dtype, np.integer)
                and np.array_equal(np.asarray(pv), expect))
        else:
            from jax.extend.core import Literal

            payload_is_iota = (not isinstance(pay, Literal)
                               and em.iota_axes.get(pay) == axis)
    if len(eqn.invars) > 2 or (len(eqn.invars) == 2
                               and not payload_is_iota):
        raise NotImplementedError(
            "jaxpr export: lax.sort with a non-index payload (only "
            "jnp.sort / jnp.argsort map to the argsort op)")
    out_v = em.fresh("sort_v")
    out_i = em.fresh("sort_i")
    em.declare(out_v, va)
    em.declare(out_i, jax.ShapeDtypeStruct(va.shape, np.int64))
    em.emit("argsort", {"X": em.literal_or_var(x)},
            {"Out": out_v, "Indices": out_i},
            {"axis": axis, "descending": False})
    if payload_is_iota:
        # argsort's Indices are int64; the traced indices dtype may be
        # int32 — cast to match the jaxpr contract
        idx_var = eqn.outvars[1]
        want = np.dtype(idx_var.aval.dtype)
        if want != np.dtype(np.int64):
            c = em.fresh("sort_ic")
            em.declare(c, idx_var.aval)
            em.emit("cast", {"X": out_i}, {"Out": c},
                    {"in_dtype": proto.np_dtype_to_vartype(
                        np.dtype(np.int64)),
                     "out_dtype": proto.np_dtype_to_vartype(want)})
            out_i = c
        em.bind(eqn.outvars[0], out_v)
        em.bind(idx_var, out_i)
    else:
        em.bind(eqn.outvars[0], out_v)


def _split_prim(em, eqn):
    """lax.split -> reference `split` op (`operators/split_op.cc`):
    equal sizes use the `num` attr, ragged use `sections`."""
    sizes = [int(s) for s in eqn.params["sizes"]]
    axis = int(eqn.params["axis"])
    outs = []
    for v in eqn.outvars:
        n = em.fresh("split")
        em.declare(n, v.aval)
        outs.append(n)
    attrs = {"axis": axis}
    if len(set(sizes)) == 1:
        attrs["num"] = len(sizes)
        attrs["sections"] = []
    else:
        attrs["num"] = 0
        attrs["sections"] = sizes
    em.emit("split", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": outs}, attrs)
    for v, n in zip(eqn.outvars, outs):
        em.bind(v, n)


def _start_vals(em, atoms):
    return [em.const_value(a) for a in atoms]


def _scalar_to_index_tensor(em, atom, clamp_hi=None):
    """Materialize a scalar start index as a [1] int tensor var (the
    shape the reference gather/scatter Ids and the interp expect).
    With clamp_hi, clamp into [0, clamp_hi] — the lax guarantee for
    dynamic_slice/dynamic_update_slice start indices, which gather/
    scatter would otherwise turn into OOB garbage."""
    name = em.literal_or_var(atom)
    aval = atom.aval
    dt = np.dtype(aval.dtype)
    if tuple(aval.shape) != (1,):
        r = em.fresh("idx")
        em.declare(r, jax.ShapeDtypeStruct((1,), dt))
        em.emit("reshape2", {"X": name}, {"Out": r}, {"shape": [1]})
        name = r
    if clamp_hi is not None:
        name = _clamp_index(em, name, dt, clamp_hi)
    return name


def _clamp_index(em, name, dt, clamp_hi):
    """Clamp a [1] index var into [0, clamp_hi] via max/min ops."""
    lo = em.emit_constant(np.asarray([0], dt), tag="idx_lo")
    hi = em.emit_constant(np.asarray([int(clamp_hi)], dt),
                          tag="idx_hi")
    mx = em.fresh("idx_clip_lo")
    em.declare(mx, jax.ShapeDtypeStruct((1,), dt))
    em.emit("elementwise_max", {"X": name, "Y": lo}, {"Out": mx},
            {"axis": -1})
    mn = em.fresh("idx_clip")
    em.declare(mn, jax.ShapeDtypeStruct((1,), dt))
    em.emit("elementwise_min", {"X": mx, "Y": hi}, {"Out": mn},
            {"axis": -1})
    return mn


def _single_dynamic_axis(em, svals, sizes, xa):
    """Validate the loop-indexing pattern: exactly one dynamic axis k
    (size 1 there), every other axis statically 0-start and full-size.
    Returns k or None."""
    dyn = [i for i, v in enumerate(svals) if v is None]
    if len(dyn) != 1:
        return None
    k = dyn[0]
    if sizes[k] != 1:
        return None
    for i in range(xa.ndim):
        if i == k:
            continue
        if svals[i] is None or int(np.asarray(svals[i]).reshape(())) != 0:
            return None
        if sizes[i] != int(xa.shape[i]):
            return None
    return k


def _dynamic_slice(em, eqn):
    """x[i] at a runtime index.  Statically-known starts serialize as a
    plain `slice`; the loop pattern (one dynamic axis, unit width)
    becomes transpose2 + `gather` + reshape2 — the reference gather op
    (`operators/gather_op.cc`) does the dim-0 dynamic row read."""
    x = eqn.invars[0]
    starts = eqn.invars[1:]
    sizes = [int(s) for s in eqn.params["slice_sizes"]]
    xa = x.aval
    svals = _start_vals(em, starts)
    if all(v is not None for v in svals):
        st = [int(np.asarray(v).reshape(())) for v in svals]
        # lax clamps starts into [0, dim - size]
        st = [min(max(s, 0), int(d) - z)
              for s, d, z in zip(st, xa.shape, sizes)]
        out = em.fresh("dsl")
        em.declare(out, eqn.outvars[0].aval)
        em.emit("slice", {"Input": em.literal_or_var(x)}, {"Out": out},
                {"axes": list(range(xa.ndim)), "starts": st,
                 "ends": [s + z for s, z in zip(st, sizes)],
                 "infer_flags": [1] * xa.ndim, "decrease_axis": []})
        em.bind(eqn.outvars[0], out)
        return
    k = _single_dynamic_axis(em, svals, sizes, xa)
    if k is None:
        raise NotImplementedError(
            "jaxpr export: dynamic_slice beyond the single-dynamic-axis "
            f"unit-width pattern (sizes {sizes} over shape "
            f"{tuple(xa.shape)})")
    xn = em.literal_or_var(x)
    shape = [int(s) for s in xa.shape]
    if k != 0:
        perm = [k] + [i for i in range(xa.ndim) if i != k]
        t = em.fresh("dsl_t")
        em.declare(t, jax.ShapeDtypeStruct(
            tuple(shape[p] for p in perm), xa.dtype))
        em.emit("transpose2", {"X": xn}, {"Out": t},
                {"axis": perm})
        xn = t
    idx = _scalar_to_index_tensor(em, starts[k],
                                  clamp_hi=int(xa.shape[k]) - 1)
    g = em.fresh("dsl_g")
    rest = [shape[i] for i in range(xa.ndim) if i != k]
    em.declare(g, jax.ShapeDtypeStruct(tuple([1] + rest), xa.dtype))
    em.emit("gather", {"X": xn, "Index": idx}, {"Out": g}, {})
    out = em.fresh("dsl")
    em.declare(out, eqn.outvars[0].aval)
    # [1, rest...] and the unit-width output have identical linear
    # element order, so a reshape2 restores the axis-k placement
    em.emit("reshape2", {"X": g}, {"Out": out},
            {"shape": [int(s) for s in eqn.outvars[0].aval.shape]})
    em.bind(eqn.outvars[0], out)


def _emit_row_overwrite(em, eqn, x_atom, upd_name, k, idx_atom,
                        overwrite=True, clamp=False, drop_oob=False):
    """Shared tail of dynamic_update_slice/scatter export: overwrite (or
    accumulate) one row of x along axis k at a runtime index, via the
    reference `scatter` op (dim-0 rows), bracketed by transpose2 when
    k != 0.  `upd_name` must already be [1, *other-dims-in-perm-order].

    `clamp` implements lax's dynamic_update_slice contract (starts clamp
    into range, the update always lands); `drop_oob` implements lax's
    default scatter mode FILL_OR_DROP (an out-of-bounds update is
    DROPPED): the index is clamped for addressing, but the written row is
    selected back to the original row when the raw index was out of
    bounds, so the program leaves x untouched exactly like lax does."""
    xa = x_atom.aval
    shape = [int(s) for s in xa.shape]
    xn = em.literal_or_var(x_atom)
    perm = [k] + [i for i in range(xa.ndim) if i != k]
    inv_perm = [perm.index(i) for i in range(xa.ndim)]
    if k != 0:
        t = em.fresh("dus_t")
        em.declare(t, jax.ShapeDtypeStruct(
            tuple(shape[p] for p in perm), xa.dtype))
        em.emit("transpose2", {"X": xn}, {"Out": t}, {"axis": perm})
        xn = t
    raw = _scalar_to_index_tensor(em, idx_atom)
    if clamp or drop_oob:
        idx = _clamp_index(em, raw, np.dtype(idx_atom.aval.dtype),
                           shape[k] - 1)
    else:
        idx = raw
    in_bounds = None
    if drop_oob:
        # raw == clamped  <=>  raw was already in [0, rows-1]
        in_bounds = em.fresh("scat_ok")
        em.declare(in_bounds, jax.ShapeDtypeStruct((1,), np.bool_))
        em.emit("equal", {"X": raw, "Y": idx}, {"Out": in_bounds},
                {"axis": -1})
    row_aval = jax.ShapeDtypeStruct(
        tuple([1] + [shape[p] for p in perm[1:]]), xa.dtype)
    if not overwrite:
        # accumulate: the reference scatter kernel's add mode zeroes
        # the target row first (scatter_op.h), so x[i] += u must
        # serialize as read-modify-write with an overwriting scatter
        g = em.fresh("rmw_row")
        em.declare(g, row_aval)
        em.emit("gather", {"X": xn, "Index": idx}, {"Out": g}, {})
        s = em.fresh("rmw_sum")
        em.declare(s, row_aval)
        em.emit("elementwise_add", {"X": g, "Y": upd_name}, {"Out": s},
                {"axis": -1})
        if in_bounds is not None:
            d = em.fresh("rmw_drop")
            em.declare(d, row_aval)
            em.emit("where", {"Condition": in_bounds, "X": s, "Y": g},
                    {"Out": d}, {})
            s = d
        upd_name = s
        overwrite = True
    elif in_bounds is not None:
        g = em.fresh("drop_row")
        em.declare(g, row_aval)
        em.emit("gather", {"X": xn, "Index": idx}, {"Out": g}, {})
        d = em.fresh("drop_sel")
        em.declare(d, row_aval)
        em.emit("where", {"Condition": in_bounds, "X": upd_name,
                          "Y": g}, {"Out": d}, {})
        upd_name = d
    sc = em.fresh("dus_sc")
    em.declare(sc, jax.ShapeDtypeStruct(
        tuple(shape[p] for p in perm), xa.dtype))
    em.emit("scatter", {"X": xn, "Ids": idx, "Updates": upd_name},
            {"Out": sc}, {"overwrite": bool(overwrite)})
    if k != 0:
        out = em.fresh("dus")
        em.declare(out, eqn.outvars[0].aval)
        em.emit("transpose2", {"X": sc}, {"Out": out},
                {"axis": inv_perm})
        sc = out
    em.bind(eqn.outvars[0], sc)


def _dynamic_update_slice(em, eqn):
    """x with a block overwritten at a runtime offset.  Static starts
    serialize as `set_value` (`operators/set_value_op.cc`); the loop
    pattern (one dynamic axis, unit width) becomes the reference
    `scatter` op on rows."""
    x, upd = eqn.invars[0], eqn.invars[1]
    starts = eqn.invars[2:]
    xa, ua = x.aval, upd.aval
    sizes = [int(s) for s in ua.shape]
    svals = _start_vals(em, starts)
    if all(v is not None for v in svals):
        st = [min(max(int(np.asarray(v).reshape(())), 0), int(d) - z)
              for v, d, z in zip(svals, xa.shape, sizes)]
        out = em.fresh("setv")
        em.declare(out, eqn.outvars[0].aval)
        em.emit("set_value",
                {"Input": em.literal_or_var(x),
                 "ValueTensor": em.literal_or_var(upd)},
                {"Out": out},
                {"axes": list(range(xa.ndim)), "starts": st,
                 "ends": [s + z for s, z in zip(st, sizes)],
                 "steps": [1] * xa.ndim, "decrease_axes": [],
                 "none_axes": [], "shape": []})
        em.bind(eqn.outvars[0], out)
        return
    k = _single_dynamic_axis(em, svals, sizes, xa)
    if k is None:
        raise NotImplementedError(
            "jaxpr export: dynamic_update_slice beyond the "
            "single-dynamic-axis unit-width pattern")
    # update arrives with the unit axis in place; move it to dim 0
    perm = [k] + [i for i in range(xa.ndim) if i != k]
    upd_shape = [1] + [int(xa.shape[i]) for i in range(xa.ndim)
                       if i != k]
    un = em.literal_or_var(upd)
    if k != 0:
        ut = em.fresh("dus_u")
        em.declare(ut, jax.ShapeDtypeStruct(tuple(upd_shape), ua.dtype))
        em.emit("transpose2", {"X": un}, {"Out": ut}, {"axis": perm})
        un = ut
    # lax clamps dynamic_update_slice starts into range (the update is
    # always applied); gather/scatter would drop an OOB row instead
    _emit_row_overwrite(em, eqn, x, un, k, starts[k], clamp=True)


def _scatter_prim(em, eqn, overwrite):
    """`.at[i].set/add` row form -> reference `scatter` op: indices [1]
    over operand dim 0 with the update covering the full row."""
    dn = eqn.params["dimension_numbers"]
    x, idx, upd = eqn.invars
    xa, ia, ua = x.aval, idx.aval, upd.aval
    row_ok = (tuple(dn.scatter_dims_to_operand_dims) == (0,)
              and tuple(dn.inserted_window_dims) == (0,)
              and not dn.operand_batching_dims
              and int(np.prod(ia.shape)) == 1
              and tuple(ua.shape[-(xa.ndim - 1):] if xa.ndim > 1 else ())
              == tuple(xa.shape[1:]))
    if not row_ok:
        raise NotImplementedError(
            "jaxpr export: general lax.scatter (only the single-row "
            ".at[i].set/.add pattern maps to the scatter op)")
    un = em.literal_or_var(upd)
    row_shape = [1] + [int(s) for s in xa.shape[1:]]
    if list(ua.shape) != row_shape:
        r = em.fresh("scat_u")
        em.declare(r, jax.ShapeDtypeStruct(tuple(row_shape), ua.dtype))
        em.emit("reshape2", {"X": un}, {"Out": r},
                {"shape": row_shape})
        un = r
    # lax's default scatter mode is FILL_OR_DROP: an out-of-bounds row
    # index drops the update; the exported program must match (ADVICE
    # round 5 — the old emission silently clamped, corrupting a row)
    _emit_row_overwrite(em, eqn, x, un, 0, idx, overwrite=overwrite,
                        drop_oob=True)


def _pow(em, eqn):
    y = int(eqn.params["y"])
    out = em.fresh("pow")
    em.declare(out, eqn.outvars[0].aval)
    em.emit("pow", {"X": em.literal_or_var(eqn.invars[0])},
            {"Out": out}, {"factor": float(y)})
    em.bind(eqn.outvars[0], out)


# ---------------------------------------------------------------------------
# Structured control flow -> reference sub-block ops.
#
# The reference captures dygraph loops/branches into ProgramDesc
# sub-blocks (`dygraph/jit.py` jit.save via the ProgramTranslator,
# `controlflow/while_op.cc`, `conditional_block_op.cc`); this is the
# produce side of the interchange contract whose consume side lives in
# `interp.py` (its `while` translator carries every body-written outer
# var and re-reads Condition each step — the program shapes emitted here
# are exactly what it consumes, and what the reference executor runs).
# ---------------------------------------------------------------------------
def _bind_closed_consts(em, closed):
    """Bind a ClosedJaxpr's constvars to persistable global-block vars
    (once per closed jaxpr — a cond_jaxpr is walked once outside the
    loop and once per body)."""
    jx = closed.jaxpr
    if id(closed) in em.closed_consts:
        for cv, name in zip(jx.constvars, em.closed_consts[id(closed)]):
            em.names.pop(cv, None)
            em.known.pop(cv, None)
            if name is not None:  # poisoned consts stay poisoned
                em.bind(cv, name)
        return jx
    names = []
    for cv, cval in zip(jx.constvars, closed.consts):
        names.append(em.bind_const_value(cv, cval, "const"))
    em.closed_consts[id(closed)] = names
    return jx


def _poison_msg(em, atom):
    """Refusal message if this atom must never materialize (an RNG key
    threaded through the jitted forward's loop carry / closure), else
    None."""
    from jax.extend.core import Literal

    import jax.dtypes as jdt

    if isinstance(atom, Literal):
        return None
    if atom in em.poison:
        return em.poison[atom]
    dt = getattr(atom.aval, "dtype", None)
    if dt is not None and jdt.issubdtype(dt, jdt.extended):
        return (f"jaxpr export: value of extended dtype {dt} (PRNG "
                "key / RNG state) feeds a serialized op — inference "
                "programs cannot carry RNG state; export with the "
                "layer in eval() mode")
    return None


def _resolve_atoms(em, atoms):
    """Program var names for a list of atoms; poisoned atoms resolve to
    None (they stay dead unless something inside actually reads them)."""
    out = []
    for a in atoms:
        msg = _poison_msg(em, a)
        out.append(None if msg else em.literal_or_var(a))
    return out


def _walk_closed(em, closed, in_names, const_atoms=None):
    """Walk a closed sub-jaxpr with its invars bound to program var
    names (None = poisoned: the refusal fires only if read); returns
    the inner jaxpr (caller reads .outvars).  Rebinding clears stale
    state from a previous walk of the same (cached) jaxpr — each eqn
    refreshes its outvars in program order, so in-order reads never see
    the prior walk's bindings."""
    jx = _bind_closed_consts(em, closed)
    const_atoms = const_atoms or {}
    for i, (v, n) in enumerate(zip(jx.invars, in_names)):
        em.names.pop(v, None)
        em.known.pop(v, None)
        atom = const_atoms.get(i)
        if n is None:
            em.poison[v] = (
                _poison_msg(em, atom) if atom is not None else None
            ) or ("jaxpr export: RNG state feeds a serialized op — "
                  "export with the layer in eval() mode")
            continue
        em.poison.pop(v, None)
        cv = em.const_value(atom) if atom is not None else None
        if cv is not None:
            # loop-invariant constant operand: keep it foldable inside
            em.known[v] = cv
        else:
            em.bind(v, n)
    _walk(em, jx)
    return jx


def _assign_carries(em, outvar_atoms, carry_names):
    """Write back loop-carried values (poisoned slots skipped).  Copy
    through fresh temps first: an identity carry's outvar can BE
    another carry's name, and a direct in-place assignment sequence
    would read already-overwritten slots (the (a, b) = (b, a) hazard)."""
    tmps = []
    for a, nm in zip(outvar_atoms, carry_names):
        if nm is None:
            tmps.append(None)
            continue
        t = em.fresh("carry_tmp")
        em.declare(t, a.aval)
        em.emit("assign", {"X": em.literal_or_var(a)}, {"Out": t}, {})
        tmps.append(t)
    for t, nm in zip(tmps, carry_names):
        if t is not None:
            em.emit("assign", {"X": t}, {"Out": nm}, {})


def _emit_condition(em, cond_closed, cond_const_names, cond_const_atoms,
                    carry_names, cond_name):
    jx = _walk_closed(em, cond_closed,
                      cond_const_names + carry_names,
                      const_atoms=cond_const_atoms)
    em.emit("assign", {"X": em.literal_or_var(jx.outvars[0])},
            {"Out": cond_name}, {})


def _init_carries(em, carry_atoms, tag):
    """Outer loop-var names aligned with the carry atoms; poisoned
    carries (an RNG key threaded through the jitted forward's loop)
    stay None — dead unless the body actually reads them."""
    names = []
    for a in carry_atoms:
        if _poison_msg(em, a):
            names.append(None)
            continue
        nm = em.fresh(tag)
        em.declare(nm, a.aval)
        em.emit("assign", {"X": em.literal_or_var(a)}, {"Out": nm}, {})
        names.append(nm)
    return names


def _emit_while_op(em, read_names, cond_name, carry_names, sub):
    from .program import BlockRef

    scopes = em.fresh("step_scopes")
    em.block.create_var(scopes, type=proto.VarType.STEP_SCOPES)
    em.emit("while",
            {"X": sorted(set(read_names)), "Condition": cond_name},
            {"Out": list(carry_names), "StepScopes": scopes},
            {"sub_block": BlockRef(sub.idx), "is_test": True})


def _while_prim(em, eqn):
    """lax.while_loop -> `while` op.  Carries become outer vars the
    sub-block writes back each step (the reference's step-scope
    write-back); the Condition var is computed once before the loop and
    recomputed at the end of each body — the exact shape fluid's
    `layers.while_loop` builds (`control_flow.py:1014`)."""
    p = eqn.params
    cn, bn = p["cond_nconsts"], p["body_nconsts"]
    cond_closed, body_closed = p["cond_jaxpr"], p["body_jaxpr"]
    cond_consts = eqn.invars[:cn]
    body_consts = eqn.invars[cn:cn + bn]
    carry_in = eqn.invars[cn + bn:]

    cond_const_names = _resolve_atoms(em, cond_consts)
    body_const_names = _resolve_atoms(em, body_consts)
    carry_names = _init_carries(em, carry_in, "loopvar")

    cond_name = em.fresh("while_cond")
    em.declare(cond_name, cond_closed.jaxpr.outvars[0].aval)
    cond_const_atoms = {i: a for i, a in enumerate(cond_consts)}
    _emit_condition(em, cond_closed, cond_const_names, cond_const_atoms,
                    carry_names, cond_name)

    sub = em.program.create_block(parent_idx=em.block.idx)
    with em.in_block(sub):
        bjx = _walk_closed(
            em, body_closed, body_const_names + carry_names,
            const_atoms={i: a for i, a in enumerate(body_consts)})
        _assign_carries(em, bjx.outvars, carry_names)
        _emit_condition(em, cond_closed, cond_const_names,
                        cond_const_atoms, carry_names, cond_name)

    live = [n for n in carry_names if n is not None]
    _emit_while_op(em,
                   [n for n in cond_const_names + body_const_names
                    if n is not None] + live,
                   cond_name, live, sub)
    for v, nm in zip(eqn.outvars, carry_names):
        if nm is None:
            em.poison[v] = _poison_msg(em, v) or (
                "jaxpr export: RNG state flows out of a serialized "
                "loop — export with the layer in eval() mode")
        else:
            em.bind(v, nm)


def _scan_prim(em, eqn):
    """lax.scan -> counter `while` + TensorArrays: xs rows read via
    `gather` at the loop index, per-step ys written with
    `write_to_array`, stacked by `tensor_array_to_tensor` after the
    loop.  The trip bound is a `less_than(i, length)` against an outer
    fill_constant, which is also how the interp (and the reference's
    LoDTensorArray sizing) statically infer TensorArray capacity."""
    p = eqn.params
    nc, ncar = p["num_consts"], p["num_carry"]
    length, reverse = int(p["length"]), bool(p["reverse"])
    closed = p["jaxpr"]
    consts = eqn.invars[:nc]
    carry_in = eqn.invars[nc:nc + ncar]
    xs = eqn.invars[nc + ncar:]
    ys_outs = eqn.outvars[ncar:]

    const_names = _resolve_atoms(em, consts)
    xs_names = _resolve_atoms(em, xs)
    carry_names = _init_carries(em, carry_in, "scanvar")

    i64 = jax.ShapeDtypeStruct((1,), np.int64)
    i_name = em.fresh("scan_i")
    em.declare(i_name, i64)
    em.emit("fill_constant", {}, {"Out": i_name},
            {"shape": [1], "dtype": proto.np_dtype_to_vartype(np.dtype(np.int64)),
             "value": 0.0})
    t_name = em.fresh("scan_n")
    em.declare(t_name, i64)
    em.emit("fill_constant", {}, {"Out": t_name},
            {"shape": [1], "dtype": proto.np_dtype_to_vartype(np.dtype(np.int64)),
             "value": float(length)})
    cond_name = em.fresh("scan_cond")
    em.declare(cond_name, jax.ShapeDtypeStruct((1,), np.bool_))
    em.emit("less_than", {"X": i_name, "Y": t_name}, {"Out": cond_name},
            {})

    ta_names = []
    for v in ys_outs:
        ta = em.fresh("ys_ta")
        em.block.create_var(ta, type=proto.VarType.LOD_TENSOR_ARRAY)
        ta_names.append(ta)

    body_invars = closed.jaxpr.invars
    sub = em.program.create_block(parent_idx=em.block.idx)
    with em.in_block(sub):
        step_idx = i_name
        if reverse:
            # write/read position runs from the far end so ys stay in
            # source order (lax.scan reverse semantics)
            tm1 = em.fresh("scan_nm1")
            em.declare(tm1, i64)
            em.emit("fill_constant", {}, {"Out": tm1},
                    {"shape": [1],
                     "dtype": proto.np_dtype_to_vartype(np.dtype(np.int64)),
                     "value": float(length - 1)})
            rev = em.fresh("scan_rev_i")
            em.declare(rev, i64)
            em.emit("elementwise_sub", {"X": tm1, "Y": i_name},
                    {"Out": rev}, {"axis": -1})
            step_idx = rev
        xt_names = []
        for j, xsn in enumerate(xs_names):
            if xsn is None:
                xt_names.append(None)
                continue
            elem = body_invars[nc + ncar + j].aval
            g = em.fresh("xt_row")
            em.declare(g, jax.ShapeDtypeStruct((1,) + tuple(elem.shape),
                                               elem.dtype))
            em.emit("gather", {"X": xsn, "Index": step_idx},
                    {"Out": g}, {})
            r = em.fresh("xt")
            em.declare(r, elem)
            em.emit("reshape2", {"X": g}, {"Out": r},
                    {"shape": [int(s) for s in elem.shape]})
            xt_names.append(r)
        bjx = _walk_closed(
            em, closed, const_names + carry_names + xt_names,
            const_atoms={i: a for i, a in enumerate(consts)})
        for ta, yv in zip(ta_names, bjx.outvars[ncar:]):
            em.emit("write_to_array",
                    {"X": em.literal_or_var(yv), "I": step_idx},
                    {"Out": ta}, {})
        _assign_carries(em, bjx.outvars[:ncar], carry_names)
        em.emit("increment", {"X": i_name}, {"Out": i_name},
                {"step": 1.0})
        em.emit("less_than", {"X": i_name, "Y": t_name},
                {"Out": cond_name}, {})

    live = [n for n in carry_names if n is not None]
    _emit_while_op(em,
                   [n for n in const_names + xs_names if n is not None]
                   + live + [i_name, t_name],
                   cond_name, live + [i_name] + ta_names, sub)
    for v, nm in zip(eqn.outvars[:ncar], carry_names):
        if nm is None:
            em.poison[v] = _poison_msg(em, v) or (
                "jaxpr export: RNG state flows out of a serialized "
                "loop — export with the layer in eval() mode")
        else:
            em.bind(v, nm)
    for v, ta in zip(ys_outs, ta_names):
        out = em.fresh("ys")
        em.declare(out, v.aval)
        ln = em.fresh("ys_len")
        em.declare(ln, jax.ShapeDtypeStruct((1,), np.int32))
        em.emit("tensor_array_to_tensor", {"X": ta},
                {"Out": out, "OutIndex": ln},
                {"axis": 0, "use_stack": True})
        em.bind(v, out)


def _cond_prim(em, eqn):
    """lax.cond / lax.switch -> one `conditional_block` per branch
    (scalar-condition mode, Cond = `equal(index, k)`) reconciled with
    `select_input` on the branch index — the reference `layers.cond`
    program shape (`conditional_block_op.cc:29`, `select_input_op.cc`)."""
    branches = eqn.params["branches"]
    idx_atom = eqn.invars[0]
    operand_atoms = eqn.invars[1:]
    iv = em.const_value(idx_atom)
    operand_names = _resolve_atoms(em, operand_atoms)
    const_atoms = {i: a for i, a in enumerate(operand_atoms)}
    if iv is not None:
        # statically-taken branch: inline it, no sub-blocks
        k = int(np.clip(int(np.asarray(iv).reshape(())), 0,
                        len(branches) - 1))
        jx = _walk_closed(em, branches[k], operand_names,
                          const_atoms=const_atoms)
        for v, a in zip(eqn.outvars, jx.outvars):
            em.bind(v, em.literal_or_var(a))
        return

    idx_name = em.literal_or_var(idx_atom)
    ia = idx_atom.aval
    if np.dtype(ia.dtype) != np.dtype(np.int32):
        c = em.fresh("branch_idx")
        em.declare(c, jax.ShapeDtypeStruct(ia.shape, np.int32))
        em.emit("cast", {"X": idx_name}, {"Out": c},
                {"in_dtype": proto.np_dtype_to_vartype(
                    np.dtype(ia.dtype)),
                 "out_dtype": proto.np_dtype_to_vartype(
                     np.dtype(np.int32))})
        idx_name = c

    from .program import BlockRef

    branch_outs: List[List[str]] = []
    for k, br in enumerate(branches):
        kconst = em.emit_constant(np.asarray([k], np.int32),
                                  tag="branch_k")
        mask = em.fresh("branch_mask")
        em.declare(mask, jax.ShapeDtypeStruct((1,), np.bool_))
        em.emit("equal", {"X": idx_name, "Y": kconst}, {"Out": mask},
                {"axis": -1})
        outs_k = []
        for v in eqn.outvars:
            nm = em.fresh("branch_out")
            em.declare(nm, v.aval)
            outs_k.append(nm)
        sub = em.program.create_block(parent_idx=em.block.idx)
        with em.in_block(sub):
            jx = _walk_closed(em, br, operand_names,
                              const_atoms=const_atoms)
            for a, nm in zip(jx.outvars, outs_k):
                em.emit("assign", {"X": em.literal_or_var(a)},
                        {"Out": nm}, {})
        scope_var = em.fresh("cond_scope")
        em.block.create_var(scope_var, type=proto.VarType.STEP_SCOPES)
        em.emit("conditional_block",
                {"Cond": mask,
                 "Input": [n for n in operand_names if n is not None]},
                {"Out": outs_k, "Scope": scope_var},
                {"sub_block": BlockRef(sub.idx),
                 "is_scalar_condition": True})
        branch_outs.append(outs_k)

    for j, v in enumerate(eqn.outvars):
        sel = em.fresh("branch_sel")
        em.declare(sel, v.aval)
        em.emit("select_input",
                {"X": [branch_outs[k][j] for k in range(len(branches))],
                 "Mask": idx_name},
                {"Out": sel}, {})
        em.bind(v, sel)


def _paddle_rnn_prim(em, eqn):
    """Export-marker primitive from `export_marker.py` (bound by
    nn.LSTM/GRU/SimpleRNN during export tracing) -> the unified `rnn`
    op (`operators/rnn_op.cc`), which is time-major: batch-major models
    get transpose2 brackets, exactly as the reference python layer does
    around its fused op call."""
    p = eqn.params
    mode = p["mode"]
    lstm = mode == "LSTM"
    x_atom, h0_atom, c0_atom = eqn.invars[:3]
    weights = eqn.invars[3:]
    xn = em.literal_or_var(x_atom)
    xa = x_atom.aval
    if not p["time_major"]:
        t = em.fresh("rnn_tm")
        em.declare(t, jax.ShapeDtypeStruct(
            (xa.shape[1], xa.shape[0], xa.shape[2]), xa.dtype))
        em.emit("transpose2", {"X": xn}, {"Out": t},
                {"axis": [1, 0, 2]})
        xn = t
    pre = [em.literal_or_var(h0_atom)]
    if lstm:
        pre.append(em.literal_or_var(c0_atom))
    wnames = [em.literal_or_var(w) for w in weights]
    T, B = (xa.shape[0], xa.shape[1]) if p["time_major"] else \
        (xa.shape[1], xa.shape[0])
    nd = 2 if p["is_bidirec"] else 1
    H = int(p["hidden_size"])
    o = em.fresh("rnn_out")
    em.declare(o, jax.ShapeDtypeStruct((T, B, H * nd), xa.dtype))
    states = []
    for _ in range(2 if lstm else 1):
        s = em.fresh("rnn_state")
        em.declare(s, eqn.outvars[1].aval)
        states.append(s)
    ds = em.fresh("rnn_dropout_state")
    em.block.create_var(ds, type=proto.VarType.RAW)
    rv = em.fresh("rnn_reserve")
    em.declare(rv, jax.ShapeDtypeStruct((0,), np.float32))
    em.emit("rnn",
            {"Input": xn, "WeightList": wnames, "PreState": pre},
            {"Out": o, "State": states, "DropoutState": ds,
             "Reserve": rv},
            {"mode": mode, "hidden_size": H,
             "num_layers": int(p["num_layers"]),
             "is_bidirec": bool(p["is_bidirec"]), "is_test": True,
             "dropout_prob": float(p["dropout"]), "seed": 0})
    if not p["time_major"]:
        ob = em.fresh("rnn_out_bm")
        em.declare(ob, eqn.outvars[0].aval)
        em.emit("transpose2", {"X": o}, {"Out": ob},
                {"axis": [1, 0, 2]})
        o = ob
    em.bind(eqn.outvars[0], o)
    for v, nm in zip(eqn.outvars[1:], states):
        em.bind(v, nm)


_HANDLERS = {
    "add": lambda em, e: _elementwise(em, e, "elementwise_add"),
    "sub": lambda em, e: _elementwise(em, e, "elementwise_sub"),
    "mul": lambda em, e: _elementwise(em, e, "elementwise_mul"),
    "div": lambda em, e: _elementwise(em, e, "elementwise_div"),
    "max": lambda em, e: _elementwise(em, e, "elementwise_max"),
    "min": lambda em, e: _elementwise(em, e, "elementwise_min"),
    "pow": lambda em, e: _elementwise(em, e, "elementwise_pow"),
    "rem": lambda em, e: _elementwise(em, e, "elementwise_mod"),
    "eq": lambda em, e: _elementwise(em, e, "equal"),
    "ne": lambda em, e: _elementwise(em, e, "not_equal"),
    "lt": lambda em, e: _elementwise(em, e, "less_than"),
    "le": lambda em, e: _elementwise(em, e, "less_equal"),
    "gt": lambda em, e: _elementwise(em, e, "greater_than"),
    "ge": lambda em, e: _elementwise(em, e, "greater_equal"),
    "and": lambda em, e: _bool_elementwise(em, e, "logical_and"),
    "or": lambda em, e: _bool_elementwise(em, e, "logical_or"),
    "xor": lambda em, e: _bool_elementwise(em, e, "logical_xor"),
    "exp": lambda em, e: _unary(em, e, "exp"),
    "log": lambda em, e: _unary(em, e, "log"),
    "tanh": lambda em, e: _unary(em, e, "tanh"),
    "logistic": lambda em, e: _unary(em, e, "sigmoid"),
    "sqrt": lambda em, e: _unary(em, e, "sqrt"),
    "rsqrt": _rsqrt,
    "abs": lambda em, e: _unary(em, e, "abs"),
    "floor": lambda em, e: _unary(em, e, "floor"),
    "ceil": lambda em, e: _unary(em, e, "ceil"),
    "sign": lambda em, e: _unary(em, e, "sign"),
    "erf": lambda em, e: _unary(em, e, "erf"),
    # erfc(x) = 1 - erf(x): erf then scale(-1, bias 1)
    "erfc": lambda em, e: _erfc(em, e),
    "square": lambda em, e: _unary(em, e, "square"),
    "log1p": lambda em, e: _unary(em, e, "log1p"),
    "cbrt": lambda em, e: _cbrt(em, e),
    "is_finite": lambda em, e: _unary(em, e, "isfinite"),
    "sin": lambda em, e: _unary(em, e, "sin"),
    "cos": lambda em, e: _unary(em, e, "cos"),
    "not": lambda em, e: _unary(em, e, "logical_not"),
    "neg": lambda em, e: _unary(em, e, "scale",
                                {"scale": -1.0, "bias": 0.0,
                                 "bias_after_scale": True}),
    "integer_pow": _pow,
    "dot_general": _dot_general,
    "conv_general_dilated": _conv,
    "reduce_sum": lambda em, e: _reduce(em, e, "reduce_sum"),
    "reduce_max": lambda em, e: _reduce(em, e, "reduce_max"),
    "reduce_min": lambda em, e: _reduce(em, e, "reduce_min"),
    "reduce_prod": lambda em, e: _reduce(em, e, "reduce_prod"),
    "reduce_and": lambda em, e: _reduce(em, e, "reduce_all"),
    "reduce_or": lambda em, e: _reduce(em, e, "reduce_any"),
    "reduce_window_max": _reduce_window,
    "cumsum": _cumsum,
    "argmax": lambda em, e: _argminmax(em, e, "arg_max"),
    "argmin": lambda em, e: _argminmax(em, e, "arg_min"),
    "clamp": _clamp,
    "iota": _iota,
    "pad": _pad,
    "atan2": _atan2,
    "expm1": lambda em, e: _unary(em, e, "expm1"),
    "top_k": _top_k,
    "reduce_window_sum": _reduce_window_sum,

    "broadcast_in_dim": _broadcast_in_dim,
    "transpose": _transpose,
    "reshape": _reshape,
    "squeeze": _reshape,
    "expand_dims": _reshape,
    "convert_element_type": _convert,
    "slice": _slice,
    "concatenate": _concatenate,
    "select_n": _select_n,
    "gather": _gather_as_lookup,
    "rev": lambda em, e: _unary(
        em, e, "flip",
        {"axis": [int(d) for d in e.params["dimensions"]]}),
    "stop_gradient": lambda em, e: _unary(em, e, "assign"),
    "copy": lambda em, e: _unary(em, e, "assign"),

    "split": _split_prim,
    "sort": _sort_prim,
    "dynamic_slice": _dynamic_slice,
    "dynamic_update_slice": _dynamic_update_slice,
    "scatter": lambda em, e: _scatter_prim(em, e, overwrite=True),
    "scatter-add": lambda em, e: _scatter_prim(em, e, overwrite=False),

    "while": _while_prim,
    "scan": _scan_prim,
    "cond": _cond_prim,
    "paddle_rnn": _paddle_rnn_prim,
}


def _try_const_fold(em, eqn) -> bool:
    """When every input is statically known, evaluate the primitive
    eagerly and record the result — no ops emitted (materialized on
    demand by var_of).  Keeps pad/clip attr resolution working when
    values route through convert/broadcast chains, and exports leaner
    programs."""
    if eqn.primitive.name in ("pjit", "jit", "closed_call",
                              "paddle_rnn"):
        return False
    vals = [em.const_value(a) for a in eqn.invars]
    if any(v is None for v in vals):
        return False
    # only fold small constants: folding a big computed tensor would
    # bloat the program with assign_value blobs
    if any(np.asarray(v).size > 4096 for v in vals):
        return False
    try:
        out = eqn.primitive.bind(*[jnp.asarray(v) for v in vals],
                                 **eqn.params)
    except Exception:
        return False
    outs = out if isinstance(out, (tuple, list)) else (out,)
    if len(outs) != len(eqn.outvars):
        return False
    import jax.dtypes as jdt

    for v, val in zip(eqn.outvars, outs):
        em.names.pop(v, None)  # cached-region var may be re-bound
        dt = getattr(val, "dtype", None)
        if dt is not None and jdt.issubdtype(dt, jdt.extended):
            # a folded RNG key (random_wrap of const bits): poisoned,
            # not materialized — dead in an eval-mode inference export
            em.poison[v] = (
                f"jaxpr export: value of extended dtype {dt} (PRNG "
                "key / RNG state) feeds a serialized op — inference "
                "programs cannot carry RNG state; export with the "
                "layer in eval() mode")
            continue
        em.known[v] = np.asarray(val)
    return True


def _walk(em: _Emitter, jaxpr):
    import jax.dtypes as jdt

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if _try_const_fold(em, eqn):
            continue
        # RNG plumbing traced by the jit path (key splits per call,
        # key slicing/reshaping) is dead in an eval-mode export; poison
        # rather than emit, so the refusal only fires if a real op's
        # result actually depends on randomness (train-mode dropout)
        rng_msg = None
        if prim.startswith("random_") or prim == "threefry2x32":
            rng_msg = (
                f"jaxpr export: RNG primitive {prim!r} feeds a "
                "serialized op — inference programs cannot carry RNG "
                "state; export with the layer in eval() mode")
        if rng_msg is None and prim not in (
                # region prims handle poison per operand slot
                "pjit", "jit", "closed_call", "while", "scan", "cond",
                "custom_jvp_call", "custom_vjp_call", "remat",
                "checkpoint"):
            for a in eqn.invars:
                rng_msg = _poison_msg(em, a)
                if rng_msg:
                    break
            else:
                for v in eqn.outvars:
                    dt = getattr(v.aval, "dtype", None)
                    if dt is not None and jdt.issubdtype(dt,
                                                         jdt.extended):
                        rng_msg = (
                            f"jaxpr export: {prim!r} produces extended "
                            f"dtype {dt} (RNG state) — inference "
                            "programs cannot carry RNG state")
                        break
        if rng_msg is not None:
            for v in eqn.outvars:
                em.names.pop(v, None)
                em.known.pop(v, None)
                em.poison[v] = rng_msg
            continue
        if prim in ("pjit", "jit", "closed_call", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr",
                    "remat", "checkpoint"):
            inner = eqn.params.get("jaxpr") or eqn.params.get(
                "call_jaxpr")
            closed = getattr(inner, "jaxpr", inner)
            consts = getattr(inner, "consts", [])
            for cv, cval in zip(closed.constvars, consts):
                em.bind_const_value(cv, cval, "const")
            # NOTE: jax CACHES identical inner jaxprs, so the same Var
            # objects recur across different pjit eqns (two
            # structurally-equal embedding wraps share one jaxpr) — a
            # re-bind must clear the var's previous-region state or a
            # stale name wins over the new const (found via BERT's
            # token-type ids resolving to the word-ids chain)
            from jax.extend.core import Literal as _Lit

            for outer, innerv in zip(eqn.invars, closed.invars):
                em.names.pop(innerv, None)
                em.known.pop(innerv, None)
                if not isinstance(outer, _Lit) and outer in em.poison:
                    em.poison[innerv] = em.poison[outer]
                    continue
                cv = em.const_value(outer)
                if cv is not None:
                    # keep constants foldable across the jit boundary
                    em.known[innerv] = cv
                else:
                    em.bind(innerv, em.literal_or_var(outer))
            _walk(em, closed)
            from jax.extend.core import Literal

            for outer, innerv in zip(eqn.outvars, closed.outvars):
                if not isinstance(innerv, Literal) and \
                        innerv in em.poison:
                    em.poison[outer] = em.poison[innerv]
                    continue
                cv = em.const_value(innerv)
                # Literal outvars (inner region returns a constant) are
                # unhashable — guard before any dict membership test
                inner_named = (not isinstance(innerv, Literal)
                               and innerv in em.names)
                if cv is not None and not inner_named:
                    em.names.pop(outer, None)  # stale walk-1 binding
                    em.known[outer] = cv
                else:
                    em.bind(outer, em.literal_or_var(innerv))
            continue
        handler = _HANDLERS.get(prim)
        if handler is None:
            raise NotImplementedError(
                f"jaxpr export: no ProgramDesc mapping for primitive "
                f"{prim!r} (op set: {sorted(_HANDLERS)})")
        handler(em, eqn)


def program_from_traced(fn, example_inputs: List, scope: Dict,
                        input_names: List[str] = None):
    """Trace `fn(*example_inputs)` and export the jaxpr as a Program.

    Closure constants (e.g. layer parameters) become persistable vars
    with their live values collected into `scope`.  Returns the
    Program; feed targets are the positional inputs, fetch targets the
    outputs.
    """
    from .program import Program
    from .proto import VarType

    from .export_marker import export_trace_context

    specs = [jax.ShapeDtypeStruct(np.shape(x),
                                  np.asarray(x).dtype if not
                                  hasattr(x, "dtype") else x.dtype)
             for x in example_inputs]
    with export_trace_context():
        closed = jax.make_jaxpr(fn)(*specs)

    program = Program()
    block = program.global_block()
    block.create_var("feed", type=VarType.FEED_MINIBATCH,
                     persistable=True)
    block.create_var("fetch", type=VarType.FETCH_LIST, persistable=True)
    em = _Emitter(program, block, scope)

    for cv, cval in zip(closed.jaxpr.constvars, closed.consts):
        em.bind_const_value(cv, cval, "param")

    names = input_names or [f"input_{i}" for i in range(len(specs))]
    for i, (v, spec, name) in enumerate(zip(closed.jaxpr.invars, specs,
                                            names)):
        block.create_var(name, list(spec.shape), str(spec.dtype),
                         need_check_feed=True)
        em.emit("feed", {"X": "feed"}, {"Out": name}, {"col": i})
        em.bind(v, name)

    _walk(em, closed.jaxpr)

    for i, v in enumerate(closed.jaxpr.outvars):
        out_name = f"output_{i}"
        aval = v.aval
        block.create_var(out_name, list(aval.shape), str(aval.dtype))
        em.emit("assign", {"X": em.literal_or_var(v)},
                {"Out": out_name}, {})
        em.emit("fetch", {"X": out_name}, {"Out": "fetch"}, {"col": i})
    return program
