"""Export-time lowering marker for the unified RNN op.

In the reference, the dygraph RNN layers ARE one fused op: `nn.LSTM`'s
forward binds `_C_ops.rnn` (`python/paddle/nn/layer/rnn.py`, kernel
`operators/rnn_op.cc`), so `jit.save` serializes a single compact `rnn`
op.  The TPU build's eager RNN layers are a traced python time loop
(XLA fuses it), which would *unroll* into T copies of the cell under
`make_jaxpr` — correct but bloated, and it loses the reference-format
`rnn` op the interchange contract calls for.

So during export tracing (`jaxpr_export.program_from_traced` sets the
flag below), `_RNNBase.forward` binds this marker primitive instead of
running its python loop; the exporter maps it 1:1 onto the `rnn` op.
The primitive exists only inside `make_jaxpr` under the flag — eager
execution and training never see it, so no jvp/batching rules are
needed.
"""
from __future__ import annotations

import contextlib
import threading

import jax.core
from jax.extend.core import Primitive

_TLS = threading.local()


def export_tracing() -> bool:
    """True while jaxpr_export is tracing a model for serialization."""
    return getattr(_TLS, "on", False)


@contextlib.contextmanager
def export_trace_context():
    prev = getattr(_TLS, "on", False)
    _TLS.on = True
    try:
        yield
    finally:
        _TLS.on = prev


rnn_p = Primitive("paddle_rnn")
rnn_p.multiple_results = True


@rnn_p.def_abstract_eval
def _rnn_abstract(x, h0, c0, *weights, mode, hidden_size, num_layers,
                  is_bidirec, time_major, dropout):
    nd = 2 if is_bidirec else 1
    if time_major:
        T, B = x.shape[0], x.shape[1]
        out_shape = (T, B, hidden_size * nd)
    else:
        B, T = x.shape[0], x.shape[1]
        out_shape = (B, T, hidden_size * nd)
    state = jax.core.ShapedArray((num_layers * nd, B, hidden_size),
                                 x.dtype)
    outs = [jax.core.ShapedArray(out_shape, x.dtype), state]
    if mode == "LSTM":
        outs.append(state)
    return outs


@rnn_p.def_impl
def _rnn_impl(*args, **kwargs):
    raise RuntimeError(
        "paddle_rnn is an export-tracing marker and is never executed; "
        "eager RNN layers run their traced time loop instead")
