"""ProgramDesc interpreter: run reference-era programs on TPU via jnp.

Reference counterpart: the single-thread `Executor::Run` op loop
(`framework/executor.cc:292`) + `NaiveExecutor` used by the inference
predictor (`inference/api/analysis_predictor.cc:889`).  TPU-native: the
whole block is interpreted ONCE under a jax trace (each op translated to
jnp / paddle_tpu functional calls), so the program compiles to a single
XLA computation — no per-op dispatch at run time.

Coverage (round 4): 403/487 reference op types (the CI floor in
`tools/op_inventory.py --program-form-floor` is the authoritative
number) — the hand-written
translators here plus the declarative OpDesc→eager bridge
(`op_bridge.py`, imported at the end of this module); the remainder are
documented in `op_bridge.PROGRAM_FORM_NA`.  Unknown ops raise with the
op name so coverage gaps stay explicit;
`tools/op_inventory.py --program-form-floor` gates the count in CI.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

OP_TRANSLATORS: Dict[str, Callable] = {}

# op types whose OUTPUT SHAPE depends on input VALUES (XLA cannot trace
# them); ProgramRunner falls back to op-by-op execution for programs
# containing one.  op_bridge extends this set as it registers such ops.
DYNAMIC_SHAPE_OPS = {"masked_select", "where_index", "unique",
                     "unique_with_counts", "linspace", "sequence_unpad"}


def register(*names):
    def deco(fn):
        for n in names:
            OP_TRANSLATORS[n] = fn
        return fn
    return deco


class OpView:
    """Convenience accessor over a decoded OpDesc dict."""

    def __init__(self, desc: Dict[str, Any]):
        self.desc = desc
        self.type = desc["type"]
        self._in = {v["parameter"]: v.get("arguments", [])
                    for v in desc.get("inputs", [])}
        self._out = {v["parameter"]: v.get("arguments", [])
                     for v in desc.get("outputs", [])}
        self._attrs = {}
        for a in desc.get("attrs", []):
            self._attrs[a["name"]] = _attr_value(a)

    def input(self, name, idx=0, default=None):
        args = self._in.get(name) or []
        return args[idx] if len(args) > idx else default

    def inputs(self, name):
        return self._in.get(name) or []

    def output(self, name, idx=0, default=None):
        args = self._out.get(name) or []
        return args[idx] if len(args) > idx else default

    def outputs(self, name):
        return self._out.get(name) or []

    def attr(self, name, default=None):
        return self._attrs.get(name, default)


def _attr_value(a: Dict[str, Any]):
    from .proto import AttrType as T

    t = a.get("type")
    if t == T.INT:
        return a.get("i", 0)
    if t == T.FLOAT:
        return a.get("f", 0.0)
    if t == T.STRING:
        return a.get("s", "")
    if t == T.INTS:
        return a.get("ints", [])
    if t == T.FLOATS:
        return a.get("floats", [])
    if t == T.STRINGS:
        return a.get("strings", [])
    if t == T.BOOLEAN:
        return a.get("b", False)
    if t == T.BOOLEANS:
        return a.get("bools", [])
    if t == T.LONG:
        return a.get("l", 0)
    if t == T.LONGS:
        return a.get("longs", [])
    if t == T.FLOAT64S:
        return a.get("float64s", [])
    if t == T.BLOCK:
        return a.get("block_idx", 0)
    if t == T.BLOCKS:
        return a.get("blocks_idx", [])
    return None


class FusedSlice:
    """coalesce_tensor output alias: a live view into the fused buffer
    (reference `operators/coalesce_tensor_op.cc` makes each Output a
    sub-tensor of FusedOutput, so a later write to the fused buffer —
    the fleet's single fused allreduce — must be observed by reads of
    the component vars).  Resolved lazily at scope-read time; a direct
    write to the component var replaces the view (same as the reference
    re-allocating the output away from the fused space)."""

    __slots__ = ("fused", "offset", "shape")

    def __init__(self, fused, offset, shape):
        self.fused = fused
        self.offset = int(offset)
        self.shape = tuple(int(s) for s in shape)

    def resolve(self, scope):
        buf = jnp.ravel(scope[self.fused])
        n = int(np.prod(self.shape)) if self.shape else 1
        return buf[self.offset:self.offset + n].reshape(self.shape)


class Scope(dict):
    """name -> jnp array."""

    def __getitem__(self, name):
        v = dict.__getitem__(self, name)
        if isinstance(v, FusedSlice):
            return v.resolve(self)
        return v

    def __setitem__(self, name, value):
        # two-way aliasing for coalesce_tensor components (reference
        # sub-tensors SHARE the fused storage): a write to a var that
        # currently holds a FusedSlice view lands in the fused buffer
        # — the fuse-grad-space layout has backward ops write component
        # grads BEFORE the fused allreduce reads the buffer
        cur = dict.get(self, name)
        if isinstance(cur, FusedSlice) and \
                not isinstance(value, FusedSlice):
            n = int(np.prod(cur.shape)) if cur.shape else 1
            flat = jnp.ravel(jnp.asarray(value))
            if flat.size == n and cur.fused in self:
                buf = jnp.ravel(self[cur.fused])
                self[cur.fused] = buf.at[
                    cur.offset:cur.offset + n].set(
                    flat.astype(buf.dtype))
                return  # the view stays live over the updated buffer
        dict.__setitem__(self, name, value)

    def update(self, other=(), **kw):
        # dict.update bypasses __setitem__ at the C level; route through
        # it so aliased writes keep their write-through semantics
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v

    def get(self, name, default=None):  # route through view resolution
        return self[name] if name in self else default

    def fetch(self, name):
        if name not in self:
            raise KeyError(f"variable {name!r} not produced by the program")
        return self[name]


def run_block(block_ops: List[Dict[str, Any]], scope: Scope,
              feeds: Dict[str, Any], fetch_holder: Dict[int, Any]):
    """Interpret a block's ops in order (program order IS execution order
    in the reference executor)."""
    for raw in block_ops:
        op = OpView(raw)
        fn = OP_TRANSLATORS.get(op.type)
        if fn is None:
            if op.type.endswith("_grad") and \
                    op.attr("__forward_op__") is not None:
                run_grad_op(op, scope, feeds, fetch_holder)
                continue
            raise NotImplementedError(
                f"ProgramDesc op {op.type!r} has no TPU translation yet")
        fn(op, scope, feeds, fetch_holder)
        _fold_consts(op)
        _propagate_lod(op, scope)


# Ops whose outputs keep row-for-row correspondence with their primary
# input, so the padded+lengths @LOD sidecar travels through them (the
# fluid DynamicRNN pattern applies lod_rank_table to an EMBEDDING output,
# not the raw feed).
_LOD_PRESERVING = {
    "lookup_table", "lookup_table_v2", "c_embedding", "cast", "scale",
    "assign", "dropout", "relu", "sigmoid", "tanh", "gelu", "softmax",
    "layer_norm", "matmul_v2", "matmul", "mul", "fc",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "reshape2", "reshape", "sequence_softmax",
}


def _propagate_lod(op: OpView, scope: Scope):
    if op.type not in _LOD_PRESERVING:
        return
    # the sidecar comes only from the ROW operand (X, or Ids for
    # embeddings) and lands only on outputs whose leading dim still
    # equals the batch — a reshape2 flatten or a matmul whose LoD
    # operand is Y must NOT inherit the lengths
    slot = "Ids" if op.type in ("lookup_table", "lookup_table_v2",
                                "c_embedding") else "X"
    name = op.input(slot)
    if not name or name + "@LOD" not in scope:
        return
    lod = scope[name + "@LOD"]
    b = lod.shape[0]
    for s in op.desc.get("outputs", []):
        for a in s.get("arguments", []):
            out = scope.get(a)
            if out is not None and getattr(out, "ndim", 0) >= 1 and \
                    out.shape[0] == b:
                scope[a + "@LOD"] = lod


def _consts() -> Dict[str, Any]:
    """Desc-level constant map for the active program run.  Under jit
    EVERYTHING in scope is a tracer (constants included), but ops whose
    value is defined purely by attrs (fill_constant chains) are
    statically known; translators that need static values (TensorArray
    write indices, while trip bounds) consult this instead of the
    scope."""
    c = getattr(_BLOCKS_TLS, "consts", None)
    if c is None:
        c = _BLOCKS_TLS.consts = {}
    return c


def _fold_consts(op: OpView):
    """Track outputs of statically-evaluable op chains as numpy values;
    any op outside the folding set invalidates its outputs."""
    from .proto import vartype_to_np_dtype

    c = _consts()
    t = op.type
    try:
        if t == "fill_constant":
            shape = [int(s) for s in op.attr("shape", [])]
            dt = vartype_to_np_dtype(op.attr("dtype", 5))
            c[op.output("Out")] = np.full(shape, op.attr("value", 0.0),
                                          dt)
            return
        if t == "assign_value":
            for key in ("fp32_values", "int32_values", "int64_values",
                        "bool_values"):
                vals = op.attr(key)
                if vals:
                    shape = [int(s) for s in op.attr("shape", [])]
                    c[op.output("Out")] = np.asarray(vals).reshape(shape)
                    return
        if t == "cast" and op.input("X") in c:
            c[op.output("Out")] = c[op.input("X")].astype(
                vartype_to_np_dtype(op.attr("out_dtype", 5)))
            return
        if t == "scale" and op.input("X") in c:
            x = c[op.input("X")]
            s, b = op.attr("scale", 1.0), op.attr("bias", 0.0)
            c[op.output("Out")] = (x * s + b) \
                if op.attr("bias_after_scale", True) else (x + b) * s
            return
        if t == "increment" and op.input("X") in c:
            x = c[op.input("X")]
            c[op.output("Out")] = x + np.asarray(
                op.attr("step", 1.0)).astype(x.dtype)
            return
        if t == "assign" and op.input("X") in c:
            c[op.output("Out")] = c[op.input("X")]
            return
    except Exception:
        pass
    for args in op._out.values():
        for a in args:
            c.pop(a, None)


GRAD_SUFFIX = "@GRAD"


def run_grad_op(op: OpView, scope: Scope, feeds, fetch_holder):
    """Generic grad-op executor: differentiate the embedded forward op by
    re-tracing its translator under jax.vjp (the TPU-native replacement
    for per-op GradOpMaker kernels — `fluid/backward.py:1015`).  Input
    gradients accumulate (the reference inserts sum ops for duplicated
    grads; here duplicate producers add in place)."""
    import json

    fwd = OpView(json.loads(op.attr("__forward_op__")))
    fwd_fn = OP_TRANSLATORS.get(fwd.type)
    if fwd_fn is None:
        raise NotImplementedError(
            f"grad of untranslated op {fwd.type!r}")

    in_args, seen = [], set()
    for p, args in fwd._in.items():
        for a in args:
            if a not in seen:
                seen.add(a)
                in_args.append(a)
    # differentiable = float arrays present in scope
    diff = [a for a in in_args if a in scope
            and jnp.issubdtype(jnp.asarray(scope[a]).dtype, jnp.inexact)]
    out_args = [a for p, args in fwd._out.items() for a in args]

    # discover which declared outputs the translator actually writes
    # (optional outputs may be skipped, e.g. batch_norm stats in eval)
    probe = Scope(scope)
    fwd_fn(fwd, probe, feeds, {})
    produced = [a for a in out_args if a in probe]

    def fwd_vals(vals):
        local = Scope(scope)
        for a, v in zip(diff, vals):
            local[a] = v
        fwd_fn(fwd, local, feeds, {})
        return tuple(local[a] for a in produced)

    primals = tuple(scope[a] for a in diff)
    outs, vjp = jax.vjp(fwd_vals, primals)
    # cotangents: @GRAD vars where produced, zeros otherwise (e.g. an
    # auxiliary output nobody differentiated through)
    def _conform(c, o):
        c = jnp.asarray(c).astype(o.dtype)
        if c.shape == o.shape:
            return c
        if c.size == o.size:  # e.g. the [1]-shaped loss seed vs scalar mean
            return c.reshape(o.shape)
        return jnp.broadcast_to(c, o.shape)

    cots = tuple(
        _conform(scope[a + GRAD_SUFFIX], o)
        if (a + GRAD_SUFFIX) in scope else jnp.zeros_like(o)
        for a, o in zip(produced, outs))
    (gin,) = vjp(cots)
    # only materialize gradients the grad op DECLARES (no_grad_set pruning
    # removes slots from the op's outputs)
    declared = {a for p, args in op._out.items() for a in args}
    for a, g in zip(diff, gin):
        key = a + GRAD_SUFFIX
        if key not in declared:
            continue
        scope[key] = scope[key] + g if key in scope else g


class ProgramRunner:
    """Jit-compiled block interpreter: the whole program becomes ONE XLA
    computation per input signature (the NaiveExecutor op loop collapsed
    at trace time).  Shared by `static.Executor` and the inference
    Predictor."""

    def __init__(self, program, scope: Dict[str, Any], jit: bool = True,
                 donate_feeds: bool = False):
        """``jit=False`` interprets the block op-by-op without the
        whole-graph XLA compile (Config.switch_ir_optim(False) semantics —
        the reference's un-optimized NaiveExecutor loop);
        ``donate_feeds=True`` donates the feed buffers to the executable so
        outputs may alias them (Config.enable_memory_optim)."""
        self.program = program
        self.params = {k: jnp.asarray(v) for k, v in scope.items()}
        self.feed_names = program.feed_target_names()
        self.fetch_names = program.fetch_target_names()
        ops = program.desc["blocks"][0]["ops"]

        # data-dependent-output-shape ops (masked_select, unique, ...)
        # cannot live under an XLA trace; the reference executor runs
        # them fine because it dispatches op-by-op — fall back to that
        # mode (the un-jitted NaiveExecutor loop) when the program
        # contains one
        if jit:
            dyn = {o["type"] for blk in program.desc["blocks"]
                   for o in blk["ops"]} & DYNAMIC_SHAPE_OPS
            if dyn:
                import warnings

                warnings.warn(
                    f"program contains data-dependent-shape ops {sorted(dyn)}; "
                    "running op-by-op without whole-graph XLA compile")
                jit = False

        blocks = program.desc["blocks"]

        def pure(params, feeds):
            s = Scope(params)
            fetches: Dict[int, Any] = {}
            with blocks_context(blocks):
                run_block(ops, s, feeds, fetches)
            # also return the full scope (as a plain dict pytree) so the
            # Executor can satisfy fetch_list entries that aren't
            # fetch-op targets; indexing through the Scope resolves any
            # coalesce_tensor FusedSlice views into arrays
            return tuple(fetches[k] for k in sorted(fetches)), \
                {k: s[k] for k in s}

        if jit:
            self._jit = jax.jit(
                pure, donate_argnums=(1,) if donate_feeds else ())
        else:
            if donate_feeds:
                import warnings

                warnings.warn("donate_feeds requires the jit-compiled "
                              "runner; ignored with jit=False")
            self._jit = pure

    def __call__(self, *inputs):
        feeds = dict(zip(self.feed_names, (jnp.asarray(i) for i in inputs)))
        outs, _ = self._jit(self.params, feeds)
        return outs

    def run_with_lods(self, inputs, lods, return_lods=False):
        """Run with per-feed sequence lengths (`<name>@LOD` sidecars,
        the padded+lengths LoD redesign — Predictor handle set_lod).
        With ``return_lods``, also return each fetch target's output
        lengths sidecar (for ZeroCopyTensor::lod on output handles)."""
        feeds = dict(zip(self.feed_names, (jnp.asarray(i) for i in inputs)))
        for name, lengths in lods.items():
            lengths = jnp.asarray(lengths)
            if name in feeds and lengths.shape[0] != feeds[name].shape[0]:
                raise ValueError(
                    f"set_lod for {name!r}: {lengths.shape[0]} sequence "
                    f"lengths for a batch of {feeds[name].shape[0]} rows")
            feeds[name + "@LOD"] = lengths
        outs, scope = self._jit(self.params, feeds)
        if not return_lods:
            return outs
        out_lods = [scope.get(fn + "@LOD") for fn in self.fetch_names]
        return outs, out_lods

    def run_with_scope(self, feeds, params=None):
        """`params` overrides the construction-time parameter values, so
        callers can update weights between runs — the static training
        loop.  Keys beyond the construction set (e.g. optimizer slot vars
        the program created on its first run) are merged in too; a new
        key changes the pytree structure and costs one retrace, after
        which the structure is stable."""
        if params is not None:
            merged = dict(self.params)
            merged.update({k: jnp.asarray(v) for k, v in params.items()})
            params = merged
        outs, scope = self._jit(params or self.params, feeds)
        return outs, scope


def _t(x):
    from ..core.tensor import Tensor

    return Tensor(x)


def _u(t):
    from ..core.tensor import Tensor

    return t._array if isinstance(t, Tensor) else jnp.asarray(t)


# ---------------------------------------------------------------------------
# feed / fetch / data movement
# ---------------------------------------------------------------------------
@register("feed")
def _feed(op, scope, feeds, fetches):
    name = op.output("Out")
    if name not in feeds:
        raise KeyError(f"feed variable {name!r} missing from feed dict")
    scope[name] = jnp.asarray(feeds[name])
    # padded+lengths LoD sidecar (Predictor handle set_lod): travels with
    # the feed for the lod_* op family
    if name + "@LOD" in feeds:
        scope[name + "@LOD"] = jnp.asarray(feeds[name + "@LOD"])


@register("fetch")
def _fetch(op, scope, feeds, fetches):
    col = op.attr("col", 0)
    fetches[col] = scope.fetch(op.input("X"))


@register("assign", "share_data", "memcpy")
def _assign(op, scope, feeds, fetches):
    scope[op.output("Out")] = scope.fetch(op.input("X"))


@register("assign_value")
def _assign_value(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    for key in ("fp32_values", "int32_values", "int64_values",
                "bool_values"):
        vals = op.attr(key)
        if vals:
            scope[op.output("Out")] = jnp.asarray(
                np.asarray(vals).reshape(shape)).astype(dtype)
            return
    scope[op.output("Out")] = jnp.zeros(shape, dtype)


@register("fill_constant")
def _fill_constant(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    scope[op.output("Out")] = jnp.full(shape, op.attr("value", 0.0), dtype)


@register("fill_any_like")
def _fill_any_like(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.full_like(x, op.attr("value", 0.0))


@register("cast")
def _cast(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = x.astype(
        vartype_to_np_dtype(op.attr("out_dtype", 5)))


@register("shape")
def _shape(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    scope[op.output("Out")] = jnp.asarray(x.shape, jnp.int32)


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------
@register("mul")
def _mul(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    xm = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    ym = y.reshape((int(np.prod(y.shape[:ync])), -1))
    out = xm @ ym
    scope[op.output("Out")] = out.reshape(
        tuple(x.shape[:xnc]) + tuple(y.shape[ync:]))


@register("matmul")
def _matmul(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    if op.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if op.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y) * op.attr("alpha", 1.0)
    scope[op.output("Out")] = out


@register("matmul_v2")
def _matmul_v2(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    if op.attr("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if op.attr("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    scope[op.output("Out")] = jnp.matmul(x, y)


@register("fc")
def _fc(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("W"))
    in_num_col_dims = op.attr("in_num_col_dims", 1)
    xm = x.reshape((int(np.prod(x.shape[:in_num_col_dims])), -1))
    out = xm @ w
    b = op.input("Bias")
    if b:
        out = out + scope.fetch(b)
    act = op.attr("activation_type", "")
    if act == "relu":
        out = jnp.maximum(out, 0)
    scope[op.output("Out")] = out.reshape(
        tuple(x.shape[:in_num_col_dims]) + (w.shape[1],))


# ---------------------------------------------------------------------------
# elementwise / unary
# ---------------------------------------------------------------------------
def _broadcast_ew(op, scope, fn):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    axis = op.attr("axis", -1)
    if axis != -1 and y.ndim < x.ndim:
        # reference broadcast: align y's dims starting at `axis`
        shape = [1] * x.ndim
        for i, d in enumerate(y.shape):
            shape[axis + i] = d
        y = y.reshape(shape)
    scope[op.output("Out")] = fn(x, y)


for _name, _fn in [
    ("elementwise_add", jnp.add), ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply), ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum), ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
    ("elementwise_mod", jnp.mod),
    ("elementwise_floordiv", jnp.floor_divide),
]:
    def _mk(fn):
        def _op(op, scope, feeds, fetches):
            _broadcast_ew(op, scope, fn)
        return _op
    OP_TRANSLATORS[_name] = _mk(_fn)

for _name, _fn in [
    ("relu", lambda x: jnp.maximum(x, 0)),
    ("sigmoid", jax.nn.sigmoid), ("tanh", jnp.tanh),
    ("sqrt", jnp.sqrt), ("rsqrt", jax.lax.rsqrt),
    ("square", jnp.square), ("abs", jnp.abs), ("exp", jnp.exp),
    ("log", jnp.log), ("floor", jnp.floor), ("ceil", jnp.ceil),
    ("round", jnp.round), ("reciprocal", lambda x: 1.0 / x),
    ("softsign", lambda x: x / (1 + jnp.abs(x))),
    ("softplus", jax.nn.softplus), ("silu", jax.nn.silu),
    ("logsigmoid", jax.nn.log_sigmoid),
    ("relu6", lambda x: jnp.clip(x, 0, 6)),
    ("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x))),
    ("sin", jnp.sin), ("cos", jnp.cos), ("erf", jax.scipy.special.erf),
    ("sign", jnp.sign),
]:
    def _mk1(fn):
        def _op(op, scope, feeds, fetches):
            scope[op.output("Out")] = fn(scope.fetch(op.input("X")))
        return _op
    OP_TRANSLATORS[_name] = _mk1(_fn)


@register("gelu")
def _gelu(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.nn.gelu(
        x, approximate=op.attr("approximate", False))


@register("leaky_relu")
def _leaky_relu(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    alpha = op.attr("alpha", 0.02)
    if op.attr("__legacy_formula__", False):
        # pre-version-1 programs (op_version.py): out = max(x, alpha*x),
        # which differs when alpha < 0 or alpha > 1
        scope[op.output("Out")] = jnp.maximum(x, alpha * x)
        return
    scope[op.output("Out")] = jnp.where(x > 0, x, alpha * x)


@register("prelu")
def _prelu(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    alpha = scope.fetch(op.input("Alpha"))
    mode = op.attr("mode", "all")
    if mode == "channel" and alpha.size > 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    scope[op.output("Out")] = jnp.where(x > 0, x, alpha * x)


@register("hard_sigmoid")
def _hard_sigmoid(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    slope = op.attr("slope", 0.2)
    offset = op.attr("offset", 0.5)
    scope[op.output("Out")] = jnp.clip(slope * x + offset, 0.0, 1.0)


@register("hard_swish")
def _hard_swish(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    threshold = op.attr("threshold", 6.0)
    scale = op.attr("scale", 6.0)
    offset = op.attr("offset", 3.0)
    scope[op.output("Out")] = x * jnp.clip(x + offset, 0,
                                           threshold) / scale


@register("swish")
def _swish(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    beta = op.attr("beta", 1.0)
    scope[op.output("Out")] = x * jax.nn.sigmoid(beta * x)


@register("scale")
def _scale(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    s = op.attr("scale", 1.0)
    b = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        out = x * s + b
    else:
        out = (x + b) * s
    scope[op.output("Out")] = out


@register("clip")
def _clip(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.clip(x, op.attr("min", 0.0),
                                       op.attr("max", 0.0))


@register("pow")
def _pow(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.power(x, op.attr("factor", 1.0))


@register("sum")
def _sum(op, scope, feeds, fetches):
    xs = [scope.fetch(n) for n in op.inputs("X")]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    scope[op.output("Out")] = out


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------
@register("reshape", "reshape2")
def _reshape(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    shape = [int(s) for s in op.attr("shape", [])]
    # 0 means "copy input dim" in the reference reshape
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    scope[op.output("Out")] = x.reshape(shape)


@register("transpose", "transpose2")
def _transpose(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.transpose(x, op.attr("axis", None))


@register("flatten2", "flatten", "flatten_contiguous_range")
def _flatten(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    if op.type == "flatten_contiguous_range":
        start = op.attr("start_axis", 1)
        stop = op.attr("stop_axis", -1)
        stop = stop % x.ndim
        shape = (x.shape[:start]
                 + (int(np.prod(x.shape[start:stop + 1])),)
                 + x.shape[stop + 1:])
    else:
        ax = op.attr("axis", 1)
        shape = (int(np.prod(x.shape[:ax])), int(np.prod(x.shape[ax:])))
    scope[op.output("Out")] = x.reshape(shape)


@register("squeeze", "squeeze2")
def _squeeze(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    axes = op.attr("axes", [])
    if axes:
        for ax in sorted((a % x.ndim for a in axes), reverse=True):
            if x.shape[ax] == 1:
                x = jnp.squeeze(x, axis=ax)
    else:
        x = jnp.squeeze(x)
    scope[op.output("Out")] = x


@register("unsqueeze", "unsqueeze2")
def _unsqueeze(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    for ax in sorted(op.attr("axes", [])):
        x = jnp.expand_dims(x, ax)
    scope[op.output("Out")] = x


@register("concat")
def _concat(op, scope, feeds, fetches):
    xs = [scope.fetch(n) for n in op.inputs("X")]
    scope[op.output("Out")] = jnp.concatenate(xs, axis=op.attr("axis", 0))


@register("split")
def _split(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    axis = op.attr("axis", 0)
    sections = op.attr("sections", [])
    num = op.attr("num", 0)
    outs = op._out.get("Out", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num or len(outs), axis=axis)
    for name, part in zip(outs, parts):
        scope[name] = part


@register("stack")
def _stack(op, scope, feeds, fetches):
    xs = [scope.fetch(n) for n in op.inputs("X")]
    scope[op.output("Y")] = jnp.stack(xs, axis=op.attr("axis", 0))


@register("slice")
def _slice(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    axes = op.attr("axes", [])
    starts = op.attr("starts", [])
    ends = op.attr("ends", [])
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = slice(int(s), int(min(e, x.shape[ax])))
    out = x[tuple(idx)]
    for ax in sorted(op.attr("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, axis=ax)
    scope[op.output("Out")] = out


@register("gather")
def _gather(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    idx = scope.fetch(op.input("Index"))
    scope[op.output("Out")] = jnp.take(x, idx.astype(jnp.int32), axis=0)


@register("expand_v2")
def _expand_v2(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    shape = [int(s) for s in op.attr("shape", [])]
    shape = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    scope[op.output("Out")] = jnp.broadcast_to(x, shape)


@register("tile")
def _tile(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.tile(x, op.attr("repeat_times", []))


# ---------------------------------------------------------------------------
# reductions / search
# ---------------------------------------------------------------------------
def _reduce(op, scope, fn):
    x = scope.fetch(op.input("X"))
    if op.attr("reduce_all", False):
        out = fn(x, axis=None, keepdims=op.attr("keep_dim", False))
    else:
        axes = tuple(op.attr("dim", [0]))
        out = fn(x, axis=axes, keepdims=op.attr("keep_dim", False))
    scope[op.output("Out")] = out


for _name, _fn in [("reduce_mean", jnp.mean), ("reduce_sum", jnp.sum),
                   ("reduce_max", jnp.max), ("reduce_min", jnp.min),
                   ("reduce_prod", jnp.prod)]:
    def _mkr(fn):
        def _op(op, scope, feeds, fetches):
            _reduce(op, scope, fn)
        return _op
    OP_TRANSLATORS[_name] = _mkr(_fn)


@register("mean")
def _mean(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.mean(scope.fetch(op.input("X")))


@register("arg_max")
def _arg_max(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    axis = op.attr("axis", -1)
    out = jnp.argmax(x, axis=int(axis))
    if op.attr("keepdims", False):
        out = jnp.expand_dims(out, int(axis))
    scope[op.output("Out")] = out.astype(jnp.int64)


@register("top_k", "top_k_v2")
def _top_k(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    k = op.attr("k", 1)
    vals, idx = jax.lax.top_k(x, int(k))
    scope[op.output("Out")] = vals
    scope[op.output("Indices")] = idx.astype(jnp.int64)


# comparison family
for _name, _fn in [("equal", jnp.equal), ("not_equal", jnp.not_equal),
                   ("less_than", jnp.less), ("less_equal", jnp.less_equal),
                   ("greater_than", jnp.greater),
                   ("greater_equal", jnp.greater_equal)]:
    def _mkc(fn):
        def _op(op, scope, feeds, fetches):
            x = scope.fetch(op.input("X"))
            y = scope.fetch(op.input("Y"))
            scope[op.output("Out")] = fn(x, y)
        return _op
    OP_TRANSLATORS[_name] = _mkc(_fn)


# ---------------------------------------------------------------------------
# NN layers (delegate to paddle_tpu functional for exact semantics)
# ---------------------------------------------------------------------------
@register("conv2d", "depthwise_conv2d")
def _conv2d(op, scope, feeds, fetches):
    from ..nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    groups = op.attr("groups", 1)
    if op.type == "depthwise_conv2d" and groups in (0, 1):
        groups = x.shape[1]
    pad = op.attr("paddings", [0, 0])
    algo = op.attr("padding_algorithm", "EXPLICIT")
    if algo in ("SAME", "VALID"):
        pad = algo
    out = F.conv2d(_t(x), _t(w), None,
                   stride=op.attr("strides", [1, 1]),
                   padding=pad,
                   dilation=op.attr("dilations", [1, 1]),
                   groups=max(groups, 1))
    scope[op.output("Output")] = _u(out)


@register("conv2d_transpose")
def _conv2d_transpose(op, scope, feeds, fetches):
    from ..nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    out = F.conv2d_transpose(
        _t(x), _t(w), None, stride=op.attr("strides", [1, 1]),
        padding=op.attr("paddings", [0, 0]),
        dilation=op.attr("dilations", [1, 1]),
        groups=max(op.attr("groups", 1), 1))
    scope[op.output("Output")] = _u(out)


@register("batch_norm", "sync_batch_norm")
def _batch_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    mean = scope.fetch(op.input("Mean"))
    var = scope.fetch(op.input("Variance"))
    scale = scope.fetch(op.input("Scale"))
    bias = scope.fetch(op.input("Bias"))
    eps = op.attr("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = jax.lax.rsqrt(var + eps)
    out = (x - mean.reshape(shape)) * (inv * scale).reshape(shape) \
        + bias.reshape(shape)
    scope[op.output("Y")] = out


@register("layer_norm")
def _layer_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    begin = op.attr("begin_norm_axis", 1)
    eps = op.attr("epsilon", 1e-5)
    red = tuple(range(begin, x.ndim))
    mu = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=red, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    s = op.input("Scale")
    b = op.input("Bias")
    norm_shape = x.shape[begin:]
    if s:
        out = out * scope.fetch(s).reshape(norm_shape)
    if b:
        out = out + scope.fetch(b).reshape(norm_shape)
    scope[op.output("Y")] = out


@register("pool2d")
def _pool2d(op, scope, feeds, fetches):
    from ..nn import functional as F

    x = scope.fetch(op.input("X"))
    ptype = op.attr("pooling_type", "max")
    ksize = op.attr("ksize", [1, 1])
    if op.attr("global_pooling", False) or op.attr("adaptive", False) and \
            list(ksize) == [1, 1]:
        out = jnp.mean(x, axis=(2, 3), keepdims=True) if ptype == "avg" \
            else jnp.max(x, axis=(2, 3), keepdims=True)
        scope[op.output("Out")] = out
        return
    if op.attr("adaptive", False):
        out = F.adaptive_avg_pool2d(_t(x), ksize) if ptype == "avg" \
            else F.adaptive_max_pool2d(_t(x), ksize)
        scope[op.output("Out")] = _u(out)
        return
    kwargs = dict(kernel_size=ksize,
                  stride=op.attr("strides", [1, 1]),
                  padding=op.attr("paddings", [0, 0]),
                  ceil_mode=op.attr("ceil_mode", False))
    if ptype == "avg":
        out = F.avg_pool2d(_t(x), exclusive=op.attr("exclusive", True),
                           **kwargs)
    else:
        out = F.max_pool2d(_t(x), **kwargs)
    scope[op.output("Out")] = _u(out)


@register("softmax")
def _softmax(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.nn.softmax(x, axis=op.attr("axis", -1))


@register("log_softmax")
def _log_softmax(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.nn.log_softmax(x,
                                                 axis=op.attr("axis", -1))


@register("dropout")
def _dropout(op, scope, feeds, fetches):
    # inference: upscale_in_train => identity; downgrade => scale
    x = scope.fetch(op.input("X"))
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    p = op.attr("dropout_prob", 0.5)
    out = x if impl == "upscale_in_train" else x * (1.0 - p)
    scope[op.output("Out")] = out


@register("lookup_table", "lookup_table_v2")
def _lookup_table(op, scope, feeds, fetches):
    w = scope.fetch(op.input("W"))
    ids = scope.fetch(op.input("Ids"))
    if op.type == "lookup_table" and ids.shape[-1] == 1:
        ids = ids[..., 0]
    out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    pad = op.attr("padding_idx", -1)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    scope[op.output("Out")] = out


@register("softmax_with_cross_entropy")
def _softmax_ce(op, scope, feeds, fetches):
    logits = scope.fetch(op.input("Logits"))
    label = scope.fetch(op.input("Label"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    if op.attr("soft_label", False):
        loss = -(label * logp).sum(-1, keepdims=True)
    else:
        lab = label[..., 0] if label.shape[-1] == 1 else label
        loss = -jnp.take_along_axis(
            logp, lab.astype(jnp.int32)[..., None], axis=-1)
    scope[op.output("Softmax")] = jnp.exp(logp)
    scope[op.output("Loss")] = loss


@register("accuracy")
def _accuracy(op, scope, feeds, fetches):
    pred_idx = scope.fetch(op.input("Indices"))
    label = scope.fetch(op.input("Label"))
    correct = (pred_idx[:, :1].astype(jnp.int64)
               == label.astype(jnp.int64)).any(axis=1)
    scope[op.output("Accuracy")] = correct.mean(dtype=jnp.float32)
    if op.output("Correct"):
        scope[op.output("Correct")] = correct.sum().astype(jnp.int32)
    if op.output("Total"):
        scope[op.output("Total")] = jnp.asarray(label.shape[0], jnp.int32)


@register("nearest_interp", "nearest_interp_v2", "bilinear_interp",
          "bilinear_interp_v2")
def _interp(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    out_h = op.attr("out_h", -1)
    out_w = op.attr("out_w", -1)
    scale = op.attr("scale", [])
    if out_h <= 0 or out_w <= 0:
        if isinstance(scale, (int, float)):
            scale = [scale, scale]
        out_h = int(x.shape[2] * scale[0])
        out_w = int(x.shape[3] * scale[1])
    method = "nearest" if op.type.startswith("nearest") else "bilinear"
    out = jax.image.resize(x, x.shape[:2] + (out_h, out_w), method)
    scope[op.output("Out")] = out.astype(x.dtype)


@register("pad2d", "pad3d")
def _pad(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    pads = op.attr("paddings", [])
    mode = op.attr("mode", "constant")
    value = op.attr("pad_value", op.attr("value", 0.0))
    # NCHW: paddings = [top, bottom, left, right] (pad2d)
    if op.type == "pad2d":
        cfg = [(0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])]
    else:
        cfg = [(0, 0), (0, 0), (pads[4], pads[5]), (pads[2], pads[3]),
               (pads[0], pads[1])]
    if mode == "constant":
        out = jnp.pad(x, cfg, constant_values=value)
    else:
        jmode = {"reflect": "reflect", "edge": "edge",
                 "replicate": "edge"}[mode]
        out = jnp.pad(x, cfg, mode=jmode)
    scope[op.output("Out")] = out


@register("pixel_shuffle")
def _pixel_shuffle(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    r = op.attr("upscale_factor", 1)
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(
        n, c // (r * r), h * r, w * r)
    scope[op.output("Out")] = out


@register("uniform_random")
def _uniform_random(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    seed = op.attr("seed", 0)
    key = jax.random.PRNGKey(seed or 0)
    scope[op.output("Out")] = jax.random.uniform(
        key, shape, jnp.float32, op.attr("min", -1.0),
        op.attr("max", 1.0)).astype(dtype)


@register("gaussian_random")
def _gaussian_random(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    key = jax.random.PRNGKey(op.attr("seed", 0) or 0)
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * \
        jax.random.normal(key, shape, jnp.float32)
    scope[op.output("Out")] = out.astype(dtype)


@register("range")
def _range(op, scope, feeds, fetches):
    start = scope.fetch(op.input("Start")).reshape(())
    end = scope.fetch(op.input("End")).reshape(())
    step = scope.fetch(op.input("Step")).reshape(())
    # static-shape requirement: bounds must be compile-time constants
    scope[op.output("Out")] = jnp.arange(float(start), float(end),
                                         float(step))


@register("cumsum")
def _cumsum(op, scope, feeds, fetches):
    """reference `operators/cum_op.cc`: flatten/exclusive/reverse
    attrs (exclusive shifts the window by one; reverse accumulates
    from the far end)."""
    x = jnp.asarray(scope.fetch(op.input("X")))
    axis = op.attr("axis", -1)
    if op.attr("flatten", False):
        x, axis = x.reshape(-1), 0
    if op.attr("reverse", False):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if op.attr("exclusive", False):
        out = out - x
    if op.attr("reverse", False):
        out = jnp.flip(out, axis)
    scope[op.output("Out")] = out


# ---------------------------------------------------------------------------
# optimizer ops (reference operators/optimizers/) — executed in-program so
# Executor.run on a minimize()d program IS a training step; the Executor
# writes updated persistable vars back into its scope between runs.
# ---------------------------------------------------------------------------
@register("sgd")
def _sgd(op, scope, feeds, fetches):
    p = scope.fetch(op.input("Param"))
    g = scope.fetch(op.input("Grad"))
    lr = jnp.reshape(scope.fetch(op.input("LearningRate")), ())
    scope[op.output("ParamOut")] = p - lr * g


@register("momentum")
def _momentum_op(op, scope, feeds, fetches):
    p = scope.fetch(op.input("Param"))
    g = scope.fetch(op.input("Grad"))
    lr = jnp.reshape(scope.fetch(op.input("LearningRate")), ())
    vname = op.input("Velocity")
    v = scope.get(vname)
    if v is None:
        v = jnp.zeros_like(p)
    mu = op.attr("mu", 0.9)
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    scope[op.output("ParamOut")] = p_new
    scope[op.output("VelocityOut")] = v_new


# ---------------------------------------------------------------------------
# reductions / comparisons / logicals (reference reduce_ops/, controlflow/
# compare_op.cc + logical_op.cc macro families)
# ---------------------------------------------------------------------------
def _reduce_axes(op, x):
    if op.attr("reduce_all", False):
        return None
    dims = op.attr("dim", [0]) or [0]
    return tuple(int(d) % x.ndim for d in dims)


for _name, _red in [
    ("reduce_sum", jnp.sum), ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max), ("reduce_min", jnp.min),
    ("reduce_prod", jnp.prod), ("reduce_all", jnp.all),
    ("reduce_any", jnp.any),
]:
    def _mkr(red):
        def _op(op, scope, feeds, fetches):
            x = scope.fetch(op.input("X"))
            scope[op.output("Out")] = red(
                x, axis=_reduce_axes(op, x),
                keepdims=op.attr("keep_dim", False))
        return _op
    OP_TRANSLATORS[_name] = _mkr(_red)

for _name, _cmp in [
    ("equal", jnp.equal), ("not_equal", jnp.not_equal),
    ("less_than", jnp.less), ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater), ("greater_equal", jnp.greater_equal),
    ("logical_and", jnp.logical_and), ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    def _mkc(fn):
        def _op(op, scope, feeds, fetches):
            scope[op.output("Out")] = fn(scope.fetch(op.input("X")),
                                         scope.fetch(op.input("Y")))
        return _op
    OP_TRANSLATORS[_name] = _mkc(_cmp)


@register("logical_not")
def _logical_not(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.logical_not(scope.fetch(op.input("X")))


@register("where")
def _where(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.where(
        scope.fetch(op.input("Condition")), scope.fetch(op.input("X")),
        scope.fetch(op.input("Y")))


@register("fill_zeros_like", "fill_zeros_like2")
def _fill_zeros_like(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.zeros_like(scope.fetch(op.input("X")))


@register("clip_by_norm")
def _clip_by_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    mn = op.attr("max_norm", 1.0)
    n = jnp.sqrt(jnp.sum(jnp.square(x)))
    scope[op.output("Out")] = jnp.where(n > mn, x * (mn / n), x)


@register("p_norm")
def _p_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    p = op.attr("porder", 2.0)
    axis = op.attr("axis", -1)
    keep = op.attr("keepdim", False)
    eps = op.attr("epsilon", 1e-12)
    if op.attr("asvector", False):
        x = x.reshape(-1)
        axis = 0
    ax = jnp.abs(x)
    if p == float("inf"):
        out = ax.max(axis=axis, keepdims=keep)
    elif p == float("-inf"):
        out = ax.min(axis=axis, keepdims=keep)
    elif p == 0:
        out = (ax > 0).sum(axis=axis, keepdims=keep).astype(x.dtype)
    else:
        out = (jnp.sum(ax ** p, axis=axis, keepdims=keep)
               + eps) ** (1.0 / p)
    scope[op.output("Out")] = out


@register("norm")
def _norm_op(op, scope, feeds, fetches):
    # reference norm_op: l2-normalize along `axis`, Norm aux output
    x = scope.fetch(op.input("X"))
    axis = op.attr("axis", -1)
    eps = op.attr("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    scope[op.output("Out")] = x / n
    if op.output("Norm"):
        scope[op.output("Norm")] = n


@register("sigmoid_cross_entropy_with_logits")
def _sce_logits(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    label = scope.fetch(op.input("Label")).astype(x.dtype)
    # max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = op.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if op.attr("normalize", False):
        denom = jnp.maximum((label != ignore).sum(), 1)
        loss = loss / denom
    scope[op.output("Out")] = loss


@register("cross_entropy", "cross_entropy2")
def _cross_entropy_op(op, scope, feeds, fetches):
    # input X holds PROBABILITIES (softmax output) in the reference op
    x = scope.fetch(op.input("X"))
    label = scope.fetch(op.input("Label"))
    if op.attr("soft_label", False):
        loss = -(label * jnp.log(jnp.clip(x, 1e-12, None))).sum(
            -1, keepdims=True)
    else:
        ignore = op.attr("ignore_index", -100)
        # arbitrary leading dims (e.g. [N,T,C] sequence labeling, which
        # the reference op supports): flatten to (-1, C), restore after
        c = x.shape[-1]
        lead = x.shape[:-1]
        xf = x.reshape(-1, c)
        lab = label.reshape(-1).astype(jnp.int32)
        picked = jnp.take_along_axis(
            xf, jnp.clip(lab, 0, c - 1)[:, None], axis=-1)
        loss = -jnp.log(jnp.clip(picked, 1e-12, None))
        loss = jnp.where(lab[:, None] == ignore, 0.0, loss)
        loss = loss.reshape(lead + (1,))
    scope[op.output("Y") or op.output("Out")] = loss


@register("group_norm")
def _group_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    groups = op.attr("groups", 1)
    eps = op.attr("epsilon", 1e-5)
    n, c = x.shape[:2]
    xg = x.reshape(n, groups, -1)
    mu = xg.mean(-1, keepdims=True)
    var = xg.var(-1, keepdims=True)
    out = ((xg - mu) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    if op.input("Scale"):
        s = scope.fetch(op.input("Scale")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
        out = out * s
    if op.input("Bias"):
        b = scope.fetch(op.input("Bias")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
        out = out + b
    scope[op.output("Y")] = out


@register("instance_norm")
def _instance_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    eps = op.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mu = x.mean(axes, keepdims=True)
    var = x.var(axes, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    c = x.shape[1]
    if op.input("Scale"):
        out = out * scope.fetch(op.input("Scale")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
    if op.input("Bias"):
        out = out + scope.fetch(op.input("Bias")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
    scope[op.output("Y")] = out


def _via_functional(fn, *tensors, **kw):
    """Run a paddle_tpu functional op inside the interp trace and return
    the raw array(s) (dispatch handles tracers transparently)."""
    from ..core.tensor import unwrap

    out = fn(*tensors, **kw)
    if isinstance(out, tuple):
        return tuple(unwrap(o) for o in out)
    return unwrap(out)


@register("grid_sampler")
def _grid_sampler(op, scope, feeds, fetches):
    from ..nn.functional.common import grid_sample

    scope[op.output("Output")] = _via_functional(
        grid_sample, scope.fetch(op.input("X")),
        scope.fetch(op.input("Grid")),
        mode=op.attr("mode", "bilinear"),
        padding_mode=op.attr("padding_mode", "zeros"),
        align_corners=op.attr("align_corners", True))


@register("roi_align")
def _roi_align_op(op, scope, feeds, fetches):
    from ..vision.ops import roi_align

    rois = scope.fetch(op.input("ROIs"))
    if op.input("RoisNum"):
        num = scope.fetch(op.input("RoisNum"))
    else:
        # the fluid-era form carries per-image ROI counts via LoD, which
        # this padded representation doesn't retain — only the
        # single-image case is unambiguous without RoisNum
        if scope.fetch(op.input("X")).shape[0] != 1:
            raise NotImplementedError(
                "roi_align without RoisNum needs batch size 1 "
                "(LoD-carried ROI counts are not representable here)")
        num = jnp.asarray([rois.shape[0]], jnp.int32)
    scope[op.output("Out")] = _via_functional(
        roi_align, scope.fetch(op.input("X")), rois, num,
        (op.attr("pooled_height", 1), op.attr("pooled_width", 1)),
        spatial_scale=op.attr("spatial_scale", 1.0),
        sampling_ratio=op.attr("sampling_ratio", -1),
        aligned=op.attr("aligned", True))


@register("box_coder")
def _box_coder_op(op, scope, feeds, fetches):
    from ..vision.ops import box_coder

    out = _via_functional(
        box_coder, scope.fetch(op.input("PriorBox")),
        scope.fetch(op.input("PriorBoxVar"))
        if op.input("PriorBoxVar") else None,
        scope.fetch(op.input("TargetBox")),
        code_type=op.attr("code_type", "encode_center_size"),
        box_normalized=op.attr("box_normalized", True),
        axis=op.attr("axis", 0))
    scope[op.output("OutputBox")] = out


@register("prior_box")
def _prior_box_op(op, scope, feeds, fetches):
    from ..vision.ops import prior_box

    boxes, var = _via_functional(
        prior_box, scope.fetch(op.input("Input")),
        scope.fetch(op.input("Image")),
        min_sizes=op.attr("min_sizes", []),
        max_sizes=op.attr("max_sizes", []) or None,
        aspect_ratios=op.attr("aspect_ratios", [1.0]),
        variance=op.attr("variances", [0.1, 0.1, 0.2, 0.2]),
        flip=op.attr("flip", False), clip=op.attr("clip", False),
        steps=(op.attr("step_w", 0.0), op.attr("step_h", 0.0)),
        offset=op.attr("offset", 0.5),
        min_max_aspect_ratios_order=op.attr("min_max_aspect_ratios_order",
                                            False))
    scope[op.output("Boxes")] = boxes
    scope[op.output("Variances")] = var


@register("yolo_box")
def _yolo_box_op(op, scope, feeds, fetches):
    from ..vision.ops import yolo_box

    if op.attr("iou_aware", False):
        raise NotImplementedError(
            "yolo_box iou_aware=True (PP-YOLO layout) is not translated")
    boxes, scores = _via_functional(
        yolo_box, scope.fetch(op.input("X")),
        scope.fetch(op.input("ImgSize")),
        anchors=op.attr("anchors", []),
        class_num=op.attr("class_num", 1),
        conf_thresh=op.attr("conf_thresh", 0.01),
        downsample_ratio=op.attr("downsample_ratio", 32),
        clip_bbox=op.attr("clip_bbox", True),
        scale_x_y=op.attr("scale_x_y", 1.0))
    scope[op.output("Boxes")] = boxes
    scope[op.output("Scores")] = scores


@register("multiclass_nms", "multiclass_nms2", "multiclass_nms3")
def _multiclass_nms_op(op, scope, feeds, fetches):
    from ..vision.detection import multiclass_nms2

    if op.input("RoisNum"):
        raise NotImplementedError(
            "multiclass_nms with LoD-batched RoisNum input is not "
            "supported; export with dense [N, M, 4] boxes")
    want_index = bool(op.output("Index"))
    res = _via_functional(
        multiclass_nms2, scope.fetch(op.input("BBoxes")),
        scope.fetch(op.input("Scores")),
        op.attr("score_threshold", 0.05), op.attr("nms_top_k", 1000),
        op.attr("keep_top_k", 100),
        nms_threshold=op.attr("nms_threshold", 0.3),
        normalized=op.attr("normalized", True),
        nms_eta=op.attr("nms_eta", 1.0),
        background_label=op.attr("background_label", 0),
        return_index=want_index)
    if want_index:
        out, counts, index = res
        scope[op.output("Index")] = index
    else:
        out, counts = res
    scope[op.output("Out")] = out
    if op.output("NmsRoisNum"):
        scope[op.output("NmsRoisNum")] = counts


# ---------------------------------------------------------------------------
# Control flow: while / conditional_block / TensorArray family / recurrent /
# lstm / gru / beam search.
#
# Reference: `operators/controlflow/while_op.cc:59` (step-scope loop),
# `conditional_block_op.cc:29`, `tensor_array_read_write_op.cc`,
# `tensor_array_to_tensor_op.cc`, `recurrent_op.cc`, `lstm_op.cc`,
# `gru_op.cc`, `beam_search_op.cc`, `beam_search_decode_op.cc:123`.
#
# TPU-native redesign: the reference executes these with dynamic scopes and
# growing LoDTensorArrays; under XLA everything must be static-shaped, so
#  * `while`   -> `lax.while_loop` whose carry is the set of outer vars the
#    body writes (the step-scope/parent-scope write-back collapsed);
#  * TensorArray -> a fixed-capacity [cap, ...] buffer + dynamic length
#    (the LoD padded+lengths stance applied to arrays).  Outside a while,
#    writes at trace-time-constant indices grow the buffer; inside, the
#    while translator pre-creates buffers with capacity inferred from the
#    loop bound (the `less_than(i, max_len)` feeding Condition), or
#    FLAGS_interp_tensor_array_capacity as a fallback;
#  * `recurrent`/`lstm`/`gru` -> `lax.scan` over the time axis;
#  * beam search -> fixed beam width K, finished-beam masking, with parent
#    pointers carried in an explicit "ParentIdx" TensorArray instead of
#    LoD levels (`beam_search_decode` backtraces it with a reverse scan).
# ---------------------------------------------------------------------------
import contextlib as _contextlib
import threading as _threading

_BLOCKS_TLS = _threading.local()


@_contextlib.contextmanager
def blocks_context(blocks):
    prev = getattr(_BLOCKS_TLS, "blocks", None)
    prev_c = getattr(_BLOCKS_TLS, "consts", None)
    prev_b = getattr(_BLOCKS_TLS, "bounds", None)
    _BLOCKS_TLS.blocks = blocks
    _BLOCKS_TLS.consts = {}
    _BLOCKS_TLS.bounds = {}
    try:
        yield
    finally:
        _BLOCKS_TLS.blocks = prev
        _BLOCKS_TLS.consts = prev_c
        _BLOCKS_TLS.bounds = prev_b


def _current_blocks():
    blocks = getattr(_BLOCKS_TLS, "blocks", None)
    if blocks is None:
        raise RuntimeError(
            "control-flow op interpreted outside a program context; run "
            "through ProgramRunner / static.Executor / the Predictor")
    return blocks


@jax.tree_util.register_pytree_node_class
class TensorArrayVal:
    """Static-capacity stand-in for the reference LoDTensorArray: a
    [capacity, *elem] buffer plus a dynamic int32 length."""

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length

    def tree_flatten(self):
        return (self.buffer, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"TensorArrayVal({self.buffer.shape}, len={self.length})"


def _is_concrete(x):
    return not isinstance(x, jax.core.Tracer)


_TA_CREATE_CAP_TLS = _threading.local()


@register("write_to_array")
def _write_to_array(op, scope, feeds, fetches):
    x = jnp.asarray(scope.fetch(op.input("X")))
    i = jnp.asarray(scope.fetch(op.input("I"))).reshape(()).astype(jnp.int32)
    # desc-level constant index (fill_constant chains): lets top-level
    # writes size/grow the buffer statically even though every scope
    # value is a tracer under jit
    i_const = _consts().get(op.input("I"))
    if i_const is not None:
        i_const = int(np.asarray(i_const).reshape(-1)[0])
    name = op.output("Out")
    arr = scope.get(name)
    if not isinstance(arr, TensorArrayVal):
        if i_const is not None:
            cap = i_const + 1
        else:
            cap = getattr(_TA_CREATE_CAP_TLS, "cap", None)
            if cap is None:
                raise NotImplementedError(
                    f"write_to_array into {name!r} at a dynamic index but "
                    "the array was not pre-created; writes inside `while` "
                    "require the loop bound to be inferable (a "
                    "less_than/less_equal feeding Condition with a "
                    "statically-known bound) or "
                    "FLAGS_interp_tensor_array_capacity set")
        arr = TensorArrayVal(jnp.zeros((cap,) + x.shape, x.dtype),
                             jnp.zeros((), jnp.int32))
    cap = arr.buffer.shape[0]
    if x.shape != arr.buffer.shape[1:]:
        raise ValueError(
            f"write_to_array {name!r}: element shape {x.shape} != array "
            f"element shape {arr.buffer.shape[1:]} (static-shape arrays "
            "require uniform elements)")
    if i_const is not None and i_const >= cap:
        grow = i_const + 1 - cap
        arr = TensorArrayVal(
            jnp.concatenate(
                [arr.buffer, jnp.zeros((grow,) + x.shape, x.dtype)]),
            arr.length)
    buf = jax.lax.dynamic_update_index_in_dim(
        arr.buffer, x.astype(arr.buffer.dtype), i, 0)
    scope[name] = TensorArrayVal(buf, jnp.maximum(arr.length, i + 1))


@register("read_from_array")
def _read_from_array(op, scope, feeds, fetches):
    arr = scope.fetch(op.input("X"))
    if not isinstance(arr, TensorArrayVal):
        raise TypeError(f"read_from_array: {op.input('X')!r} is not a "
                        "TensorArray")
    i = jnp.asarray(scope.fetch(op.input("I"))).reshape(()).astype(jnp.int32)
    scope[op.output("Out")] = jax.lax.dynamic_index_in_dim(
        arr.buffer, i, 0, keepdims=False)


@register("lod_array_length")
def _lod_array_length(op, scope, feeds, fetches):
    arr = scope.fetch(op.input("X"))
    scope[op.output("Out")] = arr.length.reshape(1).astype(jnp.int64)


@register("tensor_array_to_tensor")
def _tensor_array_to_tensor(op, scope, feeds, fetches):
    """Stack/concat the array.  With a trace-time-constant length the
    exact [length, ...] prefix is emitted; a dynamic length (array built
    in a `while`) emits the full capacity-padded buffer (padded+lengths
    stance) with OutIndex carrying the true length."""
    arr = scope.fetch(op.input("X"))
    axis = op.attr("axis", 0)
    use_stack = op.attr("use_stack", False)
    buf, n = arr.buffer, arr.length
    if _is_concrete(n):
        buf = buf[: int(n)]
    elems = buf.shape[0]
    if use_stack:
        out = jnp.moveaxis(buf, 0, axis) if axis else buf
    elif elems:
        out = jnp.concatenate([buf[i] for i in range(elems)], axis=axis)
    else:
        shape = list(buf.shape[1:])
        shape[axis if axis >= 0 else axis + len(shape)] = 0
        out = jnp.zeros(shape, buf.dtype)
    scope[op.output("Out")] = out
    if op.output("OutIndex"):
        scope[op.output("OutIndex")] = n.reshape(1).astype(jnp.int32)


@register("increment")
def _increment(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = x + jnp.asarray(
        op.attr("step", 1.0)).astype(x.dtype)


@register("is_empty")
def _is_empty(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.asarray([x.size == 0])


@register("select_input")
def _select_input(op, scope, feeds, fetches):
    """reference `operators/select_input_op.cc`: Out = X[Mask]."""
    mask = jnp.asarray(scope.fetch(op.input("Mask"))).reshape(
        ()).astype(jnp.int32)
    xs = [scope.fetch(n) for n in op.inputs("X")]
    scope[op.output("Out")] = jax.lax.switch(
        jnp.clip(mask, 0, len(xs) - 1),
        [lambda x=x: x for x in xs])


@register("select_output")
def _select_output(op, scope, feeds, fetches):
    """reference `operators/select_output_op.cc` routes X to Out[Mask];
    in the traced world every branch executes, so X is written to every
    listed output — only the branch later chosen by `select_input`
    reaches the program outputs."""
    x = scope.fetch(op.input("X"))
    for n in op._out.get("Out", []):
        scope[n] = x


def _sub_block_ops(op):
    return _current_blocks()[op.attr("sub_block", 0)]["ops"]


def _block_written_names(ops):
    out: List[str] = []
    seen = set()
    for raw in ops:
        for slot in raw.get("outputs", []):
            for a in slot.get("arguments", []):
                if a not in seen:
                    seen.add(a)
                    out.append(a)
    return out


@register("conditional_block", "conditional_block_infer")
def _conditional_block(op, scope, feeds, fetches):
    """reference `operators/controlflow/conditional_block_op.cc:29`.
    Out vars that don't pre-exist get zeros on the false path (the fluid
    `cond` layer pairs two conditional_blocks and reconciles with
    select_input, so only the taken branch's values survive)."""
    sub = _sub_block_ops(op)
    out_names = [n for n in op._out.get("Out", [])]

    def _run_sub():
        local = Scope(scope)
        run_block(sub, local, feeds, {})
        return tuple(jnp.asarray(local.fetch(n)) for n in out_names)

    if not op.attr("is_scalar_condition", False):
        # reference: the non-scalar mode gates on ALL Input tensors being
        # non-empty (`conditional_block_op.cc` need_run = numel != 0) —
        # numel is static under XLA, so this resolves at trace time
        need_run = all(
            jnp.asarray(scope.fetch(n)).size != 0
            for n in op.inputs("Input"))
        if need_run:
            for n, v in zip(out_names, _run_sub()):
                scope[n] = v
        return

    pred = jnp.asarray(scope.fetch(op.input("Cond"))).reshape(())
    missing = [n for n in out_names if n not in scope]
    shapes = jax.eval_shape(_run_sub) if missing else None

    def _true():
        return _run_sub()

    def _false():
        return tuple(
            jnp.asarray(scope[n]) if n in scope
            else jnp.zeros(s.shape, s.dtype)
            for n, s in zip(out_names,
                            shapes or [None] * len(out_names)))

    outs = jax.lax.cond(pred.astype(bool), _true, _false)
    for n, v in zip(out_names, outs):
        scope[n] = v


def _infer_trip_bound(op, scope, sub_ops):
    """Upper bound on while trip count, for TensorArray capacity: find a
    less_than/less_equal writing the Condition var and read its RHS from
    the desc-level constant map; else
    FLAGS_interp_tensor_array_capacity."""
    cond_name = op.input("Condition")
    for raw in sub_ops:
        v = OpView(raw)
        if v.type in ("less_than", "less_equal") and \
                v.output("Out") == cond_name:
            y = _consts().get(v.input("Y"))
            if y is not None:
                bound = int(np.asarray(y).reshape(-1)[0])
            else:
                # a STATIC upper bound registered for the RHS (e.g.
                # max_sequence_len: dynamic value, static T_max)
                bound = _consts_bounds().get(v.input("Y"))
            if bound is not None:
                return int(bound) + (1 if v.type == "less_equal" else 0)
    from ..core import flags as _flags

    try:
        cap = int(_flags.flag("interp_tensor_array_capacity"))
    except Exception:
        cap = 0
    return cap if cap > 0 else None


@register("while")
def _while(op, scope, feeds, fetches):
    """reference `operators/controlflow/while_op.cc:59`.  The carry is
    every outer-scope var the body writes (the reference's step-scope
    write-back); iteration-local temporaries are recomputed per step.
    Loop-variant shapes are unsupported (XLA static shapes)."""
    sub = _sub_block_ops(op)
    cond_name = op.input("Condition")
    written = _block_written_names(sub)
    # anything the body writes is loop-dependent: drop stale desc-level
    # constants (e.g. the counter's fill_constant value)
    consts = _consts()
    for n in written:
        consts.pop(n, None)

    # pre-create TensorArrays the body writes (they must be loop carries
    # with static capacity before the loop starts)
    ta_targets = [OpView(r).output("Out") for r in sub
                  if r["type"] == "write_to_array"]
    missing_tas = [n for n in ta_targets
                   if not isinstance(scope.get(n), TensorArrayVal)]
    if missing_tas:
        bound = _infer_trip_bound(op, scope, sub)
        if bound is None:
            raise NotImplementedError(
                f"while: cannot infer a trip bound for TensorArray(s) "
                f"{missing_tas}; make the loop condition a "
                "less_than(i, bound) with a constant bound, or set "
                "FLAGS_interp_tensor_array_capacity")

        def _abstract_body():
            local = Scope(scope)
            prev_cap = getattr(_TA_CREATE_CAP_TLS, "cap", None)
            _TA_CREATE_CAP_TLS.cap = bound
            try:
                run_block(sub, local, feeds, {})
            finally:
                # restore (not clear): a nested while's abstract pass must
                # not clobber the enclosing pass's capacity
                _TA_CREATE_CAP_TLS.cap = prev_cap
            return {n: local[n] for n in missing_tas}

        shapes = jax.eval_shape(_abstract_body)
        for n, s in shapes.items():
            scope[n] = TensorArrayVal(
                jnp.zeros(s.buffer.shape, s.buffer.dtype),
                jnp.zeros((), jnp.int32))

    carry_names = [n for n in written if n in scope]
    if cond_name not in carry_names:
        raise ValueError(
            f"while: body never updates Condition var {cond_name!r} "
            "(infinite loop in the source program?)")
    # while's declared Out vars must be loop carries — a body-written Out
    # with no pre-loop value can't be given a static carry shape, and
    # silently dropping it would surface as a confusing missing-var error
    # at some later fetch
    dropped = [n for n in op._out.get("Out", [])
               if n not in carry_names and n != op.output("StepScopes")]
    if dropped:
        raise ValueError(
            f"while: Out var(s) {dropped} are written by the body but "
            "have no value before the loop; initialize them (e.g. "
            "fill_constant) so they can join the loop carry")
    cond_idx = carry_names.index(cond_name)

    def _cond(carry):
        return jnp.asarray(carry[cond_idx]).reshape(()).astype(bool)

    def _body(carry):
        local = Scope(scope)
        local.update(zip(carry_names, carry))
        run_block(sub, local, feeds, {})
        return tuple(local[n] for n in carry_names)

    init = tuple(scope[n] for n in carry_names)
    final = jax.lax.while_loop(_cond, _body, init)
    for n, v in zip(carry_names, final):
        scope[n] = v


@register("recurrent")
def _recurrent(op, scope, feeds, fetches):
    """reference `operators/recurrent_op.cc` (StaticRNN): time-major
    inputs sliced per step, ex_states <- previous states, outputs stacked
    by name — `lax.scan` over dim 0."""
    sub = _sub_block_ops(op)
    in_names = op.inputs("inputs")
    init_names = op.inputs("initial_states")
    out_names = op._out.get("outputs", [])
    ex_names = op.attr("ex_states", []) or []
    st_names = op.attr("states", []) or []
    reverse = bool(op.attr("reverse", False))
    has_states = bool(op.attr("has_states", bool(st_names)))

    xs = tuple(jnp.asarray(scope.fetch(n)) for n in in_names)
    init = tuple(jnp.asarray(scope.fetch(n)) for n in init_names)

    def step(carry, xt):
        local = Scope(scope)
        local.update(zip(in_names, xt))
        if has_states:
            local.update(zip(ex_names, carry))
        run_block(sub, local, feeds, {})
        new_carry = tuple(local.fetch(n) for n in st_names) \
            if has_states else carry
        return new_carry, tuple(local.fetch(n) for n in out_names)

    _, ys = jax.lax.scan(step, init, xs, reverse=reverse)
    for n, y in zip(out_names, ys):
        scope[n] = y


def _rnn_act(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": lambda x: jnp.maximum(x, 0),
            "identity": lambda x: x}[name or "sigmoid"]


@register("lstm")
def _lstm_op(op, scope, feeds, fetches):
    """reference `operators/lstm_op.cc`: Input is the pre-projected
    x·W_x [*, 4D] sequence; Weight = {W_ch, W_ih, W_fh, W_oh} [D, 4D]
    (gate order c, i, f, o), Bias [1, 4D] (+{W_ic, W_fc, W_oc} when
    use_peepholes).  LoD redesign: Input is padded [B, T, 4D] (or a
    single [T, 4D] sequence); BatchGate/BatchCellPreAct (batch-reordered
    internals) are not materialized."""
    x = jnp.asarray(scope.fetch(op.input("Input")))
    w = jnp.asarray(scope.fetch(op.input("Weight")))
    d = w.shape[0]
    single = x.ndim == 2
    if single:
        x = x[None]
    b, t = x.shape[0], x.shape[1]
    gates_b = jnp.zeros((4 * d,), x.dtype)
    peep = op.attr("use_peepholes", True) and op.input("Bias")
    w_ic = w_fc = w_oc = None
    if op.input("Bias"):
        bias = jnp.asarray(scope.fetch(op.input("Bias"))).reshape(-1)
        gates_b = bias[: 4 * d]
        if peep and bias.size >= 7 * d:
            w_ic = bias[4 * d:5 * d]
            w_fc = bias[5 * d:6 * d]
            w_oc = bias[6 * d:7 * d]
    h0 = jnp.asarray(scope.fetch(op.input("H0"))) if op.input("H0") \
        else jnp.zeros((b, d), x.dtype)
    c0 = jnp.asarray(scope.fetch(op.input("C0"))) if op.input("C0") \
        else jnp.zeros((b, d), x.dtype)
    actg = _rnn_act(op.attr("gate_activation", "sigmoid"))
    actc = _rnn_act(op.attr("cell_activation", "tanh"))
    actn = _rnn_act(op.attr("candidate_activation", "tanh"))

    def step(carry, xt):
        h, c = carry
        g = xt + h @ w + gates_b
        gc, gi, gf, go = jnp.split(g, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = actg(gi)
        f = actg(gf)
        cand = actc(gc)
        c_new = f * c + i * cand
        if w_oc is not None:
            go = go + c_new * w_oc
        o = actg(go)
        h_new = o * actn(c_new)
        return (h_new, c_new), (h_new, c_new)

    reverse = bool(op.attr("is_reverse", False))
    _, (hs, cs) = jax.lax.scan(step, (h0, c0),
                               jnp.moveaxis(x, 1, 0), reverse=reverse)
    hidden = jnp.moveaxis(hs, 0, 1)
    cell = jnp.moveaxis(cs, 0, 1)
    if single:
        hidden, cell = hidden[0], cell[0]
    scope[op.output("Hidden")] = hidden
    if op.output("Cell"):
        scope[op.output("Cell")] = cell


@register("gru")
def _gru_op(op, scope, feeds, fetches):
    """reference `operators/gru_op.cc`: Input = pre-projected [*, 3D]
    (xu, xr, xc), Weight [D, 3D] = {W_u|W_r [D,2D], W_c [D,D]}.
    h_t = (1-u)h_{t-1} + u*h~ (origin_mode flips the blend)."""
    x = jnp.asarray(scope.fetch(op.input("Input")))
    w = jnp.asarray(scope.fetch(op.input("Weight")))
    d = w.shape[0]
    single = x.ndim == 2
    if single:
        x = x[None]
    b = x.shape[0]
    w_ur = w[:, : 2 * d]
    w_c = w[:, 2 * d:]
    bias = jnp.zeros((3 * d,), x.dtype)
    if op.input("Bias"):
        bias = jnp.asarray(scope.fetch(op.input("Bias"))).reshape(-1)
    h0 = jnp.asarray(scope.fetch(op.input("H0"))) if op.input("H0") \
        else jnp.zeros((b, d), x.dtype)
    actg = _rnn_act(op.attr("gate_activation", "sigmoid"))
    actn = _rnn_act(op.attr("activation", "tanh"))
    origin = bool(op.attr("origin_mode", False))

    def step(h, xt):
        xur = xt[:, : 2 * d] + h @ w_ur + bias[: 2 * d]
        u = actg(xur[:, :d])
        r = actg(xur[:, d:])
        cand = actn(xt[:, 2 * d:] + (r * h) @ w_c + bias[2 * d:])
        h_new = u * h + (1 - u) * cand if origin \
            else (1 - u) * h + u * cand
        return h_new, h_new

    reverse = bool(op.attr("is_reverse", False))
    _, hs = jax.lax.scan(step, h0, jnp.moveaxis(x, 1, 0), reverse=reverse)
    hidden = jnp.moveaxis(hs, 0, 1)
    if single:
        hidden = hidden[0]
    scope[op.output("Hidden")] = hidden


@register("beam_search")
def _beam_search(op, scope, feeds, fetches):
    """reference `operators/beam_search_op.cc`, static-shape redesign:
    fixed beam width K per source (no LoD shrinking); finished beams
    (pre_id == end_id) compete with their frozen score on the end_id
    column only.  parent_idx is the global [B*K] source-beam index."""
    k = int(op.attr("beam_size", 4))
    end_id = int(op.attr("end_id", 1))
    is_acc = bool(op.attr("is_accumulated", True))
    pre_ids = jnp.asarray(scope.fetch(op.input("pre_ids"))).reshape(-1)
    pre_scores = jnp.asarray(
        scope.fetch(op.input("pre_scores"))).reshape(-1)
    scores = jnp.asarray(scope.fetch(op.input("scores")))
    bk, v = scores.shape
    bsz = bk // k
    acc = scores.astype(jnp.float32) if is_acc else \
        pre_scores[:, None] + jnp.log(
            jnp.clip(scores.astype(jnp.float32), 1e-20, None))
    finished = pre_ids == end_id
    neg = jnp.full_like(acc, -1e30)
    acc = jnp.where(finished[:, None], neg, acc)
    acc = acc.at[:, end_id].set(
        jnp.where(finished, pre_scores, acc[:, end_id]))
    top_s, top_i = jax.lax.top_k(acc.reshape(bsz, k * v), k)
    parent_local = top_i // v
    token = (top_i % v).astype(pre_ids.dtype)
    parent = (jnp.arange(bsz, dtype=jnp.int32)[:, None] * k +
              parent_local.astype(jnp.int32)).reshape(bk)
    scope[op.output("selected_ids")] = token.reshape(bk, 1)
    scope[op.output("selected_scores")] = top_s.reshape(bk, 1)
    if op.output("parent_idx"):
        scope[op.output("parent_idx")] = parent


@register("beam_search_decode")
def _beam_search_decode(op, scope, feeds, fetches):
    """reference `operators/beam_search_decode_op.cc:123`.  The reference
    backtracks parent pointers encoded in the Ids array's LoD levels; the
    static redesign carries them in an explicit ParentIdx TensorArray
    (written per step by the search loop).  SentenceIds is [B, K, T_cap]
    end_id-padded; SentenceScores [B, K] is each surviving beam's final
    accumulated score."""
    end_id = int(op.attr("end_id", 1))
    k = int(op.attr("beam_size", 4))
    ids_ta = scope.fetch(op.input("Ids"))
    scores_ta = scope.fetch(op.input("Scores"))
    if not op.input("ParentIdx"):
        raise NotImplementedError(
            "beam_search_decode requires the ParentIdx TensorArray input "
            "in the static-shape redesign (LoD parent chains are not "
            "representable); wire the beam_search op's parent_idx output "
            "through a write_to_array")
    par_ta = scope.fetch(op.input("ParentIdx"))
    t_cap = ids_ta.buffer.shape[0]
    bk = int(np.prod(ids_ta.buffer.shape[1:]))
    bsz = bk // k
    ids = ids_ta.buffer.reshape(t_cap, bk)
    par = par_ta.buffer.reshape(t_cap, bk).astype(jnp.int32)
    length = ids_ta.length

    def back(beam, xs):
        t_ids, t_par, t = xs
        valid = t < length
        tok = jnp.where(valid, t_ids[beam], end_id)
        nxt = jnp.where(valid, t_par[beam], beam)
        return nxt, tok

    init = jnp.arange(bk, dtype=jnp.int32)
    _, toks = jax.lax.scan(
        back, init, (ids, par, jnp.arange(t_cap)), reverse=True)
    sent = jnp.moveaxis(toks, 0, 1).reshape(bsz, k, t_cap)
    last = jnp.clip(length - 1, 0, t_cap - 1)
    final_scores = jax.lax.dynamic_index_in_dim(
        scores_ta.buffer.reshape(t_cap, bk), last, 0,
        keepdims=False).reshape(bsz, k)
    scope[op.output("SentenceIds")] = sent
    scope[op.output("SentenceScores")] = final_scores


# ---------------------------------------------------------------------------
# LoD dynamic-RNN interchange family: lod_rank_table /
# lod_tensor_to_array / array_to_lod_tensor / shrink_rnn_memory /
# max_sequence_len / reorder_lod_tensor_by_rank / split_lod_tensor /
# merge_lod_tensor / lod_reset.
#
# Reference: `operators/lod_rank_table_op.cc`,
# `operators/lod_tensor_to_array_op.cc`, `operators/array_to_lod_tensor_op.cc`,
# `operators/shrink_rnn_memory_op.cc`, `operators/max_sequence_len_op.cc`,
# `operators/reorder_lod_tensor_by_rank_op.cc`,
# `operators/controlflow/` split/merge — the op set fluid's DynamicRNN and
# IfElse layers emit into machine-translation-era programs.
#
# Padded+lengths redesign (the repo's LoD stance): sequence feeds arrive
# padded [B, T, ...] with their lengths in a `<name>@LOD` sidecar feed
# (the Predictor input handle's `set_lod`).  The reference SHRINKS the
# batch as sequences finish (sorted-by-length batches); here the batch
# stays FULL-width with masking implied by lengths — rows past a
# sequence's end compute garbage that `array_to_lod_tensor` never emits
# (it zero-masks beyond each row's length), which preserves the observable
# semantics with static shapes.  `shrink_rnn_memory` is therefore the
# identity.
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
class RankTableVal:
    """LoDRankTable stand-in: sequence order sorted by decreasing length
    (stable) + the lengths, with the source's static max time kept as
    pytree aux so while-loop TensorArray capacities stay inferable."""

    def __init__(self, idx, lengths, t_max: int):
        self.idx = idx            # [B] int32, sorted by length desc
        self.lengths = lengths    # [B] int32, ORIGINAL order
        self.t_max = int(t_max)

    def tree_flatten(self):
        return (self.idx, self.lengths), self.t_max

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


def _lod_lengths(scope, name):
    key = name + "@LOD"
    if key not in scope:
        raise NotImplementedError(
            f"op needs sequence lengths for {name!r}: feed them via the "
            "Predictor input handle's set_lod() (padded+lengths LoD "
            "redesign) — the `<name>@LOD` sidecar is missing")
    return jnp.asarray(scope[key]).reshape(-1).astype(jnp.int32)


@register("lod_rank_table")
def _lod_rank_table(op, scope, feeds, fetches):
    name = op.input("X")
    x = scope.fetch(name)
    lengths = _lod_lengths(scope, name)
    # stable sort by decreasing length (reference sorts (len, index))
    order = jnp.argsort(lengths, stable=True,
                        descending=True).astype(jnp.int32)
    t_max = int(x.shape[1]) if getattr(x, "ndim", 0) >= 2 else \
        int(lengths.shape[0])
    scope[op.output("Out")] = RankTableVal(order, lengths, t_max)


@register("max_sequence_len")
def _max_sequence_len(op, scope, feeds, fetches):
    rt = scope.fetch(op.input("RankTable"))
    out = op.output("Out")
    scope[out] = jnp.max(rt.lengths).reshape(1).astype(jnp.int64)
    # static upper bound for while-loop TensorArray capacity inference
    _consts_bounds()[out] = rt.t_max


def _consts_bounds() -> Dict[str, int]:
    b = getattr(_BLOCKS_TLS, "bounds", None)
    if b is None:
        b = _BLOCKS_TLS.bounds = {}
    return b


@register("lod_tensor_to_array")
def _lod_tensor_to_array(op, scope, feeds, fetches):
    """x [B, T, ...] -> TensorArray of T steps, each [B, ...] with rows
    reordered by the rank table (longest first, like the reference's
    shrinking batches — but full-width)."""
    x = jnp.asarray(scope.fetch(op.input("X")))
    rt = scope.fetch(op.input("RankTable"))
    xr = x[rt.idx]                       # reorder rows
    buf = jnp.moveaxis(xr, 1, 0)         # [T, B, ...]
    scope[op.output("Out")] = TensorArrayVal(
        buf, jnp.asarray(buf.shape[0], jnp.int32))


@register("array_to_lod_tensor")
def _array_to_lod_tensor(op, scope, feeds, fetches):
    """TensorArray of per-step [B, ...] rows (rank order) -> padded
    [B, T, ...] in ORIGINAL order, zero past each sequence's length."""
    arr = scope.fetch(op.input("X"))
    rt = scope.fetch(op.input("RankTable"))
    stacked = jnp.moveaxis(arr.buffer, 0, 1)    # [B(rank order), T, ...]
    inv = jnp.zeros_like(rt.idx).at[rt.idx].set(
        jnp.arange(rt.idx.shape[0], dtype=rt.idx.dtype))
    out = stacked[inv]                          # original order
    t = out.shape[1]
    mask = jnp.arange(t)[None, :] < rt.lengths[:, None]
    mask = mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    name = op.output("Out")
    scope[name] = jnp.where(mask, out, 0)
    scope[name + "@LOD"] = rt.lengths


@register("shrink_rnn_memory")
def _shrink_rnn_memory(op, scope, feeds, fetches):
    # full-width masked batches: nothing shrinks; rows belonging to
    # finished sequences keep computing and are masked at emission
    scope[op.output("Out")] = scope.fetch(op.input("X"))


@register("reorder_lod_tensor_by_rank")
def _reorder_lod_tensor_by_rank(op, scope, feeds, fetches):
    x = jnp.asarray(scope.fetch(op.input("X")))
    rt = scope.fetch(op.input("RankTable"))
    scope[op.output("Out")] = x[rt.idx]


@register("split_lod_tensor")
def _split_lod_tensor(op, scope, feeds, fetches):
    """reference controlflow/split_lod_tensor_op: route rows by Mask —
    masked full-width (rows keep their slot; the untaken branch's rows
    are zeroed), merged back by merge_lod_tensor."""
    x = jnp.asarray(scope.fetch(op.input("X")))
    mask = jnp.asarray(scope.fetch(op.input("Mask"))).reshape(-1)
    m = mask.astype(bool).reshape((-1,) + (1,) * (x.ndim - 1))
    scope[op.output("OutTrue")] = jnp.where(m, x, 0)
    scope[op.output("OutFalse")] = jnp.where(m, 0, x)
    xkey = op.input("X") + "@LOD"
    if xkey in scope:  # full-width rows: both halves keep the lengths
        scope[op.output("OutTrue") + "@LOD"] = scope[xkey]
        scope[op.output("OutFalse") + "@LOD"] = scope[xkey]


@register("merge_lod_tensor", "merge_lod_tensor_infer")
def _merge_lod_tensor(op, scope, feeds, fetches):
    t = jnp.asarray(scope.fetch(op.input("InTrue")))
    f = jnp.asarray(scope.fetch(op.input("InFalse")))
    mask = jnp.asarray(scope.fetch(op.input("Mask"))).reshape(-1)
    m = mask.astype(bool).reshape((-1,) + (1,) * (t.ndim - 1))
    scope[op.output("Out")] = jnp.where(m, t, f)
    for side in ("InTrue", "InFalse"):
        key = op.input(side) + "@LOD"
        if key in scope:
            scope[op.output("Out") + "@LOD"] = scope[key]
            break


@register("lod_reset")
def _lod_reset(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    name = op.output("Out")
    scope[name] = x
    if op.input("Y"):
        ykey = op.input("Y") + "@LOD"
        if ykey in scope:
            scope[name + "@LOD"] = scope[ykey]
        else:
            # reference lod_reset_op: a plain int Y supplies the target
            # OFFSETS as data
            yv = jnp.asarray(scope.fetch(op.input("Y"))).reshape(-1)
            scope[name + "@LOD"] = jnp.diff(yv).astype(jnp.int32)
    else:
        target = op.attr("target_lod", [])
        if target:
            # offset-based lod -> lengths
            off = np.asarray(target, np.int64)
            scope[name + "@LOD"] = jnp.asarray(np.diff(off), jnp.int32)


# ---------------------------------------------------------------------------
# Sequence-family translators (reference `operators/sequence_ops/`) on the
# padded+lengths representation: the time dim is X.shape[1], valid steps
# come from the `@LOD` sidecar (a feed's set_lod, or full length when
# absent — the dense-batch degenerate case).
# ---------------------------------------------------------------------------


def _seq_lengths_or_full(scope, name, x):
    key = name + "@LOD"
    if key in scope:
        return jnp.asarray(scope[key]).reshape(-1).astype(jnp.int32)
    t = x.shape[1] if getattr(x, "ndim", 0) >= 2 else 1
    return jnp.full((x.shape[0],), t, jnp.int32)


@register("sequence_pool")
def _sequence_pool_op(op, scope, feeds, fetches):
    from ..ops.sequence import sequence_pool

    name = op.input("X")
    x = scope.fetch(name)
    lengths = _seq_lengths_or_full(scope, name, x)
    scope[op.output("Out")] = _via_functional(
        sequence_pool, x, lengths,
        pool_type=str(op.attr("pooltype", "SUM")).lower())


@register("sequence_softmax")
def _sequence_softmax_op(op, scope, feeds, fetches):
    from ..ops.sequence import sequence_softmax

    name = op.input("X")
    x = scope.fetch(name)
    lengths = _seq_lengths_or_full(scope, name, x)
    # @LOD propagation is handled centrally (_LOD_PRESERVING)
    scope[op.output("Out")] = _via_functional(sequence_softmax, x,
                                              lengths)


@register("sequence_reverse")
def _sequence_reverse_op(op, scope, feeds, fetches):
    from ..ops.sequence import sequence_reverse

    name = op.input("X")
    x = scope.fetch(name)
    lengths = _seq_lengths_or_full(scope, name, x)
    scope[op.output("Y")] = _via_functional(sequence_reverse, x,
                                            lengths)
    if name + "@LOD" in scope:  # sequence_reverse is not in the
        # central set (its Y slot name differs); forward explicitly
        scope[op.output("Y") + "@LOD"] = scope[name + "@LOD"]


@register("sequence_mask")
def _sequence_mask_op(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    x = jnp.asarray(scope.fetch(op.input("X")))
    maxlen = op.attr("maxlen", -1)
    if maxlen is None or maxlen <= 0:
        c = _consts().get(op.input("X"))
        if c is None:
            raise NotImplementedError(
                "sequence_mask without a static maxlen attr needs "
                "statically-known lengths (XLA static shapes); set the "
                "maxlen attribute")
        maxlen = int(np.max(np.asarray(c)))
    dt = vartype_to_np_dtype(op.attr("out_dtype", 3))
    mask = (jnp.arange(int(maxlen))[None, :] <
            x.reshape(-1, 1)).astype(dt)
    scope[op.output("Y")] = mask.reshape(tuple(x.shape) + (int(maxlen),))


@register("sequence_pad")
def _sequence_pad_op(op, scope, feeds, fetches):
    """Padded+lengths stance: X already arrives padded [B, T, ...]; the
    op re-pads to the attr maxlen (crop/extend) and emits Length."""
    name = op.input("X")
    x = jnp.asarray(scope.fetch(name))
    lengths = _seq_lengths_or_full(scope, name, x)
    pad_value = 0.0
    if op.input("PadValue"):
        pad_value = scope.fetch(op.input("PadValue"))
    maxlen = op.attr("padded_length", -1)
    t = x.shape[1]
    if maxlen and maxlen > 0 and maxlen != t:
        if maxlen < t:
            x = x[:, :maxlen]
        else:
            pads = [(0, 0), (0, int(maxlen) - t)] + \
                [(0, 0)] * (x.ndim - 2)
            x = jnp.pad(x, pads)
        t = int(maxlen)
    # the reference enforces padded_length >= max length; the padded
    # redesign clamps instead so Length never exceeds the time dim
    lengths = jnp.minimum(lengths, t)
    mask = jnp.arange(t)[None, :] < lengths[:, None]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    scope[op.output("Out")] = jnp.where(mask, x, pad_value)
    if op.output("Length"):
        scope[op.output("Length")] = lengths.astype(jnp.int64)


@register("one_hot", "one_hot_v2")
def _one_hot_op(op, scope, feeds, fetches):
    from ..ops.creation import one_hot

    x = jnp.asarray(scope.fetch(op.input("X"))).astype(jnp.int32)
    if x.ndim and x.shape[-1] == 1 and op.type == "one_hot":
        x = x[..., 0]
    scope[op.output("Out")] = _via_functional(
        one_hot, x, int(op.attr("depth", 1)))


@register("gather_nd")
def _gather_nd_op(op, scope, feeds, fetches):
    from ..ops.manipulation import gather_nd

    scope[op.output("Out")] = _via_functional(
        gather_nd, scope.fetch(op.input("X")),
        scope.fetch(op.input("Index")))


@register("scatter")
def _scatter_op(op, scope, feeds, fetches):
    from ..ops.manipulation import scatter

    scope[op.output("Out")] = _via_functional(
        scatter, scope.fetch(op.input("X")),
        scope.fetch(op.input("Ids")), scope.fetch(op.input("Updates")),
        overwrite=bool(op.attr("overwrite", True)))


@register("argsort")
def _argsort_op(op, scope, feeds, fetches):
    x = jnp.asarray(scope.fetch(op.input("X")))
    axis = op.attr("axis", -1)
    # descending=True (not argsort(-x)): negation mis-sorts unsigned
    # and bool dtypes
    idx = jnp.argsort(x, axis=axis, stable=True,
                      descending=bool(op.attr("descending", False)))
    scope[op.output("Indices")] = idx.astype(jnp.int64)
    scope[op.output("Out")] = jnp.take_along_axis(x, idx, axis=axis)


@register("rnn")
def _rnn_unified_op(op, scope, feeds, fetches):
    """The unified cudnn-style RNN op (`operators/rnn_op.cc`) that
    paddle-2.x `nn.LSTM/GRU/SimpleRNN` serialize to: Input [T, B, I]
    (time-major), WeightList flattened as [w_ih, w_hh per (layer, dir)
    ... then b_ih, b_hh per (layer, dir)], PreState = (h0[, c0]) each
    [L*D, B, H], optional SequenceLength [B].  Gate orders follow the
    python cells (`python/paddle/nn/layer/rnn.py`): LSTM i,f,g,o; GRU
    r,z,c with the reset gate applied AFTER the hidden matmul and
    h = (h_prev - c) * z + c.  With SequenceLength, states freeze and
    outputs zero past each row's length (cudnn semantics); the backward
    direction reverses within the valid region."""
    mode = op.attr("mode", "LSTM")
    nl = int(op.attr("num_layers", 1))
    bidirec = bool(op.attr("is_bidirec", False))
    nd = 2 if bidirec else 1
    if not op.attr("is_test", True) and op.attr("dropout_prob", 0.0):
        raise NotImplementedError(
            "rnn op: train-mode inter-layer dropout is not translated "
            "(inference interpreter); run with is_test=True or train "
            "through the eager nn.LSTM/GRU layers")

    x = jnp.asarray(scope.fetch(op.input("Input")))  # [T, B, I]
    t_len, bsz = x.shape[0], x.shape[1]
    # valid-region reverse index map for the backward direction (loop
    # invariant: depends only on t_len / seq_len)
    rev_src = None
    weights = [jnp.asarray(scope.fetch(n))
               for n in op.inputs("WeightList")]
    npairs = nl * nd
    w_ih = weights[0:2 * npairs:2]
    w_hh = weights[1:2 * npairs:2]
    has_bias = len(weights) >= 4 * npairs
    b_ih = weights[2 * npairs:4 * npairs:2] if has_bias else \
        [0.0] * npairs
    b_hh = weights[2 * npairs + 1:4 * npairs:2] if has_bias else \
        [0.0] * npairs
    pre = [jnp.asarray(scope.fetch(n)) for n in op.inputs("PreState")]
    seq_len = None
    if op.input("SequenceLength"):
        seq_len = jnp.asarray(
            scope.fetch(op.input("SequenceLength"))).reshape(-1) \
            .astype(jnp.int32)

    def cell_step(kind, wi, wh, bi, bh, xt, h, c):
        gates_x = xt @ wi.T + bi
        gates_h = h @ wh.T + bh
        if kind == "LSTM":
            g = gates_x + gates_h
            i_, f_, g_, o_ = jnp.split(g, 4, axis=-1)
            c_new = jax.nn.sigmoid(f_) * c + \
                jax.nn.sigmoid(i_) * jnp.tanh(g_)
            return jax.nn.sigmoid(o_) * jnp.tanh(c_new), c_new
        if kind == "GRU":
            x_r, x_z, x_c = jnp.split(gates_x, 3, axis=-1)
            h_r, h_z, h_c = jnp.split(gates_h, 3, axis=-1)
            r = jax.nn.sigmoid(x_r + h_r)
            z = jax.nn.sigmoid(x_z + h_z)
            cand = jnp.tanh(x_c + r * h_c)
            return (h - cand) * z + cand, c
        act = jnp.tanh if kind == "RNN_TANH" else \
            (lambda v: jnp.maximum(v, 0))
        return act(gates_x + gates_h), c

    def run_dir(xs, pair, h0, c0, reverse):
        wi, wh, bi, bh = (w_ih[pair], w_hh[pair],
                          b_ih[pair], b_hh[pair])
        def rev(a):
            # reverse WITHIN each row's valid region (padding stays)
            if seq_len is None:
                return a[::-1]
            return jnp.take_along_axis(
                a, rev_src.reshape(t_len, bsz, 1), axis=0)

        if reverse:
            xs = rev(xs)

        def step(carry, xt_t):
            h, c = carry
            xt, tt = xt_t
            h_new, c_new = cell_step(mode, wi, wh, bi, bh, xt, h, c)
            if seq_len is not None:
                live = (tt < seq_len)[:, None]
                h_new = jnp.where(live, h_new, h)
                c_new = jnp.where(live, c_new, c)
            return (h_new, c_new), h_new

        (hT, cT), ys = jax.lax.scan(
            step, (h0, c0), (xs, jnp.arange(t_len)))
        if reverse:
            ys = rev(ys)
        return ys, hT, cT

    if seq_len is not None and bidirec:
        tpos = jnp.arange(t_len)[:, None]
        rev_src = jnp.where(tpos < seq_len[None, :],
                            seq_len[None, :] - 1 - tpos, tpos)
    h0s = pre[0]
    c0s = pre[1] if mode == "LSTM" and len(pre) > 1 else \
        jnp.zeros_like(pre[0])
    out = x
    fin_h, fin_c = [], []
    for layer in range(nl):
        ys_dirs = []
        for d in range(nd):
            pair = layer * nd + d
            ys, hT, cT = run_dir(out, pair, h0s[pair], c0s[pair],
                                 reverse=(d == 1))
            ys_dirs.append(ys)
            fin_h.append(hT)
            fin_c.append(cT)
        out = ys_dirs[0] if nd == 1 else \
            jnp.concatenate(ys_dirs, axis=-1)
    if seq_len is not None:
        live = (jnp.arange(t_len)[:, None] < seq_len[None, :])
        out = jnp.where(live[..., None], out, 0)
    scope[op.output("Out")] = out
    states = op._out.get("State", [])
    if states:
        scope[states[0]] = jnp.stack(fin_h)
        if mode == "LSTM" and len(states) > 1:
            scope[states[1]] = jnp.stack(fin_c)
    if op.output("Reserve"):
        scope[op.output("Reserve")] = jnp.zeros((1,), jnp.uint8)
    if op.output("DropoutState"):
        scope[op.output("DropoutState")] = jnp.zeros((1,), jnp.uint8)


# ---------------------------------------------------------------------------
# declarative OpDesc->eager bridge: registers translators for every
# remaining implemented eager op (reference executor.cc:166 contract —
# any registered op is runnable from a ProgramDesc)
# ---------------------------------------------------------------------------
from . import op_bridge  # noqa: E402,F401  (registers into OP_TRANSLATORS)
