"""ProgramDesc interpreter: run reference-era programs on TPU via jnp.

Reference counterpart: the single-thread `Executor::Run` op loop
(`framework/executor.cc:292`) + `NaiveExecutor` used by the inference
predictor (`inference/api/analysis_predictor.cc:889`).  TPU-native: the
whole block is interpreted ONCE under a jax trace (each op translated to
jnp / paddle_tpu functional calls), so the program compiles to a single
XLA computation — no per-op dispatch at run time.

Covers the common inference op set (~70 types incl. the fused/common
CNN + transformer inference ops); unknown ops raise with the op name so
coverage gaps are explicit.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

OP_TRANSLATORS: Dict[str, Callable] = {}


def register(*names):
    def deco(fn):
        for n in names:
            OP_TRANSLATORS[n] = fn
        return fn
    return deco


class OpView:
    """Convenience accessor over a decoded OpDesc dict."""

    def __init__(self, desc: Dict[str, Any]):
        self.desc = desc
        self.type = desc["type"]
        self._in = {v["parameter"]: v.get("arguments", [])
                    for v in desc.get("inputs", [])}
        self._out = {v["parameter"]: v.get("arguments", [])
                     for v in desc.get("outputs", [])}
        self._attrs = {}
        for a in desc.get("attrs", []):
            self._attrs[a["name"]] = _attr_value(a)

    def input(self, name, idx=0, default=None):
        args = self._in.get(name) or []
        return args[idx] if len(args) > idx else default

    def inputs(self, name):
        return self._in.get(name) or []

    def output(self, name, idx=0, default=None):
        args = self._out.get(name) or []
        return args[idx] if len(args) > idx else default

    def attr(self, name, default=None):
        return self._attrs.get(name, default)


def _attr_value(a: Dict[str, Any]):
    from .proto import AttrType as T

    t = a.get("type")
    if t == T.INT:
        return a.get("i", 0)
    if t == T.FLOAT:
        return a.get("f", 0.0)
    if t == T.STRING:
        return a.get("s", "")
    if t == T.INTS:
        return a.get("ints", [])
    if t == T.FLOATS:
        return a.get("floats", [])
    if t == T.STRINGS:
        return a.get("strings", [])
    if t == T.BOOLEAN:
        return a.get("b", False)
    if t == T.BOOLEANS:
        return a.get("bools", [])
    if t == T.LONG:
        return a.get("l", 0)
    if t == T.LONGS:
        return a.get("longs", [])
    if t == T.FLOAT64S:
        return a.get("float64s", [])
    if t == T.BLOCK:
        return a.get("block_idx", 0)
    if t == T.BLOCKS:
        return a.get("blocks_idx", [])
    return None


class Scope(dict):
    """name -> jnp array."""

    def fetch(self, name):
        if name not in self:
            raise KeyError(f"variable {name!r} not produced by the program")
        return self[name]


def run_block(block_ops: List[Dict[str, Any]], scope: Scope,
              feeds: Dict[str, Any], fetch_holder: Dict[int, Any]):
    """Interpret a block's ops in order (program order IS execution order
    in the reference executor)."""
    for raw in block_ops:
        op = OpView(raw)
        fn = OP_TRANSLATORS.get(op.type)
        if fn is None:
            if op.type.endswith("_grad") and \
                    op.attr("__forward_op__") is not None:
                run_grad_op(op, scope, feeds, fetch_holder)
                continue
            raise NotImplementedError(
                f"ProgramDesc op {op.type!r} has no TPU translation yet")
        fn(op, scope, feeds, fetch_holder)


GRAD_SUFFIX = "@GRAD"


def run_grad_op(op: OpView, scope: Scope, feeds, fetch_holder):
    """Generic grad-op executor: differentiate the embedded forward op by
    re-tracing its translator under jax.vjp (the TPU-native replacement
    for per-op GradOpMaker kernels — `fluid/backward.py:1015`).  Input
    gradients accumulate (the reference inserts sum ops for duplicated
    grads; here duplicate producers add in place)."""
    import json

    fwd = OpView(json.loads(op.attr("__forward_op__")))
    fwd_fn = OP_TRANSLATORS.get(fwd.type)
    if fwd_fn is None:
        raise NotImplementedError(
            f"grad of untranslated op {fwd.type!r}")

    in_args, seen = [], set()
    for p, args in fwd._in.items():
        for a in args:
            if a not in seen:
                seen.add(a)
                in_args.append(a)
    # differentiable = float arrays present in scope
    diff = [a for a in in_args if a in scope
            and jnp.issubdtype(jnp.asarray(scope[a]).dtype, jnp.inexact)]
    out_args = [a for p, args in fwd._out.items() for a in args]

    # discover which declared outputs the translator actually writes
    # (optional outputs may be skipped, e.g. batch_norm stats in eval)
    probe = Scope(scope)
    fwd_fn(fwd, probe, feeds, {})
    produced = [a for a in out_args if a in probe]

    def fwd_vals(vals):
        local = Scope(scope)
        for a, v in zip(diff, vals):
            local[a] = v
        fwd_fn(fwd, local, feeds, {})
        return tuple(local[a] for a in produced)

    primals = tuple(scope[a] for a in diff)
    outs, vjp = jax.vjp(fwd_vals, primals)
    # cotangents: @GRAD vars where produced, zeros otherwise (e.g. an
    # auxiliary output nobody differentiated through)
    def _conform(c, o):
        c = jnp.asarray(c).astype(o.dtype)
        if c.shape == o.shape:
            return c
        if c.size == o.size:  # e.g. the [1]-shaped loss seed vs scalar mean
            return c.reshape(o.shape)
        return jnp.broadcast_to(c, o.shape)

    cots = tuple(
        _conform(scope[a + GRAD_SUFFIX], o)
        if (a + GRAD_SUFFIX) in scope else jnp.zeros_like(o)
        for a, o in zip(produced, outs))
    (gin,) = vjp(cots)
    # only materialize gradients the grad op DECLARES (no_grad_set pruning
    # removes slots from the op's outputs)
    declared = {a for p, args in op._out.items() for a in args}
    for a, g in zip(diff, gin):
        key = a + GRAD_SUFFIX
        if key not in declared:
            continue
        scope[key] = scope[key] + g if key in scope else g


class ProgramRunner:
    """Jit-compiled block interpreter: the whole program becomes ONE XLA
    computation per input signature (the NaiveExecutor op loop collapsed
    at trace time).  Shared by `static.Executor` and the inference
    Predictor."""

    def __init__(self, program, scope: Dict[str, Any], jit: bool = True,
                 donate_feeds: bool = False):
        """``jit=False`` interprets the block op-by-op without the
        whole-graph XLA compile (Config.switch_ir_optim(False) semantics —
        the reference's un-optimized NaiveExecutor loop);
        ``donate_feeds=True`` donates the feed buffers to the executable so
        outputs may alias them (Config.enable_memory_optim)."""
        self.program = program
        self.params = {k: jnp.asarray(v) for k, v in scope.items()}
        self.feed_names = program.feed_target_names()
        self.fetch_names = program.fetch_target_names()
        ops = program.desc["blocks"][0]["ops"]

        def pure(params, feeds):
            s = Scope(params)
            fetches: Dict[int, Any] = {}
            run_block(ops, s, feeds, fetches)
            # also return the full scope (as a plain dict pytree) so the
            # Executor can satisfy fetch_list entries that aren't
            # fetch-op targets
            return tuple(fetches[k] for k in sorted(fetches)), dict(s)

        if jit:
            self._jit = jax.jit(
                pure, donate_argnums=(1,) if donate_feeds else ())
        else:
            if donate_feeds:
                import warnings

                warnings.warn("donate_feeds requires the jit-compiled "
                              "runner; ignored with jit=False")
            self._jit = pure

    def __call__(self, *inputs):
        feeds = dict(zip(self.feed_names, (jnp.asarray(i) for i in inputs)))
        outs, _ = self._jit(self.params, feeds)
        return outs

    def run_with_scope(self, feeds, params=None):
        """`params` overrides the construction-time parameter values, so
        callers can update weights between runs — the static training
        loop.  Keys beyond the construction set (e.g. optimizer slot vars
        the program created on its first run) are merged in too; a new
        key changes the pytree structure and costs one retrace, after
        which the structure is stable."""
        if params is not None:
            merged = dict(self.params)
            merged.update({k: jnp.asarray(v) for k, v in params.items()})
            params = merged
        outs, scope = self._jit(params or self.params, feeds)
        return outs, scope


def _t(x):
    from ..core.tensor import Tensor

    return Tensor(x)


def _u(t):
    from ..core.tensor import Tensor

    return t._array if isinstance(t, Tensor) else jnp.asarray(t)


# ---------------------------------------------------------------------------
# feed / fetch / data movement
# ---------------------------------------------------------------------------
@register("feed")
def _feed(op, scope, feeds, fetches):
    name = op.output("Out")
    if name not in feeds:
        raise KeyError(f"feed variable {name!r} missing from feed dict")
    scope[name] = jnp.asarray(feeds[name])


@register("fetch")
def _fetch(op, scope, feeds, fetches):
    col = op.attr("col", 0)
    fetches[col] = scope.fetch(op.input("X"))


@register("assign", "share_data", "memcpy")
def _assign(op, scope, feeds, fetches):
    scope[op.output("Out")] = scope.fetch(op.input("X"))


@register("assign_value")
def _assign_value(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    for key in ("fp32_values", "int32_values", "int64_values",
                "bool_values"):
        vals = op.attr(key)
        if vals:
            scope[op.output("Out")] = jnp.asarray(
                np.asarray(vals).reshape(shape)).astype(dtype)
            return
    scope[op.output("Out")] = jnp.zeros(shape, dtype)


@register("fill_constant")
def _fill_constant(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    scope[op.output("Out")] = jnp.full(shape, op.attr("value", 0.0), dtype)


@register("fill_any_like")
def _fill_any_like(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.full_like(x, op.attr("value", 0.0))


@register("cast")
def _cast(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = x.astype(
        vartype_to_np_dtype(op.attr("out_dtype", 5)))


@register("shape")
def _shape(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    scope[op.output("Out")] = jnp.asarray(x.shape, jnp.int32)


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------
@register("mul")
def _mul(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    xm = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    ym = y.reshape((int(np.prod(y.shape[:ync])), -1))
    out = xm @ ym
    scope[op.output("Out")] = out.reshape(
        tuple(x.shape[:xnc]) + tuple(y.shape[ync:]))


@register("matmul")
def _matmul(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    if op.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if op.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y) * op.attr("alpha", 1.0)
    scope[op.output("Out")] = out


@register("matmul_v2")
def _matmul_v2(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    if op.attr("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if op.attr("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    scope[op.output("Out")] = jnp.matmul(x, y)


@register("fc")
def _fc(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("W"))
    in_num_col_dims = op.attr("in_num_col_dims", 1)
    xm = x.reshape((int(np.prod(x.shape[:in_num_col_dims])), -1))
    out = xm @ w
    b = op.input("Bias")
    if b:
        out = out + scope.fetch(b)
    act = op.attr("activation_type", "")
    if act == "relu":
        out = jnp.maximum(out, 0)
    scope[op.output("Out")] = out.reshape(
        tuple(x.shape[:in_num_col_dims]) + (w.shape[1],))


# ---------------------------------------------------------------------------
# elementwise / unary
# ---------------------------------------------------------------------------
def _broadcast_ew(op, scope, fn):
    x = scope.fetch(op.input("X"))
    y = scope.fetch(op.input("Y"))
    axis = op.attr("axis", -1)
    if axis != -1 and y.ndim < x.ndim:
        # reference broadcast: align y's dims starting at `axis`
        shape = [1] * x.ndim
        for i, d in enumerate(y.shape):
            shape[axis + i] = d
        y = y.reshape(shape)
    scope[op.output("Out")] = fn(x, y)


for _name, _fn in [
    ("elementwise_add", jnp.add), ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply), ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum), ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
    ("elementwise_mod", jnp.mod),
    ("elementwise_floordiv", jnp.floor_divide),
]:
    def _mk(fn):
        def _op(op, scope, feeds, fetches):
            _broadcast_ew(op, scope, fn)
        return _op
    OP_TRANSLATORS[_name] = _mk(_fn)

for _name, _fn in [
    ("relu", lambda x: jnp.maximum(x, 0)),
    ("sigmoid", jax.nn.sigmoid), ("tanh", jnp.tanh),
    ("sqrt", jnp.sqrt), ("rsqrt", jax.lax.rsqrt),
    ("square", jnp.square), ("abs", jnp.abs), ("exp", jnp.exp),
    ("log", jnp.log), ("floor", jnp.floor), ("ceil", jnp.ceil),
    ("round", jnp.round), ("reciprocal", lambda x: 1.0 / x),
    ("softsign", lambda x: x / (1 + jnp.abs(x))),
    ("softplus", jax.nn.softplus), ("silu", jax.nn.silu),
    ("logsigmoid", jax.nn.log_sigmoid),
    ("relu6", lambda x: jnp.clip(x, 0, 6)),
    ("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x))),
    ("sin", jnp.sin), ("cos", jnp.cos), ("erf", jax.scipy.special.erf),
    ("sign", jnp.sign),
]:
    def _mk1(fn):
        def _op(op, scope, feeds, fetches):
            scope[op.output("Out")] = fn(scope.fetch(op.input("X")))
        return _op
    OP_TRANSLATORS[_name] = _mk1(_fn)


@register("gelu")
def _gelu(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.nn.gelu(
        x, approximate=op.attr("approximate", False))


@register("leaky_relu")
def _leaky_relu(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    alpha = op.attr("alpha", 0.02)
    scope[op.output("Out")] = jnp.where(x > 0, x, alpha * x)


@register("prelu")
def _prelu(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    alpha = scope.fetch(op.input("Alpha"))
    mode = op.attr("mode", "all")
    if mode == "channel" and alpha.size > 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    scope[op.output("Out")] = jnp.where(x > 0, x, alpha * x)


@register("hard_sigmoid")
def _hard_sigmoid(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    slope = op.attr("slope", 0.2)
    offset = op.attr("offset", 0.5)
    scope[op.output("Out")] = jnp.clip(slope * x + offset, 0.0, 1.0)


@register("hard_swish")
def _hard_swish(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    threshold = op.attr("threshold", 6.0)
    scale = op.attr("scale", 6.0)
    offset = op.attr("offset", 3.0)
    scope[op.output("Out")] = x * jnp.clip(x + offset, 0,
                                           threshold) / scale


@register("swish")
def _swish(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    beta = op.attr("beta", 1.0)
    scope[op.output("Out")] = x * jax.nn.sigmoid(beta * x)


@register("scale")
def _scale(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    s = op.attr("scale", 1.0)
    b = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        out = x * s + b
    else:
        out = (x + b) * s
    scope[op.output("Out")] = out


@register("clip")
def _clip(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.clip(x, op.attr("min", 0.0),
                                       op.attr("max", 0.0))


@register("pow")
def _pow(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.power(x, op.attr("factor", 1.0))


@register("sum")
def _sum(op, scope, feeds, fetches):
    xs = [scope.fetch(n) for n in op.inputs("X")]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    scope[op.output("Out")] = out


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------
@register("reshape", "reshape2")
def _reshape(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    shape = [int(s) for s in op.attr("shape", [])]
    # 0 means "copy input dim" in the reference reshape
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    scope[op.output("Out")] = x.reshape(shape)


@register("transpose", "transpose2")
def _transpose(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.transpose(x, op.attr("axis", None))


@register("flatten2", "flatten", "flatten_contiguous_range")
def _flatten(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    if op.type == "flatten_contiguous_range":
        start = op.attr("start_axis", 1)
        stop = op.attr("stop_axis", -1)
        stop = stop % x.ndim
        shape = (x.shape[:start]
                 + (int(np.prod(x.shape[start:stop + 1])),)
                 + x.shape[stop + 1:])
    else:
        ax = op.attr("axis", 1)
        shape = (int(np.prod(x.shape[:ax])), int(np.prod(x.shape[ax:])))
    scope[op.output("Out")] = x.reshape(shape)


@register("squeeze", "squeeze2")
def _squeeze(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    axes = op.attr("axes", [])
    if axes:
        for ax in sorted((a % x.ndim for a in axes), reverse=True):
            if x.shape[ax] == 1:
                x = jnp.squeeze(x, axis=ax)
    else:
        x = jnp.squeeze(x)
    scope[op.output("Out")] = x


@register("unsqueeze", "unsqueeze2")
def _unsqueeze(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    for ax in sorted(op.attr("axes", [])):
        x = jnp.expand_dims(x, ax)
    scope[op.output("Out")] = x


@register("concat")
def _concat(op, scope, feeds, fetches):
    xs = [scope.fetch(n) for n in op.inputs("X")]
    scope[op.output("Out")] = jnp.concatenate(xs, axis=op.attr("axis", 0))


@register("split")
def _split(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    axis = op.attr("axis", 0)
    sections = op.attr("sections", [])
    num = op.attr("num", 0)
    outs = op._out.get("Out", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num or len(outs), axis=axis)
    for name, part in zip(outs, parts):
        scope[name] = part


@register("stack")
def _stack(op, scope, feeds, fetches):
    xs = [scope.fetch(n) for n in op.inputs("X")]
    scope[op.output("Y")] = jnp.stack(xs, axis=op.attr("axis", 0))


@register("slice")
def _slice(op, scope, feeds, fetches):
    x = scope.fetch(op.input("Input"))
    axes = op.attr("axes", [])
    starts = op.attr("starts", [])
    ends = op.attr("ends", [])
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = slice(int(s), int(min(e, x.shape[ax])))
    out = x[tuple(idx)]
    for ax in sorted(op.attr("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, axis=ax)
    scope[op.output("Out")] = out


@register("gather")
def _gather(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    idx = scope.fetch(op.input("Index"))
    scope[op.output("Out")] = jnp.take(x, idx.astype(jnp.int32), axis=0)


@register("expand_v2")
def _expand_v2(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    shape = [int(s) for s in op.attr("shape", [])]
    shape = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    scope[op.output("Out")] = jnp.broadcast_to(x, shape)


@register("tile")
def _tile(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.tile(x, op.attr("repeat_times", []))


# ---------------------------------------------------------------------------
# reductions / search
# ---------------------------------------------------------------------------
def _reduce(op, scope, fn):
    x = scope.fetch(op.input("X"))
    if op.attr("reduce_all", False):
        out = fn(x, axis=None, keepdims=op.attr("keep_dim", False))
    else:
        axes = tuple(op.attr("dim", [0]))
        out = fn(x, axis=axes, keepdims=op.attr("keep_dim", False))
    scope[op.output("Out")] = out


for _name, _fn in [("reduce_mean", jnp.mean), ("reduce_sum", jnp.sum),
                   ("reduce_max", jnp.max), ("reduce_min", jnp.min),
                   ("reduce_prod", jnp.prod)]:
    def _mkr(fn):
        def _op(op, scope, feeds, fetches):
            _reduce(op, scope, fn)
        return _op
    OP_TRANSLATORS[_name] = _mkr(_fn)


@register("mean")
def _mean(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.mean(scope.fetch(op.input("X")))


@register("arg_max")
def _arg_max(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    axis = op.attr("axis", -1)
    out = jnp.argmax(x, axis=int(axis))
    if op.attr("keepdims", False):
        out = jnp.expand_dims(out, int(axis))
    scope[op.output("Out")] = out.astype(jnp.int64)


@register("top_k", "top_k_v2")
def _top_k(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    k = op.attr("k", 1)
    vals, idx = jax.lax.top_k(x, int(k))
    scope[op.output("Out")] = vals
    scope[op.output("Indices")] = idx.astype(jnp.int64)


# comparison family
for _name, _fn in [("equal", jnp.equal), ("not_equal", jnp.not_equal),
                   ("less_than", jnp.less), ("less_equal", jnp.less_equal),
                   ("greater_than", jnp.greater),
                   ("greater_equal", jnp.greater_equal)]:
    def _mkc(fn):
        def _op(op, scope, feeds, fetches):
            x = scope.fetch(op.input("X"))
            y = scope.fetch(op.input("Y"))
            scope[op.output("Out")] = fn(x, y)
        return _op
    OP_TRANSLATORS[_name] = _mkc(_fn)


# ---------------------------------------------------------------------------
# NN layers (delegate to paddle_tpu functional for exact semantics)
# ---------------------------------------------------------------------------
@register("conv2d", "depthwise_conv2d")
def _conv2d(op, scope, feeds, fetches):
    from ..nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    groups = op.attr("groups", 1)
    if op.type == "depthwise_conv2d" and groups in (0, 1):
        groups = x.shape[1]
    pad = op.attr("paddings", [0, 0])
    algo = op.attr("padding_algorithm", "EXPLICIT")
    if algo in ("SAME", "VALID"):
        pad = algo
    out = F.conv2d(_t(x), _t(w), None,
                   stride=op.attr("strides", [1, 1]),
                   padding=pad,
                   dilation=op.attr("dilations", [1, 1]),
                   groups=max(groups, 1))
    scope[op.output("Output")] = _u(out)


@register("conv2d_transpose")
def _conv2d_transpose(op, scope, feeds, fetches):
    from ..nn import functional as F

    x = scope.fetch(op.input("Input"))
    w = scope.fetch(op.input("Filter"))
    out = F.conv2d_transpose(
        _t(x), _t(w), None, stride=op.attr("strides", [1, 1]),
        padding=op.attr("paddings", [0, 0]),
        dilation=op.attr("dilations", [1, 1]),
        groups=max(op.attr("groups", 1), 1))
    scope[op.output("Output")] = _u(out)


@register("batch_norm", "sync_batch_norm")
def _batch_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    mean = scope.fetch(op.input("Mean"))
    var = scope.fetch(op.input("Variance"))
    scale = scope.fetch(op.input("Scale"))
    bias = scope.fetch(op.input("Bias"))
    eps = op.attr("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = jax.lax.rsqrt(var + eps)
    out = (x - mean.reshape(shape)) * (inv * scale).reshape(shape) \
        + bias.reshape(shape)
    scope[op.output("Y")] = out


@register("layer_norm")
def _layer_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    begin = op.attr("begin_norm_axis", 1)
    eps = op.attr("epsilon", 1e-5)
    red = tuple(range(begin, x.ndim))
    mu = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=red, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    s = op.input("Scale")
    b = op.input("Bias")
    norm_shape = x.shape[begin:]
    if s:
        out = out * scope.fetch(s).reshape(norm_shape)
    if b:
        out = out + scope.fetch(b).reshape(norm_shape)
    scope[op.output("Y")] = out


@register("pool2d")
def _pool2d(op, scope, feeds, fetches):
    from ..nn import functional as F

    x = scope.fetch(op.input("X"))
    ptype = op.attr("pooling_type", "max")
    ksize = op.attr("ksize", [1, 1])
    if op.attr("global_pooling", False) or op.attr("adaptive", False) and \
            list(ksize) == [1, 1]:
        out = jnp.mean(x, axis=(2, 3), keepdims=True) if ptype == "avg" \
            else jnp.max(x, axis=(2, 3), keepdims=True)
        scope[op.output("Out")] = out
        return
    if op.attr("adaptive", False):
        out = F.adaptive_avg_pool2d(_t(x), ksize) if ptype == "avg" \
            else F.adaptive_max_pool2d(_t(x), ksize)
        scope[op.output("Out")] = _u(out)
        return
    kwargs = dict(kernel_size=ksize,
                  stride=op.attr("strides", [1, 1]),
                  padding=op.attr("paddings", [0, 0]),
                  ceil_mode=op.attr("ceil_mode", False))
    if ptype == "avg":
        out = F.avg_pool2d(_t(x), exclusive=op.attr("exclusive", True),
                           **kwargs)
    else:
        out = F.max_pool2d(_t(x), **kwargs)
    scope[op.output("Out")] = _u(out)


@register("softmax")
def _softmax(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.nn.softmax(x, axis=op.attr("axis", -1))


@register("log_softmax")
def _log_softmax(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jax.nn.log_softmax(x,
                                                 axis=op.attr("axis", -1))


@register("dropout")
def _dropout(op, scope, feeds, fetches):
    # inference: upscale_in_train => identity; downgrade => scale
    x = scope.fetch(op.input("X"))
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    p = op.attr("dropout_prob", 0.5)
    out = x if impl == "upscale_in_train" else x * (1.0 - p)
    scope[op.output("Out")] = out


@register("lookup_table", "lookup_table_v2")
def _lookup_table(op, scope, feeds, fetches):
    w = scope.fetch(op.input("W"))
    ids = scope.fetch(op.input("Ids"))
    if op.type == "lookup_table" and ids.shape[-1] == 1:
        ids = ids[..., 0]
    out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    pad = op.attr("padding_idx", -1)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    scope[op.output("Out")] = out


@register("softmax_with_cross_entropy")
def _softmax_ce(op, scope, feeds, fetches):
    logits = scope.fetch(op.input("Logits"))
    label = scope.fetch(op.input("Label"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    if op.attr("soft_label", False):
        loss = -(label * logp).sum(-1, keepdims=True)
    else:
        lab = label[..., 0] if label.shape[-1] == 1 else label
        loss = -jnp.take_along_axis(
            logp, lab.astype(jnp.int32)[..., None], axis=-1)
    scope[op.output("Softmax")] = jnp.exp(logp)
    scope[op.output("Loss")] = loss


@register("accuracy")
def _accuracy(op, scope, feeds, fetches):
    pred_idx = scope.fetch(op.input("Indices"))
    label = scope.fetch(op.input("Label"))
    correct = (pred_idx[:, :1].astype(jnp.int64)
               == label.astype(jnp.int64)).any(axis=1)
    scope[op.output("Accuracy")] = correct.mean(dtype=jnp.float32)
    if op.output("Correct"):
        scope[op.output("Correct")] = correct.sum().astype(jnp.int32)
    if op.output("Total"):
        scope[op.output("Total")] = jnp.asarray(label.shape[0], jnp.int32)


@register("nearest_interp", "nearest_interp_v2", "bilinear_interp",
          "bilinear_interp_v2")
def _interp(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    out_h = op.attr("out_h", -1)
    out_w = op.attr("out_w", -1)
    scale = op.attr("scale", [])
    if out_h <= 0 or out_w <= 0:
        if isinstance(scale, (int, float)):
            scale = [scale, scale]
        out_h = int(x.shape[2] * scale[0])
        out_w = int(x.shape[3] * scale[1])
    method = "nearest" if op.type.startswith("nearest") else "bilinear"
    out = jax.image.resize(x, x.shape[:2] + (out_h, out_w), method)
    scope[op.output("Out")] = out.astype(x.dtype)


@register("pad2d", "pad3d")
def _pad(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    pads = op.attr("paddings", [])
    mode = op.attr("mode", "constant")
    value = op.attr("pad_value", op.attr("value", 0.0))
    # NCHW: paddings = [top, bottom, left, right] (pad2d)
    if op.type == "pad2d":
        cfg = [(0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])]
    else:
        cfg = [(0, 0), (0, 0), (pads[4], pads[5]), (pads[2], pads[3]),
               (pads[0], pads[1])]
    if mode == "constant":
        out = jnp.pad(x, cfg, constant_values=value)
    else:
        jmode = {"reflect": "reflect", "edge": "edge",
                 "replicate": "edge"}[mode]
        out = jnp.pad(x, cfg, mode=jmode)
    scope[op.output("Out")] = out


@register("pixel_shuffle")
def _pixel_shuffle(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    r = op.attr("upscale_factor", 1)
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(
        n, c // (r * r), h * r, w * r)
    scope[op.output("Out")] = out


@register("uniform_random")
def _uniform_random(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    seed = op.attr("seed", 0)
    key = jax.random.PRNGKey(seed or 0)
    scope[op.output("Out")] = jax.random.uniform(
        key, shape, jnp.float32, op.attr("min", -1.0),
        op.attr("max", 1.0)).astype(dtype)


@register("gaussian_random")
def _gaussian_random(op, scope, feeds, fetches):
    from .proto import vartype_to_np_dtype

    shape = [int(s) for s in op.attr("shape", [])]
    dtype = vartype_to_np_dtype(op.attr("dtype", 5))
    key = jax.random.PRNGKey(op.attr("seed", 0) or 0)
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * \
        jax.random.normal(key, shape, jnp.float32)
    scope[op.output("Out")] = out.astype(dtype)


@register("range")
def _range(op, scope, feeds, fetches):
    start = scope.fetch(op.input("Start")).reshape(())
    end = scope.fetch(op.input("End")).reshape(())
    step = scope.fetch(op.input("Step")).reshape(())
    # static-shape requirement: bounds must be compile-time constants
    scope[op.output("Out")] = jnp.arange(float(start), float(end),
                                         float(step))


@register("cumsum")
def _cumsum(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    scope[op.output("Out")] = jnp.cumsum(x, axis=op.attr("axis", -1))


# ---------------------------------------------------------------------------
# optimizer ops (reference operators/optimizers/) — executed in-program so
# Executor.run on a minimize()d program IS a training step; the Executor
# writes updated persistable vars back into its scope between runs.
# ---------------------------------------------------------------------------
@register("sgd")
def _sgd(op, scope, feeds, fetches):
    p = scope.fetch(op.input("Param"))
    g = scope.fetch(op.input("Grad"))
    lr = jnp.reshape(scope.fetch(op.input("LearningRate")), ())
    scope[op.output("ParamOut")] = p - lr * g


@register("momentum")
def _momentum_op(op, scope, feeds, fetches):
    p = scope.fetch(op.input("Param"))
    g = scope.fetch(op.input("Grad"))
    lr = jnp.reshape(scope.fetch(op.input("LearningRate")), ())
    vname = op.input("Velocity")
    v = scope.get(vname)
    if v is None:
        v = jnp.zeros_like(p)
    mu = op.attr("mu", 0.9)
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    scope[op.output("ParamOut")] = p_new
    scope[op.output("VelocityOut")] = v_new


# ---------------------------------------------------------------------------
# reductions / comparisons / logicals (reference reduce_ops/, controlflow/
# compare_op.cc + logical_op.cc macro families)
# ---------------------------------------------------------------------------
def _reduce_axes(op, x):
    if op.attr("reduce_all", False):
        return None
    dims = op.attr("dim", [0]) or [0]
    return tuple(int(d) % x.ndim for d in dims)


for _name, _red in [
    ("reduce_sum", jnp.sum), ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max), ("reduce_min", jnp.min),
    ("reduce_prod", jnp.prod), ("reduce_all", jnp.all),
    ("reduce_any", jnp.any),
]:
    def _mkr(red):
        def _op(op, scope, feeds, fetches):
            x = scope.fetch(op.input("X"))
            scope[op.output("Out")] = red(
                x, axis=_reduce_axes(op, x),
                keepdims=op.attr("keep_dim", False))
        return _op
    OP_TRANSLATORS[_name] = _mkr(_red)

for _name, _cmp in [
    ("equal", jnp.equal), ("not_equal", jnp.not_equal),
    ("less_than", jnp.less), ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater), ("greater_equal", jnp.greater_equal),
    ("logical_and", jnp.logical_and), ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    def _mkc(fn):
        def _op(op, scope, feeds, fetches):
            scope[op.output("Out")] = fn(scope.fetch(op.input("X")),
                                         scope.fetch(op.input("Y")))
        return _op
    OP_TRANSLATORS[_name] = _mkc(_cmp)


@register("logical_not")
def _logical_not(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.logical_not(scope.fetch(op.input("X")))


@register("where")
def _where(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.where(
        scope.fetch(op.input("Condition")), scope.fetch(op.input("X")),
        scope.fetch(op.input("Y")))


@register("fill_zeros_like", "fill_zeros_like2")
def _fill_zeros_like(op, scope, feeds, fetches):
    scope[op.output("Out")] = jnp.zeros_like(scope.fetch(op.input("X")))


@register("clip_by_norm")
def _clip_by_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    mn = op.attr("max_norm", 1.0)
    n = jnp.sqrt(jnp.sum(jnp.square(x)))
    scope[op.output("Out")] = jnp.where(n > mn, x * (mn / n), x)


@register("p_norm")
def _p_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    p = op.attr("porder", 2.0)
    axis = op.attr("axis", -1)
    keep = op.attr("keepdim", False)
    eps = op.attr("epsilon", 1e-12)
    if op.attr("asvector", False):
        x = x.reshape(-1)
        axis = 0
    ax = jnp.abs(x)
    if p == float("inf"):
        out = ax.max(axis=axis, keepdims=keep)
    elif p == float("-inf"):
        out = ax.min(axis=axis, keepdims=keep)
    elif p == 0:
        out = (ax > 0).sum(axis=axis, keepdims=keep).astype(x.dtype)
    else:
        out = (jnp.sum(ax ** p, axis=axis, keepdims=keep)
               + eps) ** (1.0 / p)
    scope[op.output("Out")] = out


@register("norm")
def _norm_op(op, scope, feeds, fetches):
    # reference norm_op: l2-normalize along `axis`, Norm aux output
    x = scope.fetch(op.input("X"))
    axis = op.attr("axis", -1)
    eps = op.attr("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    scope[op.output("Out")] = x / n
    if op.output("Norm"):
        scope[op.output("Norm")] = n


@register("sigmoid_cross_entropy_with_logits")
def _sce_logits(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    label = scope.fetch(op.input("Label")).astype(x.dtype)
    # max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = op.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if op.attr("normalize", False):
        denom = jnp.maximum((label != ignore).sum(), 1)
        loss = loss / denom
    scope[op.output("Out")] = loss


@register("cross_entropy", "cross_entropy2")
def _cross_entropy_op(op, scope, feeds, fetches):
    # input X holds PROBABILITIES (softmax output) in the reference op
    x = scope.fetch(op.input("X"))
    label = scope.fetch(op.input("Label"))
    if op.attr("soft_label", False):
        loss = -(label * jnp.log(jnp.clip(x, 1e-12, None))).sum(
            -1, keepdims=True)
    else:
        ignore = op.attr("ignore_index", -100)
        # arbitrary leading dims (e.g. [N,T,C] sequence labeling, which
        # the reference op supports): flatten to (-1, C), restore after
        c = x.shape[-1]
        lead = x.shape[:-1]
        xf = x.reshape(-1, c)
        lab = label.reshape(-1).astype(jnp.int32)
        picked = jnp.take_along_axis(
            xf, jnp.clip(lab, 0, c - 1)[:, None], axis=-1)
        loss = -jnp.log(jnp.clip(picked, 1e-12, None))
        loss = jnp.where(lab[:, None] == ignore, 0.0, loss)
        loss = loss.reshape(lead + (1,))
    scope[op.output("Y") or op.output("Out")] = loss


@register("group_norm")
def _group_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    groups = op.attr("groups", 1)
    eps = op.attr("epsilon", 1e-5)
    n, c = x.shape[:2]
    xg = x.reshape(n, groups, -1)
    mu = xg.mean(-1, keepdims=True)
    var = xg.var(-1, keepdims=True)
    out = ((xg - mu) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    if op.input("Scale"):
        s = scope.fetch(op.input("Scale")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
        out = out * s
    if op.input("Bias"):
        b = scope.fetch(op.input("Bias")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
        out = out + b
    scope[op.output("Y")] = out


@register("instance_norm")
def _instance_norm(op, scope, feeds, fetches):
    x = scope.fetch(op.input("X"))
    eps = op.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mu = x.mean(axes, keepdims=True)
    var = x.var(axes, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    c = x.shape[1]
    if op.input("Scale"):
        out = out * scope.fetch(op.input("Scale")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
    if op.input("Bias"):
        out = out + scope.fetch(op.input("Bias")).reshape(
            (1, c) + (1,) * (x.ndim - 2))
    scope[op.output("Y")] = out


def _via_functional(fn, *tensors, **kw):
    """Run a paddle_tpu functional op inside the interp trace and return
    the raw array(s) (dispatch handles tracers transparently)."""
    from ..core.tensor import unwrap

    out = fn(*tensors, **kw)
    if isinstance(out, tuple):
        return tuple(unwrap(o) for o in out)
    return unwrap(out)


@register("grid_sampler")
def _grid_sampler(op, scope, feeds, fetches):
    from ..nn.functional.common import grid_sample

    scope[op.output("Output")] = _via_functional(
        grid_sample, scope.fetch(op.input("X")),
        scope.fetch(op.input("Grid")),
        mode=op.attr("mode", "bilinear"),
        padding_mode=op.attr("padding_mode", "zeros"),
        align_corners=op.attr("align_corners", True))


@register("roi_align")
def _roi_align_op(op, scope, feeds, fetches):
    from ..vision.ops import roi_align

    rois = scope.fetch(op.input("ROIs"))
    if op.input("RoisNum"):
        num = scope.fetch(op.input("RoisNum"))
    else:
        # the fluid-era form carries per-image ROI counts via LoD, which
        # this padded representation doesn't retain — only the
        # single-image case is unambiguous without RoisNum
        if scope.fetch(op.input("X")).shape[0] != 1:
            raise NotImplementedError(
                "roi_align without RoisNum needs batch size 1 "
                "(LoD-carried ROI counts are not representable here)")
        num = jnp.asarray([rois.shape[0]], jnp.int32)
    scope[op.output("Out")] = _via_functional(
        roi_align, scope.fetch(op.input("X")), rois, num,
        (op.attr("pooled_height", 1), op.attr("pooled_width", 1)),
        spatial_scale=op.attr("spatial_scale", 1.0),
        sampling_ratio=op.attr("sampling_ratio", -1),
        aligned=op.attr("aligned", True))


@register("box_coder")
def _box_coder_op(op, scope, feeds, fetches):
    from ..vision.ops import box_coder

    out = _via_functional(
        box_coder, scope.fetch(op.input("PriorBox")),
        scope.fetch(op.input("PriorBoxVar"))
        if op.input("PriorBoxVar") else None,
        scope.fetch(op.input("TargetBox")),
        code_type=op.attr("code_type", "encode_center_size"),
        box_normalized=op.attr("box_normalized", True),
        axis=op.attr("axis", 0))
    scope[op.output("OutputBox")] = out


@register("prior_box")
def _prior_box_op(op, scope, feeds, fetches):
    from ..vision.ops import prior_box

    boxes, var = _via_functional(
        prior_box, scope.fetch(op.input("Input")),
        scope.fetch(op.input("Image")),
        min_sizes=op.attr("min_sizes", []),
        max_sizes=op.attr("max_sizes", []) or None,
        aspect_ratios=op.attr("aspect_ratios", [1.0]),
        variance=op.attr("variances", [0.1, 0.1, 0.2, 0.2]),
        flip=op.attr("flip", False), clip=op.attr("clip", False),
        steps=(op.attr("step_w", 0.0), op.attr("step_h", 0.0)),
        offset=op.attr("offset", 0.5),
        min_max_aspect_ratios_order=op.attr("min_max_aspect_ratios_order",
                                            False))
    scope[op.output("Boxes")] = boxes
    scope[op.output("Variances")] = var


@register("yolo_box")
def _yolo_box_op(op, scope, feeds, fetches):
    from ..vision.ops import yolo_box

    if op.attr("iou_aware", False):
        raise NotImplementedError(
            "yolo_box iou_aware=True (PP-YOLO layout) is not translated")
    boxes, scores = _via_functional(
        yolo_box, scope.fetch(op.input("X")),
        scope.fetch(op.input("ImgSize")),
        anchors=op.attr("anchors", []),
        class_num=op.attr("class_num", 1),
        conf_thresh=op.attr("conf_thresh", 0.01),
        downsample_ratio=op.attr("downsample_ratio", 32),
        clip_bbox=op.attr("clip_bbox", True),
        scale_x_y=op.attr("scale_x_y", 1.0))
    scope[op.output("Boxes")] = boxes
    scope[op.output("Scores")] = scores


@register("multiclass_nms", "multiclass_nms2", "multiclass_nms3")
def _multiclass_nms_op(op, scope, feeds, fetches):
    from ..vision.detection import multiclass_nms2

    if op.input("RoisNum"):
        raise NotImplementedError(
            "multiclass_nms with LoD-batched RoisNum input is not "
            "supported; export with dense [N, M, 4] boxes")
    want_index = bool(op.output("Index"))
    res = _via_functional(
        multiclass_nms2, scope.fetch(op.input("BBoxes")),
        scope.fetch(op.input("Scores")),
        op.attr("score_threshold", 0.05), op.attr("nms_top_k", 1000),
        op.attr("keep_top_k", 100),
        nms_threshold=op.attr("nms_threshold", 0.3),
        normalized=op.attr("normalized", True),
        nms_eta=op.attr("nms_eta", 1.0),
        background_label=op.attr("background_label", 0),
        return_index=want_index)
    if want_index:
        out, counts, index = res
        scope[op.output("Index")] = index
    else:
        out, counts = res
    scope[op.output("Out")] = out
    if op.output("NmsRoisNum"):
        scope[op.output("NmsRoisNum")] = counts
