"""`paddle.static.nn` — static-graph layer/control-flow surface.

Reference: `python/paddle/static/nn/__init__.py` (fc, control flow ops).
The control-flow ops lower to lax primitives (see ops/control_flow.py);
layer builders delegate to the shared nn layers since this framework has
one compiled representation rather than a separate static op graph.
"""
from ..ops.control_flow import case, cond, switch_case, while_loop

__all__ = ["case", "cond", "switch_case", "while_loop"]
