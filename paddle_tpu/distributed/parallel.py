"""Parallel environment bootstrap.

Reference: `python/paddle/distributed/parallel.py:58` init_parallel_env
(gloo KV store + NCCL id broadcast + ncclCommInitRank) and ParallelEnv
(`fluid/dygraph/parallel.py:71`, PADDLE_TRAINER_ID env conventions).

TPU-native: multi-host bootstrap is `jax.distributed.initialize` (PJRT
coordination service = the KV-store role); intra-host devices need no
process-per-device — one controller owns all local chips and SPMD partitions
work across them (SURVEY.md §2.3 row 1).
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))
        self._world = int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world

    @property
    def nranks(self):
        return self._world

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]


_INITIALIZED = [False]


def _jax_distributed_active() -> bool:
    """Whether jax.distributed.initialize already ran, WITHOUT touching the
    XLA backend (jax.process_count() would initialize it and make a later
    explicit initialize() call fail)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Initialize the distributed runtime.  Single-host: no-op (the
    controller already owns all chips).  Multi-host: wires up the PJRT
    coordination service."""
    if _INITIALIZED[0] or _jax_distributed_active():
        # already wired up (env-driven bootstrap at package import, or an
        # earlier call) — jax.distributed.initialize may only run once and
        # only before backend init
        _INITIALIZED[0] = True
        return ParallelEnv()
    addr = coordinator_address or os.environ.get("PADDLE_MASTER") \
        or os.environ.get("COORDINATOR_ADDRESS")
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = process_id if process_id is not None else int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if addr and nproc > 1:
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=nproc, process_id=pid
        )
    _INITIALIZED[0] = True
    return ParallelEnv()


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    return jax.process_count()
