"""Dataset / DataFeed fleet-run path.

Reference: `python/paddle/distributed/fleet/dataset/dataset.py`
(DatasetBase/InMemoryDataset/QueueDataset facades) over the C++
MultiSlotDataFeed (`framework/data_feed.cc:628` ParseOneInstance — per
line, per slot: `<num> v1 ... vnum`, float or uint64 by the slot var's
dtype) and the Dataset/Trainer run loop (`framework/data_set.h:157`,
`framework/trainer.h` MultiTrainer + HogwildWorker threads).

TPU-native: files are parsed by a thread pool (`thread_num` workers, the
multithread DataFeed analog), instances are batched into PADDED dense
arrays (+ `<name>.lod` lengths for ragged slots — the LoD replacement),
and `Executor.train_from_dataset` drives the whole-program XLA executable
over the batch stream, optimizer ops included.
"""
from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset",
           "DatasetFactory"]


def _is_int_dtype(dtype: str) -> bool:
    return "int" in str(dtype)


class DatasetBase:
    """reference `dataset.py DatasetBase` — batch_size/thread_num/use_var
    config plus a MultiSlot-format file list."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_vars = []
        self.pipe_command = "cat"
        self.input_type = 0

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._set_batch_size(batch_size)
        self._set_thread(thread_num)
        if use_var is not None:
            self._set_use_var(use_var)
        self._set_pipe_command(pipe_command)
        self.input_type = input_type

    def _set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def _set_thread(self, thread_num):
        self.thread_num = max(1, int(thread_num))

    def _set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def _set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def get_filelist(self):
        return list(self.filelist)

    # -- parsing ------------------------------------------------------------
    def _slot_specs(self):
        specs = []
        for v in self.use_vars:
            name = getattr(v, "name", str(v))
            dtype = str(getattr(v, "dtype", "float32"))
            shape = getattr(v, "shape", None)
            # rank-1 feed var ([-1] / [N]) => scalar-per-instance slot,
            # batched as [B]; anything else stays [B, width]
            rank1 = (len(shape) == 1) if shape else None
            specs.append((name, _is_int_dtype(dtype), rank1))
        return specs

    def _read_lines(self, path: str):
        if self.pipe_command and self.pipe_command != "cat":
            # reference: every file is piped through pipe_command
            # (`data_feed.cc` fp_ = popen) — e.g. "zcat" for gzip parts
            import subprocess

            # pipe_command is a user-supplied shell pipeline (reference
            # semantics), but the *filename* must not be interpolated
            # into the shell — feed it via stdin instead so paths with
            # spaces/metacharacters can't break parsing or run commands
            with open(path, "rb") as fin:
                r = subprocess.run(self.pipe_command, shell=True,
                                   stdin=fin, capture_output=True,
                                   text=True, check=True)
            return r.stdout.splitlines()
        with open(path, "r") as f:
            return f.read().splitlines()

    def _parse_file(self, path: str) -> List[List[np.ndarray]]:
        """One instance per line; per slot `<num> v1..vnum` in use_var
        order (MultiSlotDataFeed::ParseOneInstance)."""
        specs = self._slot_specs()
        instances = []
        for line in self._read_lines(path):
            parts = line.split()
            if not parts:
                continue
            pos = 0
            inst = []
            for _, is_int, _rank1 in specs:
                num = int(parts[pos])
                pos += 1
                vals = parts[pos:pos + num]
                pos += num
                if is_int:
                    inst.append(np.asarray([int(v) for v in vals],
                                           np.int64))
                else:
                    inst.append(np.asarray([float(v) for v in vals],
                                           np.float32))
            instances.append(inst)
        return instances

    def _parse_all(self) -> List[List[np.ndarray]]:
        if not self.filelist:
            return []
        with ThreadPoolExecutor(max_workers=self.thread_num) as pool:
            chunks = list(pool.map(self._parse_file, self.filelist))
        return [inst for chunk in chunks for inst in chunk]

    def _batches(self, instances, fixed_widths: Optional[List[int]] = None):
        """Yield {name: padded array, name+'.lod': lengths} per batch,
        including the final partial batch (the reference DataFeed yields
        it too).  `fixed_widths` pads each ragged slot to a constant
        width so batch shapes are stable across the epoch (one XLA
        compile); without it the width is the batch max.  A slot whose
        use_var is rank-1 collapses to [B] (the scalar-label case)."""
        specs = self._slot_specs()
        bs = self.batch_size
        for i in range(0, len(instances), bs):
            group = instances[i:i + bs]
            out: Dict[str, np.ndarray] = {}
            for s, (name, is_int, rank1) in enumerate(specs):
                vals = [inst[s] for inst in group]
                lens = np.asarray([len(v) for v in vals], np.int64)
                width = fixed_widths[s] if fixed_widths else \
                    (int(lens.max()) if len(lens) else 0)
                dt = np.int64 if is_int else np.float32
                pad = np.zeros((len(group), width), dt)
                for r, v in enumerate(vals):
                    pad[r, :len(v)] = v
                squeeze = rank1 if rank1 is not None else width == 1
                if squeeze and width == 1:
                    pad = pad[:, 0]
                out[name] = pad
                out[name + ".lod"] = lens
            yield out

    def _widths_of(self, instances) -> List[int]:
        specs = self._slot_specs()
        widths = [1] * len(specs)
        for inst in instances:
            for s in range(len(specs)):
                widths[s] = max(widths[s], len(inst[s]))
        return widths

    def _desc(self):
        specs = self._slot_specs()
        return "\n".join(
            f"slot {n} {'uint64' if i else 'float'}" for n, i, _ in specs)


class QueueDataset(DatasetBase):
    """Streaming dataset (reference QueueDataset): parses and yields
    batches file by file, nothing held in memory."""

    def iter_batches(self):
        for path in self.filelist:
            yield from self._batches(self._parse_file(path))


class InMemoryDataset(DatasetBase):
    """reference InMemoryDataset: load_into_memory + local/global shuffle
    before training."""

    def __init__(self):
        super().__init__()
        self._memory: List = []
        self._seed = 0

    def load_into_memory(self, is_shuffle=False):
        self._memory = self._parse_all()
        if is_shuffle:
            self.local_shuffle()

    def preload_into_memory(self, thread_num=None):
        if thread_num:
            self._set_thread(thread_num)
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        rng = np.random.RandomState(self._seed)
        rng.shuffle(self._memory)
        self._seed += 1

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-controller TPU runtime: every worker sees the global
        # stream, so a seeded local shuffle IS the global shuffle
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._memory)

    def iter_batches(self):
        # pad ragged slots to the global max so every batch has the same
        # shapes (one XLA compile per epoch stream)
        yield from self._batches(self._memory,
                                 fixed_widths=self._widths_of(self._memory))


class DatasetFactory:
    """reference `fluid/dataset.py DatasetFactory`."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")
