"""DataParallel wrapper.

Reference: `python/paddle/fluid/dygraph/parallel.py:382` (paddle.DataParallel
wrapping a Layer, broadcasting params via `sync_params_buffers` `:347`, and
bucketed fused allreduce through the C++ `Reducer`, `imperative/reducer.h:130`).

TPU-native: in the single-controller SPMD model, parameters live as global
(replicated) arrays, so there is nothing to broadcast; gradient reduction is
inserted by XLA when the train step is jit-compiled with the batch sharded
over 'dp'.  The Reducer's bucketing/overlap role is performed by the XLA
scheduler (async all-reduce overlapped with remaining backward — the same
overlap the Reducer implements manually with comm streams).  The wrapper
therefore (a) preserves the reference API, and (b) marks the model so
fleet.build_train_step shards the batch.
"""
from __future__ import annotations

from ...nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 hcg=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self.find_unused_parameters = find_unused_parameters
        # reference sync_params_buffers: ensure all ranks start identical.
        # Single-controller: params are already one global (replicated)
        # array — identity by construction.

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # reference DataParallel.scale_loss divides by nranks before
        # allreduce-sum; XLA's mean-over-global-batch does this implicitly
        return loss

    def apply_collective_grads(self):
        # grads are reduced inside the compiled step; nothing to flush
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    @property
    def parameters_(self):
        return self._layers.parameters()

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def sync_params_buffers(model, comm_group=None, src_rank=0,
                        is_model_parallel=False):
    """reference `parallel.py:347` — broadcast params from src.  Identity in
    single-controller mode (one global array)."""
    return model
