"""Sharded (hybrid-parallel) fused train step.

This is the TPU-native replacement for the reference's whole meta-optimizer
program-rewriting stack (`fleet/meta_optimizers/*`, SURVEY.md §2.3):
instead of inserting c_allreduce/c_broadcast/cast ops into a ProgramDesc,
the train step is jit-compiled over a named-axis Mesh with NamedShardings:

* data parallel      — batch sharded over 'dp'; XLA inserts the gradient
                       all-reduce (reference RawProgramOptimizer/Reducer).
* tensor parallel    — params carry ``mesh_axes`` specs ('mp'); XLA
                       partitions matmuls and inserts the activation
                       collectives (reference mp_layers + c_identity/
                       c_allreduce pattern).
* ZeRO sharding      — stage 1/2: optimizer state sharded over 'dp';
                       stage 3: parameters themselves sharded; XLA emits
                       reduce-scatter/all-gather (reference ShardingOptimizer
                       broadcast+reduce segments).
* gradient merge     — k-step micro-batch accumulation via lax.scan
                       (reference GradientMergeOptimizer).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...core import framework
from ...core.tensor import Tensor
from ...jit import _SwappedState


def _param_spec(p, zero_stage: int, mesh: Mesh) -> PartitionSpec:
    axes = getattr(p, "mesh_axes", None)
    dp = int(mesh.shape.get("dp", 1))
    if axes is not None:
        spec = list(axes)
    else:
        spec = [None] * max(p.ndim, 0)
    if zero_stage >= 3 and dp > 1:
        # shard over dp on the first unsharded dim divisible by dp
        for i, s in enumerate(spec):
            if s is None and p.shape[i] % dp == 0 and p.shape[i] >= dp:
                spec[i] = "dp"
                break
    return PartitionSpec(*spec)


def _opt_state_spec(pspec: PartitionSpec, p, zero_stage: int, mesh: Mesh):
    """Moment buffers follow the param spec; for ZeRO-1/2 they additionally
    shard over 'dp' even when the param is replicated."""
    dp = int(mesh.shape.get("dp", 1))
    spec = list(pspec)
    if zero_stage >= 1 and zero_stage < 3 and dp > 1:
        for i, s in enumerate(spec):
            if s is None and i < p.ndim and p.shape[i] % dp == 0 and p.shape[i] >= dp:
                spec[i] = "dp"
                break
    return PartitionSpec(*spec)


def _wrap_recompute_blocks(model, checkpoint_names):
    """Wrap selected sublayers' forwards in jax.checkpoint (reference
    RecomputeOptimizer checkpoints / recompute_configs["checkpoints"]).

    ``checkpoint_names`` selects sublayers by their `named_sublayers` name
    prefix; empty means every direct child with parameters.  Wrapping is
    idempotent and only active under a jit trace — eager calls fall
    through untouched."""
    targets = []
    if checkpoint_names:
        wanted = set(checkpoint_names)
        for name, ly in model.named_sublayers():
            if name in wanted:
                targets.append(ly)
    else:
        for _, ly in model.named_children():
            if ly.parameters():
                targets.append(ly)

    for ly in targets:
        if getattr(ly, "_recompute_wrapped", False):
            continue
        orig = ly.forward

        def ckpt_forward(*args, __orig=orig, **kwargs):
            if not framework.in_trace():
                return __orig(*args, **kwargs)
            t_pos = [i for i, a in enumerate(args)
                     if isinstance(a, Tensor)]
            arrs = [args[i]._array for i in t_pos]

            def pure(*xs):
                new_args = list(args)
                for i, x in zip(t_pos, xs):
                    new_args[i] = Tensor(x)
                out = __orig(*new_args, **kwargs)
                return out._array if isinstance(out, Tensor) else out

            return Tensor(jax.checkpoint(pure)(*arrs))

        ly.forward = ckpt_forward
        ly._recompute_wrapped = True


class ShardedTrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer, mesh: Mesh,
                 zero_stage: int = 0, grad_accum: int = 1,
                 batch_axis: str = "dp", donate: bool = True,
                 loss_dtype=jnp.float32, recompute: bool = False,
                 offload: bool = False, recompute_checkpoints=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.zero_stage = zero_stage
        self.grad_accum = max(1, grad_accum)
        self.batch_axis = batch_axis
        # strategy.recompute: rematerialize forward activations during the
        # backward pass (reference RecomputeOptimizer / fleet recompute).
        # Each recompute block's forward is wrapped in jax.checkpoint, so
        # only block-boundary activations are saved between fwd and bwd —
        # whole-forward remat would NOT reduce peak (the rematerialized
        # backward still holds every activation at once).
        self.recompute = recompute
        if recompute:
            names = list(recompute_checkpoints or [])
            _wrap_recompute_blocks(model, names)
        # sharding_configs["offload"]: keep optimizer moments in host
        # memory (reference sharding/offload_helper.py); falls back to
        # device memory where the backend has no pinned_host space
        self.offload = offload
        self._donate = donate
        params, buffers = model.functional_state()
        self._params = params
        self._buffers = buffers
        self._pnames = sorted(params)
        self._bnames = sorted(buffers)
        self._opt_state = None
        self._compiled = None
        self._step = 0
        self._buf_order = []

        self.param_shardings = {
            k: NamedSharding(mesh, _param_spec(params[k], zero_stage, mesh))
            for k in self._pnames
        }
        self.buffer_shardings = {
            k: NamedSharding(mesh, PartitionSpec()) for k in self._bnames
        }
        self._repl = NamedSharding(mesh, PartitionSpec())
        self._batch_sharding = NamedSharding(mesh, PartitionSpec(batch_axis))

    # -- placement ----------------------------------------------------------
    def place_state(self):
        """device_put params/buffers onto the mesh with their shardings."""
        for k in self._pnames:
            p = self._params[k]
            p._array = jax.device_put(p._array, self.param_shardings[k])
        for k in self._bnames:
            b = self._buffers[k]
            b._array = jax.device_put(b._array, self.buffer_shardings[k])

    def _maybe_host(self, sh: NamedSharding) -> NamedSharding:
        """Offload variant of a sharding: pinned host memory when the
        backend supports it (TPU), unchanged otherwise."""
        if not self.offload:
            return sh
        if not hasattr(self, "_host_ok"):
            # probe once: not just device_put — the whole in-jit
            # host->device->host round trip must compile (the CPU SPMD
            # partitioner rejects pinned_host placement annotations)
            try:
                host = self._repl.with_memory_kind("pinned_host")
                dev = self._repl.with_memory_kind("device")
                probe = jax.jit(
                    lambda a: jax.device_put(
                        jax.device_put(a, dev) + 1.0, host),
                    in_shardings=host, out_shardings=host)
                jax.block_until_ready(probe(
                    jax.device_put(jnp.zeros((), jnp.float32), host)))
                self._host_ok = True
            except Exception:
                self._host_ok = False
        if not self._host_ok:
            return sh
        try:
            return sh.with_memory_kind("pinned_host")
        except Exception:
            return sh

    def _opt_shardings(self, opt_state):
        out = {}
        for k in self._pnames:
            p = self._params[k]
            pspec = _param_spec(p, self.zero_stage, self.mesh)
            sspec = _opt_state_spec(pspec, p, self.zero_stage, self.mesh)
            slots = {}
            for sk, sv in opt_state[k].items():
                if getattr(sv, "ndim", 0) == p.ndim and p.ndim > 0:
                    slots[sk] = self._maybe_host(
                        NamedSharding(self.mesh, sspec))
                else:
                    slots[sk] = self._maybe_host(self._repl)
            out[k] = slots
        return out

    def _build(self, n_batch_args: int):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        params, buffers = self._params, self._buffers
        pnames, bnames = self._pnames, self._bnames
        buf_order = self._buf_order
        K = self.grad_accum
        from ...optimizer.optimizer import collect_lr_mults
        lr_mults = collect_lr_mults(params)

        def forward_loss(pa, barr, rng, micro_batch):
            writes: Dict[int, Any] = {}
            swap = {k: params[k] for k in pnames}
            swap.update({f"__buf__{k}": buffers[k] for k in bnames})
            with _SwappedState(swap) as sw:
                sw.bind(pa)
                sw.bind({f"__buf__{k}": barr[k] for k in bnames})
                with framework.trace_guard(rng_key=rng, writes=writes):
                    batch_t = [Tensor(b) for b in micro_batch]
                    loss = loss_fn(model, *batch_t)
            loss_arr = loss._array if isinstance(loss, Tensor) else loss
            buf_order.clear()
            wmap = {}
            for k in bnames:
                t = buffers[k]
                if id(t) in writes:
                    buf_order.append(k)
                    wmap[k] = writes[id(t)]
            return loss_arr.astype(jnp.float32), wmap

        def pure(parr, opt_state, barr, lr, step, rng, batch):
            if K == 1:
                (loss, wmap), grads = jax.value_and_grad(
                    forward_loss, has_aux=True)(parr, barr, rng, batch)
            else:
                # gradient merge: micro-batch scan (reference
                # GradientMergeOptimizer k_steps accumulation)
                micro = [
                    b.reshape((K, b.shape[0] // K) + b.shape[1:]) for b in batch
                ]
                keys = jax.random.split(rng, K)

                def body(carry, xs):
                    acc, loss_acc = carry
                    key, *mb = xs
                    (l, w), g = jax.value_and_grad(
                        forward_loss, has_aux=True)(parr, barr, key, tuple(mb))
                    acc = jax.tree_util.tree_map(jnp.add, acc, g)
                    return (acc, loss_acc + l), w

                zero = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), parr
                )
                (gsum, lsum), wmaps = jax.lax.scan(
                    body, (zero, jnp.zeros((), jnp.float32)),
                    (keys, *micro),
                )
                grads = jax.tree_util.tree_map(lambda g: (g / K).astype(jnp.float32), gsum)
                loss = lsum / K
                wmap = jax.tree_util.tree_map(lambda w: w[-1], wmaps)

            if host_opt_shardings is not None:
                # offload: moments live in pinned host memory between
                # steps; bring them on-device for the update, push back
                # after (XLA overlaps the transfers with compute)
                opt_state = jax.device_put(opt_state, dev_opt_shardings)
            new_params, new_opt = optimizer.apply_gradients(
                parr, grads, opt_state, lr, step, lr_mults=lr_mults
            )
            if host_opt_shardings is not None:
                new_opt = jax.device_put(new_opt, host_opt_shardings)
            new_bufs = dict(barr)
            new_bufs.update(wmap)
            return loss, new_params, new_opt, new_bufs

        opt_sh = self._opt_shardings(self._opt_state)
        if self.offload and getattr(self, "_host_ok", False):
            host_opt_shardings = opt_sh
            dev_opt_shardings = jax.tree_util.tree_map(
                lambda s: s.with_memory_kind("device"), opt_sh,
                is_leaf=lambda x: isinstance(x, NamedSharding))
        else:
            host_opt_shardings = dev_opt_shardings = None

        in_shardings = (
            {k: self.param_shardings[k] for k in pnames},
            opt_sh,
            {k: self.buffer_shardings[k] for k in bnames},
            self._repl, self._repl, self._repl,
            tuple(self._batch_sharding for _ in range(n_batch_args)),
        )
        out_shardings = (
            self._repl,
            {k: self.param_shardings[k] for k in pnames},
            opt_sh,
            {k: self.buffer_shardings[k] for k in bnames},
        )
        donate = (1, 2) if self._donate else ()
        with self.mesh:
            return jax.jit(pure, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate)

    def __call__(self, *batch) -> Tensor:
        batch_arrs = tuple(
            b._array if isinstance(b, Tensor) else jnp.asarray(b) for b in batch
        )
        if self._opt_state is None:
            self.place_state()
            state = self.optimizer.init_state(self._params)
            # place optimizer slots on their (possibly dp-sharded) shardings —
            # zeros_like inherits the param placement, which differs under
            # ZeRO-1/2 where moments shard but params stay replicated
            shardings = self._opt_shardings(state)
            self._opt_state = {
                k: {sk: jax.device_put(sv, shardings[k][sk])
                    for sk, sv in slots.items()}
                for k, slots in state.items()
            }
        if self._compiled is None:
            self._compiled = self._build(len(batch_arrs))
        self._step += 1
        parr = {k: self._params[k]._array for k in self._pnames}
        barr = {k: self._buffers[k]._array for k in self._bnames}
        batch_arrs = tuple(
            jax.device_put(b, self._batch_sharding) for b in batch_arrs
        )
        rng = framework.default_generator.next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        with self.mesh:
            loss, new_params, new_opt, new_bufs = self._compiled(
                parr, self._opt_state, barr, lr, self._step, rng, batch_arrs
            )
        with framework.no_grad_guard():
            for k in self._pnames:
                self._params[k]._array = new_params[k]
            for k in self._bnames:
                self._buffers[k]._array = new_bufs[k]
        self._opt_state = new_opt
        return Tensor(loss)
