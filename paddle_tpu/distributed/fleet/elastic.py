"""Elastic training manager.

Reference: `python/paddle/distributed/fleet/elastic.py:90-328`
(`ElasticManager`): registers this node in **etcd**, watches the
host/np/endpoint keys, and on membership change kills local trainers and
relaunches them with re-assigned ranks; scale-in/out is matched against
`PADDLE_ELASTIC_NP`.

TPU-native: etcd is an environment detail — the manager takes a pluggable
KV store.  `FileKVStore` (shared filesystem, the common TPU-pod case)
ships in-tree; an etcd adapter can implement the same 4-method interface.
The watch loop and rank-reassignment semantics follow the reference.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ElasticManager", "FileKVStore", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class FileKVStore:
    """Membership KV on a shared filesystem (stands in for the reference's
    etcd prefix `/paddle/<job_id>/nodes/`)."""

    def __init__(self, root: str):
        self._root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key: str, value: str):
        path = os.path.join(self._root, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[str]:
        path = os.path.join(self._root, key)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return f.read()

    def delete(self, key: str):
        path = os.path.join(self._root, key)
        if os.path.exists(path):
            os.remove(path)

    def list(self, prefix: str) -> Dict[str, str]:
        d = os.path.join(self._root, prefix)
        out = {}
        if os.path.isdir(d):
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".tmp"):
                    continue
                with open(os.path.join(d, fn)) as f:
                    out[f"{prefix}/{fn}"] = f.read()
        return out


class ElasticManager:
    """Watches membership; on change, re-ranks and triggers restart.

    `on_restart(new_ranks: dict)` is the relaunch hook (the reference kills
    and respawns local trainer procs; tests inject a recorder)."""

    def __init__(self, kv, job_id: Optional[str] = None,
                 host: Optional[str] = None,
                 np_target: Optional[int] = None,
                 watch_interval_s: float = 0.2,
                 on_restart: Optional[Callable] = None):
        self.kv = kv
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        self.host = host or os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                           "127.0.0.1:0")
        self.np_target = int(np_target if np_target is not None else
                             os.environ.get("PADDLE_ELASTIC_NP", "1"))
        self.watch_interval_s = watch_interval_s
        self.on_restart = on_restart
        self._prefix = f"{self.job_id}/nodes"
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._last_members: Optional[List[str]] = None
        self.enabled = self.np_target > 0

    # -- membership ---------------------------------------------------------
    def register(self):
        """reference `:154` — publish this node under the job prefix."""
        self.kv.put(f"{self._prefix}/{self.host.replace(':', '_')}",
                    json.dumps({"host": self.host, "ts": time.time()}))

    def deregister(self):
        self.kv.delete(f"{self._prefix}/{self.host.replace(':', '_')}")

    def hosts(self) -> List[str]:
        vals = self.kv.list(self._prefix)
        return sorted(json.loads(v)["host"] for v in vals.values())

    def _assign_ranks(self, members: List[str]) -> Dict[str, int]:
        return {h: i for i, h in enumerate(members)}

    # -- scale decisions (reference `_match` / scale-in/out `:246`) ---------
    def match(self) -> bool:
        """True when membership equals the elastic target np."""
        return len(self.hosts()) == self.np_target

    def status(self) -> str:
        n = len(self.hosts())
        if n == self.np_target:
            return ElasticStatus.COMPLETED
        return ElasticStatus.HOLD

    # -- watch loop (reference `watch` `:301`) ------------------------------
    def start(self):
        self._running = True
        self._last_members = self.hosts()
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()

    def stop(self):
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _watch(self):
        while self._running:
            time.sleep(self.watch_interval_s)
            try:
                members = self.hosts()
            except OSError:
                continue
            if members != self._last_members:
                self._last_members = members
                ranks = self._assign_ranks(members)
                # reference `_update_hosts` `:246`: re-rank, then restart
                if self.on_restart is not None:
                    self.on_restart(ranks)
