"""fleet — distributed training API (reference
`python/paddle/distributed/fleet/`)."""
from . import meta_optimizers, meta_parallel, utils
from .base import Fleet, PaddleCloudRoleMaker, RoleMakerBase, fleet
from .dataset import (DatasetBase, DatasetFactory, InMemoryDataset,
                      QueueDataset)
from .data_parallel import DataParallel
from .sharded_step import ShardedTrainStep
from .strategy import DistributedStrategy

# module-level singleton API, matching `fleet.init(...)` usage
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
build_train_step = fleet.build_train_step
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
minimize = fleet.minimize
