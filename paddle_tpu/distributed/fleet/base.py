"""fleet singleton.

Reference: `python/paddle/distributed/fleet/base/fleet_base.py:139`
(fleet.init), `:783` (distributed_optimizer), `:836` (distributed_model),
`:1288` (minimize) and the StrategyCompiler meta-optimizer chain
(`fleet/base/strategy_compiler.py:91,173`).

TPU-native: `init` builds the 4-D mesh topology; `distributed_model` +
`distributed_optimizer` wire the model/optimizer into a ShardedTrainStep
whose jit shardings express the strategy — the "meta-optimizer chain" is the
(zero_stage, grad_accum, mesh axes, recompute) configuration of that one
compiled step rather than a sequence of program rewrites.
"""
from __future__ import annotations

from typing import Optional

import jax

from ..topology import (HybridCommunicateGroup, build_mesh,
                        set_hybrid_communicate_group)
from .strategy import DistributedStrategy


class RoleMakerBase:
    def __init__(self, is_collective=True):
        self._is_collective = is_collective

    def worker_index(self):
        return jax.process_index()

    def worker_num(self):
        return jax.process_count()

    def is_worker(self):
        return True

    def is_server(self):
        return False


class PaddleCloudRoleMaker(RoleMakerBase):
    """reference `fleet/base/role_maker.py:530` — parses PADDLE_* env,
    including the PS-mode role split (TRAINING_ROLE=PSERVER/TRAINER,
    PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_PORT)."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__(is_collective)
        import os

        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID",
                                              jax.process_index()))
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                                jax.process_count()))
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in eps.split(",") if e]
        self._current_endpoint = os.environ.get(
            "PADDLE_CURRENT_ENDPOINT",
            "127.0.0.1:" + os.environ.get("PADDLE_PORT", "0"))

    def worker_index(self):
        return self._trainer_id

    def worker_num(self):
        return self._trainers_num

    def is_worker(self):
        return self._role == "TRAINER"

    def is_server(self):
        return self._role == "PSERVER"

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def server_index(self):
        """This server's position in PADDLE_PSERVERS_IP_PORT_LIST (its
        dense-table shard index)."""
        try:
            return self._server_endpoints.index(self._current_endpoint)
        except ValueError:
            return 0


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._optimizer = None
        self._user_optimizer = None

    # -- lifecycle ----------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        if self._role_maker.is_server() or self._strategy.a_sync:
            # PS mode: no device mesh; tables/clients are built lazily by
            # init_server/init_worker (reference TheOnePSRuntime split)
            return self
        h = self._strategy.hybrid_configs
        dp = int(h.get("dp_degree", 1))
        mp = int(h.get("mp_degree", 1))
        pp = int(h.get("pp_degree", 1))
        sp = int(h.get("sp_degree", 1))
        sharding = int(h.get("sharding_degree", 1))
        ndev = len(jax.devices())
        total = dp * mp * pp * sp * max(sharding, 1)
        if dp == 1 and mp == 1 and pp == 1 and sp == 1 and sharding <= 1:
            dp = ndev  # pure DP over all devices by default
        mesh = build_mesh(dp=dp * max(sharding, 1), pp=pp, sp=sp, mp=mp)
        self._hcg = HybridCommunicateGroup(mesh=mesh, sharding=sharding)
        set_hybrid_communicate_group(self._hcg)
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def mesh(self):
        return self._hcg.mesh if self._hcg else None

    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    # -- strategy wiring ----------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        st = self._strategy or DistributedStrategy()
        from .meta_optimizers import StrategyCompiler

        optimizer, applied = StrategyCompiler().generate_optimizer(
            optimizer, st)
        self._applied_meta_optimizers = applied
        self._user_optimizer = optimizer
        return optimizer

    def distributed_model(self, model):
        from .data_parallel import DataParallel
        from .meta_parallel.pipeline_parallel import PipelineLayer

        if self._hcg is None:
            self.init()
        if isinstance(model, PipelineLayer):
            from .meta_parallel.pipeline_parallel import PipelineParallel

            return PipelineParallel(model, self._hcg, self._strategy)
        return DataParallel(model, hcg=self._hcg, strategy=self._strategy)

    def build_train_step(self, model, loss_fn, optimizer=None):
        """TPU-native entry: compile the strategy into one sharded step.

        With ``hybrid_configs["pp_degree"] > 1`` and a PipelineLayer model,
        this returns the compiled 1F1B pipeline step (params sharded per
        stage over 'pp'); loss_fn then takes ``(output, label)`` like the
        reference PipelineLayer loss.  Otherwise the GSPMD ShardedTrainStep
        (dp/mp/zero/grad-merge) with ``loss_fn(model, *batch)``."""
        from .sharded_step import ShardedTrainStep

        opt = optimizer or self._user_optimizer
        st = self._strategy or DistributedStrategy()
        inner = model.network if hasattr(model, "network") else model
        inner = getattr(inner, "_layers", inner)
        mesh = self._hcg.mesh
        pp = int(mesh.shape.get("pp", 1))
        from .meta_parallel.pipeline_parallel import PipelineLayer

        if pp > 1 and isinstance(inner, PipelineLayer):
            from .pipeline_step import PipelineTrainStep

            n_micro = int(st.pipeline_configs.get("accumulate_steps", pp)) \
                if st.pipeline else pp
            return PipelineTrainStep(inner, loss_fn, opt, mesh,
                                     n_micro=n_micro)
        zero = int(st.sharding_configs.get("stage", 1)) if st.sharding else 0
        k = int(st.gradient_merge_configs.get("k_steps", 1)) if st.gradient_merge else 1
        offload = bool(st.sharding and
                       st.sharding_configs.get("offload", False))
        return ShardedTrainStep(inner, loss_fn, opt, mesh,
                                zero_stage=zero, grad_accum=k,
                                recompute=bool(st.recompute),
                                offload=offload,
                                recompute_checkpoints=st.recompute_configs
                                .get("checkpoints") if st.recompute
                                else None)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._user_optimizer is None:
            raise RuntimeError("call fleet.distributed_optimizer first")
        return self._user_optimizer.minimize(loss)

    # -- parameter-server runtime (reference fleet_base init_server/
    #    run_server/init_worker/stop_worker + TheOnePSRuntime) --------------
    def init_server(self, tables=None, port=None, n_trainers=None):
        """Build the native PS with `tables`:
        {table_id: ("dense", size, lr, optimizer) | ("sparse", dim, lr)}.

        With multiple configured pservers, `size` is the GLOBAL dense size:
        each server creates only its contiguous block
        (`shard_dense_sizes`), matching the trainer-side ShardedPSClient
        routing."""
        from ..ps import PSServer, shard_dense_sizes

        eps = getattr(self._role_maker, "get_pserver_endpoints",
                      lambda: [])() or []
        n_servers = max(1, len(eps))
        my_idx = getattr(self._role_maker, "server_index", lambda: 0)() \
            if n_servers > 1 else 0
        srv = PSServer()
        for tid, spec in (tables or {}).items():
            kind, *rest = spec
            if kind == "dense":
                size = rest[0]
                if n_servers > 1:
                    size = shard_dense_sizes(size, n_servers)[my_idx]
                lr = rest[1] if len(rest) > 1 else 0.01
                opt = rest[2] if len(rest) > 2 else "sgd"
                srv.create_dense_table(tid, size, lr, opt)
            elif kind == "sparse":
                dim = rest[0]
                lr = rest[1] if len(rest) > 1 else 0.01
                opt = rest[2] if len(rest) > 2 else "sgd"
                srv.create_sparse_table(tid, dim, lr, opt)
            else:
                raise ValueError(f"unknown table kind {spec[0]}")
        ep = getattr(self._role_maker, "_current_endpoint", "127.0.0.1:0")
        if port is None:
            port = int(ep.rsplit(":", 1)[1]) if ":" in ep else 0
        # bind the interface the endpoint advertises: loopback endpoints
        # stay loopback (safe default); a routable endpoint must accept
        # remote trainers, so bind all interfaces there
        host = ep.rsplit(":", 1)[0] if ":" in ep else "127.0.0.1"
        bind = "127.0.0.1" if host in ("127.0.0.1", "localhost") else "0.0.0.0"
        self._ps_server = srv
        self._ps_port = srv.start(port, n_trainers or self.worker_num(),
                                  host=bind)
        return self._ps_port

    def run_server(self):
        """Block serving until stop (reference server_proc.join)."""
        import time

        srv = getattr(self, "_ps_server", None)
        while srv is not None and not srv.is_stopped():
            time.sleep(0.2)
        if srv is not None:
            srv.stop()  # join native threads after a remote OP_STOP

    def init_worker(self, endpoint=None, mode=None):
        from ..ps import Communicator, PSClient, ShardedPSClient

        if endpoint is None:
            eps = self._role_maker.get_pserver_endpoints()
            if len(eps) > 1:
                # client-side table sharding across all configured servers
                # (reference brpc_ps_client fan-out)
                self._ps_client = ShardedPSClient(eps)
            else:
                endpoint = eps[0] if eps else "127.0.0.1:0"
        if endpoint is not None:
            host, port = endpoint.rsplit(":", 1)
            self._ps_client = PSClient(host, int(port))
        st = self._strategy or DistributedStrategy()
        if mode is None:
            k = int(st.a_sync_configs.get("k_steps", -1))
            mode = "geo" if k > 0 else ("async" if st.a_sync else "sync")
        self._ps_communicator = Communicator(
            self._ps_client, mode=mode,
            k_steps=max(1, int(st.a_sync_configs.get("k_steps", 1))))
        if mode == "async":
            self._ps_communicator.start()
        return self._ps_client

    def stop_worker(self):
        comm = getattr(self, "_ps_communicator", None)
        if comm is not None:
            comm.stop()
        client = getattr(self, "_ps_client", None)
        if client is not None:
            try:
                client.barrier(trainer_id=self.worker_index())
            except RuntimeError:
                pass  # server already stopping
            client.close()

    def stop_server(self):
        srv = getattr(self, "_ps_server", None)
        if srv is not None:
            srv.stop()

    # -- persistence hooks (reference fleet save/load) ----------------------
    def save_persistables(self, executor=None, dirname=None, main_program=None):
        pass


fleet = Fleet()
