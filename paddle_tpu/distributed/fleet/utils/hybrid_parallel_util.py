"""Hybrid-parallel gradient sync helpers.

Reference: `fleet/utils/hybrid_parallel_util.py:117` fused_allreduce_gradients
— manual bucketed allreduce of grads across the DP group for dygraph hybrid
runs.  TPU-native: gradient reduction happens inside the compiled sharded
step (XLA all-reduce over 'dp'), so this is the identity; it exists so
reference training scripts run unchanged.
"""
from __future__ import annotations


def fused_allreduce_gradients(parameter_list, hcg=None):
    return parameter_list


def sharding_reduce_gradients(parameter_list, hcg=None):
    return parameter_list
