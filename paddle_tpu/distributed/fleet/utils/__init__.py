from .recompute import recompute
from .hybrid_parallel_util import fused_allreduce_gradients
