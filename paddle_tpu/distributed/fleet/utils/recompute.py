"""Activation recompute (gradient checkpointing).

Reference: `python/paddle/distributed/fleet/utils/recompute.py:63` —
RecomputeFunction(PyLayer) stashes RNG state, drops activations, and replays
forward during backward.

TPU-native: under a jit trace this is exactly `jax.checkpoint` (XLA
rematerialization — RNG replay is automatic because keys are explicit).
In eager mode the function simply runs (the eager tape keeps residuals;
memory savings only materialize on the compiled path, which is the one that
matters on TPU).
"""
from __future__ import annotations

import jax

from ....core import framework
from ....core.dispatch import dispatch
from ....core.tensor import Tensor


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    if framework.in_trace():
        tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        const = list(args)

        def inner(*arrs):
            call = list(const)
            for p, a in zip(tensor_pos, arrs):
                call[p] = Tensor(a)
            out = function(*call, **kwargs)
            outs = out if isinstance(out, (list, tuple)) else (out,)
            return tuple(o._array if isinstance(o, Tensor) else o for o in outs)

        ck = jax.checkpoint(inner)
        out = dispatch(ck, *[args[i] for i in tensor_pos])
        if isinstance(out, tuple) and len(out) == 1:
            return out[0]
        return out
    return function(*args, **kwargs)
