"""Fleet meta-optimizers.

Reference: `python/paddle/distributed/fleet/meta_optimizers/` — 16 program-
rewriting optimizers chained by `StrategyCompiler`
(`fleet/base/strategy_compiler.py:91,173` longest-compatible-chain).

TPU-native re-design: a meta-optimizer here is a **gradient/step transform
wrapper** around the functional `Optimizer` (compose like optax transforms)
instead of a ProgramDesc rewriter.  The SPMD concerns the reference handles
by inserting collective ops (raw_program, sharding, tensor_parallel,
pipeline) live in `fleet.build_train_step`/`ShardedTrainStep` shardings;
what remains here are the *numerical* strategies:

| reference meta-optimizer                  | this module                    |
|-------------------------------------------|--------------------------------|
| GradientMergeOptimizer (`gradient_merge_optimizer.py:20`) | GradientMergeOptimizer |
| LocalSGDOptimizer / Adaptive (`localsgd_optimizer.py:26,197`) | LocalSGDOptimizer |
| DGCOptimizer (`dgc_optimizer.py:21` + dgc_op)  | DGCOptimizer          |
| FP16AllReduceOptimizer (`fp16_allreduce_optimizer.py:20`) | FP16AllReduceOptimizer |
| LambOptimizer / LarsOptimizer (`lamb_optimizer.py:22`, `lars_optimizer.py:21`) | swap handled by StrategyCompiler |
| LookaheadOptimizer (`fluid/optimizer.py:5969`) | LookaheadOptimizer    |
| ModelAverage (`fluid/optimizer.py:3573`)       | ModelAverage          |
| ExponentialMovingAverage (`fluid/optimizer.py:3882`) | ExponentialMovingAverage |
| AMPOptimizer (`amp_optimizer.py:20`)           | paddle_tpu.amp.GradScaler/decorate |
| RecomputeOptimizer (`recompute_optimizer.py:20`) | fleet.utils.recompute |
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ....core import framework
from ....core.tensor import Tensor

__all__ = [
    "MetaOptimizerBase", "GradientMergeOptimizer", "LocalSGDOptimizer",
    "DGCOptimizer", "FP16AllReduceOptimizer", "LookaheadOptimizer",
    "ModelAverage", "ExponentialMovingAverage", "StrategyCompiler",
    "DygraphShardingOptimizer",
]


class MetaOptimizerBase:
    """Wraps a user Optimizer; delegates everything not overridden."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def inner(self):
        return self._inner

    def step(self):
        self._inner.step()

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)


class GradientMergeOptimizer(MetaOptimizerBase):
    """Accumulate grads for k micro-steps, apply once (reference
    `gradient_merge_optimizer.py:20`; static twin `fluid/optimizer.py:6141`).
    """

    def __init__(self, inner, k_steps=2, avg=True):
        super().__init__(inner)
        self.k_steps = max(1, int(k_steps))
        self.avg = avg
        self._acc = {}
        self._count = 0

    def step(self):
        with framework.no_grad_guard():
            params = self._inner._parameters or []
            for p in params:
                if p.grad is None:
                    continue
                a = self._acc.get(id(p))
                self._acc[id(p)] = p.grad._array if a is None else a + p.grad._array
            self._count += 1
            if self._count < self.k_steps:
                for p in params:
                    p.grad = None
                return
            scale = 1.0 / self.k_steps if self.avg else 1.0
            for p in params:
                a = self._acc.get(id(p))
                if a is not None:
                    p.grad = Tensor(a * scale)
            self._inner.step()
            self._acc.clear()
            self._count = 0


class LocalSGDOptimizer(MetaOptimizerBase):
    """Step locally every step; every `k_steps`, average parameters across
    the data-parallel group (reference `localsgd_optimizer.py:26`).

    `adaptive=True` follows AdaptiveLocalSGD (`localsgd_optimizer.py:197`):
    the averaging interval grows as loss shrinks —
    ``k = clip(ceil(init_k_steps * sqrt(loss_0 / loss_t)), 1, k_steps)``;
    pass the current loss to `step(loss=...)` to drive it."""

    def __init__(self, inner, k_steps=4, group=None, adaptive=False,
                 init_k_steps=1):
        super().__init__(inner)
        self.k_steps = max(1, int(k_steps))
        self._group = group
        self._adaptive = bool(adaptive)
        self._init_k_steps = max(1, int(init_k_steps))
        self._loss0 = None
        self._cur_k = self._init_k_steps if adaptive else self.k_steps
        self._tick = 0

    def step(self, loss=None):
        self._inner.step()
        self._tick += 1
        if self._adaptive and loss is not None:
            val = float(loss.numpy()) if hasattr(loss, "numpy") else float(loss)
            if self._loss0 is None:
                self._loss0 = max(val, 1e-12)
            import math

            self._cur_k = int(min(self.k_steps, max(
                1, math.ceil(self._init_k_steps *
                             math.sqrt(self._loss0 / max(val, 1e-12))))))
        if self._tick % self._cur_k:
            return
        from ...collective import all_reduce
        from ...parallel import get_world_size

        n = get_world_size(self._group)
        if n <= 1:
            return
        with framework.no_grad_guard():
            for p in self._inner._parameters or []:
                all_reduce(p, group=self._group)
                p._array = p._array / n


class DGCOptimizer(MetaOptimizerBase):
    """Deep Gradient Compression (reference `dgc_optimizer.py:21`,
    `operators/dgc_op.*`, lib `cmake/external/dgc.cmake`): before the
    gradient exchange, keep only the top-`sparsity` fraction of gradient
    entries by magnitude; the residual accumulates locally with momentum
    correction and is added back next step."""

    def __init__(self, inner, rampup_begin_step=0, sparsity=0.999,
                 momentum=0.9):
        super().__init__(inner)
        self.rampup_begin_step = int(rampup_begin_step)
        self.sparsity = float(sparsity)
        self.momentum = float(momentum)
        self._u = {}  # momentum-corrected residual per param
        self._tick = 0

    @staticmethod
    def _topk_mask(g, keep_ratio):
        k = max(1, int(round(g.size * keep_ratio)))
        flat = jnp.abs(g.reshape(-1))
        thresh = jax.lax.top_k(flat, k)[0][-1]
        return (jnp.abs(g) >= thresh).astype(g.dtype)

    def step(self):
        self._tick += 1
        if self._tick <= self.rampup_begin_step:
            return self._inner.step()
        keep = 1.0 - self.sparsity
        with framework.no_grad_guard():
            for p in self._inner._parameters or []:
                if p.grad is None:
                    continue
                g = p.grad._array
                u = self._u.get(id(p))
                u = g if u is None else self.momentum * u + g
                mask = self._topk_mask(u, keep)
                sparse = u * mask
                self._u[id(p)] = u - sparse  # residual stays local
                p.grad = Tensor(sparse)
        self._inner.step()


class FP16AllReduceOptimizer(MetaOptimizerBase):
    """Halve gradient-exchange bytes: THIS wrapper performs the gradient
    all-reduce itself on bf16/fp16-cast grads (then averages and upcasts),
    so it must be used with unreduced local grads — i.e. without
    DataParallel's own reduction (reference `fp16_allreduce_optimizer.py:20`
    casts the c_allreduce inputs the same way)."""

    def __init__(self, inner, dtype="bfloat16", group=None):
        super().__init__(inner)
        self._dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
        self._group = group

    def step(self):
        from ...collective import all_reduce
        from ...parallel import get_world_size

        n = get_world_size(self._group)
        with framework.no_grad_guard():
            for p in self._inner._parameters or []:
                if p.grad is None:
                    continue
                g16 = Tensor(p.grad._array.astype(self._dtype))
                if n > 1:
                    all_reduce(g16, group=self._group)
                p.grad = Tensor(g16._array.astype(jnp.float32) / max(n, 1))
        self._inner.step()


class LookaheadOptimizer(MetaOptimizerBase):
    """Lookahead (reference `fluid/optimizer.py:5969`): fast weights step
    every iteration; every k steps slow weights interpolate
    slow += alpha * (fast - slow) and fast resets to slow."""

    def __init__(self, inner, alpha=0.5, k=5):
        super().__init__(inner)
        self.alpha = float(alpha)
        self.k = max(1, int(k))
        self._slow = {}
        self._tick = 0

    def step(self):
        with framework.no_grad_guard():
            # slow weights initialize from the params BEFORE the first step
            for p in self._inner._parameters or []:
                if id(p) not in self._slow:
                    self._slow[id(p)] = p._array
        self._inner.step()
        self._tick += 1
        with framework.no_grad_guard():
            if self._tick % self.k == 0:
                for p in self._inner._parameters or []:
                    slow = self._slow[id(p)]
                    slow = slow + self.alpha * (p._array - slow)
                    self._slow[id(p)] = slow
                    p._array = slow


class ModelAverage(MetaOptimizerBase):
    """Windowed running average of parameters applied at eval time
    (reference `fluid/optimizer.py:3573`): `apply()` swaps averaged weights
    in, `restore()` swaps back.  Follows the reference's accumulator
    rotation: when the live window exceeds `max_average_window`, it rolls
    into an "old" accumulator, so the average covers at most roughly the
    last 2×max_average_window steps rather than all of history."""

    def __init__(self, inner, average_window_rate=0.15, min_average_window=2,
                 max_average_window=10000):
        super().__init__(inner)
        self.average_window_rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._sum = {}
        self._old_sum = {}
        self._num = 0
        self._old_num = 0
        self._updates = 0
        self._backup = None

    def step(self):
        self._inner.step()
        with framework.no_grad_guard():
            self._updates += 1
            window = max(self.min_average_window,
                         min(self.max_average_window,
                             int(self._updates * self.average_window_rate)))
            if self._num >= window:
                self._old_sum = self._sum
                self._old_num = self._num
                self._sum = {}
                self._num = 0
            for p in self._inner._parameters or []:
                s = self._sum.get(id(p))
                self._sum[id(p)] = p._array if s is None else s + p._array
            self._num += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {}
        total = self._num + self._old_num
        with framework.no_grad_guard():
            for p in self._inner._parameters or []:
                if total == 0:
                    continue
                acc = self._sum.get(id(p), 0)
                if id(p) in self._old_sum:
                    acc = acc + self._old_sum[id(p)]
                self._backup[id(p)] = p._array
                p._array = acc / total
        return _SwapGuard(self) if need_restore else None

    def restore(self, executor=None):
        if self._backup:
            for p in self._inner._parameters or []:
                if id(p) in self._backup:
                    p._array = self._backup[id(p)]
            self._backup = None


class _SwapGuard:
    def __init__(self, owner):
        self._owner = owner

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._owner.restore()
        return False


class ExponentialMovingAverage:
    """EMA of parameters (reference `fluid/optimizer.py:3882`): call
    `update()` after each optimizer step; `apply()`/`restore()` swap the
    shadow weights for evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, parameters=None):
        self._decay = float(decay)
        self._parameters = list(parameters) if parameters else None
        self._shadow = {}
        self._backup = None
        self._step = 0

    def _params(self):
        if self._parameters is None:
            raise RuntimeError("ExponentialMovingAverage needs parameters=")
        return self._parameters

    def update(self):
        self._step += 1
        d = min(self._decay, (1.0 + self._step) / (10.0 + self._step))
        with framework.no_grad_guard():
            for p in self._params():
                s = self._shadow.get(id(p), p._array)
                self._shadow[id(p)] = d * s + (1.0 - d) * p._array

    def apply(self, executor=None, need_restore=True):
        self._backup = {}
        for p in self._params():
            if id(p) in self._shadow:
                self._backup[id(p)] = p._array
                p._array = self._shadow[id(p)]
        return _SwapGuard(self) if need_restore else None

    def restore(self, executor=None):
        if self._backup:
            for p in self._params():
                if id(p) in self._backup:
                    p._array = self._backup[id(p)]
            self._backup = None


# ---------------------------------------------------------------------------
# StrategyCompiler
# ---------------------------------------------------------------------------
class StrategyCompiler:
    """Select and stack meta-optimizers from a DistributedStrategy.

    Reference `fleet/base/strategy_compiler.py:91` runs a
    maximum-path-length search over declared compatibility; the strategy
    space here is small enough to encode the valid orderings directly.
    Returns (wrapped_optimizer, applied_names).
    """

    # outermost-first application order; tuples are mutually exclusive with
    # earlier entries winning (deterministic priority)
    _EXCLUSIVE = [("dgc", "localsgd", "fp16_allreduce")]
    _ORDER = ["gradient_merge", "dgc", "localsgd", "fp16_allreduce",
              "lookahead"]

    def generate_optimizer(self, optimizer, strategy):
        applied: List[str] = []
        flags = {
            "gradient_merge": getattr(strategy, "gradient_merge", False),
            "dgc": getattr(strategy, "dgc", False),
            "localsgd": getattr(strategy, "localsgd", False),
            "fp16_allreduce": getattr(strategy, "fp16_allreduce", False),
            "lookahead": getattr(strategy, "lookahead", False),
        }
        for group in self._EXCLUSIVE:
            on = [k for k in group if flags.get(k)]
            for k in on[1:]:  # keep the first, drop the rest
                flags[k] = False
        # lamb/lars swap the base optimizer (reference replaces the op),
        # carrying over the user's lr/decay/clip hyperparameters
        from ....optimizer import Lamb, Lars

        if getattr(strategy, "lamb", False) and not isinstance(optimizer, Lamb):
            kw = {}
            if optimizer._weight_decay:
                kw["lamb_weight_decay"] = optimizer._weight_decay
            optimizer = Lamb(learning_rate=optimizer._learning_rate,
                             parameters=optimizer._parameters,
                             grad_clip=optimizer._grad_clip, **kw)
            applied.append("lamb")
        elif getattr(strategy, "lars", False) and not isinstance(optimizer, Lars):
            kw = {}
            if optimizer._weight_decay:
                kw["lars_weight_decay"] = optimizer._weight_decay
            optimizer = Lars(learning_rate=optimizer._learning_rate,
                             parameters=optimizer._parameters,
                             grad_clip=optimizer._grad_clip, **kw)
            applied.append("lars")

        def _cfg(name, keys):
            cfg = getattr(strategy, name, None) or {}
            return {k: cfg[k] for k in keys if k in cfg}

        def _dgc(o):
            cfg = getattr(strategy, "dgc_configs", None) or {}
            kw = {}
            if "rampup_begin_step" in cfg:
                kw["rampup_begin_step"] = cfg["rampup_begin_step"]
            sp = cfg.get("sparsity")
            if sp is not None:  # proto stores a rampup list; use final value
                kw["sparsity"] = sp[-1] if isinstance(sp, (list, tuple)) else sp
            return DGCOptimizer(o, **kw)

        wrappers = {
            "gradient_merge": lambda o: GradientMergeOptimizer(
                o, **_cfg("gradient_merge_configs", ("k_steps", "avg"))),
            "dgc": _dgc,
            "localsgd": lambda o: LocalSGDOptimizer(
                o, **_cfg("localsgd_configs",
                          ("k_steps", "adaptive", "init_k_steps"))),
            "fp16_allreduce": lambda o: FP16AllReduceOptimizer(o),
            "lookahead": lambda o: LookaheadOptimizer(
                o, **_cfg("lookahead_configs", ("alpha", "k"))),
        }
        # innermost-first wrapping so _ORDER[0] ends up outermost
        for name in reversed(self._ORDER):
            if flags.get(name):
                optimizer = wrappers[name](optimizer)
                applied.insert(0, name)
        return optimizer, applied


class DygraphShardingOptimizer(MetaOptimizerBase):
    """ZeRO-1 optimizer-state sharding API shim (reference
    `fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py`).

    TPU-native: the actual state sharding happens in
    `fleet.build_train_step`'s NamedShardings (`sharded_step.py`,
    strategy.sharding stage 1); this class keeps the reference's
    constructor/step surface for ported scripts and simply delegates —
    wrapping it around an optimizer used with a ShardedTrainStep yields
    exactly the sharded behavior the reference builds by hand."""

    def __init__(self, hcg=None, user_defined_strategy=None, params=None,
                 inner_optimizer_class=None, optimizer=None, **inner_kw):
        # reference positional signature: (hcg, strategy, params,
        # inner_optimizer_class, **inner_opt_kargs); `optimizer=` accepts a
        # pre-built optimizer for the TPU-native flow
        if optimizer is None:
            if inner_optimizer_class is None:
                raise TypeError(
                    "DygraphShardingOptimizer needs inner_optimizer_class "
                    "(reference signature) or optimizer=")
            optimizer = inner_optimizer_class(parameters=params, **inner_kw)
        super().__init__(optimizer)
        self._hcg = hcg
