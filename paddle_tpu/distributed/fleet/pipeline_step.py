"""Compiled 1F1B pipeline-parallel train step for arbitrary PipelineLayer
models.

Reference: the static PP runtime — `PipelineOptimizer` program split +
`PipelineTrainer`/`SectionWorker` 1F1B schedule
(`framework/section_worker.cc:144`, startup = num_stages - stage - 1) and
the dygraph driver `meta_parallel/pipeline_parallel.py:109` — generalized
the TPU way: the WHOLE schedule (all micro-batch forwards, backwards and
the optimizer update) is one jit-compiled SPMD program over the 'pp' (and
'dp') mesh axes, with `lax.ppermute` playing send_v2/recv_v2.

Stage partitioning supports HETEROGENEOUS stages (embedding stage,
transformer stages, head stage — arbitrary `PipelineLayer.segment_parts`):
each stage's parameters are flattened into one f32 vector, padded to the
largest stage, and stacked into a ``[L, S_max]`` array sharded over 'pp' —
so every device materializes ONLY its own stage's parameters (plus
padding), giving PP its memory scaling.  Inside the schedule, a
`lax.switch` over the stage index applies the right stage computation.

Constraints (documented, enforced):
* stage-boundary activations must share one shape/dtype (the reference
  exchanges fixed shape meta the same way, `pipeline_parallel.py:282`).

Round-3 generalizations (former constraints, now supported):
* buffer-writing stages (BatchNorm running stats): buffers pack into a
  second 'pp'-sharded [L, B_max] vector threaded through the schedule's
  forward slots in micro-batch order (the backward's recompute binds the
  step-initial buffers — sound because train-mode BN normalizes with
  batch statistics, so running stats never affect gradients);
* non-elementwise optimizers (Lamb/Lars per-param trust ratios): when
  ``optimizer._elementwise_update`` is False the update unpacks each
  stage row into its real per-parameter tensors and applies
  ``_update_param`` per parameter before repacking (elementwise
  optimizers keep the cheaper fused packed-vector update — numerically
  identical for them).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...core import framework
from ...core.tensor import Tensor
from ...jit import _SwappedState
from ...parallel.pipeline import pipeline_1f1b_local


def _call_seq(layers, x):
    for ly in layers:
        x = ly(*x) if isinstance(x, tuple) else ly(x)
    return x


class _StageMeta:
    """Host-side flatten/unflatten spec for one stage's parameters."""

    def __init__(self, params: Dict[str, Tensor]):
        self.names = sorted(params)
        self.tensors = params
        self.offsets = {}
        off = 0
        for k in self.names:
            t = params[k]
            n = int(np.prod(t.shape)) if t.ndim else 1
            self.offsets[k] = (off, tuple(t.shape), t._array.dtype)
            off += n
        self.size = off

    def pack(self) -> np.ndarray:
        out = np.zeros(self.size, np.float32)
        for k in self.names:
            off, shape, _ = self.offsets[k]
            a = np.asarray(jax.device_get(self.tensors[k]._array),
                           np.float32).reshape(-1)
            out[off:off + a.size] = a
        return out

    def unpack(self, vec):
        """vec [>=size] -> dict of arrays in original shapes/dtypes."""
        return {
            k: vec[off:off + int(np.prod(shape) if shape else 1)]
            .reshape(shape).astype(dtype)
            for k, (off, shape, dtype) in self.offsets.items()
        }

    def repack(self, arrays: Dict, total: int):
        """dict of arrays -> f32 vector [total] (traced; zero padding)."""
        pieces = []
        off = 0
        for k in self.names:
            o, shape, _ = self.offsets[k]
            assert o == off, (k, o, off)
            a = arrays[k].astype(jnp.float32).reshape(-1)
            pieces.append(a)
            off += a.size
        if total > off:
            pieces.append(jnp.zeros((total - off,), jnp.float32))
        return jnp.concatenate(pieces) if pieces else \
            jnp.zeros((total,), jnp.float32)


class PipelineTrainStep:
    """fleet.build_train_step product for pp>1 + PipelineLayer.

    __call__(inputs, labels) -> mean loss (replicated).  Parameters live as
    a ``[L, S_max]`` f32 master copy sharded over 'pp'; `sync_params` writes
    them back into the layer's Tensors (for checkpointing/eval).
    """

    def __init__(self, model, loss_fn: Callable, optimizer, mesh: Mesh,
                 n_micro: Optional[int] = None, donate: bool = True,
                 unroll: int = 1):
        self.model = model
        self.loss_fn = loss_fn or getattr(model, "_loss_fn", None)
        if self.loss_fn is None:
            raise ValueError("pipeline train step needs loss_fn(out, label)")
        self.optimizer = optimizer
        self.mesh = mesh
        self.L = int(mesh.shape.get("pp", 1))
        if self.L < 2:
            raise ValueError("PipelineTrainStep requires pp_degree >= 2")
        self.dp = int(mesh.shape.get("dp", 1))
        self.n_micro = int(n_micro or self.L)
        self._donate = donate
        self._unroll = unroll
        nstages = len(model.segment_parts) - 1
        if nstages != self.L:
            raise ValueError(
                f"PipelineLayer has {nstages} stages but mesh pp={self.L}")
        self.stage_layers: List[list] = [
            model.get_stage_layers(r) for r in range(self.L)
        ]
        self.stage_meta: List[_StageMeta] = []
        self.buf_meta: List[_StageMeta] = []
        for r in range(self.L):
            params: Dict[str, Tensor] = {}
            bufs: Dict[str, Tensor] = {}
            for i, ly in enumerate(self.stage_layers[r]):
                p, b = ly.functional_state()
                for k, t in p.items():
                    params[f"l{i}.{k}"] = t
                for k, t in b.items():
                    bufs[f"l{i}.{k}"] = t
            self.stage_meta.append(_StageMeta(params))
            self.buf_meta.append(_StageMeta(bufs))
        self.S = max(m.size for m in self.stage_meta)
        if self.S == 0:
            raise ValueError("PipelineLayer has no parameters")
        # [L, S] packed master params, 'pp'-sharded: each device holds only
        # its own stage (the memory-scaling property VERDICT required)
        packed = np.zeros((self.L, self.S), np.float32)
        for r, m in enumerate(self.stage_meta):
            packed[r, :m.size] = m.pack()
        self.vec_sharding = NamedSharding(mesh, PartitionSpec("pp", None))
        self._repl = NamedSharding(mesh, PartitionSpec())
        self._vec = jax.device_put(jnp.asarray(packed), self.vec_sharding)
        # [L, B] packed buffers (BatchNorm running stats etc.), threaded
        # through the schedule's forward slots; absent when no stage has
        # buffers
        self.B = max(m.size for m in self.buf_meta)
        if self.B:
            bpacked = np.zeros((self.L, self.B), np.float32)
            for r, m in enumerate(self.buf_meta):
                bpacked[r, :m.size] = m.pack()
            self._buf = jax.device_put(jnp.asarray(bpacked),
                                       self.vec_sharding)
        else:
            self._buf = None
        self._buf_placeholder = None  # created lazily for buffer-free runs
        self._opt_state = None
        self._compiled = None
        self._step = 0
        self._act_spec = None  # (shape, dtype) of stage-boundary activation
        self._dirty = False    # master copy ahead of the layer Tensors?

    # -- stage application (traced) -----------------------------------------
    def _apply_stage(self, r: int, vec_local, x, rng, buf_local=None,
                     capture_writes=False):
        """Run stage r's layers with params (and buffers) bound from the
        packed vectors.  x: Tensor input (activation or raw micro-batch
        for r=0).  With ``capture_writes`` returns (out, new_buf_row) —
        buffer mutations (BatchNorm running stats) recorded during the
        forward become the stage's updated buffer vector."""
        meta = self.stage_meta[r]
        bmeta = self.buf_meta[r]
        arrays = meta.unpack(vec_local)
        bound: Dict[str, Tensor] = dict(meta.tensors)
        if buf_local is not None and bmeta.size:
            barrays = bmeta.unpack(buf_local)
            for k, t in bmeta.tensors.items():
                bound[f"__buf__{k}"] = t
            arrays = dict(arrays)
            arrays.update({f"__buf__{k}": barrays[k] for k in bmeta.names})
        writes: Dict[int, object] = {}
        with _SwappedState(bound) as sw:
            sw.bind(arrays)
            with framework.trace_guard(rng_key=rng, writes=writes):
                out = _call_seq(self.stage_layers[r], x)
            if capture_writes:
                new_bufs = {}
                for k, t in bmeta.tensors.items():
                    w = writes.get(id(t))
                    new_bufs[k] = w if w is not None else \
                        (barrays[k] if buf_local is not None and bmeta.size
                         else t._array)
        out = out._array if isinstance(out, Tensor) else out
        if capture_writes:
            total = buf_local.shape[0] if buf_local is not None else self.B
            return out, bmeta.repack(new_bufs, total)
        return out

    def _infer_act_spec(self, mb_input):
        """Trace stage boundaries to find the (uniform) activation spec."""
        def s0(vec, x):
            return self._apply_stage(0, vec, Tensor(x),
                                     framework.make_rng_key(0))

        out = jax.eval_shape(s0, jax.ShapeDtypeStruct((self.S,),
                                                      jnp.float32),
                             jax.ShapeDtypeStruct(mb_input.shape,
                                                  mb_input.dtype))
        spec = (tuple(out.shape), out.dtype)
        # verify every middle boundary matches (heterogeneity is allowed in
        # params, not in boundary activations)
        for r in range(1, self.L - 1):
            def sr(vec, a, _r=r):
                return self._apply_stage(_r, vec, Tensor(a),
                                         framework.make_rng_key(0))
            o = jax.eval_shape(sr,
                               jax.ShapeDtypeStruct((self.S,), jnp.float32),
                               jax.ShapeDtypeStruct(spec[0], spec[1]))
            if (tuple(o.shape), o.dtype) != spec:
                raise ValueError(
                    f"stage {r} changes the boundary activation to "
                    f"{o.shape}/{o.dtype}; all stage boundaries must share "
                    f"one shape/dtype for the ppermute schedule")
        return spec

    # -- compiled step -------------------------------------------------------
    def _build(self, mb_in_sds, mb_lab_sds):
        L, M, S = self.L, self.n_micro, self.S
        act_shape, act_dtype = self._act_spec
        loss_fn = self.loss_fn
        apply_stage = self._apply_stage
        unroll = self._unroll
        with_bufs = self._buf is not None
        buf_meta = self.buf_meta

        def make_fwd(r):
            # a stage only pays buffer capture when IT has buffers (static
            # per-stage check); a buffer-free stage under a buffered model
            # passes the vector through untouched
            stage_has_bufs = with_bufs and buf_meta[r].size > 0
            if r == L - 1:
                # last stage computes nothing forward: its real work (loss
                # fwd+bwd) happens in the backward slot via value_and_grad
                # EXCEPT for its buffer updates, which only the forward
                # slot may thread (the backward recomputes)
                def fl(vec, act_in, mb_x, rng, buf):
                    if stage_has_bufs:
                        _, nbuf = apply_stage(L - 1, vec, Tensor(act_in),
                                              rng, buf, True)
                        return jnp.zeros(act_shape, act_dtype), nbuf
                    if with_bufs:
                        return jnp.zeros(act_shape, act_dtype), buf
                    return jnp.zeros(act_shape, act_dtype)
                return fl

            def fr(vec, act_in, mb_x, rng, buf, _r=r,
                   _has=stage_has_bufs):
                x = Tensor(mb_x) if _r == 0 else Tensor(act_in)
                if _has:
                    out, nbuf = apply_stage(_r, vec, x, rng, buf, True)
                    return out.astype(act_dtype), nbuf
                if with_bufs:
                    return (apply_stage(_r, vec, x, rng)
                            .astype(act_dtype), buf)
                return apply_stage(_r, vec, x, rng).astype(act_dtype)
            return fr

        def make_bwd(r):
            # the backward's recompute binds the STEP-INITIAL buffers
            # (closed over via init_buf): train-mode BN normalizes with
            # batch stats, so running stats never affect the gradients
            if r == L - 1:
                def bl(vec, act_saved, g_in, mb_y, rng, init_buf):
                    def loss_of(v, a):
                        out = apply_stage(L - 1, v, Tensor(a), rng,
                                          init_buf)
                        lt = loss_fn(Tensor(out), Tensor(mb_y))
                        la = lt._array if isinstance(lt, Tensor) else lt
                        return la.astype(jnp.float32)

                    lss, (gvec, gact) = jax.value_and_grad(
                        loss_of, argnums=(0, 1))(vec, act_saved)
                    return gvec, gact.astype(jnp.float32), lss
                return bl
            if r == 0:
                def b0(vec, act_saved, g_in, mb_x, rng, init_buf):
                    def out_of(v):
                        return apply_stage(0, v, Tensor(mb_x), rng,
                                           init_buf).astype(act_dtype)

                    _, vjp = jax.vjp(out_of, vec)
                    (gvec,) = vjp(g_in.astype(act_dtype))
                    return (gvec, jnp.zeros(act_shape, jnp.float32),
                            jnp.zeros((), jnp.float32))
                return b0

            def br(vec, act_saved, g_in, mb_y, rng, init_buf, _r=r):
                def out_of(v, a):
                    return apply_stage(_r, v, Tensor(a), rng,
                                       init_buf).astype(act_dtype)

                _, vjp = jax.vjp(out_of, vec, act_saved)
                gvec, gact = vjp(g_in.astype(act_dtype))
                return (gvec, gact.astype(jnp.float32),
                        jnp.zeros((), jnp.float32))
            return br

        fwd_branches = [make_fwd(r) for r in range(L)]
        bwd_branches = [make_bwd(r) for r in range(L)]

        def local(vec2d, buf2d, micro_in, micro_lab, rng):
            # vec2d: [1, S] (this device's stage); micro_*: [M, mb, ...]
            vec = vec2d[0]
            init_buf = buf2d[0] if with_bufs else None
            rank = lax.axis_index("pp")

            def fwd_apply(v, act_in, mb_idx, key, buf=None):
                return lax.switch(
                    rank,
                    [lambda args, _r=r: fwd_branches[_r](*args)
                     for r in range(L)],
                    (v, act_in, micro_in[mb_idx], key, buf))

            def bwd_apply(v, act_saved, g_in, mb_idx, key):
                # stage 0 needs its micro-batch input (recompute); the last
                # stage needs the labels — pass per-rank operand
                def branch(args, _r=0):
                    v_, a_, g_, mi, ml, k_ = args
                    mb = mi if _r == 0 else ml
                    return bwd_branches[_r](v_, a_, g_, mb, k_, init_buf)

                return lax.switch(
                    rank,
                    [lambda args, _r=r: branch(args, _r)
                     for r in range(L)],
                    (v, act_saved, g_in, micro_in[mb_idx],
                     micro_lab[mb_idx], key))

            if with_bufs:
                gacc, loss_sum, new_buf = pipeline_1f1b_local(
                    fwd_apply, bwd_apply, vec, M, act_shape, act_dtype,
                    axis_name="pp", rng=rng, unroll=unroll,
                    state=init_buf)
            else:
                gacc, loss_sum = pipeline_1f1b_local(
                    lambda v, a, i, k: fwd_apply(v, a, i, k, None),
                    bwd_apply, vec, M, act_shape, act_dtype,
                    axis_name="pp", rng=rng, unroll=unroll)
                new_buf = jnp.zeros((0,), jnp.float32)
            # mean over micro-batches; grads also mean over dp replicas
            gacc = gacc / M
            if self.dp > 1:
                gacc = lax.pmean(gacc, "dp")
                # running stats advanced independently per dp replica on
                # disjoint shards: average them (DataParallel BN stance)
                if with_bufs:
                    new_buf = lax.pmean(new_buf, "dp")
            loss = loss_sum / M
            # make loss visible on all pp ranks (only last stage has it)
            loss = lax.psum(loss, "pp")
            if self.dp > 1:
                loss = lax.pmean(loss, "dp")
            return gacc[None], new_buf[None], loss

        in_specs = (PartitionSpec("pp", None), PartitionSpec("pp", None),
                    PartitionSpec(None, "dp"), PartitionSpec(None, "dp"),
                    PartitionSpec())
        out_specs = (PartitionSpec("pp", None), PartitionSpec("pp", None),
                     PartitionSpec())
        sched = jax.shard_map(local, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)

        optimizer = self.optimizer
        elementwise = getattr(optimizer, "_elementwise_update", True)
        stage_meta = self.stage_meta

        def _per_stage_update(vec, grads, opt_state, lr, step):
            """Unpacked per-parameter update for non-elementwise
            optimizers: each stage row unpacks into its real tensors,
            `_update_param` runs per parameter (correct per-param norms
            for Lamb/Lars), rows repack.  L is static, so this is L
            per-row programs — XLA keeps each on its own 'pp' shard."""
            if optimizer._grad_clip is not None:
                # same packed-vector clip the elementwise path gets via
                # apply_gradients (padding rows are zero, so the global
                # norm over the packed matrix equals the per-param norm)
                grads = optimizer._grad_clip.clip_arrays([grads])[0]
            slots = opt_state.get("__pp_vec__", {})
            new_rows, new_slot_rows = [], {k: [] for k in slots}
            scalar_out = {}
            for r in range(L):
                meta = stage_meta[r]
                p_r = meta.unpack(vec[r])
                g_r = meta.unpack(grads[r].astype(jnp.float32))
                np_r, ns_r = {}, {k: {} for k in slots}
                for name in meta.names:
                    slot_p = {}
                    for sk, sv in slots.items():
                        if getattr(sv, "ndim", 0) == 2:
                            off, shape, _ = meta.offsets[name]
                            n = int(np.prod(shape) if shape else 1)
                            slot_p[sk] = sv[r][off:off + n].reshape(shape)
                        else:
                            slot_p[sk] = sv
                    g = optimizer._apply_decay(p_r[name], g_r[name]
                                               .astype(p_r[name].dtype))
                    newp, news = optimizer._update_param(
                        p_r[name], g, slot_p, lr, step)
                    np_r[name] = newp
                    for sk in slots:
                        ns_r[sk][name] = news.get(sk)
                new_rows.append(meta.repack(np_r, S))
                for sk, sv in slots.items():
                    if getattr(sv, "ndim", 0) == 2:
                        new_slot_rows[sk].append(
                            meta.repack(ns_r[sk], S))
                    else:
                        scalar_out[sk] = next(iter(ns_r[sk].values())) \
                            if ns_r[sk] else sv
            new_vec = jnp.stack(new_rows)
            new_slots = {}
            for sk, sv in slots.items():
                if getattr(sv, "ndim", 0) == 2:
                    new_slots[sk] = jnp.stack(new_slot_rows[sk])
                else:
                    new_slots[sk] = scalar_out.get(sk, sv)
            return new_vec, {"__pp_vec__": new_slots}

        def pure(vec, bufvec, opt_state, micro_in, micro_lab, lr, step,
                 rng):
            grads, new_buf, loss = sched(vec, bufvec, micro_in, micro_lab,
                                         rng)
            if elementwise:
                new_params, new_opt = optimizer.apply_gradients(
                    {"__pp_vec__": vec}, {"__pp_vec__": grads}, opt_state,
                    lr, step)
                new_vec = new_params["__pp_vec__"]
            else:
                new_vec, new_opt = _per_stage_update(vec, grads, opt_state,
                                                     lr, step)
            return loss, new_vec, new_buf, new_opt

        opt_shardings = {
            "__pp_vec__": {
                sk: (self.vec_sharding
                     if getattr(sv, "ndim", 0) == 2 else self._repl)
                for sk, sv in (self._opt_state or {}).get("__pp_vec__",
                                                          {}).items()
            }
        }
        in_shardings = (
            self.vec_sharding, self.vec_sharding, opt_shardings,
            NamedSharding(self.mesh, PartitionSpec(None, "dp")),
            NamedSharding(self.mesh, PartitionSpec(None, "dp")),
            self._repl, self._repl, self._repl,
        )
        out_shardings = (self._repl, self.vec_sharding, self.vec_sharding,
                         opt_shardings)
        # the buffer-free placeholder is persistent — don't donate it
        donate = ((0, 1, 2) if with_bufs else (0, 2)) if self._donate \
            else ()
        with self.mesh:
            return jax.jit(pure, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate)

    def __call__(self, inputs, labels) -> Tensor:
        xin = inputs._array if isinstance(inputs, Tensor) else \
            jnp.asarray(inputs)
        ylab = labels._array if isinstance(labels, Tensor) else \
            jnp.asarray(labels)
        M, dp = self.n_micro, self.dp
        B = xin.shape[0]
        if B % (M * dp):
            raise ValueError(
                f"batch {B} must divide by n_micro*dp = {M * dp}")
        mb = B // (M * dp)
        # [M, mb*dp, ...]: micro-batch-major so each dp shard slices its
        # portion of every micro-batch
        micro_in = xin.reshape((M, B // M) + xin.shape[1:])
        micro_lab = ylab.reshape((M, B // M) + ylab.shape[1:])
        if self._act_spec is None:
            self._act_spec = self._infer_act_spec(
                jax.ShapeDtypeStruct((mb,) + xin.shape[1:], xin.dtype))
        if self._opt_state is None:
            state = self.optimizer.init_state({"__pp_vec__": self._vec})
            self._opt_state = {
                "__pp_vec__": {
                    sk: jax.device_put(
                        sv, self.vec_sharding
                        if getattr(sv, "ndim", 0) == 2 else self._repl)
                    for sk, sv in state["__pp_vec__"].items()
                }
            }
        if self._compiled is None:
            self._compiled = self._build(None, None)
        self._step += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        rng = framework.default_generator.next_key()
        self._dirty = True
        if self._buf is not None:
            bufvec = self._buf
        else:
            if self._buf_placeholder is None:
                self._buf_placeholder = jax.device_put(
                    jnp.zeros((self.L, 1), jnp.float32),
                    self.vec_sharding)
            bufvec = self._buf_placeholder
        loss, self._vec, new_buf, self._opt_state = self._compiled(
            self._vec, bufvec, self._opt_state,
            jax.device_put(micro_in,
                           NamedSharding(self.mesh,
                                         PartitionSpec(None, "dp"))),
            jax.device_put(micro_lab,
                           NamedSharding(self.mesh,
                                         PartitionSpec(None, "dp"))),
            lr, self._step, rng)
        if self._buf is not None:
            self._buf = new_buf
        return Tensor(loss)

    # -- state sync ----------------------------------------------------------
    def sync_params(self):
        """Write the packed master params back into the layer's Tensors
        (host gather; for checkpointing/eval after training).  No-op when
        the layer copy is already current — callers may invoke this per
        eval batch without paying a device->host gather each time."""
        if not self._dirty:
            return
        self._dirty = False
        packed = np.asarray(jax.device_get(self._vec))
        with framework.no_grad_guard():
            for r, meta in enumerate(self.stage_meta):
                arrays = meta.unpack(jnp.asarray(packed[r]))
                for k, t in meta.tensors.items():
                    t._array = arrays[k]
            if self._buf is not None:
                bpacked = np.asarray(jax.device_get(self._buf))
                for r, bmeta in enumerate(self.buf_meta):
                    if not bmeta.size:
                        continue
                    barrays = bmeta.unpack(jnp.asarray(bpacked[r]))
                    for k, t in bmeta.tensors.items():
                        t._array = barrays[k]

    def state_dict(self):
        self.sync_params()
        return self.model.state_dict()
