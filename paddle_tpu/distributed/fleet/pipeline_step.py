"""Compiled 1F1B pipeline-parallel train step for arbitrary PipelineLayer
models.

Reference: the static PP runtime — `PipelineOptimizer` program split +
`PipelineTrainer`/`SectionWorker` 1F1B schedule
(`framework/section_worker.cc:144`, startup = num_stages - stage - 1) and
the dygraph driver `meta_parallel/pipeline_parallel.py:109` — generalized
the TPU way: the WHOLE schedule (all micro-batch forwards, backwards and
the optimizer update) is one jit-compiled SPMD program over the 'pp' (and
'dp') mesh axes, with `lax.ppermute` playing send_v2/recv_v2.

Stage partitioning supports HETEROGENEOUS stages (embedding stage,
transformer stages, head stage — arbitrary `PipelineLayer.segment_parts`):
each stage's parameters are flattened into one f32 vector, padded to the
largest stage, and stacked into a ``[L, S_max]`` array sharded over 'pp' —
so every device materializes ONLY its own stage's parameters (plus
padding), giving PP its memory scaling.  Inside the schedule, a
`lax.switch` over the stage index applies the right stage computation.

Constraints (documented, enforced):
* stage-boundary activations must share one shape/dtype (the reference
  exchanges fixed shape meta the same way, `pipeline_parallel.py:282`);
* stages must be pure wrt buffers (no BatchNorm running-stat writes);
* optimizers must have elementwise update rules (SGD/Momentum/Adam/...;
  Lamb's per-param norms are not representable on the packed vector).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...core import framework
from ...core.tensor import Tensor
from ...jit import _SwappedState
from ...parallel.pipeline import pipeline_1f1b_local


def _call_seq(layers, x):
    for ly in layers:
        x = ly(*x) if isinstance(x, tuple) else ly(x)
    return x


class _StageMeta:
    """Host-side flatten/unflatten spec for one stage's parameters."""

    def __init__(self, params: Dict[str, Tensor]):
        self.names = sorted(params)
        self.tensors = params
        self.offsets = {}
        off = 0
        for k in self.names:
            t = params[k]
            n = int(np.prod(t.shape)) if t.ndim else 1
            self.offsets[k] = (off, tuple(t.shape), t._array.dtype)
            off += n
        self.size = off

    def pack(self) -> np.ndarray:
        out = np.zeros(self.size, np.float32)
        for k in self.names:
            off, shape, _ = self.offsets[k]
            a = np.asarray(jax.device_get(self.tensors[k]._array),
                           np.float32).reshape(-1)
            out[off:off + a.size] = a
        return out

    def unpack(self, vec):
        """vec [>=size] -> dict of arrays in original shapes/dtypes."""
        return {
            k: vec[off:off + int(np.prod(shape) if shape else 1)]
            .reshape(shape).astype(dtype)
            for k, (off, shape, dtype) in self.offsets.items()
        }


class PipelineTrainStep:
    """fleet.build_train_step product for pp>1 + PipelineLayer.

    __call__(inputs, labels) -> mean loss (replicated).  Parameters live as
    a ``[L, S_max]`` f32 master copy sharded over 'pp'; `sync_params` writes
    them back into the layer's Tensors (for checkpointing/eval).
    """

    def __init__(self, model, loss_fn: Callable, optimizer, mesh: Mesh,
                 n_micro: Optional[int] = None, donate: bool = True,
                 unroll: int = 1):
        self.model = model
        self.loss_fn = loss_fn or getattr(model, "_loss_fn", None)
        if self.loss_fn is None:
            raise ValueError("pipeline train step needs loss_fn(out, label)")
        self.optimizer = optimizer
        self.mesh = mesh
        self.L = int(mesh.shape.get("pp", 1))
        if self.L < 2:
            raise ValueError("PipelineTrainStep requires pp_degree >= 2")
        self.dp = int(mesh.shape.get("dp", 1))
        self.n_micro = int(n_micro or self.L)
        self._donate = donate
        self._unroll = unroll
        nstages = len(model.segment_parts) - 1
        if nstages != self.L:
            raise ValueError(
                f"PipelineLayer has {nstages} stages but mesh pp={self.L}")
        self.stage_layers: List[list] = [
            model.get_stage_layers(r) for r in range(self.L)
        ]
        self.stage_meta: List[_StageMeta] = []
        for r in range(self.L):
            params: Dict[str, Tensor] = {}
            for i, ly in enumerate(self.stage_layers[r]):
                p, _ = ly.functional_state()
                for k, t in p.items():
                    params[f"l{i}.{k}"] = t
            self.stage_meta.append(_StageMeta(params))
        self.S = max(m.size for m in self.stage_meta)
        if self.S == 0:
            raise ValueError("PipelineLayer has no parameters")
        # [L, S] packed master params, 'pp'-sharded: each device holds only
        # its own stage (the memory-scaling property VERDICT required)
        packed = np.zeros((self.L, self.S), np.float32)
        for r, m in enumerate(self.stage_meta):
            packed[r, :m.size] = m.pack()
        self.vec_sharding = NamedSharding(mesh, PartitionSpec("pp", None))
        self._repl = NamedSharding(mesh, PartitionSpec())
        self._vec = jax.device_put(jnp.asarray(packed), self.vec_sharding)
        self._opt_state = None
        self._compiled = None
        self._step = 0
        self._act_spec = None  # (shape, dtype) of stage-boundary activation
        self._dirty = False    # master copy ahead of the layer Tensors?

    # -- stage application (traced) -----------------------------------------
    def _apply_stage(self, r: int, vec_local, x, rng):
        """Run stage r's layers with params bound from the packed vector.
        x: Tensor input (activation or raw micro-batch for r=0)."""
        meta = self.stage_meta[r]
        arrays = meta.unpack(vec_local)
        with _SwappedState(meta.tensors) as sw:
            sw.bind(arrays)
            with framework.trace_guard(rng_key=rng):
                out = _call_seq(self.stage_layers[r], x)
        return out._array if isinstance(out, Tensor) else out

    def _infer_act_spec(self, mb_input):
        """Trace stage boundaries to find the (uniform) activation spec."""
        def s0(vec, x):
            return self._apply_stage(0, vec, Tensor(x),
                                     framework.make_rng_key(0))

        out = jax.eval_shape(s0, jax.ShapeDtypeStruct((self.S,),
                                                      jnp.float32),
                             jax.ShapeDtypeStruct(mb_input.shape,
                                                  mb_input.dtype))
        spec = (tuple(out.shape), out.dtype)
        # verify every middle boundary matches (heterogeneity is allowed in
        # params, not in boundary activations)
        for r in range(1, self.L - 1):
            def sr(vec, a, _r=r):
                return self._apply_stage(_r, vec, Tensor(a),
                                         framework.make_rng_key(0))
            o = jax.eval_shape(sr,
                               jax.ShapeDtypeStruct((self.S,), jnp.float32),
                               jax.ShapeDtypeStruct(spec[0], spec[1]))
            if (tuple(o.shape), o.dtype) != spec:
                raise ValueError(
                    f"stage {r} changes the boundary activation to "
                    f"{o.shape}/{o.dtype}; all stage boundaries must share "
                    f"one shape/dtype for the ppermute schedule")
        return spec

    # -- compiled step -------------------------------------------------------
    def _build(self, mb_in_sds, mb_lab_sds):
        L, M, S = self.L, self.n_micro, self.S
        act_shape, act_dtype = self._act_spec
        loss_fn = self.loss_fn
        apply_stage = self._apply_stage
        unroll = self._unroll

        def make_fwd(r):
            if r == L - 1:
                # last stage computes nothing forward: its real work (loss
                # fwd+bwd) happens in the backward slot via value_and_grad
                return lambda vec, act_in, mb_x, rng: jnp.zeros(
                    act_shape, act_dtype)
            if r == 0:
                def f0(vec, act_in, mb_x, rng):
                    return apply_stage(0, vec, Tensor(mb_x),
                                       rng).astype(act_dtype)
                return f0

            def fr(vec, act_in, mb_x, rng, _r=r):
                return apply_stage(_r, vec, Tensor(act_in),
                                   rng).astype(act_dtype)
            return fr

        def make_bwd(r):
            if r == L - 1:
                def bl(vec, act_saved, g_in, mb_y, rng):
                    def loss_of(v, a):
                        out = apply_stage(L - 1, v, Tensor(a), rng)
                        lt = loss_fn(Tensor(out), Tensor(mb_y))
                        la = lt._array if isinstance(lt, Tensor) else lt
                        return la.astype(jnp.float32)

                    lss, (gvec, gact) = jax.value_and_grad(
                        loss_of, argnums=(0, 1))(vec, act_saved)
                    return gvec, gact.astype(jnp.float32), lss
                return bl
            if r == 0:
                def b0(vec, act_saved, g_in, mb_x, rng):
                    def out_of(v):
                        return apply_stage(0, v, Tensor(mb_x),
                                           rng).astype(act_dtype)

                    _, vjp = jax.vjp(out_of, vec)
                    (gvec,) = vjp(g_in.astype(act_dtype))
                    return (gvec, jnp.zeros(act_shape, jnp.float32),
                            jnp.zeros((), jnp.float32))
                return b0

            def br(vec, act_saved, g_in, mb_y, rng, _r=r):
                def out_of(v, a):
                    return apply_stage(_r, v, Tensor(a),
                                       rng).astype(act_dtype)

                _, vjp = jax.vjp(out_of, vec, act_saved)
                gvec, gact = vjp(g_in.astype(act_dtype))
                return (gvec, gact.astype(jnp.float32),
                        jnp.zeros((), jnp.float32))
            return br

        fwd_branches = [make_fwd(r) for r in range(L)]
        bwd_branches = [make_bwd(r) for r in range(L)]

        def local(vec2d, micro_in, micro_lab, rng):
            # vec2d: [1, S] (this device's stage); micro_*: [M, mb, ...]
            vec = vec2d[0]
            rank = lax.axis_index("pp")

            def fwd_apply(v, act_in, mb_idx, key):
                return lax.switch(
                    rank,
                    [lambda args, _r=r: fwd_branches[_r](*args)
                     for r in range(L)],
                    (v, act_in, micro_in[mb_idx], key))

            def bwd_apply(v, act_saved, g_in, mb_idx, key):
                # stage 0 needs its micro-batch input (recompute); the last
                # stage needs the labels — pass per-rank operand
                def branch(args, _r=0):
                    v_, a_, g_, mi, ml, k_ = args
                    mb = mi if _r == 0 else ml
                    return bwd_branches[_r](v_, a_, g_, mb, k_)

                return lax.switch(
                    rank,
                    [lambda args, _r=r: branch(args, _r)
                     for r in range(L)],
                    (v, act_saved, g_in, micro_in[mb_idx],
                     micro_lab[mb_idx], key))

            gacc, loss_sum = pipeline_1f1b_local(
                fwd_apply, bwd_apply, vec, M, act_shape, act_dtype,
                axis_name="pp", rng=rng, unroll=unroll)
            # mean over micro-batches; grads also mean over dp replicas
            gacc = gacc / M
            if self.dp > 1:
                gacc = lax.pmean(gacc, "dp")
            loss = loss_sum / M
            # make loss visible on all pp ranks (only last stage has it)
            loss = lax.psum(loss, "pp")
            if self.dp > 1:
                loss = lax.pmean(loss, "dp")
            return gacc[None], loss

        in_specs = (PartitionSpec("pp", None),
                    PartitionSpec(None, "dp"), PartitionSpec(None, "dp"),
                    PartitionSpec())
        out_specs = (PartitionSpec("pp", None), PartitionSpec())
        sched = jax.shard_map(local, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)

        optimizer = self.optimizer

        def pure(vec, opt_state, micro_in, micro_lab, lr, step, rng):
            grads, loss = sched(vec, micro_in, micro_lab, rng)
            new_params, new_opt = optimizer.apply_gradients(
                {"__pp_vec__": vec}, {"__pp_vec__": grads}, opt_state, lr,
                step)
            return loss, new_params["__pp_vec__"], new_opt

        opt_shardings = {
            "__pp_vec__": {
                sk: self.vec_sharding
                for sk in (self._opt_state or {}).get("__pp_vec__", {})
            }
        }
        in_shardings = (
            self.vec_sharding, opt_shardings,
            NamedSharding(self.mesh, PartitionSpec(None, "dp")),
            NamedSharding(self.mesh, PartitionSpec(None, "dp")),
            self._repl, self._repl, self._repl,
        )
        out_shardings = (self._repl, self.vec_sharding, opt_shardings)
        donate = (0, 1) if self._donate else ()
        with self.mesh:
            return jax.jit(pure, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate)

    def __call__(self, inputs, labels) -> Tensor:
        xin = inputs._array if isinstance(inputs, Tensor) else \
            jnp.asarray(inputs)
        ylab = labels._array if isinstance(labels, Tensor) else \
            jnp.asarray(labels)
        M, dp = self.n_micro, self.dp
        B = xin.shape[0]
        if B % (M * dp):
            raise ValueError(
                f"batch {B} must divide by n_micro*dp = {M * dp}")
        mb = B // (M * dp)
        # [M, mb*dp, ...]: micro-batch-major so each dp shard slices its
        # portion of every micro-batch
        micro_in = xin.reshape((M, B // M) + xin.shape[1:])
        micro_lab = ylab.reshape((M, B // M) + ylab.shape[1:])
        if self._act_spec is None:
            self._act_spec = self._infer_act_spec(
                jax.ShapeDtypeStruct((mb,) + xin.shape[1:], xin.dtype))
        if self._opt_state is None:
            state = self.optimizer.init_state({"__pp_vec__": self._vec})
            self._opt_state = {
                "__pp_vec__": {
                    sk: jax.device_put(sv, self.vec_sharding)
                    for sk, sv in state["__pp_vec__"].items()
                }
            }
        if self._compiled is None:
            self._compiled = self._build(None, None)
        self._step += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        rng = framework.default_generator.next_key()
        self._dirty = True
        loss, self._vec, self._opt_state = self._compiled(
            self._vec, self._opt_state,
            jax.device_put(micro_in,
                           NamedSharding(self.mesh,
                                         PartitionSpec(None, "dp"))),
            jax.device_put(micro_lab,
                           NamedSharding(self.mesh,
                                         PartitionSpec(None, "dp"))),
            lr, self._step, rng)
        return Tensor(loss)

    # -- state sync ----------------------------------------------------------
    def sync_params(self):
        """Write the packed master params back into the layer's Tensors
        (host gather; for checkpointing/eval after training).  No-op when
        the layer copy is already current — callers may invoke this per
        eval batch without paying a device->host gather each time."""
        if not self._dirty:
            return
        self._dirty = False
        packed = np.asarray(jax.device_get(self._vec))
        with framework.no_grad_guard():
            for r, meta in enumerate(self.stage_meta):
                arrays = meta.unpack(jnp.asarray(packed[r]))
                for k, t in meta.tensors.items():
                    t._array = arrays[k]

    def state_dict(self):
        self.sync_params()
        return self.model.state_dict()
