"""Tensor-parallel layers.

Reference: `python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py` — VocabParallelEmbedding (:30), ColumnParallelLinear (:97),
RowParallelLinear (:170), ParallelCrossEntropy (:249), backed by the
c_embedding / c_identity+c_allreduce_sum / c_concat/c_split /
c_softmax_with_cross_entropy collective ops.

TPU-native (GSPMD): the layers are ordinary matmuls whose weights carry
``mesh_axes`` PartitionSpecs; when the train step jits over the mesh, XLA
partitions the matmul over 'mp' and inserts exactly the collectives the
reference codes by hand (identity forward + all-reduce backward for column
parallel; all-reduce forward for row parallel; the vocab-parallel softmax-CE
becomes a sharded logits matmul + global reduction).  Activation shardings
are pinned with `with_sharding_constraint` so the partitioner cannot undo
the intended layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ....core import framework
from ....core.dispatch import WHITE, dispatch
from ....core.tensor import Tensor, unwrap
from ....nn import functional as F
from ....nn import initializer as init
from ....nn.layer.layers import Layer
from ...topology import get_hybrid_communicate_group


def _constrain(x, *spec):
    """with_sharding_constraint when tracing under a mesh; no-op otherwise."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return x

    def f(a):
        try:
            from jax.sharding import PartitionSpec

            return lax.with_sharding_constraint(
                a, jax.sharding.NamedSharding(hcg.mesh, PartitionSpec(*spec))
            )
        except Exception:
            return a

    if framework.in_trace():
        return dispatch(f, x)
    return x


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out ('mp'); output stays mp-sharded unless
    gather_output (reference mp_layers.py:97)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=init.XavierUniform(),
        )
        self.weight.mesh_axes = (None, "mp")
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.mesh_axes = ("mp",)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # pin activation sharding: last dim stays split over mp
            out = _constrain(out, *([None] * (out.ndim - 1)), "mp")
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in ('mp'); input expected mp-split;
    output is the full (all-reduced) tensor (reference mp_layers.py:170)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=init.XavierUniform(),
        )
        self.weight.mesh_axes = ("mp", None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, *([None] * (x.ndim - 1)), "mp")
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, *([None] * out.ndim))


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over vocab dim (reference mp_layers.py:30 /
    c_embedding op)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=init.Normal(0.0, 0.02),
        )
        self.weight.mesh_axes = ("mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, *([None] * out.ndim))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross-entropy (reference mp_layers.py:249 /
    `c_softmax_with_cross_entropy_op.cu`): logits arrive vocab-sharded over
    'mp'; the log-sum-exp reduction spans the full vocab because XLA sees the
    global logical array."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        logits = _constrain(input, *([None] * (input.ndim - 1)), "mp")
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)


class TensorParallel(Layer):
    """Model wrapper for TP runs (reference
    `meta_parallel/tensor_parallel.py:25`): in single-controller SPMD the
    parameter broadcast it performs is unnecessary; forwarding is identity."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)
